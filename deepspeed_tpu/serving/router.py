"""Fleet router: the single front door over N serving replicas.

The router owns three decisions and one promise:

* **Routing** — prefix-hash session affinity first (requests sharing a
  prompt prefix land where those KV blocks are already cached, the
  MII-replica-router / vLLM-prefix-aware-routing idea), then one of two
  policies: ``least_loaded`` by live load report, or ``predictive`` —
  route by predicted TTFT per replica from the five-phase model's
  decomposition (queue-wait estimate = reported queue depth x the
  observed per-request service-time EWMA, plus a prefill estimate from
  prompt length over the replica's observed prefill token rate). The
  predictive policy is what lets a degraded replica shed load *before*
  its queue builds: its service EWMA rises, so its predicted TTFT does
  too.
* **Disaggregation** — with ``prefill``/``decode``-role replicas, a new
  request goes to a prefill replica with a one-token budget; when its
  first token lands, the prompt's KV blocks are serialized from the
  prefill replica and installed into a decode replica
  (serving/disagg.py), and the remainder of the budget decodes there.
  Decode p99 never waits behind another request's prompt.
* **Failover** — a per-replica health state machine (``healthy →
  suspect → dead``) driven by *monotonic* heartbeat age and consecutive
  transport-error counts. A ``suspect`` replica (heartbeat past
  ``suspect_after_s`` or any transport error) stops receiving new
  routes but keeps its in-flight streams; it recovers to ``healthy``
  only after ``health_recover_checks`` consecutive clean checks
  (hysteresis — a flapping link doesn't flap the fleet). A ``dead``
  replica (heartbeat past ``stale_after_s``, a failed send, or
  ``transport_error_dead`` consecutive transport errors) has every one
  of its in-flight requests resubmitted elsewhere with the tokens
  generated so far folded into the prompt — PR 8's zero-drop contract
  (preempt-and-requeue) extended across replica death. Greedy decoding
  makes the continuation bit-identical to the uninterrupted stream;
  tokens already handed out are never re-emitted.
  ``health_mode="legacy"`` restores the single stale-threshold flip
  bit-exactly.
* **Hedged requests** — with ``hedge_enabled``, a routed request whose
  predicted TTFT has been exceeded by ``hedge_ttft_factor`` with no
  first token is resubmitted to a second replica; whichever stream
  emits first owns the request (greedy decoding makes both streams
  bit-identical, so the loser is dropped by the existing stale-emission
  uid guard). Hedges are HEDGE spans on the request trace plus
  ``serve.hedged``/``serve.hedge_wins`` counters.
* **Live migration** — ``migrate_sessions`` moves every in-flight
  decode session off a replica *warm*: committed KV blocks, the
  partial tail block, generated tokens, and the per-request
  spec-acceptance EWMA ship over the quantized handoff wire and
  resume on the target with zero re-prefill. Drains, rolling weight
  swaps, and migration-backed scale-down all ride it; a capture that
  can't happen degrades down the documented ladder (host-tier page-in
  on the target -> fold-and-recompute -> finish in place), each rung
  counted, never an error. ``migrate_hedges`` extends the same
  machinery to hedge promotion (off by default — legacy duplicate-
  stream hedging stays bit-exact).
* **The promise** — every accepted request completes with its full
  token budget, through overload, handoff, and replica death alike.

Every decision lands in the observability stack: ``ROUTE``/``HANDOFF``/
``FAILOVER`` spans on the per-request traces, fleet-level SLO
attribution aggregated over all replicas' tracers, per-replica Perfetto
lanes, and ``serve.fleet.*`` gauges (including the autoscaler's
desired-replica signal, serving/autoscale.py).

Threading: the router never touches an engine directly — it enqueues
:class:`Submission` objects into replica inboxes and receives emissions
via callbacks that run on the replica pump threads. Router state is
lock-protected, so the same code drives both the synchronous test mode
(``step()``/``run_until_complete()``) and the threaded bench mode
(``start()``/``drain()``).

Process fleets (serving/supervisor.py) reuse this router unchanged: a
``RemoteReplica`` satisfies the same surface (``submit``,
``load_report``, ``alive``, ``serialize_handoff``), emissions arrive on
the supervisor's receive threads instead of pump threads, and
``add_replica``/``remove_replica`` let the supervisor act on the
autoscale signal with real spin-up and drain.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.observability.clocksync import wall_time
from deepspeed_tpu.observability.journal import get_journal
from deepspeed_tpu.serving.replica import ServingReplica, Submission


def build_fleet(model, router_cfg=None, engine_kw=None,
                run_dir: Optional[str] = None,
                eos_token_id: Optional[int] = None) -> "FleetRouter":
    """Construct replicas + router from a ``serving.router`` config
    block (config.RouterConfig or any object with its fields; None uses
    the defaults). ``engine_kw`` is forwarded to every replica's
    engine constructor — pass shared ``params`` so the fleet serves one
    model, not N random inits."""
    from deepspeed_tpu.config.config import RouterConfig
    from deepspeed_tpu.serving.autoscale import AutoscaleSignal

    cfg = router_cfg if router_cfg is not None else RouterConfig()
    engine_kw = dict(engine_kw or {})
    n = int(cfg.replicas)
    n_prefill = int(cfg.prefill_replicas) if cfg.mode == "disagg" else 0
    replicas = []
    for i in range(n):
        role = "unified" if cfg.mode == "unified" else (
            "prefill" if i < n_prefill else "decode")
        replicas.append(ServingReplica.create(
            model, i, role=role, run_dir=run_dir, **engine_kw))
    from deepspeed_tpu.observability.hub import get_hub

    autoscale = AutoscaleSignal(
        min_replicas=cfg.autoscale_min, max_replicas=cfg.autoscale_max,
        queue_high=cfg.queue_high, queue_low=cfg.queue_low,
        slo_miss_high=cfg.slo_miss_high,
        hysteresis_rounds=cfg.hysteresis_rounds, hub=get_hub())
    return FleetRouter(replicas, affinity_blocks=cfg.affinity_blocks,
                       stale_after_s=cfg.stale_after_seconds,
                       autoscale=autoscale, eos_token_id=eos_token_id,
                       routing=getattr(cfg, "routing", "least_loaded"),
                       health_mode=getattr(cfg, "health_mode",
                                           "state_machine"),
                       suspect_after_s=getattr(cfg, "suspect_after_seconds",
                                               None),
                       transport_error_dead=getattr(
                           cfg, "transport_error_dead", 3),
                       health_recover_checks=getattr(
                           cfg, "health_recover_checks", 2),
                       hedge_enabled=getattr(cfg, "hedge_enabled", False),
                       hedge_ttft_factor=getattr(
                           cfg, "hedge_ttft_factor", 3.0),
                       hedge_min_s=getattr(cfg, "hedge_min_seconds", 0.25),
                       migrate_enabled=getattr(cfg, "migrate_sessions",
                                               True),
                       migrate_hedges=getattr(cfg, "migrate_hedges",
                                              False),
                       migrate_wire=(getattr(cfg, "migrate_wire", None)
                                     or None),
                       alerter=_build_alerter(
                           getattr(cfg, "burn_rate", None)))


def _build_alerter(burn_cfg):
    """BurnRateAlerter from a RouterConfig.burn_rate block (None when
    disabled — the default keeps the router alert-free, bit-exact with
    pre-alerting behavior)."""
    if burn_cfg is None:
        return None
    from deepspeed_tpu.observability.burn_rate import BurnRateAlerter
    from deepspeed_tpu.observability.hub import get_hub

    return BurnRateAlerter.from_config(burn_cfg, hub=get_hub())


class _RequestRecord:
    __slots__ = ("uid", "tokens", "max_new_tokens", "replica_id", "phase",
                 "emitted", "done", "failovers", "affinity_key",
                 "submitted_ts", "first_emit_ts", "last_emit_ts",
                 "submitted_mono", "hedge_replica_id", "hedge_at_mono",
                 "stale_rids")

    def __init__(self, uid, tokens, max_new_tokens, replica_id, phase,
                 affinity_key):
        self.uid = uid
        self.tokens = tokens
        self.max_new_tokens = max_new_tokens
        self.replica_id = replica_id
        self.phase = phase  # "prefill" (awaiting handoff) or "decode"
        self.emitted: List[int] = []
        self.done = False
        self.failovers = 0
        self.affinity_key = affinity_key
        # wall_time(), not time.time(): _on_emissions derives TTFT from
        # this stamp on the same clock domain as spans and the journal
        self.submitted_ts = wall_time()
        self.submitted_mono = time.monotonic()
        self.first_emit_ts = 0.0
        self.last_emit_ts = 0.0
        self.hedge_replica_id: Optional[int] = None
        self.hedge_at_mono: Optional[float] = None
        # replicas that may STILL be streaming this uid (a hedge that
        # lost the race, a primary abandoned by a hedge win): their
        # late emissions are dropped by the ownership guard, but they
        # must never be picked as a failover target for this request —
        # the engine would hold two live streams of one uid
        self.stale_rids: set = set()


ROUTING_POLICIES = ("least_loaded", "predictive")
HEALTH_MODES = ("state_machine", "legacy")
_HEALTH_ORDER = {"healthy": 0, "suspect": 1, "dead": 2}


class FleetRouter:
    def __init__(self, replicas: List[ServingReplica],
                 affinity_blocks: int = 2,
                 stale_after_s: float = 5.0,
                 autoscale=None,
                 eos_token_id: Optional[int] = None,
                 routing: str = "least_loaded",
                 service_ewma_alpha: float = 0.3,
                 health_mode: str = "state_machine",
                 suspect_after_s: Optional[float] = None,
                 transport_error_dead: int = 3,
                 health_recover_checks: int = 2,
                 hedge_enabled: bool = False,
                 hedge_ttft_factor: float = 3.0,
                 hedge_min_s: float = 0.25,
                 migrate_enabled: bool = True,
                 migrate_hedges: bool = False,
                 migrate_wire: Optional[str] = None,
                 alerter=None):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        if routing not in ROUTING_POLICIES:
            raise ValueError(f"routing must be one of {ROUTING_POLICIES},"
                             f" got {routing!r}")
        if health_mode not in HEALTH_MODES:
            raise ValueError(f"health_mode must be one of {HEALTH_MODES},"
                             f" got {health_mode!r}")
        self.replicas = {r.replica_id: r for r in replicas}
        self.prefill_pool = [r.replica_id for r in replicas
                             if r.role == "prefill"]
        self.decode_pool = [r.replica_id for r in replicas
                            if r.role in ("decode", "unified")]
        self.disagg = bool(self.prefill_pool)
        if self.disagg and not self.decode_pool:
            raise ValueError("disaggregated fleet needs decode replicas")
        self.affinity_blocks = max(0, int(affinity_blocks))
        self.stale_after_s = float(stale_after_s)
        self.autoscale = autoscale
        self.eos_token_id = eos_token_id
        self.routing = routing
        self.health_mode = health_mode
        # suspect at half the dead threshold unless configured — early
        # enough to stop routing onto a silent replica well before the
        # failover fires
        self.suspect_after_s = (float(suspect_after_s)
                                if suspect_after_s
                                else self.stale_after_s / 2.0)
        self.transport_error_dead = max(1, int(transport_error_dead))
        self.health_recover_checks = max(1, int(health_recover_checks))
        self.hedge_enabled = bool(hedge_enabled)
        self.hedge_ttft_factor = float(hedge_ttft_factor)
        self.hedge_min_s = float(hedge_min_s)
        # live session migration (ISSUE 20): drains and scale-downs
        # move mid-stream decode state warm instead of recompute-
        # requeueing. migrate_hedges extends migrate-first to hedge
        # promotion — OFF by default so legacy hedge behavior (race a
        # duplicate stream) stays bit-exact. migrate_wire picks the
        # session wire codec (None = the engine's handoff_wire).
        self.migrate_enabled = bool(migrate_enabled)
        self.migrate_hedges = bool(migrate_hedges)
        self.migrate_wire = migrate_wire
        # rid -> {"state", "since" (monotonic), "ok_checks",
        # "transitions"} — the per-replica health state machine
        self._health: Dict[int, Dict[str, Any]] = {}
        self._lock = threading.RLock()
        self._requests: Dict[int, _RequestRecord] = {}
        # (pool, prefix-hash) -> replica id that holds those KV blocks
        self._affinity: Dict[Any, int] = {}
        self.dead: set = set()
        self.draining: set = set()
        self._last_policy = "least_loaded"
        self._last_predicted_ms: Optional[float] = None
        # per-candidate forensics for the fleet journal's ROUTE records
        # — populated by _pick only while a journal is installed, so
        # the disabled path stays allocation-free
        self._last_candidates: Optional[List[Dict[str, Any]]] = None
        # per-replica observations feeding the predictive policy:
        # service EWMA in seconds per completed request, and the
        # observed prefill token rate from first-token latencies
        self._svc_ewma: Dict[int, float] = {}
        self._prefill_rate: Dict[int, float] = {}
        # decode seconds-per-token from emission gaps: learned within a
        # couple of rounds of a replica's FIRST request, long before
        # any completion feeds _svc_ewma — the predictor's cold-start
        # service estimate (spt x typical budget)
        self._spt_ewma: Dict[int, float] = {}
        self._avg_budget = 0.0
        self._ewma_alpha = float(service_ewma_alpha)
        # a fresh replica's FIRST request pays the one-time JIT compile
        # (seconds, vs milliseconds steady-state); folding that sample
        # into the EWMAs would make a fast replica look 100x slower for
        # the first dozen requests, so each signal discards its first
        # per-replica observation as the compile-warming round
        self._prefill_seen: Dict[int, int] = {}
        self._svc_seen: Dict[int, int] = {}
        self.stats = {"submitted": 0, "completed": 0, "handoffs": 0,
                      "handoff_recompute": 0, "failovers": 0,
                      "failed_over_requests": 0, "affinity_hits": 0,
                      "tier_affinity_hits": 0,
                      "hedged": 0, "hedge_wins": 0, "stranded": 0,
                      # the migration ladder, router view: sessions
                      # moved warm / degraded to fold-and-recompute /
                      # left in place (no eligible target)
                      "migrations": 0, "migrate_recompute": 0,
                      "migrate_skipped": 0,
                      # bytes actually shipped for warm migrations —
                      # the deploy drill certifies bytes/session stays
                      # near the quantized-wire budget, not bf16
                      "migrate_wire_bytes": 0}
        # one BurnRateAlerter for the FLEET (observability/burn_rate.py):
        # every replica's finished traces feed it through the tracer
        # hook, and check_health runs its fire/clear state machine —
        # the burn rate is a fleet property, not a per-replica one
        self.alerter = alerter
        for r in replicas:
            r.emit_callback = self._on_emissions
            if alerter is not None:
                r.engine.tracer.alerter = alerter
        from deepspeed_tpu.observability.hub import get_hub

        self._hub = get_hub()
        jr = get_journal()
        if jr is not None:
            # the router owns request identity, so it owns ADMIT/EMIT
            # journaling; engines sharing this process defer to it
            jr.claim_ingress("router")

    # -- fleet membership (supervisor spin-up / drain) -----------------
    def add_replica(self, replica: ServingReplica) -> None:
        """Wire a freshly spun-up replica into the pools (supervisor
        scale-up / crash-restart path), or READMIT one that was
        quiesced with ``remove_replica`` — the rolling-swap rejoin:
        same id, same channel, it just starts receiving work again."""
        with self._lock:
            rid = replica.replica_id
            if (rid in self.replicas and rid not in self.dead
                    and rid not in self.draining):
                raise ValueError(f"replica id {rid} already in the fleet")
            self.replicas[rid] = replica
            self.dead.discard(rid)
            self.draining.discard(rid)
            if replica.role == "prefill":
                if rid not in self.prefill_pool:
                    self.prefill_pool.append(rid)
            elif rid not in self.decode_pool:
                self.decode_pool.append(rid)
            replica.emit_callback = self._on_emissions
            if self.alerter is not None:
                replica.engine.tracer.alerter = self.alerter

    def remove_replica(self, replica_id: int) -> None:
        """Stop routing NEW work to a replica (supervisor drain). The
        replica stays in ``self.replicas`` so its in-flight requests
        finish through the normal emission path — drain means 'no new
        admissions', never 'drop what you hold'."""
        with self._lock:
            self.draining.add(replica_id)
            if replica_id in self.prefill_pool:
                self.prefill_pool.remove(replica_id)
            if replica_id in self.decode_pool:
                self.decode_pool.remove(replica_id)

    # -- admission + routing -------------------------------------------
    def submit(self, uid: int, tokens, max_new_tokens: int = 64) -> int:
        """Route one request. Returns the chosen replica id. Raises
        ValueError (before accepting) for a prompt no replica could
        ever schedule — the fleet-wide analog of ``put()``'s never-fit
        contract; once accepted, completion is guaranteed."""
        toks = np.asarray(tokens, np.int32).ravel()
        jr = get_journal()
        if jr is not None:
            # a journal installed after __init__ still belongs to the
            # router: claim before any engine sees the request
            jr.claim_ingress("router")
        with self._lock:
            if uid in self._requests:
                raise ValueError(f"uid={uid} already in flight")
            key = self._affinity_key(toks)
            if self.disagg:
                target = self._pick(self.prefill_pool, key, len(toks),
                                    tokens=toks)
                phase, budget = "prefill", 1
            else:
                target = self._pick(self.decode_pool, key, len(toks),
                                    tokens=toks)
                phase, budget = "decode", int(max_new_tokens)
            self._check_fits(target, toks, max_new_tokens)
            rec = _RequestRecord(uid, toks, int(max_new_tokens),
                                 target.replica_id, phase, key)
            if self.hedge_enabled and phase == "decode":
                pred = self.predict_ttft(target, len(toks))
                rec.hedge_at_mono = rec.submitted_mono + max(
                    self.hedge_min_s, self.hedge_ttft_factor * pred)
            self._requests[uid] = rec
            self.stats["submitted"] += 1
            self._avg_budget = float(max_new_tokens) \
                if self._avg_budget <= 0.0 else (
                    self._ewma_alpha * float(max_new_tokens)
                    + (1.0 - self._ewma_alpha) * self._avg_budget)
            route = self._route_fields(target, self._last_policy,
                                       self._last_predicted_ms, uid=uid)
            if jr is not None:
                jr.admit(uid, toks.tolist(), int(max_new_tokens))
                jr.decision(
                    "ROUTE", uid=uid, replica=target.replica_id,
                    phase=phase, policy=self._last_policy,
                    predicted_ttft_ms=self._last_predicted_ms,
                    candidates=self._last_candidates)
        target.submit(Submission(
            uid=uid, tokens=toks, max_new_tokens=budget,
            span_notes=[("ROUTE", route)]))
        return target.replica_id

    def _route_fields(self, target: ServingReplica, policy: str,
                      predicted_ms: Optional[float] = None,
                      uid: Optional[int] = None) -> Dict[str, Any]:
        """ROUTE span fields: placement decision + the transport byte
        counters at decision time, so cross-process lanes show what each
        hop had already paid on the wire (replica_id itself is stamped
        by the replica applying the submission — in ITS process).

        With ``uid`` the fields double as Dapper-style trace context:
        the router-side trace id and clock-domain label travel inside
        the ROUTE span note, land in the worker's trace via
        ``tracer.note``, and ship back with the trace dicts — the merge
        side joins both processes' spans on ``fleet_trace_id`` without
        any wire-protocol change."""
        fields: Dict[str, Any] = {"replica": target.replica_id,
                                  "role": target.role, "policy": policy}
        if uid is not None:
            fields["fleet_trace_id"] = f"fleet-{int(uid)}"
            fields["parent_domain"] = "router"
        tx = getattr(target, "transport_bytes", None)
        if tx is not None:
            sent, received = tx()
            fields["wire_tx_bytes"] = int(sent)
            fields["wire_rx_bytes"] = int(received)
        if predicted_ms is not None:
            fields["predicted_ttft_ms"] = round(predicted_ms, 3)
        return fields

    def _affinity_key(self, toks: np.ndarray) -> Optional[str]:
        if self.affinity_blocks <= 0:
            return None
        any_r = next(iter(self.replicas.values()))
        span = self.affinity_blocks * \
            any_r.engine.kv_cache.config.block_size
        if len(toks) < span:
            return None
        return hashlib.sha1(
            np.ascontiguousarray(toks[:span], np.int32).tobytes()
        ).hexdigest()

    def _instant_health(self, r: ServingReplica, now: float) -> str:
        """Stateless health read from the replica's observables at
        monotonic ``now`` (the state machine adds hysteresis on top)."""
        if getattr(r, "killed", False) or getattr(r, "_send_failed",
                                                  False):
            return "dead"
        age = r.heartbeat_age(now)
        terr = getattr(r, "transport_errors", 0)
        if age >= self.stale_after_s or terr >= self.transport_error_dead:
            return "dead"
        if age >= self.suspect_after_s or terr > 0:
            return "suspect"
        return "healthy"

    def _route_state(self, rid: int, now: float) -> str:
        """Health as routing sees it: the worse of the instantaneous
        read and the stored state — a suspect mid-recovery stays
        suspect until the hysteresis clears it."""
        inst = self._instant_health(self.replicas[rid], now)
        stored = self._health.get(rid, {}).get("state", "healthy")
        return (inst if _HEALTH_ORDER[inst] >= _HEALTH_ORDER[stored]
                else stored)

    def _alive(self, pool: List[int]) -> List[ServingReplica]:
        now = time.monotonic()
        if self.health_mode == "legacy":
            out = [self.replicas[rid] for rid in pool
                   if rid not in self.dead
                   and self.replicas[rid].alive(now, self.stale_after_s)]
        else:
            cands = [rid for rid in pool if rid not in self.dead]
            states = {rid: self._route_state(rid, now) for rid in cands}
            # healthy replicas take new routes; suspects only when
            # nothing healthy is left (they keep in-flight streams
            # either way — emissions don't pass through here)
            out = [self.replicas[rid] for rid in cands
                   if states[rid] == "healthy"]
            if not out:
                out = [self.replicas[rid] for rid in cands
                       if states[rid] == "suspect"]
        if not out:  # last resort: any replica not yet declared dead
            out = [r for rid, r in self.replicas.items()
                   if rid not in self.dead]
        if not out:
            raise RuntimeError("no live replicas left in the fleet")
        return out

    def _pick(self, pool: List[int], key: Optional[str],
              n_tokens: int = 0,
              exclude: Optional[set] = None,
              tokens: Optional[np.ndarray] = None) -> ServingReplica:
        """Affinity if the remembered replica is still live, else the
        host-KV-tier probe (the replica already HOLDING a returning
        session's paged-out blocks warm-resumes it without re-prefill —
        worth more than a marginally lower load score), else the
        configured policy (least-loaded or predicted-TTFT). Caller
        holds the lock. ``exclude`` removes replicas that may still
        hold a live stream of the request being placed (hedge losers);
        an all-excluded pool raises like a dead one, which parks the
        failover until fresh capacity arrives."""
        alive = self._alive(pool)
        if exclude:
            alive = [r for r in alive if r.replica_id not in exclude]
            if not alive:
                raise RuntimeError(
                    "no live replicas without a stale stream of this "
                    "request")
        pool_tag = id(pool)
        self._last_predicted_ms = None
        if get_journal() is not None:
            # decision forensics: every candidate's health / load /
            # predicted-TTFT at decision time, not just the winner —
            # computed only while the black box is recording
            mono = time.monotonic()
            self._last_candidates = [
                {"replica": r.replica_id,
                 "health": self._route_state(r.replica_id, mono),
                 "load_score": round(float(r.load_score()), 4),
                 "predicted_ttft_ms": round(
                     self.predict_ttft(r, n_tokens) * 1e3, 3)}
                for r in alive]
        else:
            self._last_candidates = None
        if key is not None:
            rid = self._affinity.get((pool_tag, key))
            if rid is not None and any(r.replica_id == rid for r in alive):
                self.stats["affinity_hits"] += 1
                self._last_policy = "affinity"
                return self.replicas[rid]
        if tokens is not None:
            # tiered-KV placement: probe only replicas WITH a host tier
            # (in-process handles expose holds_prefix; RemoteReplica
            # proxies don't and are skipped — they compete on load).
            # Probing every submit is an O(prefix blocks) hash walk per
            # tiered replica, host-side only.
            best, best_hits = None, 0
            for r in alive:
                eng = getattr(r, "engine", None)
                if getattr(getattr(eng, "kv_cache", None),
                           "host_tier", None) is None:
                    continue
                hits = r.holds_prefix(tokens)
                if hits > best_hits or (hits == best_hits and hits > 0
                                        and r.load_score()
                                        < best.load_score()):
                    best, best_hits = r, hits
            if best is not None and best_hits > 0:
                self.stats["tier_affinity_hits"] += 1
                self._last_policy = "tier_affinity"
                if key is not None:
                    self._affinity[(pool_tag, key)] = best.replica_id
                return best
        if self.routing == "predictive":
            # ties (no observations yet) fall back to load score, so a
            # cold fleet degrades to exactly the least-loaded policy
            best = min(alive, key=lambda r: (
                self.predict_ttft(r, n_tokens), r.load_score()))
            self._last_policy = "predictive"
            self._last_predicted_ms = \
                self.predict_ttft(best, n_tokens) * 1e3
        else:
            best = min(alive, key=lambda r: r.load_score())
            self._last_policy = "least_loaded"
        if key is not None:
            self._affinity[(pool_tag, key)] = best.replica_id
        return best

    def predict_ttft(self, replica: ServingReplica,
                     n_tokens: int = 0) -> float:
        """Predicted TTFT in seconds for a new ``n_tokens`` prompt on
        ``replica`` — the five-phase model's first two phases estimated
        from fleet observables: queue_wait ~= (everything already
        queued or running there) x the replica's observed per-request
        service EWMA, prefill ~= prompt length over its observed
        prefill token rate. Both EWMAs are router-side observations, so
        the estimate works identically for thread and process replicas."""
        rid = replica.replica_id
        rep = replica.load_report()
        depth = rep.get("inflight",
                        rep.get("queue_wait_depth", 0)
                        + rep.get("live_seqs", 0))
        svc = self._svc_ewma.get(rid, 0.0)
        if svc <= 0.0:
            # no completion observed yet: estimate service time from
            # the replica's decode cadence x the typical budget (learned
            # within rounds, not requests), else borrow the fleet's
            # observed service time, else a 1s prior — a zero here
            # would erase the queue term entirely and leave the ranking
            # to prefill-rate noise
            spt = self._spt_ewma.get(rid, 0.0)
            if spt > 0.0 and self._avg_budget > 0.0:
                svc = spt * self._avg_budget
            else:
                known = [v for v in self._svc_ewma.values() if v > 0.0]
                svc = (sum(known) / len(known)) if known else 1.0
        queue_wait = float(depth) * svc
        rate = self._prefill_rate.get(rid, 0.0)
        prefill = (float(n_tokens) / rate) if rate > 0.0 else 0.0
        return queue_wait + prefill

    @staticmethod
    def _check_fits(replica: ServingReplica, toks: np.ndarray,
                    max_new: int) -> None:
        e = replica.engine
        blocks = e.kv_cache.blocks_needed(len(toks) + 1)
        if (blocks > e.max_blocks_per_seq
                or blocks > e.kv_cache.allocator.total_blocks):
            raise ValueError(
                f"prompt of {len(toks)} tokens needs {blocks} KV blocks "
                f"and can never be scheduled on replica "
                f"{replica.replica_id}")

    # -- emissions (runs on replica pump threads) ----------------------
    def _on_emissions(self, replica: ServingReplica,
                      emitted: Dict[int, List[int]]) -> None:
        handoffs = []
        now = wall_time()  # same clock domain as spans + journal
        jr = get_journal()
        with self._lock:
            for uid, toks in emitted.items():
                rec = self._requests.get(uid)
                if rec is None or rec.done:
                    continue
                if rec.replica_id != replica.replica_id:
                    if (rec.hedge_replica_id == replica.replica_id
                            and not rec.emitted and toks):
                        # hedge wins: the secondary produced the first
                        # token first — adopt its stream; the primary's
                        # later emissions become the stale ones (and it
                        # still streams this uid: taint it)
                        rec.stale_rids.add(rec.replica_id)
                        rec.replica_id = replica.replica_id
                        rec.hedge_replica_id = None
                        self.stats["hedge_wins"] += 1
                        self._hub.counter_add("serve.hedge_wins")
                    else:
                        # stale emission from a failed-over replica or
                        # a hedge that lost the race
                        continue
                if (rec.hedge_replica_id is not None and toks
                        and not rec.emitted):
                    # first token came from the primary: the hedge lost,
                    # but its replica still streams this uid to the end
                    # of the budget — taint it for failover picks
                    rec.stale_rids.add(rec.hedge_replica_id)
                    rec.hedge_replica_id = None
                if not rec.emitted and toks:
                    self._observe_first_token(replica.replica_id, rec, now)
                elif toks and rec.last_emit_ts > 0.0:
                    # decode cadence: gap since the last batch over the
                    # tokens it produced -> seconds-per-token EWMA
                    spt = max(now - rec.last_emit_ts, 1e-6) / len(toks)
                    prev = self._spt_ewma.get(replica.replica_id)
                    self._spt_ewma[replica.replica_id] = \
                        spt if prev is None else (
                            self._ewma_alpha * spt
                            + (1.0 - self._ewma_alpha) * prev)
                if toks:
                    rec.last_emit_ts = now
                    if jr is not None:
                        # under the lock, after the ownership guards:
                        # the checksum chain records exactly the tokens
                        # the request adopted, in adoption order
                        jr.emit(uid, toks)
                rec.emitted.extend(int(t) for t in toks)
                if rec.phase == "prefill":
                    handoffs.append(rec)  # budget-1 stage just finished
                elif len(rec.emitted) >= rec.max_new_tokens:
                    rec.done = True
                    self.stats["completed"] += 1
                    self._observe_completion(replica.replica_id, rec, now)
        for rec in handoffs:
            self._handoff(rec, replica)

    def _observe_first_token(self, rid: int, rec: _RequestRecord,
                             now: float) -> None:
        """Feed the predictive policy's prefill-rate EWMA: prompt
        tokens over observed first-token latency (queue wait included —
        an *effective* rate, which is the one a new arrival will see).
        Caller holds the lock."""
        rec.first_emit_ts = now
        seen = self._prefill_seen.get(rid, 0)
        self._prefill_seen[rid] = seen + 1
        if seen == 0:
            return  # compile-warming round (see __init__)
        ttft = max(now - rec.submitted_ts, 1e-6)
        rate = len(rec.tokens) / ttft
        prev = self._prefill_rate.get(rid)
        self._prefill_rate[rid] = rate if prev is None else (
            self._ewma_alpha * rate + (1.0 - self._ewma_alpha) * prev)

    def _observe_completion(self, rid: int, rec: _RequestRecord,
                            now: float) -> None:
        """Feed the per-request service-time EWMA (first token -> full
        budget, queue wait excluded: the ``depth x svc`` queue term of
        predict_ttft models waiting separately, and folding a backlog
        into svc would make a busy-but-fast replica look slower than a
        genuinely slow one). Caller holds the lock."""
        seen = self._svc_seen.get(rid, 0)
        self._svc_seen[rid] = seen + 1
        if seen == 0:
            return  # compile-warming round (see __init__)
        svc = max(now - (rec.first_emit_ts or rec.submitted_ts), 1e-6)
        prev = self._svc_ewma.get(rid)
        self._svc_ewma[rid] = svc if prev is None else (
            self._ewma_alpha * svc + (1.0 - self._ewma_alpha) * prev)

    def _handoff(self, rec: _RequestRecord,
                 prefill_replica: ServingReplica) -> None:
        """Move a prefill-complete request to a decode replica. The
        prefill replica serializes its own KV pool — on its pump thread
        for local replicas, in its own process for remote ones — and
        the completion callback submits to the decode target (local
        replicas invoke it synchronously; remote ones when the payload
        message arrives). The install then runs on the decode replica's
        own thread (Submission.handoff)."""
        with self._lock:
            remaining = rec.max_new_tokens - len(rec.emitted)
            if remaining <= 0:
                rec.done = True
                self.stats["completed"] += 1
                return
            target = self._pick(self.decode_pool, rec.affinity_key,
                                len(rec.tokens))
            rec.phase = "decode"
            rec.replica_id = target.replica_id
            self.stats["handoffs"] += 1
            tokens = np.concatenate(
                [rec.tokens, np.asarray(rec.emitted, np.int32)])

        def _complete(payload) -> None:
            if payload is None:
                with self._lock:
                    self.stats["handoff_recompute"] += 1
            route = self._route_fields(target, "disagg_handoff",
                                       uid=rec.uid)
            target.submit(Submission(
                uid=rec.uid, tokens=tokens, max_new_tokens=remaining,
                handoff=payload, span_notes=[("ROUTE", route)]))

        prefill_replica.serialize_handoff(rec.tokens, _complete)

    # -- failover ------------------------------------------------------
    def check_health(self, now: Optional[float] = None) -> List[int]:
        """Advance the per-replica health state machine (or, in legacy
        mode, the single stale flip), declare dead replicas and
        re-route their in-flight requests, fire due hedges, and feed
        the autoscaler + fleet gauges. ``now`` is a monotonic
        timestamp. Returns replica ids newly declared dead."""
        now = time.monotonic() if now is None else now
        newly_dead = []
        if self.health_mode == "legacy":
            for rid, r in self.replicas.items():
                if rid not in self.dead \
                        and not r.alive(now, self.stale_after_s):
                    newly_dead.append(rid)
        else:
            with self._lock:
                for rid, r in self.replicas.items():
                    if rid in self.dead:
                        continue
                    if self._observe_health(rid, r, now) == "dead":
                        newly_dead.append(rid)
        for rid in newly_dead:
            self._failover(rid)
        # victims parked during a total outage (every replica dead in
        # one window) retry every round: once the supervisor restores
        # capacity they fail over like any other victim
        with self._lock:
            parked = sorted({rec.replica_id
                             for rec in self._requests.values()
                             if not rec.done
                             and rec.replica_id in self.dead
                             and rec.replica_id not in newly_dead})
        for rid in parked:
            self._failover(rid)
        with self._lock:
            self.stats["stranded"] = sum(
                1 for rec in self._requests.values()
                if not rec.done and rec.replica_id in self.dead)
        if self.hedge_enabled:
            self._check_hedges(now)
        self._update_fleet_gauges()
        if self.alerter is not None:
            self.alerter.evaluate()
        return newly_dead

    def _observe_health(self, rid: int, r: ServingReplica,
                        now: float) -> str:
        """One state-machine tick for one replica. Demotion is
        immediate; promotion back to healthy requires
        ``health_recover_checks`` consecutive clean reads (hysteresis).
        Caller holds the lock."""
        h = self._health.get(rid)
        if h is None:
            h = self._health[rid] = {"state": "healthy", "since": now,
                                     "ok_checks": 0, "transitions": 0}
        target = self._instant_health(r, now)
        state = h["state"]
        if target == "dead":
            new = "dead"
        elif state == "suspect":
            if target == "healthy":
                h["ok_checks"] += 1
                new = ("healthy"
                       if h["ok_checks"] >= self.health_recover_checks
                       else "suspect")
            else:
                h["ok_checks"] = 0
                new = "suspect"
        else:
            new = target
        if new != state:
            h["state"] = new
            h["since"] = now
            h["transitions"] += 1
            h["ok_checks"] = 0
        return new

    def _check_hedges(self, now: float) -> None:
        """Resubmit requests whose predicted TTFT has been exceeded by
        ``hedge_ttft_factor`` with no first token. Plans are built
        under the lock, submits happen outside it (the failover
        discipline). Greedy decoding makes both streams bit-identical,
        so whichever emits first wins and the loser is dropped by the
        stale-emission guard in _on_emissions."""
        if self.disagg:
            return  # prefill handoffs have their own recompute path
        plans = []
        migrate_plans = []
        with self._lock:
            for rec in self._requests.values():
                if (rec.done or rec.emitted or rec.phase != "decode"
                        or rec.hedge_replica_id is not None
                        or rec.hedge_at_mono is None
                        or now < rec.hedge_at_mono):
                    continue
                try:
                    alive = [r for r in self._alive(self.decode_pool)
                             if r.replica_id != rec.replica_id
                             and r.replica_id not in rec.stale_rids]
                except RuntimeError:
                    continue
                if not alive:
                    continue
                if self.routing == "predictive":
                    target = min(alive, key=lambda r: (
                        self.predict_ttft(r, len(rec.tokens)),
                        r.load_score()))
                else:
                    target = min(alive, key=lambda r: r.load_score())
                rec.hedge_replica_id = target.replica_id
                self.stats["hedged"] += 1
                waited_ms = (now - rec.submitted_mono) * 1e3
                jr = get_journal()
                if jr is not None:
                    jr.decision(
                        "HEDGE", uid=rec.uid,
                        from_replica=rec.replica_id,
                        to_replica=target.replica_id,
                        waited_ms=round(waited_ms, 3),
                        migrate=self.migrate_hedges,
                        hedge_ttft_factor=self.hedge_ttft_factor)
                if self.migrate_hedges and self.migrate_enabled:
                    # migrate-first hedge promotion: MOVE the stuck
                    # request instead of racing a duplicate stream —
                    # one stream, no loser to drop, and a mid-decode
                    # victim carries its KV state along. Pre-first-
                    # token captures degrade to recompute on the
                    # target (the same outcome a hedge win delivers).
                    src = self.replicas[rec.replica_id]
                    migrate_plans.append(
                        (rec, src,
                         self._plan_migration(rec, src, target,
                                              "hedge")))
                    continue
                plans.append((rec, target,
                              self._route_fields(target, "hedge",
                                                 uid=rec.uid),
                              waited_ms))
        for rec, src, cb in migrate_plans:
            src.migrate_out(rec.uid, cb, wire=self.migrate_wire)
            self._hub.counter_add("serve.hedged")
        for rec, target, route, waited_ms in plans:
            target.submit(Submission(
                uid=rec.uid, tokens=rec.tokens,
                max_new_tokens=rec.max_new_tokens,
                span_notes=[
                    ("HEDGE", {"from_replica": rec.replica_id,
                               "to_replica": target.replica_id,
                               "waited_ms": round(waited_ms, 3)}),
                    ("ROUTE", route)]))
            self._hub.counter_add("serve.hedged")

    def _failover(self, dead_rid: int) -> None:
        with self._lock:
            if dead_rid not in self.dead:
                self.dead.add(dead_rid)
                if dead_rid in self._health:
                    self._health[dead_rid]["state"] = "dead"
                self.stats["failovers"] += 1
            victims = [rec for rec in self._requests.values()
                       if rec.replica_id == dead_rid and not rec.done]
            for rec in self._requests.values():
                # a dead hedge target just stops being a hedge
                if rec.hedge_replica_id == dead_rid:
                    rec.hedge_replica_id = None
            plans = []
            for rec in victims:
                remaining = rec.max_new_tokens - len(rec.emitted)
                if remaining <= 0:
                    rec.done = True
                    self.stats["completed"] += 1
                    continue
                if (rec.hedge_replica_id is not None
                        and rec.hedge_replica_id not in self.dead
                        and not rec.emitted):
                    # a live hedge already holds this request verbatim —
                    # promote it instead of resubmitting a third copy
                    rec.replica_id = rec.hedge_replica_id
                    rec.hedge_replica_id = None
                    continue
                rec.hedge_replica_id = None
                try:
                    if rec.phase == "prefill":
                        pool = self.prefill_pool
                        alive = [r for r in self._alive(pool)
                                 if r.replica_id != dead_rid]
                        if not alive:  # prefill pool gone: decode e2e
                            rec.phase = "decode"
                            pool = self.decode_pool
                        budget = 1 if rec.phase == "prefill" \
                            else remaining
                    else:
                        pool, budget = self.decode_pool, remaining
                    rec.stale_rids.add(dead_rid)
                    target = self._pick(pool, rec.affinity_key,
                                        len(rec.tokens),
                                        exclude=rec.stale_rids)
                except RuntimeError:
                    # transient total outage: every candidate died in
                    # the same health window. Park the victim on its
                    # dead replica id — check_health retries it once
                    # the supervisor restores capacity; raising here
                    # would turn a survivable outage into a crashed
                    # router (new submits still fail loud).
                    continue
                old = rec.replica_id
                rec.replica_id = target.replica_id
                rec.failovers += 1
                self.stats["failed_over_requests"] += 1
                jr = get_journal()
                if jr is not None:
                    jr.decision(
                        "FAILOVER", uid=rec.uid, from_replica=old,
                        to_replica=target.replica_id,
                        dead_replica=dead_rid,
                        recovered_tokens=len(rec.emitted),
                        failovers=rec.failovers)
                tokens = np.concatenate(
                    [rec.tokens, np.asarray(rec.emitted, np.int32)]) \
                    if rec.emitted else rec.tokens
                plans.append((rec.uid, tokens, budget, old, target,
                              len(rec.emitted),
                              self._route_fields(target, "failover",
                                                 uid=rec.uid)))
        for uid, tokens, budget, old, target, recovered, route in plans:
            target.submit(Submission(
                uid=uid, tokens=tokens, max_new_tokens=budget,
                span_notes=[
                    ("FAILOVER", {"from_replica": old,
                                  "to_replica": target.replica_id,
                                  "recovered_tokens": recovered}),
                    ("ROUTE", route)]))
            self._hub.counter_add("serve.fleet.failed_over_requests")

    # -- live session migration (ISSUE 20) -----------------------------
    def migrate_sessions(self, src_rid: int,
                         reason: str = "drain") -> Dict[str, int]:
        """Move every in-flight decode session off ``src_rid`` warm:
        each session's committed KV blocks + partial tail block +
        generated tokens + spec-acceptance EWMA are captured on the
        source (releasing it there), shipped over the quantized wire,
        and installed on a picked target — decode resumes with zero
        re-prefill. The graceful degradation ladder, never an error:

        1. **warm** — capture lands, install resumes from the wire
           blocks (or parks in the target's host KV tier until HBM
           frees up: same zero-recompute outcome, deferred);
        2. **recompute** — capture returned None (session mid-prefill,
           already finished, transport death): fold emitted tokens into
           the prompt and resubmit — PR 8's legacy path, bit-identical
           output under greedy decoding;
        3. **skip** — no eligible target (pool of one, all candidates
           tainted): the session stays put and finishes on the source
           (a draining worker finishes what it holds before exiting).

        Call with the source already removed from the pools
        (``remove_replica``) so no new work lands behind the captures.
        Plans are built under the lock, capture RPCs sent outside it;
        installs happen in the capture callbacks (receive/pump
        threads). Returns plan counts — the rung each migration
        actually landed on accumulates in ``stats`` as callbacks
        fire."""
        if not self.migrate_enabled:
            return {"requested": 0, "skipped": 0}
        plans = []
        counts = {"requested": 0, "skipped": 0}
        with self._lock:
            src = self.replicas.get(src_rid)
            if src is None:
                return counts
            for rec in self._requests.values():
                if (rec.done or rec.replica_id != src_rid
                        or rec.phase != "decode"):
                    continue  # prefill-phase recs have the handoff path
                try:
                    target = self._pick(
                        self.decode_pool, rec.affinity_key,
                        len(rec.tokens),
                        exclude={src_rid} | rec.stale_rids)
                except RuntimeError:
                    self.stats["migrate_skipped"] += 1
                    counts["skipped"] += 1
                    continue
                plans.append((rec, target,
                              self._plan_migration(rec, src, target,
                                                   reason)))
                counts["requested"] += 1
        for rec, target, cb in plans:
            src.migrate_out(rec.uid, cb, wire=self.migrate_wire)
        return counts

    def _plan_migration(self, rec: _RequestRecord, src, target,
                        reason: str):
        """Build the capture continuation for one migration. The
        callback runs on the source's receive/pump thread when the
        SessionHandoff (or None) lands; it transfers ownership, folds
        the emitted tokens (the recompute fallback AND the guard
        prompt), journals the MIGRATE decision with the inputs that
        drove it, and submits to the target. Caller holds the lock."""
        src_rid = src.replica_id
        src_score = round(float(src.load_score()), 4)
        tgt_score = round(float(target.load_score()), 4)

        def _cb(sess) -> None:
            with self._lock:
                if rec.done or rec.replica_id != src_rid:
                    # finished, or a failover/hedge raced the capture
                    # and already owns the stream elsewhere — drop the
                    # payload (its tokens are folded wherever it went)
                    return
                remaining = rec.max_new_tokens - len(rec.emitted)
                if remaining <= 0:
                    rec.done = True
                    self.stats["completed"] += 1
                    return
                # the source released the session on capture (or still
                # streams it after a None capture): either way it must
                # never be picked again for this request
                rec.stale_rids.add(src_rid)
                rec.replica_id = target.replica_id
                rec.hedge_replica_id = None  # migrate-first hedge done
                tokens = np.concatenate(
                    [rec.tokens, np.asarray(rec.emitted, np.int32)]) \
                    if rec.emitted else rec.tokens
                rung = "warm" if sess is not None else "recompute"
                self.stats["migrations" if sess is not None
                           else "migrate_recompute"] += 1
                fields = {"from_replica": src_rid,
                          "to_replica": target.replica_id,
                          "reason": reason, "rung": rung,
                          "recovered_tokens": len(rec.emitted),
                          "source_score": src_score,
                          "target_score": tgt_score}
                if sess is not None:
                    fields["wire_bytes"] = int(sess.wire_nbytes)
                    fields["n_blocks"] = int(sess.n_blocks)
                    self.stats["migrate_wire_bytes"] += \
                        int(sess.wire_nbytes)
                jr = get_journal()
                if jr is not None:
                    jr.decision("MIGRATE", uid=rec.uid, **fields)
                route = self._route_fields(target, "migrate",
                                           uid=rec.uid)
                notes = [("MIGRATE", dict(fields)), ("ROUTE", route)]
            target.submit(Submission(
                uid=rec.uid, tokens=tokens, max_new_tokens=remaining,
                session=sess, span_notes=notes))
            self._hub.counter_add("serve.fleet.migrations"
                                  if sess is not None
                                  else "serve.fleet.migrate_recompute")

        return _cb

    # -- driving -------------------------------------------------------
    def step(self) -> int:
        """Synchronous mode: pump every live replica once, then health-
        check. Returns the number of requests still pending."""
        for r in self.replicas.values():
            if r.replica_id not in self.dead and not r.killed:
                r.pump(eos_token_id=self.eos_token_id)
        self.check_health()
        return self.pending()

    def run_until_complete(self, max_rounds: int = 100000) -> None:
        for _ in range(max_rounds):
            if self.step() == 0:
                return
        raise RuntimeError(
            f"fleet did not drain in {max_rounds} rounds "
            f"({self.pending()} requests pending)")

    def start(self) -> None:
        for r in self.replicas.values():
            r.start(eos_token_id=self.eos_token_id)

    def stop(self) -> None:
        for r in self.replicas.values():
            r.stop()

    def drain(self, timeout_s: float = 120.0,
              poll_s: float = 0.02) -> None:
        """Threaded mode: wait (health-checking) until every accepted
        request completed."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.check_health()
            if self.pending() == 0:
                return
            time.sleep(poll_s)
        raise TimeoutError(
            f"fleet did not drain in {timeout_s}s "
            f"({self.pending()} requests pending)")

    def pending(self) -> int:
        with self._lock:
            return sum(1 for rec in self._requests.values()
                       if not rec.done)

    def results(self) -> Dict[int, List[int]]:
        with self._lock:
            return {uid: list(rec.emitted)
                    for uid, rec in self._requests.items() if rec.done}

    # -- fleet observability -------------------------------------------
    def _update_fleet_gauges(self) -> None:
        reports = [r.load_report() for r in self.replicas.values()
                   if r.replica_id not in self.dead]
        waiting = sum(r["queue_wait_depth"] for r in reports)
        goodput = sum(r["goodput_tokens_per_s"] for r in reports)
        self._hub.gauge("serve.fleet.replicas_alive", len(reports))
        self._hub.gauge("serve.fleet.replicas_dead", len(self.dead))
        self._hub.gauge("serve.fleet.queue_wait_depth", waiting)
        self._hub.gauge("serve.fleet.pending_requests", self.pending())
        self._hub.gauge("serve.fleet.goodput_tokens_per_s", goodput)
        if self.autoscale is not None:
            self.autoscale.update(
                n_replicas=max(1, len(reports)),
                queue_wait_depth=waiting,
                slo_miss_rate=self._slo_miss_rate(),
                goodput_tokens_per_s=goodput)

    def _slo_miss_rate(self, last: int = 128) -> float:
        total = misses = 0
        for r in self.replicas.values():
            tracer = r.engine.tracer
            for t in tracer.finished(last=last):
                total += 1
                if tracer.is_slo_miss(t):
                    misses += 1
        return misses / total if total else 0.0

    def traces_by_replica(self) -> Dict[int, List[Any]]:
        return {rid: r.engine.tracer.finished()
                for rid, r in self.replicas.items()}

    def slo_attribution(self, deadline_s: Optional[float] = None
                        ) -> Dict[str, Any]:
        """Fleet-level "why did p99 miss": one attribution report over
        every replica's finished traces, plus the per-replica counts the
        single-replica report cannot show."""
        from deepspeed_tpu.observability.request_trace import \
            slo_attribution

        by_replica = self.traces_by_replica()
        all_traces = [t for ts in by_replica.values() for t in ts]
        report = slo_attribution(all_traces, deadline_s=deadline_s)
        report["per_replica"] = {
            rid: {"traces": len(ts),
                  "slo_misses": sum(
                      1 for t in ts
                      if self.replicas[rid].engine.tracer.is_slo_miss(t))}
            for rid, ts in by_replica.items()}
        return report

    def export_perfetto(self, path: str) -> str:
        """One Perfetto file, one lane group per replica (shared
        wall-clock base, so handoffs and failovers line up)."""
        from deepspeed_tpu.observability.chrome_trace import \
            export_fleet_request_traces

        return export_fleet_request_traces(path, self.traces_by_replica())

    def fleet_snapshot(self, deadline_s: Optional[float] = None
                       ) -> Dict[str, Any]:
        """The ``serve_top --fleet`` document: load reports, router
        stats, autoscale state, and fleet SLO attribution."""
        with self._lock:
            stats = dict(self.stats)
            dead = sorted(self.dead)
            now = time.monotonic()
            health = {
                str(rid): {
                    "state": ("dead" if rid in self.dead
                              else self._route_state(rid, now)),
                    "transitions": self._health.get(rid, {}).get(
                        "transitions", 0),
                }
                for rid in self.replicas}
        snap = {
            "schema": "serving_fleet/v3",
            "ts": wall_time(),  # fleet clock domain, not raw time.time
            "mode": "disagg" if self.disagg else "unified",
            "replicas": [r.load_report()
                         for r in self.replicas.values()],
            "dead_replicas": dead,
            "health": health,
            "router": stats,
            "slo_attribution": self.slo_attribution(deadline_s),
        }
        if self.autoscale is not None:
            snap["autoscale"] = self.autoscale.snapshot()
        if self.alerter is not None:
            snap["alerts"] = self.alerter.snapshot()
        clock = {
            str(rid): info for rid, r in self.replicas.items()
            if (info := getattr(r, "clock_info", lambda: None)())
            is not None}
        if clock:
            snap["clock"] = clock
        jr = get_journal()
        if jr is not None:
            # v3: the black-box handle — where the journal lives and
            # how much it has captured, so an incident snapshot points
            # straight at its own replay artifact
            snap["journal"] = jr.snapshot()
        return snap
