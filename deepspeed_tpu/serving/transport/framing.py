"""Length-prefixed, CRC-checked frames for the fleet socket transport.

A frame is ``MAGIC(4) | payload_len(u32 BE) | crc32(u32 BE) | payload``.
The CRC covers the payload only; the length field is bounded by
``max_frame_bytes`` *before* any buffering so a corrupted length cannot
make the reader allocate gigabytes. TCP gives a byte stream, not
messages — :class:`FrameReader` is the stateful reassembler that turns
arbitrary read chunks (including frames torn across reads) back into
complete payloads, and raises :class:`FrameError` the moment the stream
desynchronizes (bad magic, oversized length, CRC mismatch). A framing
error is never recoverable in-stream: the caller must drop the
connection and reconnect, which is exactly what the channel layer's
backoff path does.
"""

from __future__ import annotations

import struct
import zlib
from typing import List

MAGIC = b"DSTF"
_HEADER = struct.Struct(">4sII")  # magic, payload length, crc32(payload)
HEADER_BYTES = _HEADER.size

# Big enough for a real-shape KV handoff (layers x blocks x block x
# 2 x heads x head_dim at int8), small enough that a corrupted length
# field cannot balloon the reassembly buffer.
DEFAULT_MAX_FRAME_BYTES = 256 << 20


class FrameError(RuntimeError):
    """Stream desynchronized: bad magic, oversized frame, or CRC
    mismatch. The connection is unusable past this point."""


def encode_frame(payload: bytes,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES) -> bytes:
    if len(payload) > max_frame_bytes:
        raise FrameError(
            f"frame payload of {len(payload)} bytes exceeds the "
            f"{max_frame_bytes}-byte frame limit")
    return _HEADER.pack(MAGIC, len(payload),
                        zlib.crc32(payload) & 0xFFFFFFFF) + payload


class FrameReader:
    """Stateful frame reassembler over an arbitrary chunk stream.

    ``feed(chunk)`` returns every payload completed by that chunk (zero
    or more); partial frames stay buffered for the next feed. All
    validation happens here — magic and length as soon as a header is
    complete, CRC once the payload is."""

    def __init__(self,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES):
        self.max_frame_bytes = int(max_frame_bytes)
        self._buf = bytearray()

    @property
    def pending_bytes(self) -> int:
        return len(self._buf)

    def feed(self, chunk: bytes) -> List[bytes]:
        self._buf.extend(chunk)
        out: List[bytes] = []
        while True:
            if len(self._buf) < HEADER_BYTES:
                return out
            magic, length, crc = _HEADER.unpack_from(self._buf)
            if magic != MAGIC:
                raise FrameError(
                    f"bad frame magic {bytes(magic)!r} (expected "
                    f"{MAGIC!r}) — stream desynchronized")
            if length > self.max_frame_bytes:
                raise FrameError(
                    f"frame of {length} bytes exceeds the "
                    f"{self.max_frame_bytes}-byte limit — corrupt "
                    "length or oversized message")
            if len(self._buf) < HEADER_BYTES + length:
                return out
            payload = bytes(self._buf[HEADER_BYTES:HEADER_BYTES + length])
            if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
                raise FrameError(
                    f"frame CRC mismatch over {length} payload bytes")
            del self._buf[:HEADER_BYTES + length]
            out.append(payload)
