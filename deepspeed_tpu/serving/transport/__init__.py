"""Cross-process serving transport: framed messages over sockets.

The fleet's wire layer, bottom-up:

* :mod:`framing` — length-prefixed, CRC-checked binary frames with a
  stateful reader that survives torn TCP reads and fails loud on
  corruption (``FrameError``);
* :mod:`messages` — the message codec: one JSON header for structure
  plus raw concatenated ndarray bytes for bulk payloads, so a quantized
  ``KVHandoff`` crosses the wire byte-identically with no base64 tax;
* :mod:`channel` — the two channel implementations behind one API:
  ``SocketChannel`` (localhost TCP, the primary) and ``FileChannel``
  (spool-dir frames via atomic renames — the ``ReplicaPublisher``-style
  degraded fallback when sockets are unavailable), both counting the
  bytes they actually put on the wire.

The process runtime on top lives in ``serving/proc_worker.py`` (the
subprocess replica entrypoint) and ``serving/supervisor.py``
(``ReplicaSupervisor`` + ``RemoteReplica``). docs/serving.md
"Cross-process fleet" has the topology diagram and degraded-mode
matrix.
"""

from deepspeed_tpu.serving.transport.channel import (ChannelError,
                                                     FileChannel,
                                                     SocketChannel,
                                                     SocketServer,
                                                     TransportError,
                                                     connect_with_backoff)
from deepspeed_tpu.serving.transport.framing import (DEFAULT_MAX_FRAME_BYTES,
                                                     FrameError, FrameReader,
                                                     encode_frame)
from deepspeed_tpu.serving.transport.messages import (decode_handoff,
                                                      decode_message,
                                                      decode_session,
                                                      encode_handoff,
                                                      encode_message,
                                                      encode_session)

__all__ = [
    "ChannelError", "DEFAULT_MAX_FRAME_BYTES", "FileChannel", "FrameError",
    "FrameReader", "SocketChannel", "SocketServer", "TransportError",
    "connect_with_backoff", "decode_handoff", "decode_message",
    "decode_session", "encode_frame", "encode_handoff", "encode_message",
    "encode_session",
]
