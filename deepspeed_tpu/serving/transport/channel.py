"""Fleet channels: one send/recv API over TCP sockets or a spool dir.

Both implementations move :mod:`messages`-encoded dicts inside
:mod:`framing` frames and count the bytes they actually put on the
wire (``bytes_sent``/``bytes_received`` include the frame header — the
real transport cost, which is what ``quant.kv_wire`` accounting wants).

* :class:`SocketChannel` — localhost TCP, the primary channel. ``recv``
  is poll-style (returns None on timeout); ``send`` is locked so the
  router and a handoff completion can share one channel.
  :func:`connect_with_backoff` retries a refused/dropped connection on
  an exponential schedule — worker spin-up and supervisor restart both
  race the connect.
* :class:`FileChannel` — the degraded fallback when sockets are
  unavailable (restricted container, no loopback): the same frames as
  numbered files in a spool directory, written atomically (tmp +
  rename, the ``observability/fleet.py`` discipline) so a reader never
  sees a torn frame. Ordering comes from the sequence number in the
  file name. Strictly slower than TCP — the degraded-mode matrix in
  docs/serving.md says when each channel is the right one.

A :class:`ChannelError` means the peer is gone or the stream is corrupt
(framing errors surface here too): callers drop the channel and either
reconnect with backoff or let the stale heartbeat drive failover.
:class:`TransportError` is the send-path subclass — the OS refused the
write — so retry policy can tell "my write failed" from "their stream
lied".

Every sent message carries a per-channel sequence number (``_chan_seq``,
stripped before delivery). The receiver delivers in-sequence frames,
silently discards duplicates (a fault-injected or retransmitted frame
replays harmlessly), and raises :class:`ChannelError` on a gap — a
silently dropped frame becomes a detectable fault at the next arrival
instead of a hung request. Chaos net faults (``DSTPU_CHAOS net_*``,
resilience/chaos.py) are injected here, on the encoded frames/chunks,
when the process-global injector is armed.

Clock sync (observability/clocksync.py) also lives at this layer:
``clock_ping``/``clock_pong`` messages are intercepted below the
message protocol — a receive path answers pings automatically and
feeds pongs into the channel's attached :class:`ClockSyncEstimator`
(``channel.clock``), so every channel owner gets per-peer offset
estimation without any protocol change. Clock messages ride normal
sequenced frames, which means the chaos net-fault matrix exercises
them like any other traffic.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

from deepspeed_tpu.serving.transport.framing import (DEFAULT_MAX_FRAME_BYTES,
                                                     FrameError, FrameReader,
                                                     encode_frame)
from deepspeed_tpu.serving.transport.messages import (decode_message,
                                                      encode_message)

_RECV_CHUNK = 1 << 16
SEQ_KEY = "_chan_seq"


class ChannelError(RuntimeError):
    """Peer gone or stream corrupt — drop the channel."""


class TransportError(ChannelError):
    """The send path itself failed (OS write/spool error) — typed so
    retry policy can distinguish it from a corrupt inbound stream."""


def _armed_net_injector():
    """The process-global chaos injector iff it carries net faults.
    Lazy import: channel.py must stay importable before proc_worker
    pins JAX_PLATFORMS, and chaos off must cost one attr check."""
    from deepspeed_tpu.resilience.chaos import get_chaos_injector

    inj = get_chaos_injector()
    if inj.armed and inj.spec.has_net_faults:
        return inj
    return None


class _SeqMixin:
    """Per-channel sequence numbering + clock-message interception
    shared by both transports."""

    def _seq_init(self) -> None:
        self._tx_seq = 0
        self._rx_expected = 0
        self.dup_frames = 0
        # attach a clocksync.ClockSyncEstimator to make this endpoint
        # the ping-initiating side; the peer side needs nothing — any
        # receive path answers pings automatically
        self.clock = None

    def ping_clock(self) -> int:
        """Send one clock ping (the pong, when it lands on any receive
        path, feeds ``self.clock``). Returns the bytes sent."""
        from deepspeed_tpu.observability.clocksync import wall_time

        return self.send({"type": "clock_ping", "t0": wall_time()})

    def _clock_intercept(self, msg: Dict[str, Any]
                         ) -> Optional[Dict[str, Any]]:
        """Consume clock messages below the protocol: answer pings,
        feed pongs into the estimator. Returns None when the message
        was a clock message (never delivered to the channel owner)."""
        kind = msg.get("type")
        if kind == "clock_ping":
            from deepspeed_tpu.observability.clocksync import wall_time

            t1 = wall_time()
            try:
                self.send({"type": "clock_pong",
                           "t0": msg.get("t0", 0.0), "t1": t1,
                           "t2": wall_time()})
            except ChannelError:
                pass  # peer gone mid-pong; the send path flagged it
            return None
        if kind == "clock_pong":
            if self.clock is not None:
                from deepspeed_tpu.observability.clocksync import \
                    wall_time

                self.clock.add_round_trip(
                    float(msg.get("t0", 0.0)), float(msg.get("t1", 0.0)),
                    float(msg.get("t2", 0.0)), wall_time())
            return None
        return msg

    def _seq_deliver(self, msg: Dict[str, Any]
                     ) -> Optional[Dict[str, Any]]:
        """In-sequence → deliver; duplicate → None (discard); gap →
        ChannelError. Unnumbered messages pass through untouched."""
        seq = msg.pop(SEQ_KEY, None)
        if seq is None:
            return msg
        if seq == self._rx_expected:
            self._rx_expected += 1
            return msg
        if seq < self._rx_expected:
            self.dup_frames += 1
            return None
        raise ChannelError(
            f"sequence gap: expected frame {self._rx_expected}, got "
            f"{seq} ({seq - self._rx_expected} frame(s) lost)")


class SocketChannel(_SeqMixin):
    def __init__(self, sock: socket.socket,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                 peer_id: Optional[int] = None):
        self._sock = sock
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader = FrameReader(max_frame_bytes)
        self._inbox: deque = deque()
        self._send_lock = threading.Lock()
        self.max_frame_bytes = int(max_frame_bytes)
        self.peer_id = peer_id
        self.bytes_sent = 0
        self.bytes_received = 0
        self.closed = False
        self._seq_init()

    def send(self, msg: Dict[str, Any]) -> int:
        """Frame + write one message; returns the bytes put on the
        wire. Raises TransportError when the peer is gone. The sequence
        number is assigned under the send lock — two sender threads
        (heartbeat + main loop) must not interleave seq order."""
        with self._send_lock:
            if self.closed:
                raise ChannelError("channel closed")
            frame = encode_frame(
                encode_message(dict(msg, **{SEQ_KEY: self._tx_seq})),
                self.max_frame_bytes)
            self._tx_seq += 1
            inj = _armed_net_injector()
            frames = ([frame] if inj is None
                      else inj.on_wire_tx(frame, peer=self.peer_id))
            sent = 0
            for fr in frames:
                try:
                    self._sock.sendall(fr)
                except OSError as e:
                    self.close()
                    raise TransportError(f"send failed: {e}") from e
                sent += len(fr)
            self.bytes_sent += sent
        return sent

    def recv(self, timeout: Optional[float] = 0.0
             ) -> Optional[Dict[str, Any]]:
        """Next message, or None when nothing arrives within
        ``timeout``. Raises ChannelError on peer close / corruption /
        a sequence gap (a dropped frame upstream)."""
        if self._inbox:
            return self._inbox.popleft()
        if self.closed:
            raise ChannelError("channel closed")
        deadline = None if timeout is None else time.time() + timeout
        while not self._inbox:
            self._sock.settimeout(
                None if deadline is None
                else max(deadline - time.time(), 1e-4))
            try:
                chunk = self._sock.recv(_RECV_CHUNK)
            except socket.timeout:
                return None
            except OSError as e:
                self.close()
                raise ChannelError(f"recv failed: {e}") from e
            if not chunk:
                self.close()
                raise ChannelError("peer closed the connection")
            self.bytes_received += len(chunk)
            inj = _armed_net_injector()
            if inj is not None:
                chunk = inj.on_wire_rx(chunk, peer=self.peer_id)
            if chunk is None:
                chunk = b""
            try:
                for payload in self._reader.feed(chunk):
                    msg = self._seq_deliver(decode_message(payload))
                    if msg is not None:
                        msg = self._clock_intercept(msg)
                    if msg is not None:
                        self._inbox.append(msg)
            except FrameError as e:
                self.close()
                raise ChannelError(str(e)) from e
            except ChannelError:
                self.close()
                raise
            if not self._inbox and deadline is not None \
                    and time.time() >= deadline:
                return None
        return self._inbox.popleft()

    def close(self) -> None:
        self.closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class SocketServer:
    """Listening side: bind 127.0.0.1:0 (or a given port), publish
    ``.port``, accept one peer at a time."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(8)
        self.host, self.port = self._srv.getsockname()
        self.max_frame_bytes = int(max_frame_bytes)

    def accept(self, timeout: Optional[float] = None) -> SocketChannel:
        self._srv.settimeout(timeout)
        try:
            sock, _ = self._srv.accept()
        except socket.timeout as e:
            raise ChannelError(
                f"no peer connected within {timeout}s") from e
        return SocketChannel(sock, self.max_frame_bytes)

    def close(self) -> None:
        try:
            self._srv.close()
        except OSError:
            pass


def connect_with_backoff(host: str, port: int, retries: int = 20,
                         backoff_s: float = 0.05,
                         backoff_max_s: float = 1.0,
                         max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                         policy: Optional[Any] = None,
                         peer_id: Optional[int] = None
                         ) -> SocketChannel:
    """Dial the peer, retrying refused/reset connects on an exponential
    schedule (worker startup and supervisor restart both race this).
    ``policy`` (a resilience.policy.RetryPolicy) supersedes the legacy
    retries/backoff_s knobs: attempts = max_retries + 1, delays from
    ``policy.backoff_s(attempt)``. Raises ChannelError once the budget
    is spent."""
    if policy is not None:
        attempts = max(1, int(policy.max_retries) + 1)
    else:
        attempts = max(1, int(retries))
    delay = float(backoff_s)
    last: Optional[Exception] = None
    for attempt in range(1, attempts + 1):
        try:
            sock = socket.create_connection((host, port), timeout=5.0)
            return SocketChannel(sock, max_frame_bytes, peer_id=peer_id)
        except OSError as e:
            last = e
            if attempt >= attempts:
                break
            if policy is not None:
                time.sleep(policy.backoff_s(attempt))
            else:
                time.sleep(delay)
                delay = min(delay * 2.0, float(backoff_max_s))
    raise ChannelError(
        f"could not connect to {host}:{port} after {attempts} attempts: "
        f"{last}")


class FileChannel(_SeqMixin):
    """Spool-dir frames: the socketless degraded fallback.

    One spool directory holds two one-way lanes (``a2b``/``b2a``); each
    endpoint sends into its outbound lane and polls the other. A
    message is one frame in one file named by a monotonically
    increasing sequence number, written tmp+rename so readers only ever
    see complete files; the reader consumes in sequence order and
    unlinks. CRC validation still applies — a corrupt spool file raises
    ChannelError exactly like a corrupt socket stream."""

    def __init__(self, spool_dir: str, side: str,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                 peer_id: Optional[int] = None):
        if side not in ("a", "b"):
            raise ValueError(f"side must be 'a' or 'b', got {side!r}")
        self.spool_dir = spool_dir
        self._tx = os.path.join(spool_dir,
                                "a2b" if side == "a" else "b2a")
        self._rx = os.path.join(spool_dir,
                                "b2a" if side == "a" else "a2b")
        os.makedirs(self._tx, exist_ok=True)
        os.makedirs(self._rx, exist_ok=True)
        self.max_frame_bytes = int(max_frame_bytes)
        self.peer_id = peer_id
        self._seq = 0
        self._lock = threading.Lock()
        self.bytes_sent = 0
        self.bytes_received = 0
        self.closed = False
        self._seq_init()

    def send(self, msg: Dict[str, Any]) -> int:
        with self._lock:
            if self.closed:
                raise ChannelError("channel closed")
            frame = encode_frame(
                encode_message(dict(msg, **{SEQ_KEY: self._tx_seq})),
                self.max_frame_bytes)
            self._tx_seq += 1
            inj = _armed_net_injector()
            frames = ([frame] if inj is None
                      else inj.on_wire_tx(frame, peer=self.peer_id))
            spool = [(fr, os.path.join(self._tx,
                                       f"{self._seq + i:012d}.frame"))
                     for i, fr in enumerate(frames)]
            self._seq += len(frames)
        sent = 0
        for fr, path in spool:
            tmp = path + f".tmp.{os.getpid()}"
            try:
                with open(tmp, "wb") as f:
                    f.write(fr)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except OSError as e:
                raise TransportError(f"spool write failed: {e}") from e
            sent += len(fr)
        with self._lock:
            self.bytes_sent += sent
        return sent

    def _next_file(self) -> Optional[str]:
        try:
            names = [n for n in os.listdir(self._rx)
                     if n.endswith(".frame")]
        except FileNotFoundError as e:
            raise ChannelError(f"spool dir vanished: {e}") from e
        return os.path.join(self._rx, min(names)) if names else None

    def recv(self, timeout: Optional[float] = 0.0,
             poll_s: float = 0.005) -> Optional[Dict[str, Any]]:
        deadline = None if timeout is None else time.time() + timeout
        while True:
            if self.closed:
                raise ChannelError("channel closed")
            path = self._next_file()
            if path is not None:
                with open(path, "rb") as f:
                    frame = f.read()
                os.unlink(path)
                self.bytes_received += len(frame)
                inj = _armed_net_injector()
                if inj is not None:
                    frame = inj.on_wire_rx(frame, peer=self.peer_id)
                if frame is None:
                    continue
                reader = FrameReader(self.max_frame_bytes)
                try:
                    payloads = reader.feed(frame)
                except FrameError as e:
                    raise ChannelError(str(e)) from e
                if len(payloads) != 1 or reader.pending_bytes:
                    raise ChannelError(
                        f"spool file {os.path.basename(path)} held "
                        f"{len(payloads)} frames + "
                        f"{reader.pending_bytes} stray bytes "
                        "(expected exactly one)")
                msg = self._seq_deliver(decode_message(payloads[0]))
                if msg is not None:
                    msg = self._clock_intercept(msg)
                if msg is None:
                    continue
                return msg
            if deadline is not None and time.time() >= deadline:
                return None
            time.sleep(poll_s)

    def close(self) -> None:
        self.closed = True
