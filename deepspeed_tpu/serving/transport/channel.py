"""Fleet channels: one send/recv API over TCP sockets or a spool dir.

Both implementations move :mod:`messages`-encoded dicts inside
:mod:`framing` frames and count the bytes they actually put on the
wire (``bytes_sent``/``bytes_received`` include the frame header — the
real transport cost, which is what ``quant.kv_wire`` accounting wants).

* :class:`SocketChannel` — localhost TCP, the primary channel. ``recv``
  is poll-style (returns None on timeout); ``send`` is locked so the
  router and a handoff completion can share one channel.
  :func:`connect_with_backoff` retries a refused/dropped connection on
  an exponential schedule — worker spin-up and supervisor restart both
  race the connect.
* :class:`FileChannel` — the degraded fallback when sockets are
  unavailable (restricted container, no loopback): the same frames as
  numbered files in a spool directory, written atomically (tmp +
  rename, the ``observability/fleet.py`` discipline) so a reader never
  sees a torn frame. Ordering comes from the sequence number in the
  file name. Strictly slower than TCP — the degraded-mode matrix in
  docs/serving.md says when each channel is the right one.

A :class:`ChannelError` means the peer is gone or the stream is corrupt
(framing errors surface here too): callers drop the channel and either
reconnect with backoff or let the stale heartbeat drive failover.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from collections import deque
from typing import Any, Dict, Optional

from deepspeed_tpu.serving.transport.framing import (DEFAULT_MAX_FRAME_BYTES,
                                                     FrameError, FrameReader,
                                                     encode_frame)
from deepspeed_tpu.serving.transport.messages import (decode_message,
                                                      encode_message)

_RECV_CHUNK = 1 << 16


class ChannelError(RuntimeError):
    """Peer gone or stream corrupt — drop the channel."""


class SocketChannel:
    def __init__(self, sock: socket.socket,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES):
        self._sock = sock
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._reader = FrameReader(max_frame_bytes)
        self._inbox: deque = deque()
        self._send_lock = threading.Lock()
        self.max_frame_bytes = int(max_frame_bytes)
        self.bytes_sent = 0
        self.bytes_received = 0
        self.closed = False

    def send(self, msg: Dict[str, Any]) -> int:
        """Frame + write one message; returns the bytes put on the
        wire. Raises ChannelError when the peer is gone."""
        frame = encode_frame(encode_message(msg), self.max_frame_bytes)
        with self._send_lock:
            if self.closed:
                raise ChannelError("channel closed")
            try:
                self._sock.sendall(frame)
            except OSError as e:
                self.close()
                raise ChannelError(f"send failed: {e}") from e
            self.bytes_sent += len(frame)
        return len(frame)

    def recv(self, timeout: Optional[float] = 0.0
             ) -> Optional[Dict[str, Any]]:
        """Next message, or None when nothing arrives within
        ``timeout``. Raises ChannelError on peer close / corruption."""
        if self._inbox:
            return self._inbox.popleft()
        if self.closed:
            raise ChannelError("channel closed")
        deadline = None if timeout is None else time.time() + timeout
        while not self._inbox:
            self._sock.settimeout(
                None if deadline is None
                else max(deadline - time.time(), 1e-4))
            try:
                chunk = self._sock.recv(_RECV_CHUNK)
            except socket.timeout:
                return None
            except OSError as e:
                self.close()
                raise ChannelError(f"recv failed: {e}") from e
            if not chunk:
                self.close()
                raise ChannelError("peer closed the connection")
            self.bytes_received += len(chunk)
            try:
                for payload in self._reader.feed(chunk):
                    self._inbox.append(decode_message(payload))
            except FrameError as e:
                self.close()
                raise ChannelError(str(e)) from e
            if not self._inbox and deadline is not None \
                    and time.time() >= deadline:
                return None
        return self._inbox.popleft()

    def close(self) -> None:
        self.closed = True
        try:
            self._sock.close()
        except OSError:
            pass


class SocketServer:
    """Listening side: bind 127.0.0.1:0 (or a given port), publish
    ``.port``, accept one peer at a time."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES):
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(8)
        self.host, self.port = self._srv.getsockname()
        self.max_frame_bytes = int(max_frame_bytes)

    def accept(self, timeout: Optional[float] = None) -> SocketChannel:
        self._srv.settimeout(timeout)
        try:
            sock, _ = self._srv.accept()
        except socket.timeout as e:
            raise ChannelError(
                f"no peer connected within {timeout}s") from e
        return SocketChannel(sock, self.max_frame_bytes)

    def close(self) -> None:
        try:
            self._srv.close()
        except OSError:
            pass


def connect_with_backoff(host: str, port: int, retries: int = 20,
                         backoff_s: float = 0.05,
                         backoff_max_s: float = 1.0,
                         max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES
                         ) -> SocketChannel:
    """Dial the peer, retrying refused/reset connects on an exponential
    schedule (worker startup and supervisor restart both race this).
    Raises ChannelError once the budget is spent."""
    delay = float(backoff_s)
    last: Optional[Exception] = None
    for _ in range(max(1, int(retries))):
        try:
            sock = socket.create_connection((host, port), timeout=5.0)
            return SocketChannel(sock, max_frame_bytes)
        except OSError as e:
            last = e
            time.sleep(delay)
            delay = min(delay * 2.0, float(backoff_max_s))
    raise ChannelError(
        f"could not connect to {host}:{port} after {retries} attempts: "
        f"{last}")


class FileChannel:
    """Spool-dir frames: the socketless degraded fallback.

    One spool directory holds two one-way lanes (``a2b``/``b2a``); each
    endpoint sends into its outbound lane and polls the other. A
    message is one frame in one file named by a monotonically
    increasing sequence number, written tmp+rename so readers only ever
    see complete files; the reader consumes in sequence order and
    unlinks. CRC validation still applies — a corrupt spool file raises
    ChannelError exactly like a corrupt socket stream."""

    def __init__(self, spool_dir: str, side: str,
                 max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES):
        if side not in ("a", "b"):
            raise ValueError(f"side must be 'a' or 'b', got {side!r}")
        self.spool_dir = spool_dir
        self._tx = os.path.join(spool_dir,
                                "a2b" if side == "a" else "b2a")
        self._rx = os.path.join(spool_dir,
                                "b2a" if side == "a" else "a2b")
        os.makedirs(self._tx, exist_ok=True)
        os.makedirs(self._rx, exist_ok=True)
        self.max_frame_bytes = int(max_frame_bytes)
        self._seq = 0
        self._lock = threading.Lock()
        self.bytes_sent = 0
        self.bytes_received = 0
        self.closed = False

    def send(self, msg: Dict[str, Any]) -> int:
        frame = encode_frame(encode_message(msg), self.max_frame_bytes)
        with self._lock:
            if self.closed:
                raise ChannelError("channel closed")
            path = os.path.join(self._tx, f"{self._seq:012d}.frame")
            self._seq += 1
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(frame)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        with self._lock:
            self.bytes_sent += len(frame)
        return len(frame)

    def _next_file(self) -> Optional[str]:
        try:
            names = [n for n in os.listdir(self._rx)
                     if n.endswith(".frame")]
        except FileNotFoundError as e:
            raise ChannelError(f"spool dir vanished: {e}") from e
        return os.path.join(self._rx, min(names)) if names else None

    def recv(self, timeout: Optional[float] = 0.0,
             poll_s: float = 0.005) -> Optional[Dict[str, Any]]:
        deadline = None if timeout is None else time.time() + timeout
        while True:
            if self.closed:
                raise ChannelError("channel closed")
            path = self._next_file()
            if path is not None:
                with open(path, "rb") as f:
                    frame = f.read()
                os.unlink(path)
                self.bytes_received += len(frame)
                reader = FrameReader(self.max_frame_bytes)
                try:
                    payloads = reader.feed(frame)
                except FrameError as e:
                    raise ChannelError(str(e)) from e
                if len(payloads) != 1 or reader.pending_bytes:
                    raise ChannelError(
                        f"spool file {os.path.basename(path)} held "
                        f"{len(payloads)} frames + "
                        f"{reader.pending_bytes} stray bytes "
                        "(expected exactly one)")
                return decode_message(payloads[0])
            if deadline is not None and time.time() >= deadline:
                return None
            time.sleep(poll_s)

    def close(self) -> None:
        self.closed = True
