"""Fleet message codec: JSON structure + raw ndarray bytes.

Every message is a plain dict (``{"type": ..., ...}``) whose ndarray
values — prompt tokens, KV block payloads, quantization scales — are
lifted out into a binary section so the wire cost of a quantized
handoff is its actual byte size, not a base64-inflated JSON string.
Payload layout::

    u32 header_len | JSON header | array 0 bytes | array 1 bytes | ...

In the JSON header each lifted array is replaced by
``{"__nd__": i, "dtype": ..., "shape": [...]}``; decode walks the same
structure and rebuilds each array with ``np.frombuffer`` — bit-exact
round-trips by construction, including bfloat16 (via ml_dtypes) and
the int4-packed handoff payloads.

``encode_handoff``/``decode_handoff`` map :class:`serving.disagg.
KVHandoff` onto that dict form field-for-field, so the PR 12 wire codec
serializes as-is: the bytes a quantized handoff puts on the socket ARE
``wire_nbytes`` plus the fixed header overhead.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_LEN = struct.Struct(">I")


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        # bfloat16 & friends register through ml_dtypes (a jax dep)
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def encode_message(msg: Dict[str, Any]) -> bytes:
    arrays: List[np.ndarray] = []

    def lift(obj):
        if isinstance(obj, np.ndarray):
            arr = np.ascontiguousarray(obj)
            arrays.append(arr)
            return {"__nd__": len(arrays) - 1,
                    "dtype": str(arr.dtype), "shape": list(arr.shape)}
        if isinstance(obj, dict):
            return {str(k): lift(v) for k, v in obj.items()}
        if isinstance(obj, (list, tuple)):
            return [lift(v) for v in obj]
        if isinstance(obj, (np.integer,)):
            return int(obj)
        if isinstance(obj, (np.floating,)):
            return float(obj)
        if isinstance(obj, (np.bool_,)):
            return bool(obj)
        return obj

    header = json.dumps(lift(msg)).encode("utf-8")
    parts = [_LEN.pack(len(header)), header]
    parts.extend(arr.tobytes() for arr in arrays)
    return b"".join(parts)


def decode_message(payload: bytes) -> Dict[str, Any]:
    (hlen,) = _LEN.unpack_from(payload)
    doc = json.loads(payload[_LEN.size:_LEN.size + hlen].decode("utf-8"))

    # first pass: placeholder metadata in __nd__ order fixes each
    # array's offset into the binary section
    placeholders: Dict[int, Tuple[np.dtype, tuple]] = {}

    def scan(obj):
        if isinstance(obj, dict):
            if "__nd__" in obj and set(obj) == {"__nd__", "dtype", "shape"}:
                placeholders[int(obj["__nd__"])] = (
                    _np_dtype(obj["dtype"]), tuple(obj["shape"]))
                return
            for v in obj.values():
                scan(v)
        elif isinstance(obj, list):
            for v in obj:
                scan(v)

    scan(doc)
    offsets: Dict[int, int] = {}
    off = _LEN.size + hlen
    for i in sorted(placeholders):
        dt, shape = placeholders[i]
        offsets[i] = off
        off += dt.itemsize * int(np.prod(shape, dtype=np.int64))
    if off > len(payload):
        raise ValueError(
            f"message binary section truncated: arrays need {off} bytes, "
            f"payload has {len(payload)}")

    def rebuild(obj):
        if isinstance(obj, dict):
            if "__nd__" in obj and set(obj) == {"__nd__", "dtype", "shape"}:
                i = int(obj["__nd__"])
                dt, shape = placeholders[i]
                n = int(np.prod(shape, dtype=np.int64))
                return np.frombuffer(payload, dtype=dt, count=n,
                                     offset=offsets[i]).reshape(shape).copy()
            return {k: rebuild(v) for k, v in obj.items()}
        if isinstance(obj, list):
            return [rebuild(v) for v in obj]
        return obj

    return rebuild(doc)


# -- KVHandoff mapping ---------------------------------------------------


def encode_handoff(handoff) -> Optional[Dict[str, Any]]:
    """KVHandoff -> message-dict form (None passes through: a
    tokens-only handoff that degraded to recompute)."""
    if handoff is None:
        return None
    return {
        "keys": list(handoff.keys),
        "block_data": handoff.block_data,
        "block_size": int(handoff.block_size),
        "scales": handoff.scales,
        "wire_bits": handoff.wire_bits,
        "packed": bool(handoff.packed),
        "src_quant_bits": handoff.src_quant_bits,
        "wire_snr_db": handoff.wire_snr_db,
    }


def decode_handoff(doc: Optional[Dict[str, Any]]):
    if doc is None:
        return None
    from deepspeed_tpu.serving.disagg import KVHandoff

    return KVHandoff(
        keys=list(doc["keys"]), block_data=doc["block_data"],
        block_size=int(doc["block_size"]), scales=doc.get("scales"),
        wire_bits=doc.get("wire_bits"), packed=bool(doc.get("packed")),
        src_quant_bits=doc.get("src_quant_bits"),
        wire_snr_db=doc.get("wire_snr_db"))


# -- SessionHandoff mapping (live migration, ISSUE 20) -------------------


def encode_session(sess) -> Optional[Dict[str, Any]]:
    """SessionHandoff -> message-dict form (None passes through: a
    capture that degraded to the fold-and-resubmit recompute path)."""
    if sess is None:
        return None
    return {
        "uid": int(sess.uid),
        "input_tokens": np.asarray(sess.input_tokens, np.int32),
        "generated": [int(t) for t in sess.generated],
        "seen_tokens": int(sess.seen_tokens),
        "max_new_tokens": int(sess.max_new_tokens),
        "prior_generated": int(sess.prior_generated),
        "block_data": sess.block_data,
        "block_size": int(sess.block_size),
        "scales": sess.scales,
        "wire_bits": sess.wire_bits,
        "packed": bool(sess.packed),
        "src_quant_bits": sess.src_quant_bits,
        "wire_snr_db": sess.wire_snr_db,
        "spec_accept_ewma": (None if sess.spec_accept_ewma is None
                             else float(sess.spec_accept_ewma)),
    }


def decode_session(doc: Optional[Dict[str, Any]]):
    if doc is None:
        return None
    from deepspeed_tpu.serving.disagg import SessionHandoff

    return SessionHandoff(
        uid=int(doc["uid"]),
        input_tokens=np.asarray(doc["input_tokens"], np.int32),
        generated=[int(t) for t in doc["generated"]],
        seen_tokens=int(doc["seen_tokens"]),
        max_new_tokens=int(doc["max_new_tokens"]),
        prior_generated=int(doc["prior_generated"]),
        block_data=doc["block_data"],
        block_size=int(doc["block_size"]),
        scales=doc.get("scales"),
        wire_bits=doc.get("wire_bits"), packed=bool(doc.get("packed")),
        src_quant_bits=doc.get("src_quant_bits"),
        wire_snr_db=doc.get("wire_snr_db"),
        spec_accept_ewma=doc.get("spec_accept_ewma"))
