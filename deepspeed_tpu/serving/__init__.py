"""Serving fleet: N engine_v2 replicas behind one router.

Reference analog: DeepSpeed serves FastGen behind MII's replica router
(``mii.serve`` with ``replica_num``); DistServe/Splitwise motivate the
prefill/decode disaggregation. Layout:

* ``replica.py`` — one engine + role + heartbeat/load report;
* ``router.py`` — admission, affinity/least-loaded/predictive routing,
  stale-heartbeat failover, fleet observability;
* ``disagg.py`` — the KV-block handoff codec between prefill and
  decode replicas;
* ``autoscale.py`` — desired-replica-count signals (+ the supervisor's
  act log);
* ``transport/`` — framed socket/spool-file channels for cross-process
  fleets;
* ``proc_worker.py`` / ``supervisor.py`` — one replica per OS process
  behind the same router: spawn, restart, autoscale spin-up/drain.

See docs/serving.md "Multi-replica fleet" and "Cross-process fleet".
"""

from deepspeed_tpu.serving.autoscale import AutoscaleSignal
from deepspeed_tpu.serving.disagg import (KVHandoff, SessionHandoff,
                                          install_prefix,
                                          install_session,
                                          serialize_prefix,
                                          serialize_session)
from deepspeed_tpu.serving.replica import ServingReplica, Submission
from deepspeed_tpu.serving.router import FleetRouter, build_fleet
from deepspeed_tpu.serving.supervisor import (RemoteReplica,
                                              ReplicaSupervisor)

__all__ = ["AutoscaleSignal", "FleetRouter", "KVHandoff",
           "RemoteReplica", "ReplicaSupervisor", "ServingReplica",
           "SessionHandoff", "Submission", "build_fleet",
           "install_prefix", "install_session", "serialize_prefix",
           "serialize_session"]
