"""AutoEP: automatic expert parallelism for HF-style MoE parameter trees.

Reference: ``deepspeed/module_inject/auto_ep.py:273`` (``AutoEP``) —
detects a stock HF MoE model's router + experts (fused-3D tensors or a
ModuleList of per-expert modules), converts the experts to grouped
(stacked) layout for grouped-GEMM execution, and partitions them over
the expert-parallel group; presets per architecture live in
``module_inject/auto_ep_presets/``.

TPU-native: expert parallelism is a sharding of the stacked expert
tensors' leading E axis over the mesh's ``ep`` axis — GSPMD inserts the
dispatch/combine collectives the reference performs with explicit
all-to-alls. AutoEP here does the two mechanical parts the reference
does: (1) **restack** ``experts.<i>.<leaf>`` ModuleList entries into
fused ``[E, ...]`` arrays (the grouped-GEMM layout
``moe/ep_experts.py:136`` builds), and (2) **classify** paths → specs:
expert-stacked tensors shard E over ep (and their matrix dims over tp
by the AutoTP policy), router/gate weights replicate.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.module_inject.auto_tp import AutoTP, SEP, _divisible
from deepspeed_tpu.utils.logging import log_dist, logger

# path fragments marking the expert container (reference presets:
# mixtral 'block_sparse_moe.experts', qwen2_moe 'mlp.experts', ...)
_EXPERT_PATTERNS = [r"experts"]
_ROUTER_PATTERNS = [r"\bgate\b", r"router", r"gate_proj\b.*router"]


class AutoEPPreset:
    """Architecture preset (reference auto_ep_presets/): where experts
    and the router live."""

    def __init__(self, expert_patterns=None, router_patterns=None):
        self.expert_patterns = list(expert_patterns or _EXPERT_PATTERNS)
        self.router_patterns = list(router_patterns or _ROUTER_PATTERNS)


PRESETS: Dict[str, AutoEPPreset] = {
    "default": AutoEPPreset(),
    "mixtral": AutoEPPreset([r"block_sparse_moe\.experts", r"experts"],
                            [r"block_sparse_moe\.gate\b"]),
    "qwen2_moe": AutoEPPreset([r"mlp\.experts", r"experts"],
                              [r"mlp\.gate\b", r"shared_expert_gate"]),
}


def _is_int_keyed(d: dict) -> bool:
    return len(d) > 0 and all(
        isinstance(k, str) and k.isdigit() for k in d)


def stack_expert_modulelist(params, preset: Optional[AutoEPPreset] = None):
    """Restack ``experts.{0..E-1}.<leaf>`` dicts into fused ``[E, ...]``
    arrays (reference GroupedExperts conversion, moe/ep_experts.py:136).
    Fused-3D checkpoints pass through unchanged. Returns a new tree.
    """
    preset = preset or PRESETS["default"]

    def walk(tree, prefix=""):
        if not isinstance(tree, dict):
            return tree
        is_expert_list = (
            _is_int_keyed(tree)
            and any(re.search(p, prefix) for p in preset.expert_patterns)
            and all(isinstance(v, dict) for v in tree.values()))
        if is_expert_list:
            order = sorted(tree, key=int)
            per_expert = [walk(tree[k], f"{prefix}{SEP}{k}") for k in order]
            # stack leaf-wise: {'w1': [E,...], 'w2': [E,...]}
            return jax.tree.map(
                lambda *xs: jax.numpy.stack(
                    [jax.numpy.asarray(x) for x in xs]), *per_expert)
        return {k: walk(v, f"{prefix}{SEP}{k}" if prefix else str(k))
                for k, v in tree.items()}

    return walk(params)


class AutoEP:
    """Classify paths of a (restacked) MoE tree → EP×TP PartitionSpecs."""

    def __init__(self, ep_axis: str = "ep", tp_axis: str = "tp",
                 preset: str = "default", tp_policy: Optional[str] = None):
        self.ep_axis = ep_axis
        self.preset = PRESETS.get(preset.lower())
        if self.preset is None:
            logger.warning(f"AutoEP: no preset '{preset}', using default")
            self.preset = PRESETS["default"]
        self.autotp = AutoTP(tp_axis=tp_axis, policy=tp_policy)

    def _is_expert(self, path: str) -> bool:
        return any(re.search(p, path) for p in self.preset.expert_patterns)

    def _is_router(self, path: str) -> bool:
        return any(re.search(p, path) for p in self.preset.router_patterns)

    def spec_for(self, path: str, shape: Tuple[int, ...]) -> P:
        if self._is_router(path):
            return P(*[None] * len(shape))  # router replicates (tiny)
        if self._is_expert(path) and len(shape) >= 2:
            # leading axis = E over ep; trailing matrix dims follow the
            # AutoTP column/row policy
            inner = self.autotp.spec_for(path, shape[1:])
            return P(self.ep_axis, *tuple(inner))
        return self.autotp.spec_for(path, shape)

    def infer_specs(self, params) -> Any:
        def walk(tree, prefix=""):
            if isinstance(tree, dict):
                return {k: walk(v, f"{prefix}{SEP}{k}" if prefix else str(k))
                        for k, v in tree.items()}
            if isinstance(tree, (list, tuple)):
                vals = [walk(v, f"{prefix}{SEP}{i}" if prefix else str(i))
                        for i, v in enumerate(tree)]
                return vals if isinstance(tree, list) else tuple(vals)
            return self.spec_for(prefix,
                                 tuple(getattr(tree, "shape", ()) or ()))

        return walk(params)


def ep_model_init(params, mesh: Optional[Mesh] = None, ep_size: int = 0,
                  preset: str = "default", dtype=None):
    """Restack + shard an HF MoE tree for expert parallelism (reference
    ``AutoEP`` runtime conversion entry). Returns (sharded_params, specs).

    Experts whose E doesn't divide the ep axis fall back to replicated
    with a warning (partial conversion, like the reference).
    """
    from deepspeed_tpu.parallel import topology as topo

    if mesh is None:
        if ep_size <= 0:
            raise ValueError("ep_model_init needs mesh or ep_size")
        mesh = topo.build_mesh(topo.TopologyConfig(ep=ep_size, dp=-1))
    # resolve once (case-insensitive, warned) so stacking and spec
    # inference cannot disagree on the preset
    preset = preset.lower()
    if preset not in PRESETS:
        logger.warning(f"AutoEP: no preset '{preset}', using default")
        preset = "default"
    stacked = stack_expert_modulelist(params, PRESETS[preset])
    aep = AutoEP(preset=preset)
    specs = aep.infer_specs(stacked)

    def place(x, spec):
        shape = tuple(getattr(x, "shape", ()) or ())
        if not _divisible(shape, spec, mesh):
            logger.warning(
                f"AutoEP: shape {shape} not divisible for spec {spec}; "
                "replicating")
            spec = P(*[None] * len(shape))
        arr = jax.numpy.asarray(x)
        if dtype is not None:
            arr = arr.astype(dtype)
        return jax.device_put(arr, NamedSharding(mesh, spec))

    sharded = jax.tree.map(place, stacked, specs,
                           is_leaf=lambda x: not isinstance(
                               x, (dict, list, tuple)))
    log_dist(f"AutoEP over ep={mesh.shape.get('ep', 1)} "
             f"(preset={preset})", ranks=[0])
    return sharded, specs
