"""Module injection: automatic tensor-parallel sharding for external
models (reference: deepspeed/module_inject/)."""

from deepspeed_tpu.module_inject.auto_tp import (  # noqa: F401
    AutoTP,
    tp_model_init,
)
from deepspeed_tpu.module_inject.auto_ep import (  # noqa: F401
    AutoEP,
    ep_model_init,
    stack_expert_modulelist,
)
