"""AutoTP: automatic tensor parallelism for arbitrary parameter trees.

Reference: ``deepspeed/module_inject/auto_tp.py:194`` (``AutoTP`` — scans
an nn.Module graph, classifies each Linear as column-parallel
(``LinearLayer``) or row-parallel (``LinearAllreduce``) from its position
in attention/MLP, then swaps modules and splits weights), and
``deepspeed.tp_model_init`` (``__init__.py:408``) as the user entry.

TPU-native: there is nothing to swap — a weight's *sharding spec* IS its
parallelism. AutoTP here classifies each parameter path of any pytree
(HF-Flax params, our zoo trees, plain dicts) by the same name policy the
reference uses (q/k/v/gate/up → column; o_proj/down/fc2 → row; embeddings
→ vocab-sharded; norms/biases → replicated), emits a PartitionSpec tree,
and ``tp_model_init`` device_puts the params onto the mesh with those
specs. XLA/GSPMD then inserts exactly the collectives the reference's
LinearAllreduce does by hand (psum after row-parallel matmuls), scheduled
on ICI.

The name → policy table is extensible per architecture
(``AutoTP.register_policy``) — the analog of the reference's injection
policy registry (module_inject/replace_policy.py).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.logging import log_dist, logger

SEP = "."

# column-parallel: output dim sharded over tp (activations become
# tp-sharded on the feature dim; no collective needed on entry)
_COLUMN_PATTERNS = [
    r"\bw?q(_proj|_lin|kv)?\b", r"\bw?k(_proj|_lin)?\b",
    r"\bw?v(_proj|_lin)?\b", r"\bquery\b", r"\bkey\b", r"\bvalue\b",
    r"\bqkv\b", r"c_attn", r"\bgate(_proj)?\b", r"\bup(_proj)?\b",
    r"\bfc1\b", r"\bwi(_\d)?\b", r"intermediate", r"c_fc\b",
    r"\bw1\b", r"\bw3\b", r"lin1",
]
# row-parallel: input dim sharded over tp (XLA inserts the psum the
# reference's LinearAllreduce does explicitly)
_ROW_PATTERNS = [
    r"\bw?o(_proj|ut_proj)?\b", r"\bdense\b", r"c_proj", r"\bdown(_proj)?\b",
    r"\bfc2\b", r"\bwo\b", r"\bw2\b", r"lin2", r"attention.output",
    r"output.dense",
]
# vocab/position embeddings: shard the embedding (vocab) dim
_EMBED_PATTERNS = [r"embed", r"\bwte\b", r"\bwpe\b", r"lm_head",
                   r"word_embeddings", r"\btok\b", r"\bpos\b"]
_REPLICATED_PATTERNS = [r"norm", r"\bln\b", r"layernorm", r"\bbias\b",
                        r"\bscale\b", r"\bb\b"]


class AutoTP:
    """Classify parameter paths → PartitionSpecs over a ``tp`` mesh axis.

    Reference AutoTP.tp_parser/module replacement collapsed into spec
    inference; ``policies`` maps architecture name → extra pattern lists.
    """

    _policies: Dict[str, Dict[str, List[str]]] = {}

    def __init__(self, tp_axis: str = "tp", policy: Optional[str] = None):
        self.tp_axis = tp_axis
        self.column = list(_COLUMN_PATTERNS)
        self.row = list(_ROW_PATTERNS)
        self.embed = list(_EMBED_PATTERNS)
        self.replicated = list(_REPLICATED_PATTERNS)
        if policy is not None:
            extra = self._policies.get(policy.lower())
            if extra is None:
                logger.warning(f"AutoTP: no policy '{policy}', using default")
            else:
                self.column += extra.get("column", [])
                self.row += extra.get("row", [])
                self.embed += extra.get("embed", [])
                self.replicated += extra.get("replicated", [])

    @classmethod
    def register_policy(cls, name: str, column=(), row=(), embed=(),
                        replicated=()):
        """Reference replace_policy registry analog."""
        cls._policies[name.lower()] = {
            "column": list(column), "row": list(row),
            "embed": list(embed), "replicated": list(replicated)}

    # -- classification --------------------------------------------------
    @staticmethod
    def _match(path: str, patterns: Sequence[str]) -> bool:
        low = path.lower()
        return any(re.search(p, low) for p in patterns)

    def classify(self, path: str, shape: Tuple[int, ...]) -> str:
        """'column' | 'row' | 'embed' | 'replicated'."""
        if len(shape) < 2 or self._match(path, self.replicated):
            return "replicated"
        if self._match(path, self.embed):
            return "embed"
        if self._match(path, self.column):
            return "column"
        if self._match(path, self.row):
            return "row"
        return "replicated"

    def spec_for(self, path: str, shape: Tuple[int, ...]) -> P:
        """PartitionSpec for one param. Convention: 2-D weights are
        [in, out] (jax matmul layout); stacked-layer tensors carry a
        leading layer axis that stays unsharded."""
        kind = self.classify(path, shape)
        lead = [None] * (len(shape) - 2)
        if kind == "column":
            return P(*lead, None, self.tp_axis)
        if kind == "row":
            return P(*lead, self.tp_axis, None)
        if kind == "embed":
            # [vocab, hidden]: shard vocab (reference VocabParallelEmbedding)
            return P(*lead, self.tp_axis, None) if len(shape) >= 2 else P()
        return P(*[None] * len(shape))

    def infer_specs(self, params) -> Any:
        """PartitionSpec pytree mirroring ``params`` (dicts, lists, and
        tuples all recurse — HF-Flax trees mix them)."""
        def walk(tree, prefix=""):
            if isinstance(tree, dict):
                return {k: walk(v, f"{prefix}{SEP}{k}" if prefix else str(k))
                        for k, v in tree.items()}
            if isinstance(tree, (list, tuple)):
                vals = [walk(v, f"{prefix}{SEP}{i}" if prefix else str(i))
                        for i, v in enumerate(tree)]
                return vals if isinstance(tree, list) else tuple(vals)
            shape = tuple(getattr(tree, "shape", ()) or ())
            return self.spec_for(prefix, shape)

        return walk(params)

    def summary(self, params) -> Dict[str, int]:
        counts = {"column": 0, "row": 0, "embed": 0, "replicated": 0}

        def walk(tree, prefix=""):
            if isinstance(tree, dict):
                for k, v in tree.items():
                    walk(v, f"{prefix}{SEP}{k}" if prefix else str(k))
            elif isinstance(tree, (list, tuple)):
                for i, v in enumerate(tree):
                    walk(v, f"{prefix}{SEP}{i}" if prefix else str(i))
            else:
                counts[self.classify(
                    prefix, tuple(getattr(tree, "shape", ()) or ()))] += 1

        walk(params)
        return counts


def _divisible(shape: Tuple[int, ...], spec: P, mesh: Mesh) -> bool:
    for dim, axis in zip(shape, tuple(spec) + (None,) * len(shape)):
        if axis is None:
            continue
        if dim % mesh.shape[axis] != 0:
            return False
    return True


def tp_model_init(params, mesh: Optional[Mesh] = None, tp_size: int = 0,
                  policy: Optional[str] = None, dtype=None):
    """Shard a parameter tree for tensor-parallel execution
    (reference ``deepspeed.tp_model_init`` __init__.py:408).

    Returns (sharded_params, spec_tree). Params whose shapes don't divide
    the tp axis fall back to replicated (with a warning), matching the
    reference's partial-injection behavior.
    """
    from deepspeed_tpu.parallel import topology as topo

    if mesh is None:
        if tp_size <= 0:
            raise ValueError("tp_model_init needs mesh or tp_size")
        mesh = topo.build_mesh(topo.TopologyConfig(tp=tp_size, dp=-1))
    atp = AutoTP(policy=policy)
    specs = atp.infer_specs(params)

    def place(x, spec):
        shape = tuple(getattr(x, "shape", ()) or ())
        if not _divisible(shape, spec, mesh):
            logger.warning(
                f"AutoTP: shape {shape} not divisible by tp axis for spec "
                f"{spec}; replicating")
            spec = P(*[None] * len(shape))
        arr = jax.numpy.asarray(x)
        if dtype is not None:
            arr = arr.astype(dtype)
        return jax.device_put(arr, NamedSharding(mesh, spec))

    sharded = jax.tree.map(place, params, specs,
                           is_leaf=lambda x: not isinstance(
                               x, (dict, list, tuple)))
    counts = atp.summary(params)
    log_dist(f"AutoTP over tp={mesh.shape.get('tp', 1)}: {counts}",
             ranks=[0])
    return sharded, specs


# built-in per-arch policies (reference containers/: llama, gpt2, bloom...)
AutoTP.register_policy("llama", column=[r"gate_proj", r"up_proj"],
                       row=[r"down_proj", r"o_proj"])
AutoTP.register_policy("gpt2", column=[r"c_attn", r"c_fc"],
                       row=[r"c_proj"])
AutoTP.register_policy("bloom", column=[r"query_key_value",
                                        r"dense_h_to_4h"],
                       row=[r"dense_4h_to_h", r"attention.dense"])
AutoTP.register_policy("mistral", column=[r"gate_proj", r"up_proj"],
                       row=[r"down_proj", r"o_proj"])
