"""Rank-aware logging utilities.

TPU-native analog of the reference's ``deepspeed/utils/logging.py``
(``logger``, ``log_dist`` — reference: deepspeed/utils/logging.py:52,104).
On TPU multi-host, "rank" means ``jax.process_index()``.
"""

from __future__ import annotations

import functools
import logging
import os
import sys

LOG_LEVEL_ENV = "DSTPU_LOG_LEVEL"

log_levels = {
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "critical": logging.CRITICAL,
}


@functools.lru_cache(None)
def _create_logger(name: str = "deepspeed_tpu") -> logging.Logger:
    logger_ = logging.getLogger(name)
    logger_.propagate = False
    level = log_levels.get(os.environ.get(LOG_LEVEL_ENV, "info").lower(), logging.INFO)
    logger_.setLevel(level)
    if not logger_.handlers:
        handler = logging.StreamHandler(stream=sys.stdout)
        handler.setFormatter(
            logging.Formatter(
                "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"
            )
        )
        logger_.addHandler(handler)
    return logger_


logger = _create_logger()


def _process_index() -> int:
    """Current host index; 0 before jax.distributed init or single-host."""
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover - jax always importable in practice
        return 0


def log_dist(message: str, ranks=None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the listed host ranks (None/-1 = all).

    Parity with reference ``log_dist`` (deepspeed/utils/logging.py:104).
    """
    my_rank = _process_index()
    if ranks is None or -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def print_rank_0(message: str) -> None:
    if _process_index() == 0:
        print(message, flush=True)


def warning_once(message: str) -> None:
    if message not in _seen_warnings:
        _seen_warnings.add(message)
        logger.warning(message)


_seen_warnings: set = set()
