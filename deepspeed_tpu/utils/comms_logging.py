"""Communication-op logging.

Analog of the reference ``CommsLogger`` (deepspeed/utils/comms_logging.py:67)
rethought for XLA: collectives execute inside compiled programs, so per-call
wall-clock timing is not observable from Python. Instead we record each
collective at **trace time** (op name, tensor bytes, mesh axes) — giving
exact per-step communication volume counts — and let ``log_summary`` report
volumes; latency/bandwidth comes from the profiler (see
deepspeed_tpu/profiling/).
"""

from __future__ import annotations

import dataclasses
from collections import defaultdict
from typing import Any, Dict, Optional, Tuple

from deepspeed_tpu.utils.logging import log_dist, logger


def convert_size(size_bytes: float) -> str:
    if size_bytes <= 0:
        return "0B"
    units = ("B", "KB", "MB", "GB", "TB", "PB")
    i = 0
    while size_bytes >= 1024 and i < len(units) - 1:
        size_bytes /= 1024.0
        i += 1
    return f"{size_bytes:.2f} {units[i]}"


@dataclasses.dataclass
class OpRecord:
    count: int = 0
    total_bytes: int = 0
    max_bytes: int = 0


class CommsLogger:
    """Trace-time collective recorder (singleton via get_comms_logger)."""

    def __init__(self):
        self.enabled = False
        self.verbose = False
        self.prof_all = True
        self.prof_ops: list = []
        self.comms_dict: Dict[str, Dict[Tuple, OpRecord]] = defaultdict(
            lambda: defaultdict(OpRecord)
        )

    def configure(self, comms_config) -> None:
        self.enabled = comms_config.enabled
        self.verbose = comms_config.verbose
        self.prof_all = comms_config.prof_all
        self.prof_ops = list(comms_config.prof_ops or [])

    def _should_log(self, op_name: str) -> bool:
        if not self.enabled:
            return False
        if self.prof_ops and op_name not in self.prof_ops:
            return False
        return True

    def record(self, op_name: str, nbytes: int, axis: Any, log_name: Optional[str] = None) -> None:
        name = log_name or op_name
        if not self._should_log(name):
            return
        key = (str(axis),)
        rec = self.comms_dict[name][key]
        rec.count += 1
        rec.total_bytes += int(nbytes)
        rec.max_bytes = max(rec.max_bytes, int(nbytes))
        if self.verbose:
            log_dist(
                f"comm op: {name} | axis: {axis} | size: {convert_size(nbytes)}",
                ranks=[0],
            )

    def reset(self) -> None:
        self.comms_dict.clear()

    def totals(self) -> Dict[str, int]:
        """Cumulative traced bytes per op, summed over axes — the shape
        the observability hub snapshots each step to compute per-step
        communication deltas. Remember these are trace-time volumes: a
        re-executed compiled step adds nothing here."""
        out: Dict[str, int] = {}
        for op_name, per_axis in self.comms_dict.items():
            out[op_name] = sum(rec.total_bytes for rec in per_axis.values())
        return out

    def log_summary(self) -> str:
        """Per-op traced communication volume (per compiled step)."""
        lines = [f"{'Comm op':<28}{'Axis':<22}{'Count':<8}{'Total traced':<16}{'Max msg':<12}"]
        for op_name, per_axis in sorted(self.comms_dict.items()):
            for key, rec in sorted(per_axis.items()):
                lines.append(
                    f"{op_name:<28}{key[0]:<22}{rec.count:<8}"
                    f"{convert_size(rec.total_bytes):<16}{convert_size(rec.max_bytes):<12}"
                )
        summary = "\n".join(lines)
        log_dist("\n" + summary, ranks=[0])
        return summary


_COMMS_LOGGER: Optional[CommsLogger] = None


def get_comms_logger() -> CommsLogger:
    global _COMMS_LOGGER
    if _COMMS_LOGGER is None:
        _COMMS_LOGGER = CommsLogger()
    return _COMMS_LOGGER
