"""Memory breadcrumbs.

Reference: ``see_memory_usage`` (deepspeed/utils/timer.py + engine
breadcrumbs) prints allocated/reserved accelerator memory and host RSS at
checkpoints through engine construction; gated by the ``memory_breakdown``
config.

TPU: device numbers come from ``Device.memory_stats()`` (PJRT; absent on
some backends, then only host stats print), host RSS from /proc.
"""

from __future__ import annotations

import os
from typing import Optional

import jax

from deepspeed_tpu.utils.logging import logger


def _host_mem_gb() -> dict:
    out = {}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith(("VmRSS", "VmHWM")):
                    k, v = line.split(":", 1)
                    out[k] = round(int(v.split()[0]) / 1024 / 1024, 2)
    except OSError:
        pass
    return out


def device_memory_stats(device=None) -> Optional[dict]:
    device = device or jax.local_devices()[0]
    stats = getattr(device, "memory_stats", lambda: None)()
    if not stats:
        return None
    gb = 1024 ** 3
    return {
        "in_use_gb": round(stats.get("bytes_in_use", 0) / gb, 3),
        "peak_gb": round(stats.get("peak_bytes_in_use", 0) / gb, 3),
        "limit_gb": round(stats.get("bytes_limit", 0) / gb, 3),
        "largest_free_block_gb": round(
            stats.get("largest_free_block_bytes", 0) / gb, 3),
    }


MEMORY_BREAKDOWN = False  # set from config.memory_breakdown at engine init


def configure(enabled: bool) -> None:
    global MEMORY_BREAKDOWN
    MEMORY_BREAKDOWN = bool(enabled)


def see_memory_usage(message: str, force: bool = False) -> Optional[dict]:
    """Log device + host memory with ``message`` (reference signature:
    breadcrumbs are no-ops unless force or memory_breakdown config)."""
    if not (force or MEMORY_BREAKDOWN):
        return None
    dev = device_memory_stats()
    host = _host_mem_gb()
    parts = [message]
    if dev:
        parts.append(f"device in_use={dev['in_use_gb']}GB "
                     f"peak={dev['peak_gb']}GB limit={dev['limit_gb']}GB")
    if host:
        parts.append(f"host rss={host.get('VmRSS')}GB "
                     f"hwm={host.get('VmHWM')}GB")
    logger.info(" | ".join(parts))
    return dev
