"""Profiler range annotations.

Reference: ``instrument_w_nvtx`` (deepspeed/utils/nvtx.py:25) +
``accelerator.range_push/pop`` wrap hot functions in NVTX ranges for
nsight timelines.

TPU: the analogs are ``jax.profiler.TraceAnnotation`` (host-side trace
ranges, visible in TensorBoard/perfetto captures) and ``jax.named_scope``
(names carried into the compiled HLO). ``instrument_w_profiler`` applies
both, so a wrapped function is findable in either view.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax


def range_push(name: str):
    """Open a trace range (reference accelerator.range_push). Returns the
    annotation object; pass it to range_pop."""
    ann = jax.profiler.TraceAnnotation(name)
    ann.__enter__()
    return ann


def range_pop(ann) -> None:
    ann.__exit__(None, None, None)


def instrument_w_profiler(fn: Callable = None, name: str = None) -> Callable:
    """Decorator: run ``fn`` inside a TraceAnnotation + named_scope
    (reference instrument_w_nvtx)."""
    if fn is None:
        return functools.partial(instrument_w_profiler, name=name)
    label = name or getattr(fn, "__qualname__", getattr(fn, "__name__", "fn"))

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        with jax.profiler.TraceAnnotation(label), jax.named_scope(label):
            return fn(*args, **kwargs)

    return wrapped


# reference-name alias so ported user code keeps working
instrument_w_nvtx = instrument_w_profiler
