"""Init-time device/dtype scoping.

Reference: ``OnDevice`` (deepspeed/utils/init_on_device.py) — a context
manager that builds models directly on a target device ("meta" for
shape-only instantiation, used to stand up trillion-param models without
materializing weights).

TPU re-design: JAX params are explicit trees, so the context simply
scopes *how* ``model.init`` materializes them:

  * ``device="meta"``  → ``jax.eval_shape`` abstract tree (no memory) —
    the ``zero.Init``-adjacent path; engines later do shard-aware init.
  * ``device="cpu"``   → host-side arrays (init big models in host RAM).
  * ``device="device"``→ default backend placement (the normal path).

Model constructors cooperate via ``OnDevice.current()`` (TransformerLM
checks it inside ``init``); any other init function can be wrapped with
``OnDevice.apply(fn, *args)``.
"""

from __future__ import annotations

import contextlib
from typing import Any, Optional

import jax


def _inside_trace() -> bool:
    """True while a jit/scan/grad trace is being staged."""
    try:
        from jax._src.core import trace_state_clean

        return not trace_state_clean()
    except Exception:  # private API moved: compare opaque trace state
        try:
            return (jax.core.get_opaque_trace_state()
                    != _EAGER_TRACE_STATE)
        except Exception:
            return False


try:
    _EAGER_TRACE_STATE = jax.core.get_opaque_trace_state()
except Exception:  # pragma: no cover
    _EAGER_TRACE_STATE = None


class OnDevice:
    """``with OnDevice(dtype=jnp.bfloat16, device="meta"): model.init(...)``"""

    _stack: list = []

    def __init__(self, dtype: Optional[Any] = None, device: str = "device",
                 enabled: bool = True):
        if device not in ("meta", "cpu", "device"):
            raise ValueError(f"device must be meta|cpu|device, got {device!r}")
        self.dtype = dtype
        self.device = device
        self.enabled = enabled
        self._cm = None

    @classmethod
    def current(cls) -> Optional["OnDevice"]:
        return cls._stack[-1] if cls._stack else None

    def __enter__(self):
        OnDevice._stack.append(self)
        if self.enabled and self.device == "cpu":
            self._cm = jax.default_device(jax.devices("cpu")[0])
            self._cm.__enter__()
        return self

    def __exit__(self, *exc):
        OnDevice._stack.pop()
        if self._cm is not None:
            self._cm.__exit__(*exc)
            self._cm = None
        return False

    @classmethod
    def apply(cls, init_fn, *args, **kwargs):
        """Run ``init_fn`` under the active context: abstract under
        "meta", eager otherwise; float leaves cast to the context dtype.

        Inside a jit trace the context is ignored: engines jit their init
        (runtime/engine.py), and an abstract/host-pinned tree cannot be a
        traced output — the context governs only eager construction.
        """
        ctx = cls.current()
        tracing = _inside_trace()
        if ctx is None or not ctx.enabled or tracing:
            return init_fn(*args, **kwargs)

        def cast(tree):
            if ctx.dtype is None:
                return tree
            import jax.numpy as jnp

            def one(x):
                if jnp.issubdtype(x.dtype, jnp.floating):
                    if isinstance(x, jax.ShapeDtypeStruct):
                        return jax.ShapeDtypeStruct(x.shape, ctx.dtype)
                    return x.astype(ctx.dtype)
                return x

            return jax.tree.map(one, tree)

        if ctx.device == "meta":
            return cast(jax.eval_shape(lambda: init_fn(*args, **kwargs)))
        return cast(init_fn(*args, **kwargs))


@contextlib.contextmanager
def on_device(dtype=None, device: str = "device", enabled: bool = True):
    """Functional alias of OnDevice (reference exports both styles)."""
    with OnDevice(dtype=dtype, device=device, enabled=enabled) as ctx:
        yield ctx
