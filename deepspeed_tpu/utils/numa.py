"""NUMA / CPU-core binding helpers for launched worker processes.

Reference: deepspeed/utils/numa.py (get_numa_cores, check_for_numactl,
parse_range_list) used by launcher/launch.py ``--bind_cores_to_rank`` to
pin each local rank to a distinct core range. On TPU hosts the analog
matters for the host-side threads (data loading, AIO swap workers, host
optimizers): pinning them away from the runtime's dispatch threads
removes jitter.

Pure-procfs implementation (no numactl dependency): node topology is read
from /sys/devices/system/node; binding uses ``os.sched_setaffinity``.
"""

from __future__ import annotations

import glob
import os
import re
from typing import Dict, List, Optional, Sequence


def parse_range(rng: str) -> List[int]:
    """'0-3' -> [0,1,2,3]; '7' -> [7]."""
    rng = rng.strip()
    if "-" in rng:
        lo, hi = rng.split("-", 1)
        return list(range(int(lo), int(hi) + 1))
    return [int(rng)]


def parse_range_list(spec: str) -> List[int]:
    """'0-3,8,10-11' -> [0,1,2,3,8,10,11] (reference numa.py parse_range_list)."""
    out: List[int] = []
    for part in spec.split(","):
        part = part.strip()
        if part:
            out.extend(parse_range(part))
    return sorted(set(out))


def get_numa_cores() -> List[List[int]]:
    """Per-NUMA-node core id lists, [[node0 cores...], [node1 cores...], ...].

    Falls back to a single node holding every online CPU when the sysfs
    topology is unavailable (containers often mask it).
    """
    nodes: Dict[int, List[int]] = {}
    for path in glob.glob("/sys/devices/system/node/node[0-9]*/cpulist"):
        m = re.search(r"node(\d+)", path)
        if not m:
            continue
        try:
            with open(path) as f:
                nodes[int(m.group(1))] = parse_range_list(f.read())
        except OSError:
            continue
    if nodes:
        return [nodes[k] for k in sorted(nodes)]
    return [sorted(os.sched_getaffinity(0))]


def cores_for_rank(local_rank: int, local_size: int,
                   cores: Optional[Sequence[int]] = None) -> List[int]:
    """Even, NUMA-contiguous slice of host cores for one local rank.

    Mirrors the reference launcher's --bind_cores_to_rank split
    (launch.py --bind_core_list): cores are divided into ``local_size``
    contiguous chunks; remainder cores go to the leading ranks.
    """
    if not 0 <= local_rank < local_size:
        raise ValueError(f"local_rank {local_rank} not in [0, {local_size})")
    if cores is None:
        cores = [c for node in get_numa_cores() for c in node]
    cores = list(cores)
    n = len(cores)
    base, rem = divmod(n, local_size)
    if base == 0:
        # more ranks than cores: round-robin single cores
        return [cores[local_rank % n]]
    start = local_rank * base + min(local_rank, rem)
    count = base + (1 if local_rank < rem else 0)
    return cores[start:start + count]


def bind_current_process(local_rank: int, local_size: int,
                         core_list: Optional[str] = None) -> List[int]:
    """Pin the calling process to its rank's core slice; returns the slice.

    ``core_list`` optionally restricts the pool ('0-15,32-47' syntax).
    """
    pool = parse_range_list(core_list) if core_list else None
    chosen = cores_for_rank(local_rank, local_size, pool)
    try:
        os.sched_setaffinity(0, chosen)
    except OSError:  # insufficient privileges / masked cpus: best effort
        pass
    os.environ["OMP_NUM_THREADS"] = str(max(1, len(chosen)))
    return chosen
