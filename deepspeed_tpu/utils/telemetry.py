"""Capability-downgrade telemetry: no silent fallbacks.

The reference surfaces engine downgrades through logs and counters (e.g.
the FastGen scheduler stats, inference/v2/ragged); round-2/3 reviews
flagged our own silent downgrades (grouped MoE -> capacity einsum, flash
-> XLA attention, ring -> dense) as the one anti-pattern the serve-path
telemetry in inference/engine_v2.py:89 had already solved locally. This
module is the process-wide version of that pattern: every capability
fallback calls :func:`count` with a stable counter name and a reason;
tests and users query :func:`get`/:func:`snapshot`.

Counters are plain Python ints incremented at *trace/dispatch* time (all
fallback decisions in this codebase are static — mesh shapes, dtypes,
geometry — so they happen outside jit-compiled code). Consequence: a
count() reached from inside a jit-traced function fires once per
*compilation* (distinct compiled configuration), not once per executed
step — during steady-state training the counter stays flat because jit
replays the cached executable. Read counters as "how many distinct
downgraded configs were built", and don't assert exact values in tests
that may retrace.
"""

from __future__ import annotations

import threading
from typing import Dict

from deepspeed_tpu.utils.logging import logger

_LOCK = threading.Lock()
_COUNTERS: Dict[str, int] = {}
_REASONS: Dict[str, Dict[str, int]] = {}
_LOGGED: set = set()


def count(name: str, reason: str = "") -> None:
    """Record one occurrence of the named fallback/downgrade.

    Logs a warning the first time each (name, reason) pair fires so the
    downgrade is visible exactly once per process, then keeps counting
    silently (queryable via :func:`get`). When called during jit
    tracing, "occurrence" means one per compiled configuration, not one
    per step (see module docstring).
    """
    with _LOCK:
        _COUNTERS[name] = _COUNTERS.get(name, 0) + 1
        if reason:
            per = _REASONS.setdefault(name, {})
            per[reason] = per.get(reason, 0) + 1
        key = (name, reason)
        if key not in _LOGGED:
            _LOGGED.add(key)
            logger.warning(
                f"capability fallback '{name}'"
                + (f": {reason}" if reason else "")
                + " (telemetry.get(%r) counts occurrences)" % name)


def get(name: str) -> int:
    """Occurrences of the named fallback since process start / reset."""
    with _LOCK:
        return _COUNTERS.get(name, 0)


def reasons(name: str) -> Dict[str, int]:
    with _LOCK:
        return dict(_REASONS.get(name, {}))


def snapshot() -> Dict[str, int]:
    with _LOCK:
        return dict(_COUNTERS)


def reset() -> None:
    """Zero all counters (tests)."""
    with _LOCK:
        _COUNTERS.clear()
        _REASONS.clear()
        _LOGGED.clear()
