"""Helpers for the multi-device CPU simulator on small hosts.

The 8-virtual-device CPU mesh (tests, dryrun) deadlocks on low-core
hosts when independent collectives race: XLA's CPU thread pool is sized
max(cores, devices), so every worker can end up blocked in a collective
rendezvous with no spare worker to run the partner collective (observed
as "Expected 8 threads to join the rendezvous, but only 4 arrived",
then abort). csrc/hostsim/affinity_shim.c widens the reported CPU
affinity so the pool gets headroom; this module compiles it on demand
and injects LD_PRELOAD into a subprocess env.
"""

from __future__ import annotations

import os
import subprocess
import tempfile
from typing import Dict, Optional

_SHIM_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "csrc", "hostsim",
    "affinity_shim.c")


def build_affinity_shim() -> Optional[str]:
    """Compile (once) and return the shim path, or None when impossible.

    Per-uid target path (no cross-user /tmp planting) and an atomic
    rename from a private temp file (concurrent builders race safely —
    last rename wins with identical content, and no reader ever sees a
    half-written .so)."""
    uid = os.getuid() if hasattr(os, "getuid") else 0
    out = os.path.join(tempfile.gettempdir(),
                       f"dstpu_affinity_shim_{uid}.so")
    if not os.path.exists(_SHIM_SRC):
        return out if os.path.exists(out) else None
    if (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(_SHIM_SRC)):
        return out  # cached build is current (rebuilt when source changes)
    for cc in ("cc", "gcc", "clang"):
        fd, tmp = tempfile.mkstemp(suffix=".so",
                                   dir=tempfile.gettempdir())
        os.close(fd)
        try:
            r = subprocess.run([cc, "-shared", "-fPIC", "-O2", "-o", tmp,
                                _SHIM_SRC], capture_output=True, timeout=60)
            if r.returncode == 0:
                os.replace(tmp, out)
                return out
        except (OSError, subprocess.TimeoutExpired):
            pass
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
    return None


def cpu_sim_env(env: Optional[Dict[str, str]] = None,
                n_devices: int = 8) -> Dict[str, str]:
    """Subprocess env for an ``n_devices`` CPU-sim worker: thread-pool
    headroom via the affinity shim when the host has fewer cores than
    virtual devices (no-op on big hosts)."""
    env = dict(env if env is not None else os.environ)
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:  # non-Linux
        cores = os.cpu_count() or 1
    if cores >= 2 * n_devices:
        return env
    shim = build_affinity_shim()
    if shim:
        pre = env.get("LD_PRELOAD", "")
        if shim not in pre:
            env["LD_PRELOAD"] = f"{shim}:{pre}" if pre else shim
    return env
