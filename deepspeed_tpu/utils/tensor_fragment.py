"""Fragment APIs: debugging access to partitioned state.

Parity with the reference's ``deepspeed/utils/tensor_fragment.py``
(``safe_get_full_fp32_param`` :134, ``safe_set_full_fp32_param``,
``safe_get_local_fp32_param``, ``safe_get_full_optimizer_state``,
``safe_get_full_grad``) — the user-facing escape hatch for reading/writing
ZeRO-partitioned master weights and optimizer state.

On TPU, "full" means the global logical array (jax assembles it across
shards on read) and "local" means this host's addressable shard — the
exact ds_tensor/full-param duality of ZeRO-3, but derived from named
sharding instead of partition bookkeeping.

Params are addressed by path: ``"layers/attn/wq"`` walks the param
pytree by dict keys.
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import numpy as np


def _walk(tree, path: str):
    node = tree
    for key in path.strip("/").split("/"):
        if isinstance(node, dict):
            if key not in node:
                raise KeyError(
                    f"param path '{path}': no key '{key}'; "
                    f"available: {sorted(node)}")
            node = node[key]
        else:
            node = getattr(node, key)
    return node


def _set_leaf(tree, path: str, value):
    keys = path.strip("/").split("/")
    node = tree
    for key in keys[:-1]:
        node = node[key]
    node[keys[-1]] = value


def _to_host(x: jax.Array) -> np.ndarray:
    """Gather a (possibly sharded) global array to host."""
    return np.asarray(jax.device_get(x))


def _offload_keystr(engine, path: str) -> str:
    """'layers/attn/wq' → the jax.tree_util.keystr form used as the
    offload optimizer's leaf key: \"['layers']['attn']['wq']\"."""
    return "".join(f"[{k!r}]" for k in path.strip("/").split("/"))


def safe_get_full_fp32_param(engine, path: str) -> np.ndarray:
    """Full fp32 master weight (reference tensor_fragment.py:134).
    With optimizer offload the masters live host-side
    (runtime/offload.py) and are assembled from local shards."""
    if getattr(engine, "_offload", None) is not None:
        return engine._offload.full_fp32_param(_offload_keystr(engine, path))
    return _to_host(_walk(engine.opt_state.master, path))


def safe_get_local_fp32_param(engine, path: str) -> np.ndarray:
    """This process's shard of the fp32 master weight (reference
    safe_get_local_fp32_param)."""
    if getattr(engine, "_offload", None) is not None:
        return engine._offload.local_fp32_param(_offload_keystr(engine, path))
    leaf = _walk(engine.opt_state.master, path)
    return np.asarray(leaf.addressable_shards[0].data)


def safe_set_full_fp32_param(engine, path: str, value) -> None:
    """Overwrite a master weight (resharded automatically) and refresh the
    compute-dtype copy (reference safe_set_full_fp32_param)."""
    params_leaf = _walk(engine.params, path)
    if getattr(engine, "_offload", None) is not None:
        engine._offload.set_full_fp32_param(_offload_keystr(engine, path),
                                            value)
        new = np.asarray(value, dtype=np.float32)
        _set_leaf(engine.params, path,
                  jax.device_put(new.astype(params_leaf.dtype),
                                 params_leaf.sharding))
        return
    master = _walk(engine.opt_state.master, path)
    new = jax.device_put(np.asarray(value, dtype=np.float32), master.sharding)
    _set_leaf(engine.opt_state.master, path, new)
    _set_leaf(engine.params, path,
              jax.device_put(new.astype(params_leaf.dtype),
                             params_leaf.sharding))


def safe_get_full_optimizer_state(engine, path: str, state_key: str
                                  ) -> Optional[np.ndarray]:
    """Optimizer state for one param, e.g. state_key='exp_avg' / 'exp_avg_sq'
    (reference safe_get_full_optimizer_state). Torch names map to optax:
    exp_avg → mu, exp_avg_sq → nu, momentum → trace/mu."""
    if getattr(engine, "_offload", None) is not None:
        # host optimizers use the torch names directly (exp_avg/exp_avg_sq)
        return engine._offload.full_optimizer_state(
            _offload_keystr(engine, path), state_key)
    alias = {"exp_avg": ("mu", "trace", "momentum"),
             "exp_avg_sq": ("nu",),
             "momentum": ("trace", "mu")}
    candidates = alias.get(state_key, (state_key,))
    for node in _iter_state_nodes(engine.opt_state.inner):
        for name in candidates:
            if hasattr(node, name):
                sub = getattr(node, name)
                try:
                    return _to_host(_walk(sub, path))
                except (KeyError, TypeError, AttributeError):
                    continue
    return None


def _iter_state_nodes(state) -> List[Any]:
    """Flatten optax's nested chain/namedtuple state into candidate nodes."""
    out = []

    def visit(node):
        if hasattr(node, "_fields"):  # namedtuple
            out.append(node)
            for f in node._fields:
                visit(getattr(node, f))
        elif isinstance(node, (tuple, list)):
            for item in node:
                visit(item)

    visit(state)
    return out


def safe_get_full_grad(engine, path: str) -> Optional[np.ndarray]:
    """Accumulated gradient between backward() and step() (reference
    safe_get_full_grad; only populated on the micro-step path — the fused
    train_batch path never exposes grads, they live inside the compiled
    program)."""
    if engine._grad_acc is None:
        return None
    return _to_host(_walk(engine._grad_acc, path))
