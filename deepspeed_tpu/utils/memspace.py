"""Backend-portable memory-space placement.

The offload tier talks to XLA memory spaces through two jax APIs that
drift across versions and backends:

  * ``jax.memory.Space.Device`` / ``.Host`` — added in jax 0.5; older
    jax spells the same transfer ``TransferToMemoryKind("pinned_host")``
    (still importable from ``jax._src.sharding_impls``).
  * ``Sharding.with_memory_kind("pinned_host" | "device")`` — raises on
    backends whose devices expose no such space. The CPU simulator is
    the important case: its only addressable memory is ``unpinned_host``,
    where host/device distinction is physically moot — every placement
    lands in the same DRAM, so degrading to the array's existing
    placement preserves the exact numerics the tests assert on.

Every memory-space placement in the tree goes through this module so
the TPU fast path and the CPU test path share one degradation policy
instead of per-call-site try/excepts.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax

_PLACEABLE = ("device", "pinned_host")


@functools.lru_cache(maxsize=None)
def backend_memory_kinds() -> frozenset:
    """Memory kinds addressable by device 0 (initializes the backend)."""
    try:
        return frozenset(
            m.kind for m in jax.devices()[0].addressable_memories())
    except Exception:
        return frozenset()


def memories_supported() -> bool:
    """True when the backend has distinct device/host memory spaces."""
    return "pinned_host" in backend_memory_kinds()


def space(kind: str) -> Optional[Any]:
    """A ``jax.device_put`` target for ``kind`` ('device'/'pinned_host'),
    or None when the backend has no such space (caller must no-op)."""
    assert kind in _PLACEABLE, kind
    if not memories_supported():
        return None
    mem = getattr(jax, "memory", None)
    if mem is not None:
        return mem.Space.Device if kind == "device" else mem.Space.Host
    from jax._src.sharding_impls import TransferToMemoryKind

    return TransferToMemoryKind(kind)


def put(a: Any, kind: str) -> Any:
    """``device_put`` into the given memory space; identity when the
    backend has only one space. Safe inside jit (the no-op branch is
    resolved at trace time)."""
    tgt = space(kind)
    return a if tgt is None else jax.device_put(a, tgt)


def put_tree(tree: Any, kind: str) -> Any:
    return jax.tree.map(lambda a: put(a, kind), tree)


def with_memory_kind(sharding: Any, kind: str) -> Any:
    """``sharding.with_memory_kind(kind)`` degrading to identity when the
    backend lacks the space (or the sharding has no memory-kind API)."""
    if sharding is None or not memories_supported():
        return sharding
    try:
        return sharding.with_memory_kind(kind)
    except (ValueError, AttributeError):
        return sharding


def memory_kind_of(a: Any) -> Optional[str]:
    """The array's memory kind, or None when unknowable."""
    return getattr(getattr(a, "sharding", None), "memory_kind", None)


def is_on_host(a: Any) -> bool:
    """True when ``a`` demonstrably lives in the pinned-host space. On
    single-space backends this is always False — callers branching on it
    treat device placement as the degenerate truth."""
    return memory_kind_of(a) == "pinned_host"
