"""Wall-clock and throughput timers.

Analog of the reference's ``SynchronizedWallClockTimer`` / ``ThroughputTimer``
(deepspeed/utils/timer.py:44,199). "Synchronized" on TPU means calling
``jax.block_until_ready`` on step outputs before stopping — there is no
per-stream event timer; fine-grained device timing comes from the XLA
profiler instead (CudaEventTimer has no analog, utils/timer.py:32).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from deepspeed_tpu.utils.logging import log_dist

FORWARD_MICRO_TIMER = "fwd_microstep"
FORWARD_GLOBAL_TIMER = "fwd"
BACKWARD_MICRO_TIMER = "bwd_microstep"
BACKWARD_GLOBAL_TIMER = "bwd"
STEP_MICRO_TIMER = "step_microstep"
STEP_GLOBAL_TIMER = "step"
TRAIN_BATCH_TIMER = "train_batch"


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self.started = False
        self._start = 0.0
        self._elapsed = 0.0
        self.records: List[float] = []

    def start(self, block=None):
        if self.started:
            return
        if block is not None:
            import jax

            jax.block_until_ready(block)
        self._start = time.perf_counter()
        self.started = True

    def stop(self, record: bool = True, block=None):
        if not self.started:
            return
        if block is not None:
            import jax

            jax.block_until_ready(block)
        self._elapsed += time.perf_counter() - self._start
        self.started = False
        if record:
            self.records.append(self._elapsed * 1000.0)
            self._elapsed = 0.0

    def record_ms(self, value_ms: float):
        """Record an externally-measured duration. The pipelined loop
        (dispatch-ahead) measures a step's wall time drain-to-drain —
        start/stop pairs cannot nest across overlapping in-flight steps,
        so the engine computes the span itself and records it here."""
        self.records.append(float(value_ms))

    def elapsed(self, reset: bool = True) -> float:
        """Milliseconds."""
        now = time.perf_counter()
        value = self._elapsed * 1000.0
        if self.started:
            value += (now - self._start) * 1000.0
        if reset:
            self._elapsed = 0.0
            if self.started:
                self._start = now  # restart so the in-flight span isn't recounted
        return value

    def mean(self) -> float:
        return sum(self.records) / len(self.records) if self.records else 0.0

    def reset(self):
        self.started = False
        self._elapsed = 0.0
        self.records = []


class SynchronizedWallClockTimer:
    """Named-timer registry (reference utils/timer.py:44)."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)
        return self.timers[name]

    def log(self, names: List[str], normalizer: float = 1.0, reset: bool = True,
            memory_breakdown: bool = False, ranks: Optional[List[int]] = None):
        assert normalizer > 0.0
        parts = []
        for name in names:
            if name in self.timers:
                elapsed = self.timers[name].elapsed(reset=reset) / normalizer
                parts.append(f"{name}: {elapsed:.2f}")
        if parts:
            log_dist("time (ms) | " + " | ".join(parts), ranks=ranks or [0])

    def get_mean(self, names: List[str], normalizer: float = 1.0) -> Dict[str, float]:
        return {
            name: self.timers[name].mean() / normalizer
            for name in names
            if name in self.timers
        }


class ThroughputTimer:
    """Samples/sec + TFLOPS tracking (reference utils/timer.py:199)."""

    def __init__(self, batch_size: int, start_step: int = 2,
                 steps_per_output: int = 50, monitor_memory: bool = False):
        self.batch_size = max(1, batch_size)
        self.start_step = start_step
        self.steps_per_output = steps_per_output
        self.global_step_count = 0
        self.total_elapsed_time = 0.0
        self.step_elapsed_time = 0.0
        self._start = 0.0
        self.started = False

    def start(self):
        self.started = True
        self._start = time.perf_counter()

    def stop(self, global_step: bool = True, report_speed: bool = True,
             flops_per_sample: float = 0.0):
        if not self.started:
            return
        self.started = False
        duration = time.perf_counter() - self._start
        self.record(duration, global_step=global_step,
                    report_speed=report_speed,
                    flops_per_sample=flops_per_sample)

    def record(self, duration: float, global_step: bool = True,
               report_speed: bool = True, flops_per_sample: float = 0.0):
        """Account an externally-measured step duration (seconds). The
        dispatch-ahead loop resolves steps out of line with their
        dispatch, so start()/stop() bracketing does not apply there."""
        self.step_elapsed_time += duration
        if not global_step:
            return
        self.global_step_count += 1
        if self.global_step_count > self.start_step:
            self.total_elapsed_time += self.step_elapsed_time
            if report_speed and self.global_step_count % self.steps_per_output == 0:
                tput = self.avg_samples_per_sec()
                msg = (f"step={self.global_step_count}, "
                       f"samples/sec={tput:.2f}, "
                       f"time/step (ms)={self.step_elapsed_time * 1000:.1f}")
                if flops_per_sample:
                    tflops = tput * flops_per_sample / 1e12
                    msg += f", TFLOPS={tflops:.2f}"
                log_dist(msg, ranks=[0])
        self.step_elapsed_time = 0.0

    def avg_samples_per_sec(self) -> float:
        counted = self.global_step_count - self.start_step
        if counted > 0 and self.total_elapsed_time > 0:
            return counted * self.batch_size / self.total_elapsed_time
        return 0.0
