from deepspeed_tpu.utils.logging import logger, log_dist  # noqa: F401
