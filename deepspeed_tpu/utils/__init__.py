from deepspeed_tpu.utils.logging import logger, log_dist  # noqa: F401

# annotate/init_on_device/memory import jax; resolve them lazily (PEP 562)
# so the host-side launcher processes (runner.py, launch.py pre-binding)
# never pay the jax import for `from deepspeed_tpu.utils.logging import ...`
_LAZY = {
    "instrument_w_nvtx": "deepspeed_tpu.utils.annotate",
    "instrument_w_profiler": "deepspeed_tpu.utils.annotate",
    "range_push": "deepspeed_tpu.utils.annotate",
    "range_pop": "deepspeed_tpu.utils.annotate",
    "OnDevice": "deepspeed_tpu.utils.init_on_device",
    "on_device": "deepspeed_tpu.utils.init_on_device",
    "see_memory_usage": "deepspeed_tpu.utils.memory",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        return getattr(importlib.import_module(_LAZY[name]), name)
    raise AttributeError(f"module 'deepspeed_tpu.utils' has no attribute "
                         f"{name!r}")


def set_z3_leaf_modules(patterns):  # reference utils/z3_leaf_module.py
    from deepspeed_tpu.runtime.sharding import set_z3_leaf_modules as _f

    return _f(patterns)


def unset_z3_leaf_modules(patterns=None):
    from deepspeed_tpu.runtime.sharding import unset_z3_leaf_modules as _f

    return _f(patterns)


def get_z3_leaf_modules():
    from deepspeed_tpu.runtime.sharding import get_z3_leaf_modules as _f

    return _f()
