"""Compiled-HLO collective wire-byte accounting.

The qgZ claim (reference blogs/zeropp: ~4x less gradient-reduction
traffic via int8/int4 wire, runtime/comm/coalesced_collectives.py:31)
should be checkable from the program XLA actually compiled, not from one
instruction match. This module parses an HLO text dump and sums the
output bytes of every cross-device collective, keyed by op kind and
element type — tests and docs divide full-width vs quantized totals.

Byte accounting uses the collective's OUTPUT tensor(s): for all-to-all,
all-gather, collective-permute and all-reduce the output is the moved
payload (within a constant factor per algorithm); comparing two programs
of the same structure cancels the constant.
"""

from __future__ import annotations

import re
from typing import Dict, Tuple

_DTYPE_BITS = {
    "pred": 8, "s4": 4, "u4": 4, "s8": 8, "u8": 8, "s16": 16, "u16": 16,
    "f16": 16, "bf16": 16, "s32": 32, "u32": 32, "f32": 32,
    "s64": 64, "u64": 64, "f64": 64, "c64": 64, "c128": 128,
}

_COLLECTIVES = ("all-to-all", "all-reduce", "reduce-scatter",
                "all-gather", "collective-permute")

# one tensor type like  f32[8,128]{1,0:T(8,128)}  (layout suffix optional)
_TENSOR_RE = re.compile(r"(\w+)\[([\d,]*)\]")


def _tensor_bytes(dtype: str, dims: str) -> float:
    bits = _DTYPE_BITS.get(dtype)
    if bits is None:
        return 0.0
    n = 1
    for d in dims.split(","):
        if d:
            n *= int(d)
    return n * bits / 8.0


def collective_wire_bytes(hlo_text: str) -> Dict[Tuple[str, str], float]:
    """Sum output bytes of every collective instruction in an HLO dump.

    Returns {(op_kind, dtype): bytes}. ``op_kind`` ∈ all-to-all /
    all-reduce / reduce-scatter / all-gather / collective-permute
    (``-start`` variants fold into their base kind; ``-done`` ops carry
    no new payload and are skipped).
    """
    out: Dict[Tuple[str, str], float] = {}
    for line in hlo_text.splitlines():
        if "=" not in line:
            continue
        _, _, rhs = line.partition("=")
        rhs = rhs.strip()
        # HLO line shape: `name = TYPE opcode(operands), attrs`; TYPE is
        # a tensor type or a tuple of them, between '=' and the opcode
        kind, op_pos, started = None, -1, False
        for c in _COLLECTIVES:
            m = re.search(rf"(?:^|\s){c}(-start)?\(", rhs[:400])
            if m and (op_pos == -1 or m.start() < op_pos):
                kind, op_pos, started = c, m.start(), bool(m.group(1))
        if kind is None:
            continue
        if re.search(r"-done\(", rhs[:400]):
            continue
        type_decl = rhs[:op_pos]
        tensors = [(d, dims) for d, dims in _TENSOR_RE.findall(type_decl)
                   if d in _DTYPE_BITS]
        if started:
            # async `-start` declares a tuple (operands..., results...,
            # u32 context...); summing all entries would double-count the
            # payload ~2x vs the sync form. Context tensors are scalar
            # u32[] — drop those (a genuinely scalar u32 *payload*, e.g.
            # a digest psum, is miscounted by 4 bytes; acceptable), then
            # keep the result half (operands and results pair up, so the
            # last half of the remaining entries — handles coalesced
            # variadic forms with N>1 operand/result pairs; a bare
            # non-tuple result, length 1, is kept whole).
            non_ctx = [(d, dims) for d, dims in tensors
                       if not (d == "u32" and not dims)]
            tensors = non_ctx[len(non_ctx) // 2:]
        for dtype, dims in tensors:
            key = (kind, dtype)
            out[key] = out.get(key, 0.0) + _tensor_bytes(dtype, dims)
    return out


def total_bytes(acct: Dict[Tuple[str, str], float],
                kinds: Tuple[str, ...] = _COLLECTIVES) -> float:
    return sum(v for (k, _), v in acct.items() if k in kinds)


def program_costs(compiled) -> Dict[str, float]:
    """Full cost picture of a compiled executable.

    Combines XLA's cost analysis (flops / bytes accessed /
    transcendentals — the roofline inputs) with this module's
    collective wire-byte accounting over the compiled HLO text. Any
    piece that a given jax version can't produce is reported as 0.0
    rather than raising, so callers can always roofline what they have.
    """
    out = {"flops": 0.0, "bytes_accessed": 0.0, "transcendentals": 0.0,
           "collective_bytes": 0.0}
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):  # older jax wraps in a list
            cost = cost[0] if cost else {}
        out["flops"] = float(cost.get("flops", 0.0))
        out["bytes_accessed"] = float(cost.get("bytes accessed", 0.0))
        out["transcendentals"] = float(cost.get("transcendentals", 0.0))
    except Exception:
        pass
    try:
        out["collective_bytes"] = total_bytes(
            collective_wire_bytes(compiled.as_text()))
    except Exception:
        pass
    return out


def quantized_fraction(acct: Dict[Tuple[str, str], float]) -> float:
    """Fraction of collective bytes moved at <=8-bit element width."""
    tot = total_bytes(acct)
    if tot == 0:
        return 0.0
    narrow = sum(v for (_, d), v in acct.items()
                 if _DTYPE_BITS.get(d, 32) <= 8)
    return narrow / tot
