"""Version-portable wrappers for jax APIs that moved between releases.

The tree targets the jax 0.5+ spellings; older jax (0.4.x, still common
on TPU pods pinned to a libtpu release) keeps the same functionality
under different names/keywords. Everything version-sensitive routes
through here so a jax bump is a one-file change:

  * ``jax.shard_map`` — 0.4.x: ``jax.experimental.shard_map.shard_map``
    with ``auto=`` (complement of ``axis_names``) and ``check_rep=``
    (renamed ``check_vma``).
  * pallas-TPU ``CompilerParams`` — 0.4.x: ``TPUCompilerParams``.
  * :func:`supports_spmd_partition_id` — capability probe for the
    partial-auto shard_map lowerings that emit a ``partition-id`` HLO
    (jax 0.4.x XLA:CPU rejects it under SPMD partitioning; tests that
    need it skip deterministically instead of failing).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax

_UNSET = object()


@functools.lru_cache(maxsize=1)
def supports_spmd_partition_id() -> bool:
    """True when the backend can execute a partial-auto shard_map (the
    lowering that materializes a ``partition-id`` HLO instruction).

    jax 0.4.x's XLA:CPU dies at execute time with "UNIMPLEMENTED:
    PartitionId instruction is not supported for SPMD partitioning" the
    moment a multi-device partial-auto region runs — which the vocab-
    parallel lookup and pipeline wave schedules rely on. The probe runs
    the smallest such program (2x2 mesh, one manual axis, one auto axis,
    an ``axis_index`` in the body) and reports whether execution
    succeeds; <2 devices can never trip the partitioner, so it reports
    True there. Cached — the answer is a property of the installed
    jax/backend pair, not of the callsite."""
    import numpy as np

    if len(jax.devices()) < 2:
        return True
    try:
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        devs = np.asarray(jax.devices()[:4]).reshape(2, 2)
        mesh = Mesh(devs, ("a", "b"))

        def body(x):
            return x + jax.lax.axis_index("a").astype(x.dtype)

        f = jax.jit(shard_map(body, mesh=mesh, in_specs=P("a"),
                              out_specs=P("a"), axis_names={"a"},
                              check_vma=False))
        x = jax.device_put(np.zeros(4, np.float32),
                           NamedSharding(mesh, P("a")))
        jax.block_until_ready(f(x))
        return True
    except Exception as e:
        if "PartitionId" in str(e):
            return False
        return True  # unrelated failure: don't mask it behind a skip


def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
              check_vma=_UNSET, **kw):
    """``jax.shard_map`` with new-API keywords on any supported jax.

    ``axis_names`` is the set of mesh axes the body is MANUAL over
    (None = all); ``check_vma`` toggles replication checking.
    """
    native = getattr(jax, "shard_map", None)
    if native is not None:
        if axis_names is not None:
            kw["axis_names"] = axis_names
        if check_vma is not _UNSET:
            kw["check_vma"] = check_vma
        return native(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _esm

    if check_vma is not _UNSET:
        kw["check_rep"] = check_vma
    if axis_names is not None:
        kw["auto"] = (frozenset(getattr(mesh, "axis_names", ()))
                      - frozenset(axis_names))
    return _esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                **kw)


def axis_size(name) -> Any:
    """``lax.axis_size`` (jax 0.5+); older jax spells it ``psum(1, ax)``
    which constant-folds to the same static size inside shard_map."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(name)
    return jax.lax.psum(1, name)


def get_abstract_mesh(fallback=None) -> Any:
    """Context abstract mesh (jax 0.5+) for nesting shard_map inside a
    partial-manual region; older jax nests on the concrete mesh, whose
    manual axes are excluded via ``auto=`` instead."""
    fn = getattr(jax.sharding, "get_abstract_mesh", None)
    if fn is not None:
        return fn()
    return fallback


def pallas_tpu_compiler_params(**kw) -> Optional[Any]:
    """Construct pallas-TPU compiler params under either spelling."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) \
        or getattr(pltpu, "TPUCompilerParams", None)
    if cls is None:  # pragma: no cover - ancient jax
        return None
    return cls(**kw)


def _patch_old_shard_map_residual_names() -> None:
    """jax 0.4.x: residuals hoisted out of a shard_map under AD are
    named over EVERY mesh axis (``_all_mesh_names_except_spmd``) —
    including the eqn's own ``auto`` axes, which a nested shard_map may
    not reference (an enclosing region already manualized them), so
    lowering dies with "Axis: pp ... is also found in manual_axes".
    Newer jax fixed this with typed mesh axes. Here we thread each
    partial-eval/transpose rule's ``auto`` set into the naming helper
    and subtract it: residuals are named over the eqn's own manual axes
    only (all dims are marked unspecified under partial-auto anyway, so
    GSPMD re-infers the auto-axis placement either way)."""
    if getattr(jax, "shard_map", None) is not None:
        return  # new jax: fixed upstream
    try:
        from jax.experimental import shard_map as _sm
        from jax._src.interpreters import ad as _ad
        from jax._src.interpreters import partial_eval as _pe
    except Exception:  # pragma: no cover - ancient jax
        return
    orig_names = getattr(_sm, "_all_mesh_names_except_spmd", None)
    if orig_names is None or getattr(orig_names, "_dstpu_patched", False):
        return

    state = {"auto": frozenset()}

    def patched_names(mesh, *a, **kw):
        names = orig_names(mesh, *a, **kw)
        return tuple(n for n in names if n not in state["auto"])

    patched_names._dstpu_patched = True
    _sm._all_mesh_names_except_spmd = patched_names

    def _scoped(_auto_axes, fn, *args, **kw):
        prev, state["auto"] = state["auto"], frozenset(_auto_axes or ())
        try:
            return fn(*args, **kw)
        finally:
            state["auto"] = prev

    orig_custom = _pe.partial_eval_jaxpr_custom_rules[_sm.shard_map_p]

    def custom_rule(saveable, unks_in, inst_in, eqn):
        return _scoped(eqn.params.get("auto"), orig_custom,
                       saveable, unks_in, inst_in, eqn)

    _pe.partial_eval_jaxpr_custom_rules[_sm.shard_map_p] = custom_rule

    orig_pe = _pe.JaxprTrace.process_shard_map

    def process(trace, prim, f, tracers, **params):
        return _scoped(params.get("auto"), orig_pe, trace, prim, f,
                       tracers, **params)

    _pe.JaxprTrace.process_shard_map = process

    orig_tr = _ad.primitive_transposes[_sm.shard_map_p]

    def transpose(out_cts, *args, **params):
        return _scoped(params.get("auto"), orig_tr, out_cts, *args,
                       **params)

    _ad.primitive_transposes[_sm.shard_map_p] = transpose


_patch_old_shard_map_residual_names()
