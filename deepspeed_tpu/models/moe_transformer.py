"""Mixture-of-Experts transformer LM (Mixtral/Qwen-MoE family).

Reference: deepspeed/moe/layer.py:17 ``MoE`` wrapping an expert FFN into a
dense model, experts deepspeed/moe/experts.py:13, EP groups
utils/groups.py:304; model family: inference/v2/model_implementations/
mixtral + qwen_v2_moe. Reuses the dense transformer's attention/norm and
swaps the FFN for parallel/moe.py's gated expert dispatch; expert weights
carry the "expert" logical axis → ep mesh axis.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.models import transformer as tfm
from deepspeed_tpu.parallel.moe import GateConfig, moe_ffn
from deepspeed_tpu.runtime.sharding import (constrain_activation,
                                            vocab_parallel_lookup)


@dataclasses.dataclass(frozen=True)
class MoETransformerConfig(tfm.TransformerConfig):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    eval_capacity_factor: float = 2.0
    min_capacity: int = 4
    drop_tokens: bool = True
    aux_loss_weight: float = 0.01
    z_loss_weight: float = 0.0
    # "auto" | "grouped" (dropless grouped-GEMM) | "einsum" (capacity pad)
    moe_impl: str = "auto"

    @property
    def gate(self) -> GateConfig:
        return GateConfig(
            num_experts=self.num_experts, top_k=self.top_k,
            capacity_factor=self.capacity_factor,
            eval_capacity_factor=self.eval_capacity_factor,
            min_capacity=self.min_capacity, drop_tokens=self.drop_tokens,
            aux_loss_weight=self.aux_loss_weight,
            z_loss_weight=self.z_loss_weight)

    def num_params(self) -> int:
        h, L, f, v = self.hidden_size, self.num_layers, self.ffn, self.vocab_size
        nh, nkv, hd = self.num_heads, self.kv_heads, self.head_dim
        attn = h * nh * hd + 2 * h * nkv * hd + nh * hd * h
        expert = (3 if self.activation == "swiglu" else 2) * h * f
        router = h * self.num_experts
        norm_width = 2 * h if self.norm == "layernorm" else h
        per_layer = attn + self.num_experts * expert + router + 2 * norm_width
        emb = v * h + (0 if self.tie_embeddings else v * h)
        pos = self.max_seq_len * h if self.pos_emb == "learned" else 0
        return L * per_layer + emb + pos + norm_width

    def active_params(self) -> int:
        """Params touched per token (top_k of num_experts)."""
        dense = self.num_params()
        h, L, f = self.hidden_size, self.num_layers, self.ffn
        expert = (3 if self.activation == "swiglu" else 2) * h * f
        return dense - L * (self.num_experts - self.top_k) * expert

    def flops_per_token(self) -> float:
        return 6 * self.active_params() + \
            12 * self.num_layers * self.hidden_size * self.max_seq_len


def init_params(cfg: MoETransformerConfig, rng: jax.Array) -> Dict[str, Any]:
    base = tfm.init_params(cfg, rng)
    # replace the dense mlp with router + stacked experts
    h, L, f, E = cfg.hidden_size, cfg.num_layers, cfg.ffn, cfg.num_experts
    keys = jax.random.split(jax.random.fold_in(rng, 17), 4)
    pd = cfg.param_dtype

    def stack(key, shape, scale):
        return jax.random.normal(key, (L, E) + shape, pd) * scale

    moe = {
        "router": jax.random.normal(keys[0], (L, h, E), pd) * (1.0 / math.sqrt(h)),
        "experts": {
            "wi": stack(keys[1], (h, f), 1.0 / math.sqrt(h)),
            "wo": stack(keys[2], (f, h), 1.0 / math.sqrt(f)),
        },
    }
    if cfg.activation == "swiglu":
        moe["experts"]["wg"] = stack(keys[3], (h, f), 1.0 / math.sqrt(h))
    base["layers"]["moe"] = moe
    del base["layers"]["mlp"]
    return base


def logical_axes(cfg: MoETransformerConfig) -> Dict[str, Any]:
    axes = tfm.logical_axes(cfg)
    moe = {
        "router": ("layers", "embed", None),
        "experts": {
            "wi": ("layers", "expert", "embed", "mlp"),
            "wo": ("layers", "expert", "mlp", "embed"),
        },
    }
    if cfg.activation == "swiglu":
        moe["experts"]["wg"] = ("layers", "expert", "embed", "mlp")
    axes["layers"]["moe"] = moe
    del axes["layers"]["mlp"]
    return axes


def _moe_layer(cfg: MoETransformerConfig, x, layer_params, positions,
               train: bool):
    """Transformer block with MoE FFN. Returns (x, l_aux_sum)."""
    from deepspeed_tpu.runtime.sharding import effective_dtype

    ap = layer_params["attn"]
    dt = effective_dtype(cfg.dtype)
    x = x.astype(dt)

    y = tfm._norm(x, layer_params["ln1"], cfg.norm, cfg.norm_eps)
    q = jnp.einsum("bsh,hnd->bsnd", y, ap["wq"].astype(dt))
    k = jnp.einsum("bsh,hnd->bsnd", y, ap["wk"].astype(dt))
    v = jnp.einsum("bsh,hnd->bsnd", y, ap["wv"].astype(dt))
    if cfg.pos_emb == "rope":
        q = tfm._rope(q, positions, cfg.rope_theta)
        k = tfm._rope(k, positions, cfg.rope_theta)
    if cfg.sequence_parallel or cfg.attn_chunks > 1:
        # head-split SP paths need equal q/kv head counts; the plain
        # path keeps KV grouped for the GQA-native flash kernel
        from deepspeed_tpu.ops.attention import repeat_kv_heads
        k, v = repeat_kv_heads(q, k, v)
    attn = tfm._attention(q, k, v, cfg)
    attn = jnp.einsum("bsnd,ndh->bsh", attn, ap["wo"].astype(dt))
    x = x + constrain_activation(attn, ("batch", "seq", "embed"))

    y = tfm._norm(x, layer_params["ln2"], cfg.norm, cfg.norm_eps)
    out, aux = moe_ffn(y, layer_params["moe"]["router"],
                       layer_params["moe"]["experts"], cfg.gate,
                       activation=cfg.activation, train=train,
                       impl=cfg.moe_impl)
    l_aux = aux["l_aux"] * cfg.aux_loss_weight
    if cfg.z_loss_weight:
        l_aux = l_aux + aux["l_zloss"] * cfg.z_loss_weight
    return x + out, l_aux


def apply(cfg: MoETransformerConfig, params, tokens, positions=None,
          train: bool = True) -> Tuple[jax.Array, jax.Array]:
    """tokens [B,S] → (logits [B,S,V] fp32, total aux loss)."""
    B, S = tokens.shape
    dt = cfg.dtype
    if positions is None:
        positions = jnp.arange(S)[None, :]
    x = vocab_parallel_lookup(params["embed"]["tokens"].astype(dt), tokens)
    if cfg.pos_emb == "learned":
        x = x + params["embed"]["positions"].astype(dt)[positions]
    x = constrain_activation(x, ("batch", "seq", "embed"))

    layer_fn = partial(_moe_layer, cfg)

    from deepspeed_tpu.parallel import topology as _topo
    from deepspeed_tpu.parallel.pipeline import (
        pipeline_enabled, pipelined_layers)

    if pipeline_enabled(_topo._GLOBAL_MESH):
        # pp > 1: microbatched stage pipeline threading the aux-loss
        # accumulator through the ring (remat applied per stage inside)
        x, aux_total = pipelined_layers(
            lambda c, lp: layer_fn(c, lp, positions, train),
            params["layers"], x, with_aux=True)
    elif cfg.param_host_offload:
        # ZeRO-Infinity streaming for the expert stack (mirrors
        # models/transformer.py): each scan step fetches one layer's
        # params — including its experts, the bulk of an MoE model —
        # inside the rematerialized body, so HBM holds one layer's
        # experts at a time
        def fetch_layer(i):
            from deepspeed_tpu.utils import memspace

            return jax.tree.map(
                lambda a: memspace.put(
                    lax.dynamic_index_in_dim(a, i, keepdims=False),
                    "device"),
                params["layers"])

        def fetched_fn(x, i):
            return layer_fn(x, fetch_layer(i), positions, train)

        if cfg.remat:
            fetched_fn = jax.checkpoint(fetched_fn)

        def host_body(carry, i):
            x, aux = carry
            x, l_aux = fetched_fn(x, i)
            return (x, aux + l_aux), None

        (x, aux_total), _ = lax.scan(
            host_body, (x, jnp.asarray(0.0, jnp.float32)),
            jnp.arange(cfg.num_layers))
    else:
        if cfg.remat:
            layer_fn = jax.checkpoint(layer_fn)

        def body(carry, layer_params):
            x, aux = carry
            x, l_aux = layer_fn(x, layer_params, positions, train)
            return (x, aux + l_aux), None

        (x, aux_total), _ = lax.scan(
            body, (x, jnp.asarray(0.0, jnp.float32)), params["layers"])

    x = tfm._norm(x, params["final_norm"], cfg.norm, cfg.norm_eps)
    if cfg.tie_embeddings:
        logits = jnp.einsum("bsh,vh->bsv", x, params["embed"]["tokens"].astype(dt))
    else:
        logits = jnp.einsum("bsh,hv->bsv", x, params["unembed"]["kernel"].astype(dt))
    return logits.astype(jnp.float32), aux_total


class MoETransformerLM:
    """Model-protocol wrapper (same contract as TransformerLM)."""

    def __init__(self, config: MoETransformerConfig):
        self.config = config

    def init(self, rng):
        return init_params(self.config, rng)

    def logical_axes(self):
        return logical_axes(self.config)

    def apply(self, params, tokens, positions=None):
        logits, _ = apply(self.config, params, tokens, positions, train=False)
        return logits

    def loss(self, params, batch):
        tokens = batch["input_ids"]
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
        logits, aux_loss = apply(self.config, params, inputs, train=True)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
        nll = (logz - gold).mean()
        total = nll + aux_loss
        return total, {"loss": total, "lm_loss": nll, "aux_loss": aux_loss,
                       "ntokens": jnp.asarray(labels.size, jnp.float32)}

    def flops_per_token(self):
        return self.config.flops_per_token()

    def num_params(self):
        return self.config.num_params()
