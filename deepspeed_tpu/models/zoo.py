"""Model zoo presets.

Named configurations for the model families the reference ships policies
for (module_inject/containers/*, inference/v2/model_implementations/*):
GPT-2 sizes, Llama-2/3, Mistral, Qwen2, Phi-3 — all instances of the
generic TransformerLM; Mixtral/Qwen-MoE live in models/moe_transformer.py.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from deepspeed_tpu.models.transformer import TransformerConfig, TransformerLM


def _gpt2(h, L, heads, vocab=50257, ctx=1024):
    return TransformerConfig(
        vocab_size=vocab, hidden_size=h, num_layers=L, num_heads=heads,
        max_seq_len=ctx, pos_emb="learned", norm="layernorm",
        activation="gelu_tanh", tie_embeddings=True)


def _llama(h, L, heads, kv_heads, ffn, vocab=128256, ctx=8192,
           theta=500000.0):
    return TransformerConfig(
        vocab_size=vocab, hidden_size=h, num_layers=L, num_heads=heads,
        num_kv_heads=kv_heads, ffn_size=ffn, max_seq_len=ctx, pos_emb="rope",
        norm="rmsnorm", activation="swiglu", tie_embeddings=False,
        rope_theta=theta, norm_eps=1e-5)


CONFIGS = {
    # GPT-2 family (reference policy: module_inject/containers/gpt2.py)
    "gpt2-125m": _gpt2(768, 12, 12),
    "gpt2-350m": _gpt2(1024, 24, 16),
    "gpt2-1.3b": _gpt2(2048, 24, 16),
    # Llama-3 family (reference: inference/v2/model_implementations/llama_v2,
    # module_inject/containers/llama.py)
    "llama3-8b": _llama(4096, 32, 32, 8, 14336),
    "llama3-70b": _llama(8192, 80, 64, 8, 28672),
    # Llama-2 (32k vocab, theta 1e4)
    "llama2-7b": _llama(4096, 32, 32, 32, 11008, vocab=32000, ctx=4096,
                        theta=10000.0),
    # Mistral-7B (reference: inference/v2/model_implementations/mistral)
    "mistral-7b": _llama(4096, 32, 32, 8, 14336, vocab=32000, ctx=8192,
                         theta=10000.0),
    # Qwen2-7B (reference: inference/v2/model_implementations/qwen_v2)
    "qwen2-7b": _llama(3584, 28, 28, 4, 18944, vocab=152064, ctx=8192,
                       theta=1000000.0),
    # Phi-3-mini (reference: inference/v2/model_implementations/phi)
    "phi3-mini": _llama(3072, 32, 32, 32, 8192, vocab=32064, ctx=4096,
                        theta=10000.0),
    # OPT family (reference: inference/v2/model_implementations/opt,
    # module_inject/containers/opt.py): learned positions, ReLU MLP
    "opt-1.3b": TransformerConfig(
        vocab_size=50272, hidden_size=2048, num_layers=24, num_heads=32,
        ffn_size=8192, max_seq_len=2048, pos_emb="learned",
        norm="layernorm", activation="relu", tie_embeddings=True),
    "opt-6.7b": TransformerConfig(
        vocab_size=50272, hidden_size=4096, num_layers=32, num_heads=32,
        ffn_size=16384, max_seq_len=2048, pos_emb="learned",
        norm="layernorm", activation="relu", tie_embeddings=True),
    # Falcon-7B (reference: .../falcon): rope + LayerNorm + GELU MLP +
    # multi-query attention (1 KV head). Deviation: residual blocks are
    # sequential here, not Falcon's fused parallel attn/mlp.
    "falcon-7b": TransformerConfig(
        vocab_size=65024, hidden_size=4544, num_layers=32, num_heads=71,
        num_kv_heads=1, ffn_size=18176, max_seq_len=2048, pos_emb="rope",
        norm="layernorm", activation="gelu", tie_embeddings=True,
        rope_theta=10000.0),
    # tiny debug config (reference tests/unit/simple_model.py role)
    "tiny": TransformerConfig(vocab_size=256, hidden_size=64, num_layers=2,
                              num_heads=4, max_seq_len=128, remat=False),
}


def _register_moe():
    from deepspeed_tpu.models.moe_transformer import MoETransformerConfig

    def _moe(h, L, heads, kv, ffn, E, k, vocab, ctx, theta):
        return MoETransformerConfig(
            vocab_size=vocab, hidden_size=h, num_layers=L, num_heads=heads,
            num_kv_heads=kv, ffn_size=ffn, max_seq_len=ctx, pos_emb="rope",
            norm="rmsnorm", activation="swiglu", tie_embeddings=False,
            rope_theta=theta, num_experts=E, top_k=k)

    CONFIGS.update({
        # Mixtral-8x7B (reference: inference/v2/model_implementations/mixtral)
        "mixtral-8x7b": _moe(4096, 32, 32, 8, 14336, E=8, k=2,
                             vocab=32000, ctx=32768, theta=1000000.0),
        # Qwen2-MoE-A14B-style (reference: .../qwen_v2_moe)
        "qwen2-moe-a14b": _moe(3584, 28, 28, 4, 2560, E=64, k=8,
                               vocab=151936, ctx=8192, theta=1000000.0),
        "tiny-moe": MoETransformerConfig(
            vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
            max_seq_len=128, pos_emb="rope", norm="rmsnorm",
            activation="swiglu", remat=False, num_experts=4, top_k=2),
    })


_register_moe()


def get_model(name: str, **overrides) -> TransformerLM:
    """Instantiate a preset, optionally overriding config fields
    (e.g. max_seq_len, remat_policy, sequence_parallel)."""
    if name not in CONFIGS:
        raise ValueError(f"unknown model '{name}'; known: {sorted(CONFIGS)}")
    cfg = CONFIGS[name]
    # env-derived fields resolve at __post_init__; presets were built at
    # import, so re-resolve here (set to None → replace re-runs
    # __post_init__) or a later DSTPU_PREFETCH/DSTPU_SERIALIZE_FETCH
    # flip would be silently ignored for zoo models
    env_fields = {f: None for f in ("prefetch_stream", "serialize_fetch",
                                    "prefetch_depth", "grads_to_host",
                                    "overlap_depth")
                  if f not in overrides}
    cfg = dataclasses.replace(cfg, **env_fields, **overrides)
    from deepspeed_tpu.models.moe_transformer import (
        MoETransformerConfig, MoETransformerLM)

    if isinstance(cfg, MoETransformerConfig):
        return MoETransformerLM(cfg)
    return TransformerLM(cfg)
