from deepspeed_tpu.models.transformer import (  # noqa: F401
    TransformerConfig, TransformerLM)
from deepspeed_tpu.models.zoo import CONFIGS, get_model  # noqa: F401
