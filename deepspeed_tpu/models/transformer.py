"""Decoder-only transformer LM, TPU-first.

This is the framework's model substrate — the role the user's ``nn.Module``
plays in the reference (and what its model zoo under
``inference/v2/model_implementations`` + ``module_inject/containers``
covers). One generic implementation expresses the GPT-2 / Llama / Mistral /
Qwen families via config switches (positional encoding, norm, activation,
GQA, tied embeddings); MoE variants live in models/moe_transformer.py.

TPU-first design choices:
  * functional: ``init(rng) -> params`` pytree, ``apply(params, tokens) ->
    logits``; no module objects at runtime, everything jit-traceable;
  * every param leaf has a tuple of logical axis names (see
    runtime/sharding.py) — this single annotation drives ZeRO-3 / TP / PP
    sharding instead of the reference's AutoTP layer surgery
    (module_inject/auto_tp.py:194);
  * layers are **stacked and scanned** (``lax.scan`` over a [L, ...] params
    tree): one compiled layer body regardless of depth — XLA compile time
    stays flat at 70B scale, and remat policy applies per scan step
    (reference analog: activation checkpointing
    runtime/activation_checkpointing/checkpointing.py:948);
  * bf16 compute, fp32 logits for the softmax-xent;
  * attention goes through ops/attention.py (Pallas flash kernel on TPU,
    XLA fallback elsewhere) and parallel/ulysses.py when sp > 1.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.runtime.sharding import (constrain_activation,
                                            vocab_parallel_lookup)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    """Architecture switches covering the GPT-2/Llama families."""

    vocab_size: int = 50257
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    num_kv_heads: Optional[int] = None  # None = MHA; < num_heads = GQA
    ffn_size: Optional[int] = None  # None = 4*hidden (gelu) or 8/3*hidden (swiglu)
    max_seq_len: int = 1024
    pos_emb: str = "learned"  # learned | rope | none
    norm: str = "layernorm"  # layernorm | rmsnorm
    activation: str = "gelu"  # gelu (exact erf) | gelu_tanh | swiglu | relu
    tie_embeddings: bool = True
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    # projection biases (GPT-2/OPT-family checkpoints; Llama-family has
    # none). Zoo presets stay bias-free; the HF loader enables this when
    # the source layout carries biases.
    use_biases: bool = False
    # ZeRO-Infinity param offload: layer params live in pinned host
    # memory; the scan fetches one layer per step (and the remat replay
    # re-fetches it for backward) so HBM never holds the full stack.
    # Set by the engine from zero_optimization.offload_param.
    param_host_offload: bool = False
    # None defers to the engine's activation_checkpointing.policy config;
    # an explicit name here wins over the config
    remat_policy: Optional[str] = None
    attn_impl: str = "auto"  # auto | xla | flash
    sequence_parallel: bool = False  # SP attention over the sp mesh axis
    sp_mode: str = "ulysses"  # ulysses (all-to-all) | ring (ppermute CP)
    # ALST-style tiled compute (reference ulysses_sp.py TiledMLP /
    # TiledFusedLogitsLoss): number of sequence tiles, 0/1 = off
    tiled_logits: int = 0
    tiled_mlp: int = 0
    # FPDT-style chunked attention (reference fpdt_layer.py): number of
    # query chunks scanned sequentially, 0/1 = off
    attn_chunks: int = 0
    # FPDT host-KV streaming (reference _FPDTGPUOffloadingAttentionImpl_
    # fpdt_layer.py:545): K/V tiles live in pinned host memory and stream
    # to the chip per chunk — beyond-HBM sequence lengths on one chip.
    # Uses attn_chunks (min 2) as the chunk count.
    fpdt_host_kv: bool = False
    # FPDT residual-stream offload (VERDICT r4 #5; reference
    # SequenceChunk fpdt_layer.py:497 applied to the residual): the
    # [B, S, H] residual itself lives as a host chunk stack between
    # layers; embedding, every layer chunk, and the fused
    # final-norm+logits+loss all fetch/emit host chunks, so the device
    # never holds ANY full-S buffer. Requires fpdt_host_kv and the fused
    # sequential block; loss must go through TransformerLM.loss (the
    # full-logits apply() assembles on device only for small-S tests).
    fpdt_host_residual: bool = False
    # Falcon-style parallel residual: x + attn(ln1(x)) + mlp(ln2(x)),
    # both branches reading the pre-attention residual
    parallel_block: bool = False
    # offload_param streamed-stack A/B knobs. None resolves from
    # DSTPU_PREFETCH / DSTPU_SERIALIZE_FETCH at *config construction* so
    # the choice participates in the jit trace-cache key — flipping the
    # env after the first compile changes the next config built, never a
    # stale cached executable.
    prefetch_stream: Optional[bool] = None
    serialize_fetch: Optional[bool] = None
    # streamer tuning (same env-at-construction contract):
    # DSTPU_PREFETCH_DEPTH layers in flight ahead of compute;
    # DSTPU_GRADS_TO_HOST streams per-layer grad cotangents to host
    # inside the backward scan (see runtime/param_stream.py)
    prefetch_depth: Optional[int] = None
    grads_to_host: Optional[bool] = None
    # per-layer overlap engine depth (runtime/param_stream.py
    # pin_stage): the K newest in-flight transfers — h2d layer fetches
    # on the offload path, fsdp all-gathers on the stage-3 resident
    # path, plus the backward grad streams — are barrier-pinned into
    # the issuing layer's scheduling stage. 0 disables (today's
    # program, bit-for-bit). Same env-at-construction contract:
    # DSTPU_OVERLAP_DEPTH; the engine bridges
    # config.performance.overlap_depth onto it.
    overlap_depth: Optional[int] = None
    # fp8 MLP matmuls (ops/fp_quantizer.py fp8_matmul_ste): e4m3
    # operands into an fp32-accumulating matmul with straight-through
    # gradients. Opt-in — off keeps exact bf16/fp32 parity. Set by the
    # engine from config.performance.fp8_mlp.
    fp8_mlp: bool = False

    def __post_init__(self):
        import os as _os
        if self.prefetch_stream is None:
            object.__setattr__(self, "prefetch_stream", bool(int(
                _os.environ.get("DSTPU_PREFETCH", "1"))))
        if self.serialize_fetch is None:
            object.__setattr__(self, "serialize_fetch", bool(int(
                _os.environ.get("DSTPU_SERIALIZE_FETCH", "0"))))
        if self.prefetch_depth is None:
            object.__setattr__(self, "prefetch_depth", int(
                _os.environ.get("DSTPU_PREFETCH_DEPTH", "2")))
        if self.grads_to_host is None:
            object.__setattr__(self, "grads_to_host", bool(int(
                _os.environ.get("DSTPU_GRADS_TO_HOST", "1"))))
        if self.overlap_depth is None:
            object.__setattr__(self, "overlap_depth", int(
                _os.environ.get("DSTPU_OVERLAP_DEPTH", "0")))
        if self.sp_mode not in ("ulysses", "ring"):
            raise ValueError(
                f"sp_mode must be ulysses|ring, got {self.sp_mode!r}")
        # fpdt_host_kv + sequence_parallel compose: the layer runs
        # inside shard_map over sp, each rank streaming the
        # sp-all-gathered host KV stacks through its local q chunks
        # (parallel/fpdt.py sp_axis mode) — the former hard error here
        # is lifted (ROADMAP item 4 planner composition).
        if self.fpdt_host_residual:
            if not self.fpdt_host_kv:
                raise ValueError(
                    "fpdt_host_residual requires fpdt_host_kv (the "
                    "residual stack rides the same chunk grid as the "
                    "KV tiles)")
            if self.parallel_block:
                raise ValueError(
                    "fpdt_host_residual requires the fused sequential "
                    "block (attention+MLP per chunk); parallel_block "
                    "is not chunk-fusable this way")
            if self.sequence_parallel:
                raise ValueError(
                    "fpdt_host_residual does not compose with "
                    "sequence_parallel: the residual lives as a host "
                    "chunk stack, which cannot also be sharded over sp")

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads or self.num_heads

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def ffn(self) -> int:
        if self.ffn_size:
            return self.ffn_size
        if self.activation == "swiglu":
            # Llama convention: 2/3 * 4h rounded to multiple of 256
            d = int(8 * self.hidden_size / 3)
            return 256 * ((d + 255) // 256)
        return 4 * self.hidden_size

    def flops_per_token(self) -> float:
        """Approximate training FLOPs/token (fwd+bwd ≈ 6·N params + attn)."""
        n = self.num_params()
        attn = 12 * self.num_layers * self.hidden_size * self.max_seq_len
        return 6 * n + attn

    def num_params(self) -> int:
        h, L, f, v = self.hidden_size, self.num_layers, self.ffn, self.vocab_size
        hd, nh, nkv = self.head_dim, self.num_heads, self.kv_heads
        attn = h * nh * hd + 2 * h * nkv * hd + nh * hd * h
        mlp = (3 if self.activation == "swiglu" else 2) * h * f
        norm_width = 2 * h if self.norm == "layernorm" else h  # scale(+bias)
        per_layer = attn + mlp + 2 * norm_width
        emb = v * h + (0 if self.tie_embeddings else v * h)
        pos = self.max_seq_len * h if self.pos_emb == "learned" else 0
        return L * per_layer + emb + pos + norm_width


# ---------------------------------------------------------------------------
# parameter init + logical axes
# ---------------------------------------------------------------------------


def act_fn(name: str):
    """Activation by config name. "gelu" is the exact erf form (HF
    Falcon/BERT-class 'gelu'); "gelu_tanh"/"gelu_new" is the tanh
    approximation (GPT-2). The two differ by up to ~4e-4 per activation
    — enough to flip greedy tokens over a deep stack."""
    if name == "relu":
        return jax.nn.relu
    if name in ("gelu_tanh", "gelu_new"):
        return partial(jax.nn.gelu, approximate=True)
    if name == "gelu":
        return partial(jax.nn.gelu, approximate=False)
    raise ValueError(f"unknown activation {name!r}")


def _dense_init(rng, shape, scale=None, dtype=jnp.float32):
    scale = scale if scale is not None else 1.0 / math.sqrt(shape[0])
    return jax.random.normal(rng, shape, dtype) * scale


def init_params(cfg: TransformerConfig, rng: jax.Array) -> Dict[str, Any]:
    """Build the full parameter pytree (layer weights stacked on dim 0)."""
    h, L, f = cfg.hidden_size, cfg.num_layers, cfg.ffn
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    keys = jax.random.split(rng, 12)
    pd = cfg.param_dtype

    def stack(fn, key):
        return jax.vmap(fn)(jax.random.split(key, L))

    params: Dict[str, Any] = {
        "embed": {"tokens": _dense_init(keys[0], (cfg.vocab_size, h), 0.02, pd)},
        "layers": {
            "attn": {
                "wq": stack(lambda k: _dense_init(k, (h, nh, hd), dtype=pd), keys[1]),
                "wk": stack(lambda k: _dense_init(k, (h, nkv, hd), dtype=pd), keys[2]),
                "wv": stack(lambda k: _dense_init(k, (h, nkv, hd), dtype=pd), keys[3]),
                "wo": stack(
                    lambda k: _dense_init(k, (nh, hd, h), 1.0 / math.sqrt(nh * hd), pd),
                    keys[4],
                ),
                **({"bq": jnp.zeros((L, nh, hd), pd),
                    "bk": jnp.zeros((L, nkv, hd), pd),
                    "bv": jnp.zeros((L, nkv, hd), pd),
                    "bo": jnp.zeros((L, h), pd)} if cfg.use_biases else {}),
            },
            "mlp": _init_mlp(cfg, keys[5], L),
            "ln1": {"scale": jnp.ones((L, h), pd)},
            "ln2": {"scale": jnp.ones((L, h), pd)},
        },
        "final_norm": {"scale": jnp.ones((h,), pd)},
    }
    if cfg.norm == "layernorm":
        params["layers"]["ln1"]["bias"] = jnp.zeros((L, h), pd)
        params["layers"]["ln2"]["bias"] = jnp.zeros((L, h), pd)
        params["final_norm"]["bias"] = jnp.zeros((h,), pd)
    if cfg.pos_emb == "learned":
        params["embed"]["positions"] = _dense_init(
            keys[6], (cfg.max_seq_len, h), 0.01, pd
        )
    if not cfg.tie_embeddings:
        params["unembed"] = {"kernel": _dense_init(keys[7], (h, cfg.vocab_size), 0.02, pd)}
    return params


def _init_mlp(cfg, key, L):
    h, f = cfg.hidden_size, cfg.ffn
    ks = jax.random.split(key, 3)
    pd = cfg.param_dtype

    def stack(fn, k):
        return jax.vmap(fn)(jax.random.split(k, L))

    mlp = {
        "wi": stack(lambda k: _dense_init(k, (h, f), dtype=pd), ks[0]),
        "wo": stack(lambda k: _dense_init(k, (f, h), dtype=pd), ks[1]),
    }
    if cfg.activation == "swiglu":
        mlp["wg"] = stack(lambda k: _dense_init(k, (h, f), dtype=pd), ks[2])
    if cfg.use_biases:
        mlp["bi"] = jnp.zeros((L, f), pd)
        mlp["bo"] = jnp.zeros((L, h), pd)
    return mlp


def logical_axes(cfg: TransformerConfig) -> Dict[str, Any]:
    """Logical axis names per param leaf (drives all sharding; see
    runtime/sharding.py rule tables)."""
    axes: Dict[str, Any] = {
        "embed": {"tokens": ("vocab", "embed")},
        "layers": {
            "attn": {
                "wq": ("layers", "embed", "heads", "head_dim"),
                "wk": ("layers", "embed", "kv_heads", "head_dim"),
                "wv": ("layers", "embed", "kv_heads", "head_dim"),
                "wo": ("layers", "heads", "head_dim", "embed"),
            },
            "mlp": {
                "wi": ("layers", "embed", "mlp"),
                "wo": ("layers", "mlp", "embed"),
            },
            "ln1": {"scale": ("layers", "embed")},
            "ln2": {"scale": ("layers", "embed")},
        },
        "final_norm": {"scale": ("embed",)},
    }
    if cfg.norm == "layernorm":
        axes["layers"]["ln1"]["bias"] = ("layers", "embed")
        axes["layers"]["ln2"]["bias"] = ("layers", "embed")
        axes["final_norm"]["bias"] = ("embed",)
    if cfg.use_biases:
        axes["layers"]["attn"]["bq"] = ("layers", "heads", "head_dim")
        axes["layers"]["attn"]["bk"] = ("layers", "kv_heads", "head_dim")
        axes["layers"]["attn"]["bv"] = ("layers", "kv_heads", "head_dim")
        axes["layers"]["attn"]["bo"] = ("layers", "embed")
        axes["layers"]["mlp"]["bi"] = ("layers", "mlp")
        axes["layers"]["mlp"]["bo"] = ("layers", "embed")
    if cfg.pos_emb == "learned":
        axes["embed"]["positions"] = ("seq", "embed")
    if cfg.activation == "swiglu":
        axes["layers"]["mlp"]["wg"] = ("layers", "embed", "mlp")
    if not cfg.tie_embeddings:
        axes["unembed"] = {"kernel": ("embed", "vocab")}
    return axes


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _norm(x, p, kind: str, eps: float):
    x32 = x.astype(jnp.float32)
    if kind == "rmsnorm":
        rms = jnp.sqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
        out = x32 / rms * p["scale"].astype(jnp.float32)
    else:
        mean = jnp.mean(x32, axis=-1, keepdims=True)
        var = jnp.var(x32, axis=-1, keepdims=True)
        out = (x32 - mean) / jnp.sqrt(var + eps)
        out = out * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return out.astype(x.dtype)


def _rope(x, positions, theta: float):
    """Rotary embedding on [..., seq, heads, head_dim]."""
    hd = x.shape[-1]
    half = hd // 2
    freqs = 1.0 / (theta ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B?, S, half]
    cos = jnp.cos(angles)[..., None, :]  # broadcast over heads
    sin = jnp.sin(angles)[..., None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


def _attention(q, k, v, cfg: TransformerConfig, causal: bool = True):
    """Dispatch to the attention impl (Pallas flash on TPU when available)."""
    from deepspeed_tpu.ops.attention import multi_head_attention

    if cfg.sequence_parallel:
        if cfg.sp_mode == "ring":
            # ring is already blockwise: per-chip attention memory is one
            # [S/p × S/p] block, so attn_chunks adds nothing there
            from deepspeed_tpu.parallel.ring_attention import ring_attention

            return ring_attention(q, k, v, causal=causal)
        if cfg.sp_mode != "ulysses":
            raise ValueError(f"sp_mode must be ulysses|ring, got "
                             f"{cfg.sp_mode!r}")
        from deepspeed_tpu.parallel.ulysses import ulysses_attention

        return ulysses_attention(q, k, v, causal=causal, impl=cfg.attn_impl,
                                 attn_chunks=cfg.attn_chunks)
    if cfg.attn_chunks > 1:
        from deepspeed_tpu.parallel.fpdt import chunked_attention

        return chunked_attention(q, k, v, causal=causal,
                                 q_chunks=cfg.attn_chunks)
    return multi_head_attention(q, k, v, causal=causal, impl=cfg.attn_impl)


def _qwz_fetch_tree(cfg: TransformerConfig, layer_params):
    """ZeRO++ stage-3 qwZ: route each layer weight through the int8 fsdp
    all-gather (runtime/sharding.py quantized_param_fetch; no-op unless
    the engine armed it via configure_qwz). Reference: quantized
    parameter all-gather in the stage-3 fetch path
    (partition_parameters.py:1446)."""
    from deepspeed_tpu.runtime.sharding import (quantized_param_fetch,
                                                qwz_active,
                                                qwz_sequence_barrier)

    if not qwz_active():
        return layer_params
    axes = logical_axes(cfg)["layers"]
    token = [None]  # chains fetches on the CPU sim (barrier is a TPU no-op)

    def fetch(p, a, path):
        if token[0] is not None:
            p, _ = qwz_sequence_barrier(p, token[0])
        out = quantized_param_fetch(p, a[1:], path=path)  # drop "layers"
        if out is not p:
            token[0] = out
        return out

    def walk(p, a, path):
        if isinstance(a, tuple):
            return fetch(p, a, path)
        # keystr-format paths ("['layers']['attn']['wq']") so z3-leaf
        # patterns match the same strings param_shardings sees
        return {k: (walk(p[k], a[k], f"{path}['{k}']")
                    if isinstance(p, dict) and k in a else p[k]) for k in p}

    return walk(layer_params, axes, "['layers']")


def _fpdt_post_fn(cfg: TransformerConfig, layer_params, dt):
    """Per-chunk fused block tail (residual add + ln2 + MLP) for the
    fpdt paths — built from the GIVEN param tree so the sp shard_map
    body can construct it from its own operand instead of closing over
    outer traced arrays (closure capture is not allowed across the
    shard_map boundary)."""
    ap = layer_params["attn"]
    mp = layer_params.get("mlp")

    def post_fn(x_chunk, attn_chunk):
        if cfg.use_biases:
            attn_chunk = attn_chunk + ap["bo"].astype(dt)
        xc = x_chunk + attn_chunk
        yc = _norm(xc, layer_params["ln2"], cfg.norm, cfg.norm_eps)
        if cfg.activation == "swiglu":
            gt = jnp.einsum("bch,hf->bcf", yc, mp["wg"].astype(dt))
            ut = jnp.einsum("bch,hf->bcf", yc, mp["wi"].astype(dt))
            zt = jax.nn.silu(gt) * ut
        else:
            pre = jnp.einsum("bch,hf->bcf", yc, mp["wi"].astype(dt))
            if cfg.use_biases:
                pre = pre + mp["bi"].astype(dt)
            zt = act_fn(cfg.activation)(pre)
        out = jnp.einsum("bcf,fh->bch", zt, mp["wo"].astype(dt))
        if cfg.use_biases:
            out = out + mp["bo"].astype(dt)
        return xc + out

    return post_fn


def _fpdt_sp_block(cfg: TransformerConfig, x, layer_params, positions,
                   fuse: bool):
    """fpdt_host_kv × sequence_parallel composed layer attention:
    shard_map over the sp mesh axis — each rank runs FPDT chunked
    attention on its LOCAL sequence shard against the sp-all-gathered,
    host-spilled KV stacks (parallel/fpdt.py ``sp_axis`` mode). Exact:
    the rank-major tiled gather keeps the global tile order
    position-sorted, and query positions carry the shard offset.

    Layer params enter the manual region replicated (P() specs), so tp
    does not further split the projections inside this block; the device
    transient is the gathered full-S KV at kv_heads width (~2·S·kv·D
    bytes — ~2 GB at 1M tokens / 8 KV heads / d128 / bf16), which is
    what the host spill then bounds. Works independently of sp_mode —
    this path replaces the ulysses/ring dispatch when KV streams from
    host. Returns the fused block output when ``fuse`` else the raw
    attention branch (wo applied, no bias)."""
    from jax.sharding import PartitionSpec as P

    from deepspeed_tpu.parallel import topology as _topo
    from deepspeed_tpu.parallel.fpdt import fpdt_attention_block
    from deepspeed_tpu.runtime.sharding import effective_dtype
    from deepspeed_tpu.utils import jaxcompat

    mesh = _topo._GLOBAL_MESH
    dt = effective_dtype(cfg.dtype)
    B, S, H = x.shape
    sp = int(mesh.shape["sp"])
    if S % sp:
        raise ValueError(
            f"fpdt_host_kv + sequence_parallel needs seq {S} divisible "
            f"by sp={sp}: pad-free shards keep global positions exact")
    positions = jnp.broadcast_to(positions, (B, S))
    batch_axes = tuple(a for a in _topo.BATCH_AXES if a in mesh.shape)
    x_spec = P(batch_axes, "sp", None)
    pos_spec = P(batch_axes, "sp")
    p_specs = jax.tree.map(lambda _: P(), layer_params)

    def body(x_loc, lp, pos_loc):
        post = _fpdt_post_fn(cfg, lp, dt) if fuse else None
        return fpdt_attention_block(
            x_loc, lp["attn"], pos_loc, num_heads=cfg.num_heads,
            kv_heads=cfg.kv_heads, head_dim=cfg.head_dim,
            rope_theta=cfg.rope_theta if cfg.pos_emb == "rope" else None,
            q_chunks=max(cfg.attn_chunks, 2), causal=True,
            use_biases=cfg.use_biases,
            norm_fn=lambda t: _norm(t, lp["ln1"], cfg.norm,
                                    cfg.norm_eps),
            post_fn=post, sp_axis="sp", sp_size=sp)

    fn = jaxcompat.shard_map(body, mesh=mesh,
                             in_specs=(x_spec, p_specs, pos_spec),
                             out_specs=x_spec, check_vma=False)
    return fn(x, layer_params, positions)


def _layer(cfg: TransformerConfig, x, layer_params, positions,
           hosted_seq_len: Optional[int] = None):
    """One transformer block. x: [B, S, H] in cfg.dtype — or, when
    ``hosted_seq_len`` is set (fpdt_host_residual), the HOST chunk stack
    [q_chunks, B*C, H]; the return matches the input form."""
    from deepspeed_tpu.runtime.sharding import effective_dtype

    layer_params = _qwz_fetch_tree(cfg, layer_params)
    ap = layer_params["attn"]
    dt = effective_dtype(cfg.dtype)
    hosted = hosted_seq_len is not None
    if not hosted:
        x = x.astype(dt)

    from jax.ad_checkpoint import checkpoint_name

    # attention
    if cfg.fpdt_host_kv:
        # host-KV streaming path: q/k/v/context never materialize at
        # full S on the chip, ln1/ln2 apply per chunk inside the scans,
        # and (for the sequential-block default) the residual add + MLP
        # fuse into the same chunk — the whole layer emits one full-S
        # buffer (parallel/fpdt.py fpdt_attention_block);
        # fpdt_host_kv + sequence_parallel composes via _fpdt_sp_block
        from deepspeed_tpu.parallel.fpdt import fpdt_attention_block

        mp = layer_params.get("mlp")
        fuse_mlp = (not cfg.parallel_block) and mp is not None
        post_fn = _fpdt_post_fn(cfg, layer_params, dt)

        if not hosted and cfg.sequence_parallel:
            from deepspeed_tpu.parallel import topology as _topo

            _mesh = _topo._GLOBAL_MESH
            if _mesh is not None and _mesh.shape.get("sp", 1) > 1:
                res = _fpdt_sp_block(cfg, x, layer_params, positions,
                                     fuse=fuse_mlp)
                if fuse_mlp:
                    return constrain_activation(
                        res, ("batch", "seq", "embed"))
                attn = res
                if cfg.use_biases:
                    attn = attn + ap["bo"].astype(dt)
                attn = constrain_activation(
                    checkpoint_name(attn, "attn_out"),
                    ("batch", "seq", "embed"))
                return _layer_mlp(cfg, x, attn, layer_params)
            # sp requested but the mesh has no sp axis > 1: degree-1
            # sequence parallelism IS the plain local path — fall through

        if hosted:
            if not fuse_mlp:
                raise ValueError(
                    "fpdt_host_residual needs the fused sequential block "
                    "(mlp present, parallel_block off)")
            # two-pass flash-style layer backward over host chunks
            # (parallel/fpdt.py fpdt_hosted_layer)
            import os as _os
            if "oldpath" in _os.environ.get("DSTPU_FPDT_BISECT", ""):
                return fpdt_attention_block(
                    x, ap, positions, num_heads=cfg.num_heads,
                    kv_heads=cfg.kv_heads, head_dim=cfg.head_dim,
                    rope_theta=(cfg.rope_theta if cfg.pos_emb == "rope"
                                else None),
                    q_chunks=max(cfg.attn_chunks, 2), causal=True,
                    use_biases=cfg.use_biases,
                    norm_fn=lambda t: _norm(t, layer_params["ln1"],
                                            cfg.norm, cfg.norm_eps),
                    post_fn=post_fn, hosted=True,
                    seq_len=hosted_seq_len)
            from deepspeed_tpu.parallel.fpdt import fpdt_hosted_layer

            B_ = positions.shape[0] if positions.ndim == 2 else 1
            T_ = x.shape[0]
            C_ = -(-hosted_seq_len // T_)
            Sp_ = T_ * C_
            pos2 = jnp.broadcast_to(positions,
                                    (x.shape[1] // C_, hosted_seq_len))
            pos_p = (jnp.pad(pos2, [(0, 0), (0, Sp_ - hosted_seq_len)])
                     if Sp_ > hosted_seq_len else pos2)
            return fpdt_hosted_layer(
                x, layer_params, pos_p, seq_len=hosted_seq_len,
                q_chunks=T_, num_heads=cfg.num_heads,
                kv_heads=cfg.kv_heads, head_dim=cfg.head_dim,
                rope_theta=(cfg.rope_theta if cfg.pos_emb == "rope"
                            else None),
                use_biases=cfg.use_biases, norm_kind=cfg.norm,
                norm_eps=cfg.norm_eps, activation=cfg.activation)
        res = fpdt_attention_block(
            x, ap, positions, num_heads=cfg.num_heads,
            kv_heads=cfg.kv_heads, head_dim=cfg.head_dim,
            rope_theta=cfg.rope_theta if cfg.pos_emb == "rope" else None,
            q_chunks=max(cfg.attn_chunks, 2), causal=True,
            use_biases=cfg.use_biases,
            norm_fn=lambda t: _norm(t, layer_params["ln1"], cfg.norm,
                                    cfg.norm_eps),
            post_fn=post_fn if fuse_mlp else None)
        if fuse_mlp:
            return constrain_activation(res, ("batch", "seq", "embed"))
        attn = res
        if cfg.use_biases:
            attn = attn + ap["bo"].astype(dt)
        attn = constrain_activation(
            checkpoint_name(attn, "attn_out"), ("batch", "seq", "embed"))
        return _layer_mlp(cfg, x, attn, layer_params)
    y = _norm(x, layer_params["ln1"], cfg.norm, cfg.norm_eps)
    q = jnp.einsum("bsh,hnd->bsnd", y, ap["wq"].astype(dt))
    k = jnp.einsum("bsh,hnd->bsnd", y, ap["wk"].astype(dt))
    v = jnp.einsum("bsh,hnd->bsnd", y, ap["wv"].astype(dt))
    if cfg.use_biases:
        q = q + ap["bq"].astype(dt)
        k = k + ap["bk"].astype(dt)
        v = v + ap["bv"].astype(dt)
    q = checkpoint_name(q, "qkv_proj")
    k = checkpoint_name(k, "qkv_proj")
    v = checkpoint_name(v, "qkv_proj")
    if cfg.pos_emb == "rope":
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
    q = constrain_activation(q, ("batch", "seq", "heads", None))
    k = constrain_activation(k, ("batch", "seq", "heads", None))
    v = constrain_activation(v, ("batch", "seq", "heads", None))
    if cfg.sequence_parallel or cfg.attn_chunks > 1:
        # GQA: the SP all-to-all / chunked paths split on the head axis
        # and need equal q/kv head counts; the plain path keeps KV at
        # kv_heads — the flash kernel reads grouped KV natively.
        from deepspeed_tpu.ops.attention import repeat_kv_heads
        k, v = repeat_kv_heads(q, k, v)
    attn = checkpoint_name(_attention(q, k, v, cfg), "attn_kernel_out")
    attn = jnp.einsum("bsnd,ndh->bsh", attn, ap["wo"].astype(dt))
    if cfg.use_biases:
        attn = attn + ap["bo"].astype(dt)
    attn = constrain_activation(
        checkpoint_name(attn, "attn_out"), ("batch", "seq", "embed"))
    return _layer_mlp(cfg, x, attn, layer_params)


def _layer_mlp(cfg: TransformerConfig, x, attn, layer_params):
    """Residual-add + MLP half of the block (shared by the standard and
    fpdt_host_kv attention paths)."""
    from jax.ad_checkpoint import checkpoint_name

    from deepspeed_tpu.runtime.sharding import effective_dtype

    mp = layer_params["mlp"]
    dt = effective_dtype(cfg.dtype)

    # mlp: sequential (x + attn first) or parallel (Falcon-style — both
    # branches read the pre-attention residual; the loader duplicates a
    # single input_layernorm into ln1/ln2 when the arch has one)
    if not cfg.parallel_block:
        x = x + attn

    if cfg.fp8_mlp:
        # fp8 MLP GEMMs (performance.fp8_mlp): e4m3 operands, fp32
        # accumulation, straight-through grads — the projections are
        # the real-shape compute bulk and tolerate fp8 forward noise
        from deepspeed_tpu.ops.fp_quantizer import fp8_matmul_ste

        def matmul(y, w):
            return fp8_matmul_ste(y, w.astype(dt), out_dtype=dt)
    else:
        def matmul(y, w):
            return jnp.einsum("...h,hf->...f", y, w.astype(dt))

    def mlp_fn(y):
        if cfg.activation == "swiglu":
            g = matmul(y, mp["wg"])
            u = matmul(y, mp["wi"])
            z = jax.nn.silu(g) * u
        else:
            act = act_fn(cfg.activation)
            pre = matmul(y, mp["wi"])
            if cfg.use_biases:
                pre = pre + mp["bi"].astype(dt)
            z = act(pre)
        z = constrain_activation(
            checkpoint_name(z, "mlp_wi"), ("batch", "seq", "mlp"))
        out = matmul(z, mp["wo"])
        if cfg.use_biases:
            out = out + mp["bo"].astype(dt)
        return checkpoint_name(out, "mlp_out")

    if cfg.tiled_mlp > 1:
        # position-wise → chunk the sequence (ALST TiledMLP analog):
        # peak MLP activation drops to one tile's worth. ln2 is
        # position-wise too — normalizing inside the tile body keeps
        # its fp32 intermediate (and the normed y) tile-sized instead
        # of full-sequence (a full-S term at 512K context)
        from deepspeed_tpu.parallel.tiled_compute import tiled_mlp

        def norm_mlp_tile(x_tile):
            return mlp_fn(_norm(x_tile, layer_params["ln2"], cfg.norm,
                                cfg.norm_eps))

        z = tiled_mlp(norm_mlp_tile, x, cfg.tiled_mlp)
    else:
        y = _norm(x, layer_params["ln2"], cfg.norm, cfg.norm_eps)
        z = mlp_fn(y)
    z = constrain_activation(z, ("batch", "seq", "embed"))
    if cfg.parallel_block:
        return x + attn + z
    return x + z


# remat policy names resolve through the activation-checkpointing
# subsystem (runtime/activation_checkpointing.py), which also applies
# partition_activations / cpu_checkpointing when configured


def apply_hidden(cfg: TransformerConfig, params: Dict[str, Any],
                 tokens: jax.Array,
                 positions: Optional[jax.Array] = None,
                 final_norm: bool = True) -> jax.Array:
    """Forward pass up to (and including, unless ``final_norm=False``)
    the final norm: tokens [B,S] → hidden [B,S,H].

    ``final_norm=False`` lets the tiled-logits path fuse the norm into
    its per-tile pass — at long context the full-sequence norm's fp32
    intermediate ([B,S,H] fp32 = 2x the bf16 residual) is one of the
    peak-memory terms (the reference chunks final-norm+logits through
    the same tiles, fpdt_layer.py:1207)."""
    B, S = tokens.shape
    dt = cfg.dtype
    if positions is None:
        positions = jnp.arange(S)[None, :]

    if cfg.fpdt_host_residual:
        raise ValueError(
            "fpdt_host_residual: use apply_hidden_hosted / the loss "
            "path — apply_hidden would materialize the full-S buffer "
            "this mode removes")

    x = vocab_parallel_lookup(params["embed"]["tokens"].astype(dt), tokens)
    if cfg.pos_emb == "learned":
        x = x + params["embed"]["positions"].astype(dt)[positions]
    x = constrain_activation(x, ("batch", "seq", "embed"))

    layer_fn = partial(_layer, cfg)

    from deepspeed_tpu.parallel import topology as _topo
    from deepspeed_tpu.parallel.pipeline import pipeline_enabled, pipelined_layers

    if pipeline_enabled(_topo._GLOBAL_MESH):
        # pp > 1: run the layer stack as a microbatched stage pipeline
        # (remat is applied per stage inside pipelined_layers)
        x = pipelined_layers(
            lambda c, lp: layer_fn(c, lp, positions), params["layers"], x)
    elif cfg.param_host_offload:
        # ZeRO-Infinity streaming: layer params live in pinned host
        # memory (engine placement); each scan step fetches ONE layer to
        # device INSIDE the rematerialized body, so neither the forward
        # nor the saved residuals ever hold the full stack in HBM — the
        # remat replay re-fetches for backward, and the cotangent of the
        # fetch is a device→host transfer, landing grads host-side
        # (reference: swap_tensor/partitioned_param_swapper.py semantics,
        # compiled by XLA instead of hand-scheduled copies).
        # default: the double-buffered prefetch streamer
        # (runtime/param_stream.py streamed_layers_prefetch — fetch of
        # layer i+1 overlaps layer i's compute; measured 2026-07-31 on
        # v5e-1 that XLA's default schedule overlaps these host fetches
        # not at all, docs/latency_hiding.md). Its custom VJP implies
        # per-layer full recompute (nothing_saveable). prefetch_stream
        # False falls back to the plain scan; serialize_fetch True
        # additionally chains each fetch on the previous layer's output
        # (the probe's no-overlap control). Both resolve from env at
        # config construction (see TransformerConfig).
        _prefetch = cfg.prefetch_stream
        _serialize_fetch = cfg.serialize_fetch

        if _prefetch and not _serialize_fetch:
            from deepspeed_tpu.runtime.param_stream import \
                streamed_layers_prefetch

            if cfg.remat and cfg.remat_policy not in (
                    None, "nothing_saveable"):
                from deepspeed_tpu.utils.logging import warning_once

                warning_once(
                    "offload_param prefetch streaming remats per layer "
                    f"(nothing_saveable); remat_policy="
                    f"{cfg.remat_policy!r} does not apply to the "
                    "streamed stack")
            x = streamed_layers_prefetch(
                layer_fn, params["layers"], x, length=cfg.num_layers,
                extra=(positions,), prefetch_depth=cfg.prefetch_depth,
                grads_to_host=cfg.grads_to_host,
                overlap_depth=cfg.overlap_depth or 0)
        else:
            def fetch_layer(i):
                from deepspeed_tpu.utils import memspace

                return jax.tree.map(
                    lambda a: memspace.put(
                        lax.dynamic_index_in_dim(a, i, keepdims=False),
                        "device"),
                    params["layers"])

            def fetched_layer_fn(carry, i):
                if _serialize_fetch:
                    carry, i = lax.optimization_barrier((carry, i))
                return layer_fn(carry, fetch_layer(i), positions)

            if cfg.remat:
                from deepspeed_tpu.runtime.activation_checkpointing import \
                    checkpoint_wrapper

                fetched_layer_fn = checkpoint_wrapper(
                    fetched_layer_fn, policy=cfg.remat_policy)

            def host_scan_body(carry, i):
                return fetched_layer_fn(carry, i), None

            x, _ = lax.scan(host_scan_body, x, jnp.arange(cfg.num_layers))
    elif (cfg.overlap_depth and _topo._GLOBAL_MESH is not None
          and _topo._GLOBAL_MESH.shape.get("fsdp", 1) > 1):
        # stage-3 resident overlap: the SAME overlap engine, with the
        # per-layer fsdp all-gather as the fetch and the per-layer grad
        # reduce-scatter as the sink — layer i+k's gather is
        # barrier-pinned into layer i's stage, and each layer's grad
        # scatter issues inside the backward scan where it overlaps the
        # previous layer's recompute (T3-style, PAPERS.md). The
        # streamer's custom VJP implies per-layer recompute, same as
        # the nothing_saveable remat the real shape runs anyway.
        from deepspeed_tpu.runtime.param_stream import \
            streamed_layers_prefetch
        from deepspeed_tpu.runtime.sharding import (fsdp_gather_slice,
                                                    fsdp_scatter_grads)

        _logical = logical_axes(cfg)["layers"]
        k = max(1, int(cfg.overlap_depth))
        x = streamed_layers_prefetch(
            layer_fn, params["layers"], x, length=cfg.num_layers,
            extra=(positions,), prefetch_depth=k,
            grads_to_host=False, overlap_depth=k,
            fetch=lambda stack, i: fsdp_gather_slice(stack, i, _logical),
            grad_sink=lambda dp: fsdp_scatter_grads(dp, _logical))
    else:
        if cfg.remat:
            from deepspeed_tpu.runtime.activation_checkpointing import \
                checkpoint_wrapper

            layer_fn = checkpoint_wrapper(layer_fn, policy=cfg.remat_policy)

        def scan_body(carry, layer_params):
            return layer_fn(carry, layer_params, positions), None

        x, _ = lax.scan(scan_body, x, params["layers"])

    if not final_norm:
        return x
    return _norm(x, params["final_norm"], cfg.norm, cfg.norm_eps)


def apply_hidden_hosted(cfg: TransformerConfig, params: Dict[str, Any],
                        tokens: jax.Array,
                        positions: Optional[jax.Array] = None):
    """fpdt_host_residual forward: tokens [B, S] → the residual stream as
    a HOST chunk stack [q_chunks, B*C, H] (padded on the chunk grid; no
    final norm — the hosted loss fuses it per chunk). The device holds
    one chunk (+ one KV tile) at a time; see parallel/fpdt.py.

    Returns (x_t, S, C).
    """
    from jax import lax

    from deepspeed_tpu.parallel.fpdt import _to_host
    from deepspeed_tpu.runtime.sharding import effective_dtype

    B, S = tokens.shape
    dt = effective_dtype(cfg.dtype)
    if positions is None:
        positions = jnp.arange(S)[None, :]
    positions = jnp.broadcast_to(positions, (B, S))

    T = max(cfg.attn_chunks, 2)
    C = -(-S // T)
    Sp = T * C
    tokens_p = (jnp.pad(tokens, [(0, 0), (0, Sp - S)]) if Sp > S
                else tokens)
    pos_p = (jnp.pad(positions, [(0, 0), (0, Sp - S)]) if Sp > S
             else positions)

    # embedding, chunk by chunk, emitted straight to the host stack
    def embed_chunk(t):
        tok_c = lax.dynamic_slice_in_dim(tokens_p, t * C, C, 1)
        x_c = vocab_parallel_lookup(
            params["embed"]["tokens"].astype(dt), tok_c)
        if cfg.pos_emb == "learned":
            p_c = lax.dynamic_slice_in_dim(pos_p, t * C, C, 1)
            x_c = x_c + params["embed"]["positions"].astype(dt)[p_c]
        return x_c

    embed_chunk = jax.checkpoint(embed_chunk)

    def embed_body(_, t):
        return None, _to_host(embed_chunk(t).reshape(B * C, -1))

    _, x_t = lax.scan(embed_body, None, jnp.arange(T))

    # layers: a python loop (static depth) — memory control lives at the
    # chunk level inside each layer; a layer-level remat would have to
    # replay host emissions (mixed memory spaces). Composes with
    # param_host_offload: stream each layer's params to device first.
    for li in range(cfg.num_layers):
        lp = jax.tree.map(lambda a: a[li], params["layers"])
        if cfg.param_host_offload:
            from deepspeed_tpu.utils import memspace

            lp = jax.tree.map(lambda a: memspace.put(a, "device"), lp)
        x_t = _layer(cfg, x_t, lp, positions, hosted_seq_len=S)
    return x_t, S, C


def hosted_logits_loss(cfg: TransformerConfig, params, x_t, labels, mask,
                       S: int, C: int):
    """Fused final-norm + unembed + CE over host residual chunks
    (the hosted analog of tiled_compute.tiled_logits_loss; reference
    chunks final-norm+logits the same way, fpdt_layer.py:1207).
    Returns (masked_nll_sum, mask_total)."""
    from jax import lax

    from deepspeed_tpu.parallel.fpdt import _to_device

    T, BC, H = x_t.shape
    B = BC // C
    dt = cfg.dtype
    if mask is None:
        mask = jnp.ones((B, S), jnp.float32)
    mask = mask.astype(jnp.float32)
    Sp = T * C
    labels_p = (jnp.pad(labels, [(0, 0), (0, Sp - S)]) if Sp > S
                else labels)
    mask_p = (jnp.pad(mask, [(0, 0), (0, Sp - S)]) if Sp > S else mask)

    if cfg.tie_embeddings:
        unembed, transpose = params["embed"]["tokens"].astype(dt), True
    else:
        unembed, transpose = params["unembed"]["kernel"].astype(dt), False

    def chunk_nll(t):
        h = _to_device(lax.dynamic_index_in_dim(
            x_t, t, keepdims=False)).reshape(B, C, H)
        h = _norm(h, params["final_norm"], cfg.norm, cfg.norm_eps)
        lbl = lax.dynamic_slice_in_dim(labels_p, t * C, C, 1)
        m = lax.dynamic_slice_in_dim(mask_p, t * C, C, 1)
        if transpose:
            logits = jnp.einsum("bch,vh->bcv", h, unembed)
        else:
            logits = jnp.einsum("bch,hv->bcv", h, unembed)
        logits = logits.astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, lbl[..., None], axis=-1)[..., 0]
        nll = (logz - gold) * m
        return jnp.sum(nll), jnp.sum(m)

    chunk_nll = jax.checkpoint(chunk_nll)

    def body(carry, t):
        a, b = chunk_nll(t)
        return (carry[0] + a, carry[1] + b), None

    (nll_sum, total), _ = lax.scan(body, (jnp.zeros((), jnp.float32),
                                          jnp.zeros((), jnp.float32)),
                                   jnp.arange(T))
    return nll_sum, total


def apply(cfg: TransformerConfig, params: Dict[str, Any], tokens: jax.Array,
          positions: Optional[jax.Array] = None) -> jax.Array:
    """Forward pass: tokens [B, S] int32 → logits [B, S, V] float32."""
    dt = cfg.dtype
    if cfg.fpdt_host_residual:
        # small-shape test path: assemble the hosted stack on device.
        # (Real long-context use goes through loss_fn, which never
        # materializes full-S anything.)
        x_t, S, C = apply_hidden_hosted(cfg, params, tokens, positions)
        T, BC, H = x_t.shape
        B = BC // C
        from deepspeed_tpu.utils import memspace

        x = memspace.put(x_t, "device")
        x = x.reshape(T, B, C, H).transpose(1, 0, 2, 3).reshape(B, T * C, H)
        x = x[:, :S]
        x = _norm(x, params["final_norm"], cfg.norm, cfg.norm_eps)
    else:
        x = apply_hidden(cfg, params, tokens, positions)
    if cfg.tie_embeddings:
        logits = jnp.einsum("bsh,vh->bsv", x, params["embed"]["tokens"].astype(dt))
    else:
        from deepspeed_tpu.runtime.sharding import (quantized_param_fetch,
                                                    qwz_sequence_barrier)

        unembed, x = qwz_sequence_barrier(params["unembed"]["kernel"], x)
        unembed = quantized_param_fetch(unembed, ("embed", "vocab"),
                                        path="['unembed']['kernel']")
        logits = jnp.einsum("bsh,hv->bsv", x, unembed.astype(dt))
    logits = constrain_activation(logits, ("batch", "seq", "vocab"))
    return logits.astype(jnp.float32)


def loss_fn(cfg: TransformerConfig, params, batch) -> Tuple[jax.Array, Dict]:
    """Causal-LM cross-entropy. batch: {input_ids [B,S]} or
    {input_ids, labels, loss_mask}."""
    tokens = batch["input_ids"]
    if "labels" in batch:
        inputs, labels = tokens, batch["labels"]
    else:
        inputs, labels = tokens[:, :-1], tokens[:, 1:]
    mask = batch.get("loss_mask")
    if mask is not None:
        mask = mask.astype(jnp.float32)
        if mask.shape[1] == tokens.shape[1] and "labels" not in batch:
            mask = mask[:, 1:]

    if cfg.fpdt_host_residual:
        # residual stream lives on host; loss fuses final-norm+unembed+CE
        # per fetched chunk — no full-S device buffer anywhere
        x_t, S_, C_ = apply_hidden_hosted(cfg, params, inputs)
        nll_sum, total = hosted_logits_loss(
            cfg, params, x_t, labels, mask, S_, C_)
        total = jnp.maximum(total, 1.0)
        loss = nll_sum / total
        return loss, {"loss": loss, "ntokens": total}

    if cfg.tiled_logits > 1:
        # fused final-norm+unembed+loss per sequence tile: neither the
        # [B,S,V] logits nor the [B,S,H] fp32 normed hidden materialize
        from deepspeed_tpu.parallel.tiled_compute import tiled_logits_loss

        hidden = apply_hidden(cfg, params, inputs, final_norm=False)

        def fnorm_tile(h):
            return _norm(h, params["final_norm"], cfg.norm, cfg.norm_eps)
        if cfg.tie_embeddings:
            # the table also feeds the token lookup; its gather stays
            # exact (quantizing it would noise embeddings, not just wire)
            unembed = params["embed"]["tokens"].astype(cfg.dtype)
            transpose = True
        else:
            from deepspeed_tpu.runtime.sharding import (
                quantized_param_fetch, qwz_sequence_barrier)

            unembed, hidden = qwz_sequence_barrier(
                params["unembed"]["kernel"], hidden)
            unembed = quantized_param_fetch(
                unembed, ("embed", "vocab"), path="['unembed']['kernel']")
            unembed = unembed.astype(cfg.dtype)
            transpose = False
        nll_sum, total = tiled_logits_loss(
            hidden, unembed, labels, mask, cfg.tiled_logits,
            transpose_unembed=transpose, tile_transform=fnorm_tile)
        total = jnp.maximum(total, 1.0)
        loss = nll_sum / total
        return loss, {"loss": loss, "ntokens": total}

    logits = apply(cfg, params, inputs)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if mask is None:
        mask = jnp.ones_like(nll)
    total = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / total
    return loss, {"loss": loss, "ntokens": total}


class TransformerLM:
    """Thin object bundling (config, init, apply, loss, logical_axes) — the
    'model' handed to deepspeed_tpu.initialize()."""

    def __init__(self, config: TransformerConfig):
        self.config = config

    def init(self, rng) -> Dict[str, Any]:
        from deepspeed_tpu.utils.init_on_device import OnDevice

        # under `with OnDevice(device="meta")` this returns the abstract
        # tree (reference OnDevice/zero.Init construction-time behavior)
        return OnDevice.apply(init_params, self.config, rng)

    def abstract_params(self, rng=None):
        """Shapes/dtypes without materializing (the zero.Init analog's
        first half; see runtime/zero_init.py)."""
        rng = rng if rng is not None else jax.random.PRNGKey(0)
        return jax.eval_shape(lambda r: init_params(self.config, r), rng)

    def logical_axes(self) -> Dict[str, Any]:
        return logical_axes(self.config)

    def apply(self, params, tokens, positions=None):
        return apply(self.config, params, tokens, positions)

    def loss(self, params, batch):
        return loss_fn(self.config, params, batch)

    def flops_per_token(self) -> float:
        return self.config.flops_per_token()

    def num_params(self) -> int:
        return self.config.num_params()
