"""Load HuggingFace checkpoints into zoo parameter trees.

Reference: the checkpoint-loading half of ``module_inject`` — policies
map HF module weights onto the reference's fused/TP layouts
(module_inject/load_checkpoint.py, containers/llama.py). TPU re-design:
a pure tensor-name mapping from an HF ``state_dict`` onto the stacked
pytree of ``models/transformer.py`` — sharding happens afterwards via
AutoTP/engine placement, so loading is layout-only.

Covered: the Llama family (Llama-2/3, Mistral, and other
``{q,k,v,o}_proj / gate,up,down_proj`` models without attention
biases). Qwen2 loads with a warning (its qkv biases are dropped —
the zoo layout is bias-free); GPT-2/OPT/Falcon need bias support in
TransformerLM first and are rejected with a clear error.

Rope parity: both sides use the rotate-half convention, so projection
weights map 1:1 (no row permutation needed).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig, TransformerLM
from deepspeed_tpu.utils.logging import logger


def _to_np(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().to("cpu").float().numpy()
    return np.asarray(t)


def config_from_hf(hf_config, **overrides) -> TransformerConfig:
    """HF LlamaConfig/MistralConfig/Qwen2Config → TransformerConfig."""
    get = lambda k, d=None: getattr(hf_config, k, d)
    if get("rope_scaling"):
        raise ValueError(
            "rope_scaling is not supported yet (Llama-3.1+ scaled rope "
            "would silently produce wrong logits); load a base-rope "
            "checkpoint or strip rope_scaling knowingly")
    head_dim = get("head_dim")
    if head_dim and head_dim != get("hidden_size") // get(
            "num_attention_heads"):
        raise ValueError(
            f"explicit head_dim={head_dim} != hidden//heads "
            f"({get('hidden_size')}//{get('num_attention_heads')}); the "
            "zoo layout derives head_dim and cannot load this model")
    if get("sliding_window"):
        logger.warning(
            f"HF config sets sliding_window={get('sliding_window')}; the "
            "loaded model attends the full causal context — outputs "
            "diverge from transformers beyond the window length")
    cfg = TransformerConfig(
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        num_layers=get("num_hidden_layers"),
        num_heads=get("num_attention_heads"),
        num_kv_heads=get("num_key_value_heads",
                         get("num_attention_heads")),
        ffn_size=get("intermediate_size"),
        max_seq_len=get("max_position_embeddings", 4096),
        pos_emb="rope", norm="rmsnorm", activation="swiglu",
        tie_embeddings=bool(get("tie_word_embeddings", False)),
        rope_theta=float(get("rope_theta", 10000.0)),
        norm_eps=float(get("rms_norm_eps", 1e-5)),
    )
    import dataclasses

    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def load_hf_llama_state_dict(state_dict: Dict[str, Any],
                             cfg: TransformerConfig) -> Dict[str, Any]:
    """HF Llama-family ``state_dict`` → stacked zoo param tree.

    HF linear weights are [out, in] (torch Linear); ours are [in, out]
    einsum operands, so every projection transposes on load.
    """
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}
    if "layers.0.self_attn.q_proj.weight" not in sd:
        known = sorted(sd)[:8]
        raise ValueError(
            "state_dict is not a Llama-family checkpoint (expected "
            f"layers.N.self_attn.q_proj.weight; got e.g. {known}). GPT-2/"
            "OPT/Falcon layouts need bias support and are not loadable "
            "yet.")
    dropped = [k for k in sd if k.endswith(
        ("q_proj.bias", "k_proj.bias", "v_proj.bias"))]
    if dropped:
        logger.warning(
            f"HF load: dropping {len(dropped)} attention bias tensors "
            "(Qwen2-style qkv biases; the zoo layout is bias-free — "
            "expect small numeric drift)")

    L, h = cfg.num_layers, cfg.hidden_size
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    pd = cfg.param_dtype

    def per_layer(name):
        return np.stack([_to_np(sd[f"layers.{i}.{name}"]) for i in range(L)])

    wq = per_layer("self_attn.q_proj.weight")    # [L, nh*hd, H]
    wk = per_layer("self_attn.k_proj.weight")    # [L, nkv*hd, H]
    wv = per_layer("self_attn.v_proj.weight")
    wo = per_layer("self_attn.o_proj.weight")    # [L, H, nh*hd]
    wg = per_layer("mlp.gate_proj.weight")       # [L, F, H]
    wi = per_layer("mlp.up_proj.weight")
    wdown = per_layer("mlp.down_proj.weight")    # [L, H, F]

    import jax.numpy as jnp

    def j(x):
        return jnp.asarray(x, pd)

    params: Dict[str, Any] = {
        "embed": {"tokens": j(_to_np(sd["embed_tokens.weight"]))},
        "layers": {
            "attn": {
                "wq": j(wq.transpose(0, 2, 1).reshape(L, h, nh, hd)),
                "wk": j(wk.transpose(0, 2, 1).reshape(L, h, nkv, hd)),
                "wv": j(wv.transpose(0, 2, 1).reshape(L, h, nkv, hd)),
                "wo": j(wo.transpose(0, 2, 1).reshape(L, nh, hd, h)),
            },
            "mlp": {
                "wg": j(wg.transpose(0, 2, 1)),          # [L, H, F]
                "wi": j(wi.transpose(0, 2, 1)),
                "wo": j(wdown.transpose(0, 2, 1)),       # [L, F, H]
            },
            "ln1": {"scale": j(per_layer("input_layernorm.weight"))},
            "ln2": {"scale": j(per_layer(
                "post_attention_layernorm.weight"))},
        },
        "final_norm": {"scale": j(_to_np(sd["norm.weight"]))},
    }
    if not cfg.tie_embeddings:
        # tied checkpoints ship no lm_head: fall back to the embedding
        lm_head = sd.get("lm_head.weight", sd["embed_tokens.weight"])
        params["unembed"] = {"kernel": j(_to_np(lm_head).T)}
    return params


def config_from_hf_gpt2(hf_config, **overrides) -> TransformerConfig:
    """HF GPT2Config → TransformerConfig (learned positions, layernorm,
    gelu_new ≈ jax.nn.gelu tanh approximation, tied embeddings,
    projection biases)."""
    get = lambda k, d=None: getattr(hf_config, k, d)
    cfg = TransformerConfig(
        vocab_size=get("vocab_size"),
        hidden_size=get("n_embd"),
        num_layers=get("n_layer"),
        num_heads=get("n_head"),
        ffn_size=4 * get("n_embd") if get("n_inner") is None
        else get("n_inner"),
        max_seq_len=get("n_positions", 1024),
        pos_emb="learned", norm="layernorm", activation="gelu",
        tie_embeddings=True, use_biases=True,
        norm_eps=float(get("layer_norm_epsilon", 1e-5)),
    )
    import dataclasses

    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def load_hf_gpt2_state_dict(state_dict: Dict[str, Any],
                            cfg: TransformerConfig) -> Dict[str, Any]:
    """HF GPT-2 ``state_dict`` → stacked zoo tree.

    GPT-2 uses Conv1D modules whose weights are already [in, out] — no
    transpose; c_attn fuses q/k/v on the output dim.
    """
    sd = {k.removeprefix("transformer."): v for k, v in state_dict.items()}
    if "h.0.attn.c_attn.weight" not in sd:
        raise ValueError(
            "state_dict is not a GPT-2 layout (expected "
            "h.N.attn.c_attn.weight)")
    L, h = cfg.num_layers, cfg.hidden_size
    nh, hd = cfg.num_heads, cfg.head_dim

    def per_layer(name):
        return np.stack([_to_np(sd[f"h.{i}.{name}"]) for i in range(L)])

    import jax.numpy as jnp

    def j(x):
        return jnp.asarray(x, cfg.param_dtype)

    cattn_w = per_layer("attn.c_attn.weight")      # [L, H, 3H]
    cattn_b = per_layer("attn.c_attn.bias")        # [L, 3H]
    wq, wk, wv = np.split(cattn_w, 3, axis=2)      # [L, H, H] each
    bq, bk, bv = np.split(cattn_b, 3, axis=1)      # [L, H]
    return {
        "embed": {
            "tokens": j(_to_np(sd["wte.weight"])),
            "positions": j(_to_np(sd["wpe.weight"])[:cfg.max_seq_len]),
        },
        "layers": {
            "attn": {
                "wq": j(wq.reshape(L, h, nh, hd)),
                "wk": j(wk.reshape(L, h, nh, hd)),
                "wv": j(wv.reshape(L, h, nh, hd)),
                "wo": j(per_layer("attn.c_proj.weight")
                        .reshape(L, nh, hd, h)),
                "bq": j(bq.reshape(L, nh, hd)),
                "bk": j(bk.reshape(L, nh, hd)),
                "bv": j(bv.reshape(L, nh, hd)),
                "bo": j(per_layer("attn.c_proj.bias")),
            },
            "mlp": {
                "wi": j(per_layer("mlp.c_fc.weight")),        # [L, H, F]
                "bi": j(per_layer("mlp.c_fc.bias")),
                "wo": j(per_layer("mlp.c_proj.weight")),      # [L, F, H]
                "bo": j(per_layer("mlp.c_proj.bias")),
            },
            "ln1": {"scale": j(per_layer("ln_1.weight")),
                    "bias": j(per_layer("ln_1.bias"))},
            "ln2": {"scale": j(per_layer("ln_2.weight")),
                    "bias": j(per_layer("ln_2.bias"))},
        },
        "final_norm": {"scale": j(_to_np(sd["ln_f.weight"])),
                       "bias": j(_to_np(sd["ln_f.bias"]))},
    }


def from_hf_pretrained(model_or_path, config: Optional[TransformerConfig]
                       = None, **overrides):
    """HF model instance or local path → (TransformerLM, params).

    Reference entry analog: ``deepspeed.init_inference(model, ...)``
    consuming an HF model; here the weights move into the TPU-native
    tree once and the HF/torch object can be dropped.
    """
    if isinstance(model_or_path, str):
        from transformers import AutoConfig, AutoModelForCausalLM

        hf_cfg = AutoConfig.from_pretrained(model_or_path)
        hf_model = AutoModelForCausalLM.from_pretrained(model_or_path)
    else:
        hf_model = model_or_path
        hf_cfg = hf_model.config
    if config is not None and overrides:
        raise ValueError("pass either config= or field overrides, not "
                         "both (overrides would be silently ignored)")
    if getattr(hf_cfg, "model_type", "") == "gpt2":
        cfg = config or config_from_hf_gpt2(hf_cfg, **overrides)
        params = load_hf_gpt2_state_dict(hf_model.state_dict(), cfg)
    else:
        cfg = config or config_from_hf(hf_cfg, **overrides)
        params = load_hf_llama_state_dict(hf_model.state_dict(), cfg)
    return TransformerLM(cfg), params
