"""Load HuggingFace checkpoints into zoo parameter trees.

Reference: the checkpoint-loading half of ``module_inject`` — per-arch
policies map HF module weights onto the reference's fused/TP layouts
(module_inject/load_checkpoint.py, containers/*, and the v2 model
implementations inference/v2/model_implementations/{llama_v2,mistral,
mixtral,opt,phi3,qwen_v2,falcon}). TPU re-design: a pure tensor-name
mapping from an HF ``state_dict`` onto the stacked pytree of
``models/transformer.py`` (or ``models/moe_transformer.py`` for MoE) —
sharding happens afterwards via AutoTP/engine placement, so loading is
layout-only.

Covered architectures (``model_type`` dispatch):
  llama / llama2 / llama3, mistral, qwen2  — {q,k,v,o}_proj layout
    (Qwen2's qkv biases load exactly; missing o/mlp biases zero-fill)
  phi3                                     — fused qkv_proj/gate_up_proj
  mixtral                                  — MoE (router + w1/w2/w3 experts)
  opt                                      — learned positions (offset 2)
  falcon                                   — fused query_key_value,
    parallel attention+MLP block (7B multi-query and classic MHA forms)
  gpt2                                     — Conv1D fused c_attn

Rope parity: both sides use the rotate-half convention, so projection
weights map 1:1 (no row permutation needed).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from deepspeed_tpu.models.transformer import TransformerConfig, TransformerLM
from deepspeed_tpu.utils.logging import logger


def _to_np(t) -> np.ndarray:
    if isinstance(t, np.ndarray):
        return t
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().to("cpu").float().numpy()
    return np.asarray(t)


def _j(x, dtype):
    import jax.numpy as jnp

    return jnp.asarray(x, dtype)


# ---------------------------------------------------------------------------
# Llama family (llama/llama2/llama3, mistral, qwen2)
# ---------------------------------------------------------------------------


def config_from_hf(hf_config, state_dict=None, **overrides
                   ) -> TransformerConfig:
    """HF LlamaConfig/MistralConfig/Qwen2Config → TransformerConfig.

    ``state_dict`` (optional) turns on ``use_biases`` when the
    checkpoint actually carries projection biases (Qwen2 qkv; Llama with
    attention_bias/mlp_bias) so no tensor is silently dropped.
    """
    get = lambda k, d=None: getattr(hf_config, k, d)
    if get("rope_scaling"):
        raise ValueError(
            "rope_scaling is not supported yet (Llama-3.1+ scaled rope "
            "would silently produce wrong logits); load a base-rope "
            "checkpoint or strip rope_scaling knowingly")
    head_dim = get("head_dim")
    if head_dim and head_dim != get("hidden_size") // get(
            "num_attention_heads"):
        raise ValueError(
            f"explicit head_dim={head_dim} != hidden//heads "
            f"({get('hidden_size')}//{get('num_attention_heads')}); the "
            "zoo layout derives head_dim and cannot load this model")
    if get("sliding_window"):
        logger.warning(
            f"HF config sets sliding_window={get('sliding_window')}; the "
            "loaded model attends the full causal context — outputs "
            "diverge from transformers beyond the window length")
    use_biases = bool(get("attention_bias") or get("mlp_bias"))
    if state_dict is not None:
        use_biases = use_biases or any(
            k.endswith((".q_proj.bias", ".o_proj.bias", ".up_proj.bias"))
            for k in state_dict)
    cfg = TransformerConfig(
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        num_layers=get("num_hidden_layers"),
        num_heads=get("num_attention_heads"),
        num_kv_heads=get("num_key_value_heads",
                         get("num_attention_heads")),
        ffn_size=get("intermediate_size"),
        max_seq_len=get("max_position_embeddings", 4096),
        pos_emb="rope", norm="rmsnorm", activation="swiglu",
        tie_embeddings=bool(get("tie_word_embeddings", False)),
        rope_theta=float(get("rope_theta", 10000.0)),
        norm_eps=float(get("rms_norm_eps", 1e-5)),
        use_biases=use_biases,
    )
    import dataclasses

    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def _bias_or_zeros(sd, name, L, shape, per_layer_np):
    """Stacked bias [L, *shape]; zero when the checkpoint has none (an
    arch that defines only some biases, e.g. Qwen2's qkv-only)."""
    if f"layers.0.{name}" in sd:
        return per_layer_np(name).reshape((L,) + shape)
    return np.zeros((L,) + shape, np.float32)


def load_hf_llama_state_dict(state_dict: Dict[str, Any],
                             cfg: TransformerConfig) -> Dict[str, Any]:
    """HF Llama-family ``state_dict`` → stacked zoo param tree.

    HF linear weights are [out, in] (torch Linear); ours are [in, out]
    einsum operands, so every projection transposes on load.
    """
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}
    if "layers.0.self_attn.q_proj.weight" not in sd:
        known = sorted(sd)[:8]
        raise ValueError(
            "state_dict is not a Llama-family checkpoint (expected "
            f"layers.N.self_attn.q_proj.weight; got e.g. {known})")
    bias_keys = [k for k in sd if k.endswith(".bias")]
    if bias_keys and not cfg.use_biases:
        raise ValueError(
            f"checkpoint carries {len(bias_keys)} bias tensors (e.g. "
            f"{bias_keys[0]}) but the target config has use_biases="
            "False — loading would silently drop them and change "
            "logits; build the config via config_from_hf(hf_config, "
            "state_dict) so biases are detected")

    L, h = cfg.num_layers, cfg.hidden_size
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    pd = cfg.param_dtype

    def per_layer(name):
        return np.stack([_to_np(sd[f"layers.{i}.{name}"]) for i in range(L)])

    wq = per_layer("self_attn.q_proj.weight")    # [L, nh*hd, H]
    wk = per_layer("self_attn.k_proj.weight")    # [L, nkv*hd, H]
    wv = per_layer("self_attn.v_proj.weight")
    wo = per_layer("self_attn.o_proj.weight")    # [L, H, nh*hd]
    wg = per_layer("mlp.gate_proj.weight")       # [L, F, H]
    wi = per_layer("mlp.up_proj.weight")
    wdown = per_layer("mlp.down_proj.weight")    # [L, H, F]

    def j(x):
        return _j(x, pd)

    attn: Dict[str, Any] = {
        "wq": j(wq.transpose(0, 2, 1).reshape(L, h, nh, hd)),
        "wk": j(wk.transpose(0, 2, 1).reshape(L, h, nkv, hd)),
        "wv": j(wv.transpose(0, 2, 1).reshape(L, h, nkv, hd)),
        "wo": j(wo.transpose(0, 2, 1).reshape(L, nh, hd, h)),
    }
    mlp: Dict[str, Any] = {
        "wg": j(wg.transpose(0, 2, 1)),          # [L, H, F]
        "wi": j(wi.transpose(0, 2, 1)),
        "wo": j(wdown.transpose(0, 2, 1)),       # [L, F, H]
    }
    if cfg.use_biases:
        attn["bq"] = j(_bias_or_zeros(
            sd, "self_attn.q_proj.bias", L, (nh, hd), per_layer))
        attn["bk"] = j(_bias_or_zeros(
            sd, "self_attn.k_proj.bias", L, (nkv, hd), per_layer))
        attn["bv"] = j(_bias_or_zeros(
            sd, "self_attn.v_proj.bias", L, (nkv, hd), per_layer))
        attn["bo"] = j(_bias_or_zeros(
            sd, "self_attn.o_proj.bias", L, (h,), per_layer))
        # swiglu zoo layout has no gate/up biases; mlp_bias checkpoints
        # carry them — refuse rather than silently drop
        if "layers.0.mlp.up_proj.bias" in sd:
            raise ValueError(
                "mlp_bias=True Llama checkpoints are not supported (the "
                "swiglu zoo layout has no gate/up bias slots)")
        # structural parity with init_params(use_biases=True): the
        # swiglu forward reads only bo; bi exists as a zero slot
        mlp["bi"] = _j(np.zeros((L, cfg.ffn), np.float32), pd)
        mlp["bo"] = _j(np.zeros((L, h), np.float32), pd)
    params: Dict[str, Any] = {
        "embed": {"tokens": j(_to_np(sd["embed_tokens.weight"]))},
        "layers": {
            "attn": attn,
            "mlp": mlp,
            "ln1": {"scale": j(per_layer("input_layernorm.weight"))},
            "ln2": {"scale": j(per_layer(
                "post_attention_layernorm.weight"))},
        },
        "final_norm": {"scale": j(_to_np(sd["norm.weight"]))},
    }
    if not cfg.tie_embeddings:
        # tied checkpoints ship no lm_head: fall back to the embedding
        lm_head = sd.get("lm_head.weight", sd["embed_tokens.weight"])
        params["unembed"] = {"kernel": j(_to_np(lm_head).T)}
    return params


# ---------------------------------------------------------------------------
# Phi-3 (fused qkv_proj / gate_up_proj; reference
# inference/v2/model_implementations/phi3)
# ---------------------------------------------------------------------------


def load_hf_phi3_state_dict(state_dict: Dict[str, Any],
                            cfg: TransformerConfig) -> Dict[str, Any]:
    """Phi-3 fuses qkv_proj and gate_up_proj; split them into synthetic
    q/k/v_proj + gate/up_proj keys and delegate to the llama loader (one
    assembly path, one bias-refusal check)."""
    if not any(k.endswith("self_attn.qkv_proj.weight") for k in state_dict):
        raise ValueError(
            "state_dict is not a Phi-3 layout (expected "
            "layers.N.self_attn.qkv_proj.weight)")
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    F = cfg.ffn
    out: Dict[str, Any] = {}
    for k, v in state_dict.items():
        if k.endswith("self_attn.qkv_proj.weight"):
            a = _to_np(v)  # [(nh+2nkv)*hd, H]
            base = k[: -len("qkv_proj.weight")]
            out[base + "q_proj.weight"] = a[: nh * hd]
            out[base + "k_proj.weight"] = a[nh * hd: nh * hd + nkv * hd]
            out[base + "v_proj.weight"] = a[nh * hd + nkv * hd:]
        elif k.endswith("mlp.gate_up_proj.weight"):
            a = _to_np(v)  # [2F, H]
            base = k[: -len("gate_up_proj.weight")]
            out[base + "gate_proj.weight"] = a[:F]
            out[base + "up_proj.weight"] = a[F:]
        else:
            out[k] = v
    return load_hf_llama_state_dict(out, cfg)


# ---------------------------------------------------------------------------
# OPT (learned positions with offset 2; reference
# inference/v2/model_implementations/opt, containers/opt.py)
# ---------------------------------------------------------------------------


def config_from_hf_opt(hf_config, **overrides) -> TransformerConfig:
    get = lambda k, d=None: getattr(hf_config, k, d)
    if get("word_embed_proj_dim", get("hidden_size")) != get("hidden_size"):
        raise ValueError(
            "OPT checkpoints with word_embed_proj_dim != hidden_size "
            "(350m-style projected embeddings) are not supported")
    if not get("do_layer_norm_before", True):
        raise ValueError(
            "OPT post-layernorm variants (do_layer_norm_before=False, "
            "e.g. opt-350m) are not supported — the zoo block is pre-norm")
    act = get("activation_function", "relu")
    if act not in ("relu", "gelu"):
        raise ValueError(f"unsupported OPT activation {act!r}")
    cfg = TransformerConfig(
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        num_layers=get("num_hidden_layers"),
        num_heads=get("num_attention_heads"),
        ffn_size=get("ffn_dim"),
        max_seq_len=get("max_position_embeddings", 2048),
        pos_emb="learned", norm="layernorm", activation=act,
        tie_embeddings=bool(get("tie_word_embeddings", True)),
        use_biases=bool(get("enable_bias", True)),
        norm_eps=1e-5,
    )
    import dataclasses

    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def load_hf_opt_state_dict(state_dict: Dict[str, Any],
                           cfg: TransformerConfig) -> Dict[str, Any]:
    sd = {k.removeprefix("model.").removeprefix("decoder."): v
          for k, v in state_dict.items()}
    if "layers.0.self_attn.q_proj.weight" not in sd or \
            "embed_positions.weight" not in sd:
        raise ValueError(
            "state_dict is not an OPT layout (expected decoder."
            "layers.N.self_attn.q_proj.weight + embed_positions.weight)")
    L, h = cfg.num_layers, cfg.hidden_size
    nh, hd = cfg.num_heads, cfg.head_dim

    def per_layer(name):
        return np.stack([_to_np(sd[f"layers.{i}.{name}"]) for i in range(L)])

    def j(x):
        return _j(x, cfg.param_dtype)

    # OPTLearnedPositionalEmbedding indexes at position+2: drop the two
    # offset rows so our arange(S) lookup lands on the same vectors
    pos = _to_np(sd["embed_positions.weight"])[2:]
    params = {
        "embed": {
            "tokens": j(_to_np(sd["embed_tokens.weight"])),
            "positions": j(pos[: cfg.max_seq_len]),
        },
        "layers": {
            "attn": {
                "wq": j(per_layer("self_attn.q_proj.weight")
                        .transpose(0, 2, 1).reshape(L, h, nh, hd)),
                "wk": j(per_layer("self_attn.k_proj.weight")
                        .transpose(0, 2, 1).reshape(L, h, nh, hd)),
                "wv": j(per_layer("self_attn.v_proj.weight")
                        .transpose(0, 2, 1).reshape(L, h, nh, hd)),
                "wo": j(per_layer("self_attn.out_proj.weight")
                        .transpose(0, 2, 1).reshape(L, nh, hd, h)),
                "bq": j(per_layer("self_attn.q_proj.bias")
                        .reshape(L, nh, hd)),
                "bk": j(per_layer("self_attn.k_proj.bias")
                        .reshape(L, nh, hd)),
                "bv": j(per_layer("self_attn.v_proj.bias")
                        .reshape(L, nh, hd)),
                "bo": j(per_layer("self_attn.out_proj.bias")),
            },
            "mlp": {
                "wi": j(per_layer("fc1.weight").transpose(0, 2, 1)),
                "bi": j(per_layer("fc1.bias")),
                "wo": j(per_layer("fc2.weight").transpose(0, 2, 1)),
                "bo": j(per_layer("fc2.bias")),
            },
            "ln1": {"scale": j(per_layer("self_attn_layer_norm.weight")),
                    "bias": j(per_layer("self_attn_layer_norm.bias"))},
            "ln2": {"scale": j(per_layer("final_layer_norm.weight")),
                    "bias": j(per_layer("final_layer_norm.bias"))},
        },
        "final_norm": {"scale": j(_to_np(sd["final_layer_norm.weight"])),
                       "bias": j(_to_np(sd["final_layer_norm.bias"]))},
    }
    if not cfg.tie_embeddings:
        lm_head = state_dict.get("lm_head.weight",
                                 sd["embed_tokens.weight"])
        params["unembed"] = {"kernel": j(_to_np(lm_head).T)}
    return params


# ---------------------------------------------------------------------------
# Falcon (fused query_key_value + parallel block; reference
# inference/v2/model_implementations/falcon, containers/)
# ---------------------------------------------------------------------------


def config_from_hf_falcon(hf_config, **overrides) -> TransformerConfig:
    get = lambda k, d=None: getattr(hf_config, k, d)
    if get("alibi"):
        raise ValueError("alibi Falcon variants are not supported (the "
                         "zoo block is rotary-only)")
    if get("new_decoder_architecture"):
        raise ValueError(
            "new_decoder_architecture Falcon (40B/180B grouped-qkv "
            "layout) is not supported yet; 7B-style checkpoints load")
    nh = get("num_attention_heads")
    nkv = 1 if get("multi_query", True) else nh
    cfg = TransformerConfig(
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        num_layers=get("num_hidden_layers"),
        num_heads=nh,
        num_kv_heads=nkv,
        ffn_size=get("ffn_hidden_size") or 4 * get("hidden_size"),
        max_seq_len=get("max_position_embeddings", 2048),
        pos_emb="rope", norm="layernorm", activation="gelu",
        tie_embeddings=bool(get("tie_word_embeddings", True)),
        use_biases=bool(get("bias", False)),
        rope_theta=float(get("rope_theta", 10000.0)),
        norm_eps=float(get("layer_norm_epsilon", 1e-5)),
        parallel_block=bool(get("parallel_attn", True)),
    )
    import dataclasses

    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def load_hf_falcon_state_dict(state_dict: Dict[str, Any],
                              cfg: TransformerConfig) -> Dict[str, Any]:
    sd = {k.removeprefix("transformer."): v for k, v in state_dict.items()}
    if "h.0.self_attention.query_key_value.weight" not in sd:
        raise ValueError(
            "state_dict is not a Falcon layout (expected "
            "h.N.self_attention.query_key_value.weight)")
    if cfg.use_biases:
        raise ValueError("bias=True Falcon variants are not supported")
    L, h = cfg.num_layers, cfg.hidden_size
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim

    def per_layer(name):
        return np.stack([_to_np(sd[f"h.{i}.{name}"]) for i in range(L)])

    def j(x):
        return _j(x, cfg.param_dtype)

    qkv = per_layer("self_attention.query_key_value.weight")
    if nkv == 1:  # multi-query: rows [q (nh*hd), k (hd), v (hd)]
        wq = qkv[:, : nh * hd]
        wk = qkv[:, nh * hd: nh * hd + hd]
        wv = qkv[:, nh * hd + hd:]
    else:  # classic MHA falcon (rw-1b): per-head interleave [nh, 3, hd]
        qkv = qkv.reshape(L, nh, 3, hd, h)
        wq = qkv[:, :, 0].reshape(L, nh * hd, h)
        wk = qkv[:, :, 1].reshape(L, nh * hd, h)
        wv = qkv[:, :, 2].reshape(L, nh * hd, h)

    ln_scale = per_layer("input_layernorm.weight")
    ln_bias = per_layer("input_layernorm.bias")
    if cfg.parallel_block:
        # parallel block: one shared input_layernorm; the zoo layout
        # keeps separate ln1/ln2 slots, so duplicate it (mathematically
        # identical — same input, same params)
        ln2_scale, ln2_bias = ln_scale.copy(), ln_bias.copy()
    else:
        # sequential falcon (rw family) trains a separate MLP norm
        ln2_scale = per_layer("post_attention_layernorm.weight")
        ln2_bias = per_layer("post_attention_layernorm.bias")
    params = {
        "embed": {"tokens": j(_to_np(sd["word_embeddings.weight"]))},
        "layers": {
            "attn": {
                "wq": j(wq.transpose(0, 2, 1).reshape(L, h, nh, hd)),
                "wk": j(wk.transpose(0, 2, 1).reshape(L, h, nkv, hd)),
                "wv": j(wv.transpose(0, 2, 1).reshape(L, h, nkv, hd)),
                "wo": j(per_layer("self_attention.dense.weight")
                        .transpose(0, 2, 1).reshape(L, nh, hd, h)),
            },
            "mlp": {
                "wi": j(per_layer("mlp.dense_h_to_4h.weight")
                        .transpose(0, 2, 1)),
                "wo": j(per_layer("mlp.dense_4h_to_h.weight")
                        .transpose(0, 2, 1)),
            },
            "ln1": {"scale": j(ln_scale), "bias": j(ln_bias)},
            "ln2": {"scale": j(ln2_scale), "bias": j(ln2_bias)},
        },
        "final_norm": {"scale": j(_to_np(sd["ln_f.weight"])),
                       "bias": j(_to_np(sd["ln_f.bias"]))},
    }
    if not cfg.tie_embeddings:
        lm_head = state_dict.get("lm_head.weight",
                                 sd["word_embeddings.weight"])
        params["unembed"] = {"kernel": j(_to_np(lm_head).T)}
    return params


# ---------------------------------------------------------------------------
# Mixtral (MoE; reference inference/v2/model_implementations/mixtral)
# ---------------------------------------------------------------------------


def config_from_hf_mixtral(hf_config, **overrides):
    from deepspeed_tpu.models.moe_transformer import MoETransformerConfig

    get = lambda k, d=None: getattr(hf_config, k, d)
    if get("rope_scaling"):
        raise ValueError("rope_scaling is not supported yet")
    cfg = MoETransformerConfig(
        vocab_size=get("vocab_size"),
        hidden_size=get("hidden_size"),
        num_layers=get("num_hidden_layers"),
        num_heads=get("num_attention_heads"),
        num_kv_heads=get("num_key_value_heads"),
        ffn_size=get("intermediate_size"),
        max_seq_len=get("max_position_embeddings", 4096),
        pos_emb="rope", norm="rmsnorm", activation="swiglu",
        tie_embeddings=bool(get("tie_word_embeddings", False)),
        rope_theta=float(get("rope_theta", 1e6)),
        norm_eps=float(get("rms_norm_eps", 1e-5)),
        num_experts=get("num_local_experts"),
        top_k=get("num_experts_per_tok"),
        # HF routes every token (no capacity drop): match for parity;
        # training configs may re-enable drop_tokens
        drop_tokens=False,
        aux_loss_weight=float(get("router_aux_loss_coef", 0.02) or 0.0),
    )
    import dataclasses

    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def load_hf_mixtral_state_dict(state_dict: Dict[str, Any], cfg
                               ) -> Dict[str, Any]:
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}
    if "layers.0.block_sparse_moe.gate.weight" not in sd:
        raise ValueError(
            "state_dict is not a Mixtral layout (expected "
            "layers.N.block_sparse_moe.gate.weight)")
    L, h = cfg.num_layers, cfg.hidden_size
    nh, nkv, hd = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    E = cfg.num_experts
    pd = cfg.param_dtype

    def per_layer(name):
        return np.stack([_to_np(sd[f"layers.{i}.{name}"]) for i in range(L)])

    def per_expert(name):
        # [L, E, out, in] → ours [L, E, in, out]
        return np.stack([
            np.stack([_to_np(sd[f"layers.{i}.block_sparse_moe.experts."
                                f"{e}.{name}"]) for e in range(E)])
            for i in range(L)]).transpose(0, 1, 3, 2)

    def j(x):
        return _j(x, pd)

    wq = per_layer("self_attn.q_proj.weight")
    wk = per_layer("self_attn.k_proj.weight")
    wv = per_layer("self_attn.v_proj.weight")
    wo = per_layer("self_attn.o_proj.weight")
    params = {
        "embed": {"tokens": j(_to_np(sd["embed_tokens.weight"]))},
        "layers": {
            "attn": {
                "wq": j(wq.transpose(0, 2, 1).reshape(L, h, nh, hd)),
                "wk": j(wk.transpose(0, 2, 1).reshape(L, h, nkv, hd)),
                "wv": j(wv.transpose(0, 2, 1).reshape(L, h, nkv, hd)),
                "wo": j(wo.transpose(0, 2, 1).reshape(L, nh, hd, h)),
            },
            "moe": {
                # HF gate.weight [E, H] → router [H, E]
                "router": j(per_layer("block_sparse_moe.gate.weight")
                            .transpose(0, 2, 1)),
                "experts": {
                    "wg": j(per_expert("w1.weight")),   # gate
                    "wo": j(per_expert("w2.weight")),   # down
                    "wi": j(per_expert("w3.weight")),   # up
                },
            },
            "ln1": {"scale": j(per_layer("input_layernorm.weight"))},
            "ln2": {"scale": j(per_layer(
                "post_attention_layernorm.weight"))},
        },
        "final_norm": {"scale": j(_to_np(sd["norm.weight"]))},
    }
    if not cfg.tie_embeddings:
        lm_head = sd.get("lm_head.weight", sd["embed_tokens.weight"])
        params["unembed"] = {"kernel": j(_to_np(lm_head).T)}
    return params


# ---------------------------------------------------------------------------
# GPT-2 (Conv1D fused c_attn)
# ---------------------------------------------------------------------------


def config_from_hf_gpt2(hf_config, **overrides) -> TransformerConfig:
    """HF GPT2Config → TransformerConfig (learned positions, layernorm,
    gelu_new ≈ jax.nn.gelu tanh approximation, tied embeddings,
    projection biases)."""
    get = lambda k, d=None: getattr(hf_config, k, d)
    cfg = TransformerConfig(
        vocab_size=get("vocab_size"),
        hidden_size=get("n_embd"),
        num_layers=get("n_layer"),
        num_heads=get("n_head"),
        ffn_size=4 * get("n_embd") if get("n_inner") is None
        else get("n_inner"),
        max_seq_len=get("n_positions", 1024),
        pos_emb="learned", norm="layernorm", activation="gelu_tanh",
        tie_embeddings=True, use_biases=True,
        norm_eps=float(get("layer_norm_epsilon", 1e-5)),
    )
    import dataclasses

    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def load_hf_gpt2_state_dict(state_dict: Dict[str, Any],
                            cfg: TransformerConfig) -> Dict[str, Any]:
    """HF GPT-2 ``state_dict`` → stacked zoo tree.

    GPT-2 uses Conv1D modules whose weights are already [in, out] — no
    transpose; c_attn fuses q/k/v on the output dim.
    """
    sd = {k.removeprefix("transformer."): v for k, v in state_dict.items()}
    if "h.0.attn.c_attn.weight" not in sd:
        raise ValueError(
            "state_dict is not a GPT-2 layout (expected "
            "h.N.attn.c_attn.weight)")
    L, h = cfg.num_layers, cfg.hidden_size
    nh, hd = cfg.num_heads, cfg.head_dim

    def per_layer(name):
        return np.stack([_to_np(sd[f"h.{i}.{name}"]) for i in range(L)])

    def j(x):
        return _j(x, cfg.param_dtype)

    cattn_w = per_layer("attn.c_attn.weight")      # [L, H, 3H]
    cattn_b = per_layer("attn.c_attn.bias")        # [L, 3H]
    wq, wk, wv = np.split(cattn_w, 3, axis=2)      # [L, H, H] each
    bq, bk, bv = np.split(cattn_b, 3, axis=1)      # [L, H]
    return {
        "embed": {
            "tokens": j(_to_np(sd["wte.weight"])),
            "positions": j(_to_np(sd["wpe.weight"])[:cfg.max_seq_len]),
        },
        "layers": {
            "attn": {
                "wq": j(wq.reshape(L, h, nh, hd)),
                "wk": j(wk.reshape(L, h, nh, hd)),
                "wv": j(wv.reshape(L, h, nh, hd)),
                "wo": j(per_layer("attn.c_proj.weight")
                        .reshape(L, nh, hd, h)),
                "bq": j(bq.reshape(L, nh, hd)),
                "bk": j(bk.reshape(L, nh, hd)),
                "bv": j(bv.reshape(L, nh, hd)),
                "bo": j(per_layer("attn.c_proj.bias")),
            },
            "mlp": {
                "wi": j(per_layer("mlp.c_fc.weight")),        # [L, H, F]
                "bi": j(per_layer("mlp.c_fc.bias")),
                "wo": j(per_layer("mlp.c_proj.weight")),      # [L, F, H]
                "bo": j(per_layer("mlp.c_proj.bias")),
            },
            "ln1": {"scale": j(per_layer("ln_1.weight")),
                    "bias": j(per_layer("ln_1.bias"))},
            "ln2": {"scale": j(per_layer("ln_2.weight")),
                    "bias": j(per_layer("ln_2.bias"))},
        },
        "final_norm": {"scale": j(_to_np(sd["ln_f.weight"])),
                       "bias": j(_to_np(sd["ln_f.bias"]))},
    }


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


def from_hf_pretrained(model_or_path, config=None, **overrides):
    """HF model instance or local path → (TransformerLM | MoETransformerLM,
    params).

    Reference entry analog: ``deepspeed.init_inference(model, ...)``
    consuming an HF model; here the weights move into the TPU-native
    tree once and the HF/torch object can be dropped. Dispatches on
    ``hf_config.model_type``:
    llama/mistral/qwen2 | phi3 | mixtral | opt | falcon | gpt2.
    """
    if isinstance(model_or_path, str):
        from transformers import AutoConfig, AutoModelForCausalLM

        hf_cfg = AutoConfig.from_pretrained(model_or_path)
        hf_model = AutoModelForCausalLM.from_pretrained(model_or_path)
    else:
        hf_model = model_or_path
        hf_cfg = hf_model.config
    if config is not None and overrides:
        raise ValueError("pass either config= or field overrides, not "
                         "both (overrides would be silently ignored)")
    sd = hf_model.state_dict()
    mt = getattr(hf_cfg, "model_type", "")
    if mt == "gpt2":
        cfg = config or config_from_hf_gpt2(hf_cfg, **overrides)
        return TransformerLM(cfg), load_hf_gpt2_state_dict(sd, cfg)
    if mt == "phi3":
        cfg = config or config_from_hf(hf_cfg, state_dict=sd, **overrides)
        return TransformerLM(cfg), load_hf_phi3_state_dict(sd, cfg)
    if mt == "opt":
        cfg = config or config_from_hf_opt(hf_cfg, **overrides)
        return TransformerLM(cfg), load_hf_opt_state_dict(sd, cfg)
    if mt == "falcon":
        cfg = config or config_from_hf_falcon(hf_cfg, **overrides)
        return TransformerLM(cfg), load_hf_falcon_state_dict(sd, cfg)
    if mt == "mixtral":
        from deepspeed_tpu.models.moe_transformer import MoETransformerLM

        cfg = config or config_from_hf_mixtral(hf_cfg, **overrides)
        return MoETransformerLM(cfg), load_hf_mixtral_state_dict(sd, cfg)
    # llama / mistral / qwen2 / other q_proj-layout models
    cfg = config or config_from_hf(hf_cfg, state_dict=sd, **overrides)
    return TransformerLM(cfg), load_hf_llama_state_dict(sd, cfg)
