"""deepspeed_tpu: a TPU-native training & inference framework.

From-scratch JAX/XLA/Pallas re-design of the capabilities of the DeepSpeed
reference (see SURVEY.md): config-driven engine, ZeRO-equivalent sharded
optimization, 3D/4D parallelism on a device mesh, sequence & expert
parallelism, host/NVMe offload, universal checkpointing, ragged inference,
and first-class observability.

Public entry points (parity with reference deepspeed/__init__.py):

  initialize(...)      -> (engine, optimizer, dataloader, lr_scheduler)
  init_inference(...)  -> InferenceEngine
  comm                 -> collectives facade (deepspeed.comm analog)
"""

from deepspeed_tpu.version import __version__, git_hash, git_branch

# Everything below imports jax transitively; resolve lazily (PEP 562) so
# host-side CLI processes (dstpu runner/ssh fan-out, elastic agent) that
# only need logging/hostfile parsing never pay the jax import, and
# launch.py can bind cores before jax spins up its thread pools.
_LAZY_EXPORTS = {
    "comm": ("deepspeed_tpu.comm", None),
    "Config": ("deepspeed_tpu.config.config", "Config"),
    "load_config": ("deepspeed_tpu.config.config", "load_config"),
    "TopologyConfig": ("deepspeed_tpu.parallel.topology", "TopologyConfig"),
    "build_mesh": ("deepspeed_tpu.parallel.topology", "build_mesh"),
}


def __getattr__(name):
    if name in _LAZY_EXPORTS:
        import importlib

        mod, attr = _LAZY_EXPORTS[name]
        module = importlib.import_module(mod)
        value = module if attr is None else getattr(module, attr)
        globals()[name] = value  # cache for next access
        return value
    raise AttributeError(
        f"module 'deepspeed_tpu' has no attribute {name!r}")


def initialize(*args, **kwargs):
    """Build a training Engine (reference deepspeed.initialize __init__.py:93).

    Lazy import keeps `import deepspeed_tpu` cheap (no engine deps)."""
    from deepspeed_tpu.runtime.engine import initialize as _initialize

    return _initialize(*args, **kwargs)


def init_inference(*args, **kwargs):
    """Build an inference engine (reference deepspeed.init_inference
    __init__.py:328)."""
    from deepspeed_tpu.inference.engine import init_inference as _init_inference

    return _init_inference(*args, **kwargs)


def tp_model_init(*args, **kwargs):
    """Shard a parameter tree for tensor parallelism (reference
    deepspeed.tp_model_init __init__.py:408)."""
    from deepspeed_tpu.module_inject.auto_tp import \
        tp_model_init as _tp_model_init

    return _tp_model_init(*args, **kwargs)


def ep_model_init(*args, **kwargs):
    """Restack + shard an HF MoE tree for expert parallelism (reference
    AutoEP module_inject/auto_ep.py:273)."""
    from deepspeed_tpu.module_inject.auto_ep import \
        ep_model_init as _ep_model_init

    return _ep_model_init(*args, **kwargs)


def init_compression(*args, **kwargs):
    """Build compression state from a config (reference
    deepspeed.compression.compress.init_compression)."""
    from deepspeed_tpu.compression import init_compression as _init

    return _init(*args, **kwargs)


def add_config_arguments(parser):
    """Augment an argparse parser with --deepspeed flags (reference
    __init__.py:305)."""
    group = parser.add_argument_group("DeepSpeed-TPU", "configuration")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to the framework JSON config file.")
    group.add_argument("--local_rank", type=int, default=-1,
                       help="Accepted for launcher compatibility; unused.")
    return parser
