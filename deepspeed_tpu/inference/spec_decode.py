"""Speculative decoding drafters for the ragged serving engine.

Decode burns one full forward per token; speculation proposes ``k``
candidate tokens per sequence and verifies them in ONE batched forward
through the existing ragged step (the chunk machinery built for
SplitFuse prefill is exactly a multi-token verifier). With greedy
sampling, acceptance keeps the longest prefix of drafts that match the
model's own argmax chain — the emitted stream is the argmax chain
itself, so speculative greedy is token-identical to non-speculative
greedy regardless of draft quality; drafts only change how many tokens
one forward yields.

The default drafter is model-free prompt-lookup / n-gram matching
(PAPERS.md: "Prompt Lookup Decoding", also shipped in vLLM and
transformers as ``prompt_lookup_num_tokens``): the continuation of the
longest recent n-gram that already occurred earlier in the sequence is
proposed verbatim. On repetitive workloads (code, extraction, RAG with
quoted context) acceptance rates are high and there is no draft model
to host. ``Drafter`` is the hook for a real draft model: anything with
``propose(tokens, k) -> list[int]`` plugs into the engine.
"""

from __future__ import annotations

from typing import List, Protocol, Sequence, runtime_checkable


@runtime_checkable
class Drafter(Protocol):
    """Draft-proposal interface (the draft-model hook).

    ``tokens`` is the sequence's full token history (prompt + generated)
    and the return value is up to ``k`` proposed next tokens. An empty
    list means "no proposal" — the engine falls back to plain decode for
    that sequence this step."""

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        ...


class PromptLookupDrafter:
    """N-gram / prompt-lookup drafter: match the last ``n`` tokens
    (``max_ngram`` down to ``min_ngram``) against earlier history and
    propose the tokens that followed the most recent match."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"({min_ngram}, {max_ngram})")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        # drafter-side observability: how often the n-gram scan finds a
        # proposal at all (acceptance lives in the engine's
        # serve.spec_* counters; a low proposal rate means the workload
        # is non-repetitive and speculation is idling, not failing)
        self.stats = {"calls": 0, "proposals": 0, "proposed_tokens": 0,
                      "empty": 0}

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        self.stats["calls"] += 1
        toks = list(tokens)
        L = len(toks)
        if k <= 0 or L < self.min_ngram + 1:
            self.stats["empty"] += 1
            return []
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            pattern = toks[L - n:]
            # most recent earlier occurrence wins (local context beats a
            # stale match from the far prompt)
            for i in range(L - n - 1, -1, -1):
                if toks[i:i + n] == pattern:
                    cont = toks[i + n:i + n + k]
                    if cont:
                        self.stats["proposals"] += 1
                        self.stats["proposed_tokens"] += len(cont)
                        return cont
        self.stats["empty"] += 1
        return []
