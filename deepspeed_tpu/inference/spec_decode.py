"""Speculative decoding drafters for the ragged serving engine.

Decode burns one full forward per token; speculation proposes ``k``
candidate tokens per sequence and verifies them in ONE batched forward
through the existing ragged step (the chunk machinery built for
SplitFuse prefill is exactly a multi-token verifier). With greedy
sampling, acceptance keeps the longest prefix of drafts that match the
model's own argmax chain — the emitted stream is the argmax chain
itself, so speculative greedy is token-identical to non-speculative
greedy regardless of draft quality; drafts only change how many tokens
one forward yields.

The default drafter is model-free prompt-lookup / n-gram matching
(PAPERS.md: "Prompt Lookup Decoding", also shipped in vLLM and
transformers as ``prompt_lookup_num_tokens``): the continuation of the
longest recent n-gram that already occurred earlier in the sequence is
proposed verbatim. On repetitive workloads (code, extraction, RAG with
quoted context) acceptance rates are high and there is no draft model
to host. ``Drafter`` is the hook for a real draft model: anything with
``propose(tokens, k) -> list[int]`` plugs into the engine.
"""

from __future__ import annotations

from typing import Any, List, Optional, Protocol, Sequence, \
    runtime_checkable


@runtime_checkable
class Drafter(Protocol):
    """Draft-proposal interface (the draft-model hook).

    ``tokens`` is the sequence's full token history (prompt + generated)
    and the return value is up to ``k`` proposed next tokens. An empty
    list means "no proposal" — the engine falls back to plain decode for
    that sequence this step."""

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        ...


class PromptLookupDrafter:
    """N-gram / prompt-lookup drafter: match the last ``n`` tokens
    (``max_ngram`` down to ``min_ngram``) against earlier history and
    propose the tokens that followed the most recent match."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"({min_ngram}, {max_ngram})")
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram
        # drafter-side observability: how often the n-gram scan finds a
        # proposal at all (acceptance lives in the engine's
        # serve.spec_* counters; a low proposal rate means the workload
        # is non-repetitive and speculation is idling, not failing)
        self.stats = {"calls": 0, "proposals": 0, "proposed_tokens": 0,
                      "empty": 0}

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        self.stats["calls"] += 1
        toks = list(tokens)
        L = len(toks)
        if k <= 0 or L < self.min_ngram + 1:
            self.stats["empty"] += 1
            return []
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            pattern = toks[L - n:]
            # most recent earlier occurrence wins (local context beats a
            # stale match from the far prompt)
            for i in range(L - n - 1, -1, -1):
                if toks[i:i + n] == pattern:
                    cont = toks[i + n:i + n + k]
                    if cont:
                        self.stats["proposals"] += 1
                        self.stats["proposed_tokens"] += len(cont)
                        return cont
        self.stats["empty"] += 1
        return []


class TransformerDrafter:
    """Real draft model behind the ``Drafter`` protocol: a (small)
    ``TransformerConfig`` model rolled out greedily for ``k`` tokens.

    The engine's acceptance rule makes correctness independent of the
    draft: any proposal stream yields bit-identical greedy output, so a
    cheap model here only changes how many tokens one verify forward
    emits. The rollout runs the drafter densely over a fixed-size
    right-padded window (causal attention makes right padding inert for
    the position being read), so one ``jax.jit`` compilation covers
    every history length — no per-length retraces in the serve loop.
    History longer than the window keeps only the trailing ``window``
    tokens (draft quality degrades gracefully; acceptance still gates).
    """

    def __init__(self, model: Any, params: Optional[Any] = None,
                 window: int = 64, seed: int = 0):
        import jax

        self.model = model
        self.window = int(window)
        if self.window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        if params is None:
            params = model.init(jax.random.PRNGKey(seed))
        self.params = params
        self._apply = jax.jit(lambda p, t: model.apply(p, t))
        self.stats = {"calls": 0, "proposals": 0, "proposed_tokens": 0,
                      "empty": 0}

    @classmethod
    def small(cls, vocab_size: int, window: int = 64, hidden: int = 32,
              layers: int = 1, heads: int = 2, seed: int = 0
              ) -> "TransformerDrafter":
        """A from-scratch tiny draft model sharing only the vocabulary
        with the target (the usual deployment shape: a model an order of
        magnitude smaller than the one being served)."""
        from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                      TransformerLM)

        cfg = TransformerConfig(
            vocab_size=int(vocab_size), hidden_size=hidden,
            num_layers=layers, num_heads=heads,
            max_seq_len=max(int(window), 16), remat=False)
        return cls(TransformerLM(cfg), window=window, seed=seed)

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        import jax.numpy as jnp
        import numpy as np

        self.stats["calls"] += 1
        if k <= 0 or not len(tokens):
            self.stats["empty"] += 1
            return []
        ctx = [int(t) for t in tokens]
        vocab = self.model.config.vocab_size
        out: List[int] = []
        for _ in range(int(k)):
            hist = ctx[-self.window:]
            buf = np.zeros((1, self.window), np.int32)
            buf[0, :len(hist)] = np.asarray(hist, np.int32) % vocab
            logits = self._apply(self.params, jnp.asarray(buf))
            nxt = int(np.asarray(logits[0, len(hist) - 1]).argmax())
            out.append(nxt)
            ctx.append(nxt)
        self.stats["proposals"] += 1
        self.stats["proposed_tokens"] += len(out)
        return out
