"""Speculative decoding drafters for the ragged serving engine.

Decode burns one full forward per token; speculation proposes ``k``
candidate tokens per sequence and verifies them in ONE batched forward
through the existing ragged step (the chunk machinery built for
SplitFuse prefill is exactly a multi-token verifier). With greedy
sampling, acceptance keeps the longest prefix of drafts that match the
model's own argmax chain — the emitted stream is the argmax chain
itself, so speculative greedy is token-identical to non-speculative
greedy regardless of draft quality; drafts only change how many tokens
one forward yields.

The default drafter is model-free prompt-lookup / n-gram matching
(PAPERS.md: "Prompt Lookup Decoding", also shipped in vLLM and
transformers as ``prompt_lookup_num_tokens``): the continuation of the
longest recent n-gram that already occurred earlier in the sequence is
proposed verbatim. On repetitive workloads (code, extraction, RAG with
quoted context) acceptance rates are high and there is no draft model
to host. ``Drafter`` is the hook for a real draft model: anything with
``propose(tokens, k) -> list[int]`` plugs into the engine.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Protocol, Sequence, \
    runtime_checkable


@runtime_checkable
class Drafter(Protocol):
    """Draft-proposal interface (the draft-model hook).

    ``tokens`` is the sequence's full token history (prompt + generated)
    and the return value is up to ``k`` proposed next tokens. An empty
    list means "no proposal" — the engine falls back to plain decode for
    that sequence this step.

    Drafters that also want acceptance feedback implement
    ``note_result(drafted, accepted)`` (see :class:`DrafterStats` — the
    engine calls it after every verify round when present). It is kept
    out of the runtime-checkable protocol so a bare ``propose``-only
    object still satisfies ``isinstance(x, Drafter)``."""

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        ...


class DrafterStats:
    """Uniform drafter-side counters for every drafter (ISSUE 17 small
    fix): proposal-side stats tracked at ``propose`` time plus
    verify-side ``drafted_tokens``/``accepted_tokens`` fed back by the
    engine through :meth:`note_result` — so acceptance rate is readable
    per drafter, not split ad hoc between the drafter and
    ``engine_v2._try_spec_step``."""

    def __init__(self):
        self.stats: Dict[str, int] = {
            "calls": 0, "proposals": 0, "proposed_tokens": 0, "empty": 0,
            "drafted_tokens": 0, "accepted_tokens": 0}

    def note_result(self, drafted: int, accepted: int) -> None:
        """Engine feedback after one verify round: ``drafted`` tokens of
        this drafter's proposal went through the verifier, ``accepted``
        of them matched the greedy chain."""
        self.stats["drafted_tokens"] += int(drafted)
        self.stats["accepted_tokens"] += int(accepted)

    @property
    def acceptance_rate(self) -> Optional[float]:
        if not self.stats["drafted_tokens"]:
            return None
        return self.stats["accepted_tokens"] / self.stats["drafted_tokens"]


class PromptLookupDrafter(DrafterStats):
    """N-gram / prompt-lookup drafter: match the last ``n`` tokens
    (``max_ngram`` down to ``min_ngram``) against earlier history and
    propose the tokens that followed the most recent match."""

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram <= max_ngram, got "
                f"({min_ngram}, {max_ngram})")
        super().__init__()
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        self.stats["calls"] += 1
        toks = list(tokens)
        L = len(toks)
        if k <= 0 or L < self.min_ngram + 1:
            self.stats["empty"] += 1
            return []
        for n in range(min(self.max_ngram, L - 1), self.min_ngram - 1, -1):
            pattern = toks[L - n:]
            # most recent earlier occurrence wins (local context beats a
            # stale match from the far prompt)
            for i in range(L - n - 1, -1, -1):
                if toks[i:i + n] == pattern:
                    cont = toks[i + n:i + n + k]
                    if cont:
                        self.stats["proposals"] += 1
                        self.stats["proposed_tokens"] += len(cont)
                        return cont
        self.stats["empty"] += 1
        return []


class TransformerDrafter(DrafterStats):
    """Real draft model behind the ``Drafter`` protocol: a (small)
    ``TransformerConfig`` model rolled out greedily for ``k`` tokens.

    The engine's acceptance rule makes correctness independent of the
    draft: any proposal stream yields bit-identical greedy output, so a
    cheap model here only changes how many tokens one verify forward
    emits. The rollout runs the drafter densely over a fixed-size
    right-padded window (causal attention makes right padding inert for
    the position being read), so one ``jax.jit`` compilation covers
    every history length — no per-length retraces in the serve loop.
    History longer than the window keeps only the trailing ``window``
    tokens (draft quality degrades gracefully; acceptance still gates).

    A fresh ``.small()`` drafter knows nothing about the target; earn
    its acceptance rate with :meth:`distill_from` (KL distillation
    against the target's logits on the target's own greedy rollouts)
    and persist the result with :meth:`save`/:meth:`load` the way the
    autotuner persists ``docs/autotuned/`` artifacts.
    """

    def __init__(self, model: Any, params: Optional[Any] = None,
                 window: int = 64, seed: int = 0):
        import jax

        super().__init__()
        self.model = model
        self.window = int(window)
        if self.window < 2:
            raise ValueError(f"window must be >= 2, got {window}")
        if params is None:
            params = model.init(jax.random.PRNGKey(seed))
        self.params = params
        self._apply = jax.jit(lambda p, t: model.apply(p, t))
        self.distill_summary: Optional[Dict[str, Any]] = None

    @classmethod
    def small(cls, vocab_size: int, window: int = 64, hidden: int = 32,
              layers: int = 1, heads: int = 2, seed: int = 0
              ) -> "TransformerDrafter":
        """A from-scratch tiny draft model sharing only the vocabulary
        with the target (the usual deployment shape: a model an order of
        magnitude smaller than the one being served)."""
        from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                      TransformerLM)

        cfg = TransformerConfig(
            vocab_size=int(vocab_size), hidden_size=hidden,
            num_layers=layers, num_heads=heads,
            max_seq_len=max(int(window), 16), remat=False)
        return cls(TransformerLM(cfg), window=window, seed=seed)

    def propose(self, tokens: Sequence[int], k: int) -> List[int]:
        import jax.numpy as jnp
        import numpy as np

        self.stats["calls"] += 1
        if k <= 0 or not len(tokens):
            self.stats["empty"] += 1
            return []
        ctx = [int(t) for t in tokens]
        vocab = self.model.config.vocab_size
        out: List[int] = []
        for _ in range(int(k)):
            hist = ctx[-self.window:]
            buf = np.zeros((1, self.window), np.int32)
            buf[0, :len(hist)] = np.asarray(hist, np.int32) % vocab
            logits = self._apply(self.params, jnp.asarray(buf))
            nxt = int(np.asarray(logits[0, len(hist) - 1]).argmax())
            out.append(nxt)
            ctx.append(nxt)
        self.stats["proposals"] += 1
        self.stats["proposed_tokens"] += len(out)
        return out

    # -- distillation (ISSUE 17 tentpole a) ----------------------------

    def distill_from(self, target_model: Any, target_params: Any,
                     steps: int = 150, batch: int = 16, lr: float = 1e-2,
                     seed: int = 0, prefix_len: int = 4,
                     temperature: float = 1.0,
                     resample_every: int = 50) -> Dict[str, Any]:
        """Short KL-distillation loop against the target's logits.

        Training data is the distribution that matters for acceptance:
        the TARGET's own greedy rollouts — drafts are verified against
        the target's argmax chain, so matching it on its own
        trajectories is exactly the objective. Each trajectory starts
        from a random prefix whose length is itself drawn uniformly in
        ``[2, prefix_len]`` (set ``prefix_len`` near the serving prompt
        length: a drafter trained only on short prefixes collapses when
        the serve prompt pushes random tokens into positions it always
        saw as greedy chain). Rollouts are resampled every
        ``resample_every`` steps so the drafter fits target dynamics,
        not one fixed batch. Loss is soft-label cross-entropy
        ``-Σ softmax(target/T) · log_softmax(draft)`` over the rollout
        positions (prefix positions masked out), optimized with Adam.
        Returns (and stores on ``self.distill_summary``) the final loss
        and held-out top-1 agreement with the target — the offline
        proxy for acceptance rate.

        Offline by design: run once per target, persist with
        :meth:`save` (the ``docs/autotuned/`` artifact pattern), load
        at serve time."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax

        W = self.window
        vocab = self.model.config.vocab_size
        prefix_len = max(2, min(int(prefix_len), W - 1))
        rng = np.random.default_rng(seed)
        t_apply = jax.jit(lambda p, t: target_model.apply(p, t))

        def rollout(n: int):
            """[n, W] target-greedy trajectories from random prefixes
            of per-sample random length in [2, prefix_len]."""
            plens = rng.integers(2, prefix_len + 1, size=n)
            toks = np.zeros((n, W), np.int32)
            toks[:, :prefix_len] = rng.integers(
                0, vocab, size=(n, prefix_len), dtype=np.int32)
            for t in range(int(plens.min()), W):
                logits = np.asarray(t_apply(target_params,
                                            jnp.asarray(toks)))
                greedy = logits[:, t - 1].argmax(-1)
                on = plens <= t
                toks[on, t] = greedy[on]
            return toks, plens

        def make_batch(n: int):
            toks, plens = rollout(n)
            inputs = jnp.asarray(toks)
            targets = np.asarray(t_apply(target_params, inputs),
                                 np.float32)
            soft = jax.nn.softmax(
                jnp.asarray(targets[:, :-1])
                / max(temperature, 1e-6), axis=-1)
            labels = jnp.asarray(toks[:, 1:])
            # position t predicts token t+1: supervised iff t+1 is a
            # rollout position, i.e. t >= plen - 1
            mask = jnp.asarray(
                (np.arange(W - 1)[None, :]
                 >= (plens - 1)[:, None]).astype(np.float32))
            return inputs, soft, labels, mask

        def loss_fn(p, inputs, soft, mask):
            logits = self.model.apply(p, inputs).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits[:, :-1], -1)
            ce = -jnp.sum(soft * logp, axis=-1)
            return jnp.sum(ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)

        opt = optax.adam(lr)
        opt_state = opt.init(self.params)

        @jax.jit
        def train_step(p, s, inputs, soft, mask):
            loss, grads = jax.value_and_grad(loss_fn)(p, inputs, soft,
                                                      mask)
            updates, s = opt.update(grads, s, p)
            return optax.apply_updates(p, updates), s, loss

        resample_every = max(1, int(resample_every))
        params, loss = self.params, float("nan")
        inputs = soft = labels = mask = None
        for step in range(int(steps)):
            if step % resample_every == 0:
                inputs, soft, labels, mask = make_batch(int(batch))
            params, opt_state, loss = train_step(params, opt_state,
                                                 inputs, soft, mask)
        self.params = params
        # held-out agreement: fresh rollouts the training never saw
        inputs, soft, labels, mask = make_batch(int(batch))
        final = self.model.apply(params, inputs).astype(jnp.float32)
        hits = (jnp.argmax(final[:, :-1], -1) == labels
                ).astype(jnp.float32)
        agree = float(jnp.sum(hits * mask)
                      / jnp.maximum(jnp.sum(mask), 1.0))
        self.distill_summary = {
            "steps": int(steps), "batch": int(batch), "lr": float(lr),
            "final_loss": float(loss), "top1_agreement": agree,
            "window": W, "vocab_size": int(vocab)}
        return self.distill_summary

    # -- persistence (the docs/autotuned/ artifact pattern) ------------

    def save(self, path: str) -> None:
        """Persist distilled weights + geometry as one ``.npz``: leaves
        in deterministic tree order, config/summary as a JSON metadata
        record — the drafter analog of ``docs/autotuned/*.json``."""
        import json

        import numpy as np
        from jax.tree_util import tree_flatten

        leaves, _ = tree_flatten(self.params)
        cfg = self.model.config
        meta = {"vocab_size": int(cfg.vocab_size),
                "hidden": int(cfg.hidden_size),
                "layers": int(cfg.num_layers),
                "heads": int(cfg.num_heads),
                "window": int(self.window),
                "distill": self.distill_summary}
        np.savez(path,
                 __meta__=np.frombuffer(json.dumps(meta).encode(),
                                        np.uint8),
                 **{f"p{i}": np.asarray(v) for i, v in enumerate(leaves)})

    @classmethod
    def load(cls, path: str) -> "TransformerDrafter":
        import json

        import jax.numpy as jnp
        import numpy as np
        from jax.tree_util import tree_flatten, tree_unflatten

        data = np.load(path)
        meta = json.loads(bytes(bytearray(data["__meta__"])))
        d = cls.small(meta["vocab_size"], window=meta["window"],
                      hidden=meta["hidden"], layers=meta["layers"],
                      heads=meta["heads"])
        leaves, treedef = tree_flatten(d.params)
        d.params = tree_unflatten(
            treedef, [jnp.asarray(data[f"p{i}"]).astype(v.dtype)
                      for i, v in enumerate(leaves)])
        d.distill_summary = meta.get("distill")
        return d
