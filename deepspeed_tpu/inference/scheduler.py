"""Dynamic SplitFuse token scheduler.

Reference: the FastGen scheduler lives in MII above
``InferenceEngineV2.query/can_schedule`` (inference/v2/engine_v2.py:184);
Dynamic SplitFuse composes each forward from (a) one decode token per
running sequence and (b) prompt *chunks* that fill the remaining token
budget, so every step has near-constant compute — which on TPU also means
ONE compiled program per bucket.
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from deepspeed_tpu.inference.ragged.sequence import (
    SequenceDescriptor, StateManager)


class SplitFuseScheduler:
    def __init__(self, state: StateManager, max_tokens_per_step: int = 256,
                 max_seqs_per_step: int = 32):
        self.state = state
        self.max_tokens = max_tokens_per_step
        self.max_seqs = max_seqs_per_step
        # scheduling observability: cumulative token mix plus the last
        # step's occupancy (exported by InferenceEngineV2.snapshot()).
        # prefill_starvation_steps counts steps where at least one
        # pending-prefill sequence got no chunk because budget/slots ran
        # out — sustained growth means the token budget is undersized
        # for the arrival rate.
        self.stats = {"steps": 0, "decode_tokens": 0, "prefill_tokens": 0,
                      "kv_starved_skips": 0, "prefill_starvation_steps": 0,
                      # paged-out is a first-class sequence state
                      # (ragged/kv_tier.py): decode tokens produced by
                      # sequences whose KV was restored from the host
                      # tier — warm-resume work the pool never
                      # re-prefilled
                      "resumed_decode_tokens": 0}
        self.last_scheduled_seqs = 0
        self.last_scheduled_tokens = 0
        # rotating start for the prefill scan: insertion order alone lets
        # an early long prompt re-win the tail budget every step and
        # starve later arrivals
        self._prefill_rr = 0
        # per-request tracing (observability/request_trace.py): the
        # engine attaches its RequestTracer so KV-starved skips land as
        # markers on the starved request's own lane — a request whose
        # TTFT is eaten by repeated skips shows it in its timeline
        self.tracer = None

    def schedule(self) -> List[Tuple[SequenceDescriptor, np.ndarray, int]]:
        """Pick (seq, new_tokens, start_pos) chunks for the next step.

        Decode tokens first (latency), then prefill chunks fill the budget
        (throughput) — the SplitFuse recipe.
        """
        budget = self.max_tokens
        slots = self.max_seqs
        out: List[Tuple[SequenceDescriptor, np.ndarray, int]] = []

        # decode: the last generated (or last prompt) token advances the seq
        for seq in self.state.seqs.values():
            if budget <= 0 or slots <= 0:
                break
            if not seq.in_decode or seq.done:
                continue
            if not self.state.ensure_capacity(seq, seq.seen_tokens + 1):
                self.stats["kv_starved_skips"] += 1
                if self.tracer is not None:
                    self.tracer.note(seq.uid, "KV_STARVED", at="decode")
                continue  # KV OOM: leave for a later step
            tok = (seq.generated[-1] if seq.generated
                   else int(seq.input_tokens[-1]))
            out.append((seq, np.asarray([tok], np.int32), seq.seen_tokens))
            self.stats["decode_tokens"] += 1
            if seq.resumed_from_tier:
                self.stats["resumed_decode_tokens"] += 1
            budget -= 1
            slots -= 1

        # prefill chunks (a chunk that reaches the end of the prompt makes
        # the engine sample that step's last-token logits); the scan
        # starts at a rotating offset so budget leftovers round-robin
        # over waiting prompts instead of always feeding the oldest
        pending_seqs = [s for s in self.state.seqs.values()
                        if s.pending_prefill > 0 and not s.done]
        if pending_seqs:
            start = self._prefill_rr % len(pending_seqs)
            self._prefill_rr += 1
            pending_seqs = pending_seqs[start:] + pending_seqs[:start]
        scheduled_prefills = 0
        for seq in pending_seqs:
            if budget <= 0 or slots <= 0:
                break
            chunk = min(seq.pending_prefill, budget)
            if not self.state.ensure_capacity(seq, seq.seen_tokens + chunk):
                self.stats["kv_starved_skips"] += 1
                if self.tracer is not None:
                    self.tracer.note(seq.uid, "KV_STARVED", at="prefill")
                continue
            toks = seq.input_tokens[seq.seen_tokens:seq.seen_tokens + chunk]
            out.append((seq, toks.astype(np.int32), seq.seen_tokens))
            self.stats["prefill_tokens"] += chunk
            budget -= chunk
            slots -= 1
            scheduled_prefills += 1
        if scheduled_prefills < len(pending_seqs) and (budget <= 0
                                                       or slots <= 0):
            self.stats["prefill_starvation_steps"] += 1
        self.stats["steps"] += 1
        self.last_scheduled_seqs = len(out)
        self.last_scheduled_tokens = self.max_tokens - budget
        return out
