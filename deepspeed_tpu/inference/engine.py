"""v1-style inference engine: jit + TP sharding + dense KV cache.

Reference: ``InferenceEngine`` (inference/engine.py:40) swaps HF blocks for
fused CUDA kernels (``replace_transformer_layer``
module_inject/replace_module.py:189), shards weights over a model-parallel
group, and optionally captures CUDA graphs (:497).

TPU re-design: no layer surgery — the model's logical axes already name
every shardable dim, so "kernel injection + TP" collapses to placing the
param tree with a tensor-parallel NamedSharding and jitting
prefill/decode. jit caching per shape bucket is the CUDA-graph analog.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.inference import model_runner
from deepspeed_tpu.models.transformer import TransformerLM
from deepspeed_tpu.parallel import topology as topo
from deepspeed_tpu.runtime.sharding import spec_from_logical
from deepspeed_tpu.utils.logging import log_dist, logger

# TP rule table for inference (reference AutoTP policy: qkv/mlp-in column,
# o/mlp-out row — module_inject/auto_tp.py:194; here one rule table)
TP_PARAM_RULES = (
    ("heads", "tp"),
    ("kv_heads", "tp"),
    ("mlp", "tp"),
    ("vocab", "tp"),
)


class InferenceEngine:
    """Generate-capable engine over a TransformerLM.

    API parity with the reference: ``forward`` (logits), ``generate``;
    ``tp_size`` via the mesh's tp axis.
    """

    def __init__(self, model: TransformerLM, mesh: Optional[Mesh] = None,
                 params: Optional[Dict[str, Any]] = None,
                 dtype=jnp.bfloat16, max_batch: int = 8,
                 max_seq_len: Optional[int] = None, seed: int = 0,
                 quantize_weights: Optional[str] = None):
        self.model = model
        self.cfg = model.config
        if mesh is None:
            mesh = topo._GLOBAL_MESH or topo.build_mesh(
                topo.TopologyConfig(dp=-1))
        self.mesh = mesh
        tp = mesh.shape.get("tp", 1)
        for name, heads in (("num_heads", self.cfg.num_heads),
                            ("kv_heads", self.cfg.kv_heads)):
            if heads % tp:
                raise ValueError(
                    f"tp={tp} does not divide {name}={heads}: the TP "
                    "placement shards the head axes evenly (reference "
                    "AutoTP has the same constraint); lower tp or use "
                    "a model whose head counts divide")
        if quantize_weights is not None and quantize_weights != "int8":
            raise ValueError(
                f"quantize_weights supports 'int8', got "
                f"{quantize_weights!r}")
        if quantize_weights is not None and tp > 1:
            raise ValueError(
                "quantize_weights does not compose with tp>1 yet "
                "(blockwise payloads have an extra rank the TP "
                "specs don't cover); serve unquantized or tp=1")
        self.max_batch = max_batch
        self.max_seq_len = max_seq_len or self.cfg.max_seq_len
        self._dtype = dtype

        axes = model.logical_axes()
        self._param_specs = jax.tree.map(
            lambda la: spec_from_logical(la, TP_PARAM_RULES), axes,
            is_leaf=lambda x: isinstance(x, tuple) and all(
                isinstance(e, (str, type(None))) for e in x))
        shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), self._param_specs)
        if params is None:
            with self.mesh:
                params = jax.jit(
                    model.init, out_shardings=shardings)(
                        jax.random.PRNGKey(seed))
        else:
            params = jax.device_put(params, shardings)
        if quantize_weights is not None:
            # weight-only int8 serving (reference MoQ/GroupQuantizer,
            # module_inject/replace_module.py:44): HBM holds ~4x less
            # weight; dequant happens lazily at each use inside the
            # compiled step (inference/weight_quant.py). Arg validation
            # ran before model materialization.
            from deepspeed_tpu.inference.weight_quant import (
                quantize_params, quantized_fraction)

            params = quantize_params(params)
            log_dist(
                f"weight-only int8 serving: "
                f"{quantized_fraction(params):.0%} of weight bytes "
                "quantized", ranks=[0])
        self.params = params

        # jit caches per input shape, so one function serves every
        # (prefill-bucket, decode) composition — the CUDA-graph analog
        self._step = jax.jit(partial(model_runner.forward_with_cache, self.cfg))
        log_dist(
            f"InferenceEngine: tp={self.mesh.shape.get('tp', 1)} "
            f"max_batch={max_batch} max_seq_len={self.max_seq_len}", ranks=[0])

    # -- API --------------------------------------------------------------

    def forward(self, tokens) -> jax.Array:
        """Full-sequence logits (no cache) — parity with reference
        InferenceEngine.forward (inference/engine.py:557)."""
        tokens = jnp.asarray(tokens, jnp.int32)
        with self.mesh:
            return self.model.apply(self.params, tokens)

    __call__ = forward

    def generate(self, tokens, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 seed: int = 0, eos_token_id: Optional[int] = None):
        """Greedy/top-k sampling with a dense KV cache.

        tokens: [B, S] prompt (list/np/jnp). Returns np.ndarray
        [B, S + max_new_tokens] (right-padded with eos if a row stops
        early).
        """
        tokens = np.asarray(tokens, np.int32)
        if tokens.ndim == 1:
            tokens = tokens[None]
        B, S = tokens.shape
        assert B <= self.max_batch, f"batch {B} > max_batch {self.max_batch}"
        total = S + max_new_tokens
        assert total <= self.max_seq_len, "prompt + new tokens > max_seq_len"

        # bucket the prompt length to bound compilations
        bucket = max(16, 1 << (S - 1).bit_length())
        bucket = min(bucket, self.max_seq_len)
        padded = np.zeros((B, bucket), np.int32)
        padded[:, :S] = tokens

        cache = model_runner.init_dense_cache(
            self.cfg, B, self.max_seq_len, self._dtype)
        with self.mesh:
            logits, cache = self._step(
                self.params, jnp.asarray(padded), cache, 0)
        # NOTE: positions beyond S wrote garbage rows into the cache, but
        # decode masks keys by position <= query pos and we overwrite row
        # S first, so only rows < S are ever attended.
        next_logits = logits[:, S - 1]  # [B, V]

        rng = jax.random.PRNGKey(seed)
        out = [tokens]
        done = np.zeros(B, bool)
        cur_pos = S
        for step in range(max_new_tokens):
            rng, sub = jax.random.split(rng)
            nxt = _sample(next_logits, temperature, top_k, sub)  # [B]
            nxt_np = np.asarray(nxt)
            if eos_token_id is not None:
                nxt_np = np.where(done, eos_token_id, nxt_np)
                done |= nxt_np == eos_token_id
            out.append(nxt_np[:, None].astype(np.int32))
            if eos_token_id is not None and done.all():
                break
            with self.mesh:
                logits, cache = self._step(
                    self.params, jnp.asarray(nxt_np[:, None], jnp.int32),
                    cache, cur_pos)
            next_logits = logits[:, 0]
            cur_pos += 1

        result = np.concatenate(out, axis=1)
        if result.shape[1] < total and eos_token_id is not None:
            pad = np.full((B, total - result.shape[1]), eos_token_id, np.int32)
            result = np.concatenate([result, pad], axis=1)
        return result


def _sample(logits, temperature, top_k, rng):
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(rng, logits, axis=-1)


def init_inference(model=None, tensor_parallel: Optional[Dict] = None,
                   dtype=jnp.bfloat16, max_batch: int = 8,
                   max_seq_len: Optional[int] = None,
                   mesh: Optional[Mesh] = None, params=None,
                   **kwargs) -> InferenceEngine:
    """Reference ``deepspeed.init_inference`` (__init__.py:328) analog.

    model: a TransformerLM or a model-zoo name (str).
    tensor_parallel: {"tp_size": N} — builds a tp mesh if none given.
    """
    if isinstance(model, str):
        from deepspeed_tpu.models.zoo import get_model

        model = get_model(model)
    tp_size = (tensor_parallel or {}).get("tp_size", 1)
    if mesh is None:
        mesh = topo._GLOBAL_MESH
    if mesh is None:
        mesh = topo.build_mesh(topo.TopologyConfig(dp=-1, tp=tp_size))
    return InferenceEngine(model, mesh=mesh, params=params, dtype=dtype,
                           max_batch=max_batch, max_seq_len=max_seq_len,
                           **kwargs)
