"""FastGen-style ragged continuous-batching engine.

Reference: ``InferenceEngineV2`` (inference/v2/engine_v2.py:30) — ``put``
(:107) runs a ragged forward over new tokens of many sequences and returns
next-token logits; ``query``/``can_schedule`` (:184) let a scheduler probe
admission; KV pages come from a blocked allocator.

TPU re-design: host-side state (StateManager/BlockedAllocator) assembles
dense int metadata per step (ragged_batch.py); ONE jitted program per
(max_tokens, max_seqs) bucket executes scatter-append KV + paged attention
(model_runner.ragged_forward). The SplitFuse scheduler keeps steps at a
near-constant token budget, so in steady state a single compiled program
serves the whole workload.
"""

from __future__ import annotations

import time
from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from deepspeed_tpu.inference import model_runner
from deepspeed_tpu.inference.ragged import (
    BlockedKVCache, KVCacheConfig, RaggedBatch, StateManager)
from deepspeed_tpu.inference.ragged.ragged_batch import build_ragged_batch
from deepspeed_tpu.inference.scheduler import SplitFuseScheduler
from deepspeed_tpu.models.transformer import TransformerLM
from deepspeed_tpu.parallel import topology as topo
from deepspeed_tpu.utils.logging import log_dist


class InferenceEngineV2:
    def __init__(self, model: TransformerLM, mesh: Optional[Mesh] = None,
                 params: Optional[Dict[str, Any]] = None,
                 kv_blocks: int = 256, kv_block_size: int = 16,
                 max_tokens_per_step: int = 128, max_seqs_per_step: int = 16,
                 max_blocks_per_seq: int = 32, dtype=jnp.bfloat16, seed: int = 0,
                 quantize_weights: Optional[str] = None,
                 decode_steps: int = 8):
        from deepspeed_tpu.inference.engine import InferenceEngine

        # reuse v1's TP placement logic for params/mesh
        self._v1 = InferenceEngine(model, mesh=mesh, params=params,
                                   dtype=dtype, seed=seed,
                                   quantize_weights=quantize_weights)
        self.model, self.cfg = model, model.config
        self.mesh, self.params = self._v1.mesh, self._v1.params

        kv_cfg = KVCacheConfig(
            num_layers=self.cfg.num_layers, kv_heads=self.cfg.kv_heads,
            head_dim=self.cfg.head_dim, block_size=kv_block_size,
            num_blocks=kv_blocks, dtype=dtype)
        self.kv_cache = BlockedKVCache(kv_cfg, mesh=self.mesh)
        # the last block is the padding-token scratch target
        # (model_runner.ragged_forward routes padded writes there): shrink
        # the allocator so it is never handed out
        from deepspeed_tpu.inference.ragged import BlockedAllocator

        self.kv_cache.allocator = BlockedAllocator(kv_blocks - 1)
        self._scratch_block = kv_blocks - 1

        self.state = StateManager(self.kv_cache,
                                  max_tracked_sequences=4 * max_seqs_per_step,
                                  max_blocks_per_seq=max_blocks_per_seq)
        self.scheduler = SplitFuseScheduler(
            self.state, max_tokens_per_step, max_seqs_per_step)
        self.max_tokens = max_tokens_per_step
        self.max_seqs = max_seqs_per_step
        self.max_blocks_per_seq = max_blocks_per_seq
        self._step_fn = jax.jit(partial(model_runner.ragged_forward, self.cfg))
        # decode-only steps use the Pallas paged-attention kernel (no
        # per-token context gather). On any multi-device mesh the kernel
        # runs inside a shard_map — manual over tp (q heads / KV heads
        # co-sharded; needs tp | kv_heads for the GQA grouping), other
        # axes replicated. Pallas can't run under plain GSPMD, so a bare
        # multi-chip mesh without the wrap is NOT a kernel-path config.
        axes = {} if self.mesh is None else dict(self.mesh.shape)
        self._tp = axes.get("tp", 1)
        single = self.mesh is None or all(v == 1 for v in axes.values())
        # v1's constructor (run above) already raised unless tp divides
        # both head counts, which is exactly the GQA co-sharding the
        # shard_map wrap needs — every constructible config runs the
        # kernel path. The flag stays as a manual escape hatch (tests
        # flip it to compare against the gather path).
        self._use_paged_kernel = True
        # serve-path telemetry (VERDICT r2: the gather fallback is a perf
        # cliff users can't see — count it; reference analog: the comms
        # logger's op counts, utils/comms_logging.py)
        self.stats = {"decode_kernel_steps": 0, "prefill_kernel_steps": 0,
                      "prefill_gather_fallbacks": 0,
                      "fallback_reasons": {"vmem": 0, "padding": 0}}
        # request-latency observability (docs/observability.md): TTFT is
        # put()->first emitted token; decode latency is the gap between
        # consecutive emitted tokens of one sequence (a burst spreads its
        # round-trip evenly over the tokens it produced). Histograms live
        # in the process-wide hub so serving percentiles land on the same
        # Prometheus page as training metrics.
        from deepspeed_tpu.observability import get_hub
        from deepspeed_tpu.observability.flight_recorder import (
            get_flight_recorder, install_crash_handlers)

        self._hub = get_hub()
        self._ttft_hist = self._hub.histogram("serve.ttft_seconds")
        self._decode_hist = self._hub.histogram("serve.decode_token_seconds")
        self._step_hist = self._hub.histogram("serve.step_seconds")
        # serving shares the crash flight recorder: a wedged serve step
        # dumps the last admits/steps the same way a training hang does
        self._flight = get_flight_recorder()
        install_crash_handlers()
        self._admit_time: Dict[int, float] = {}
        self._last_emit_time: Dict[int, float] = {}
        self._burst_tokens = 0
        self._burst_capacity = 0
        kernel_mesh = None if single else self.mesh
        self._decode_fn = jax.jit(partial(
            model_runner.ragged_decode_forward, self.cfg,
            mesh=kernel_mesh))
        self._prefill_fn = jax.jit(partial(
            model_runner.ragged_prefill_forward, self.cfg,
            mesh=kernel_mesh))
        # device-side token pick: the step fetches only sampled ids (or
        # the consumed rows when temperature > 0), never the full [T, V]
        # logits buffer (see step())
        self._pick_greedy = jax.jit(lambda lg, idx: jnp.argmax(
            lg.reshape(-1, lg.shape[-1])[idx].astype(jnp.float32),
            axis=-1).astype(jnp.int32))
        self._take_rows = jax.jit(
            lambda lg, idx: lg.reshape(-1, lg.shape[-1])[idx])
        # multi-step greedy decode: one device program per `decode_steps`
        # tokens when every live sequence is in steady decode
        # (model_runner.ragged_multi_decode; decode_steps=1 restores
        # strict per-token SplitFuse admission)
        self.decode_steps = max(1, int(decode_steps))
        self._multi_decode_fn = jax.jit(partial(
            model_runner.ragged_multi_decode, self.cfg, mesh=kernel_mesh),
            static_argnames=("steps",))
        log_dist(
            f"InferenceEngineV2: kv_blocks={kv_blocks}x{kv_block_size} "
            f"budget={max_tokens_per_step}tok/{max_seqs_per_step}seq",
            ranks=[0])

    # -- admission (reference engine_v2.py:184 query/can_schedule) --------

    def can_schedule(self, prompt_len: int) -> bool:
        blocks = self.kv_cache.blocks_needed(prompt_len + 1)
        return (blocks <= self.kv_cache.free_blocks
                and blocks <= self.max_blocks_per_seq
                and len(self.state.seqs) < self.state.max_tracked_sequences)

    # -- core step (reference engine_v2.py:107 put) -----------------------

    def put(self, uids: List[int], tokens_list: List[np.ndarray],
            max_new_tokens: int = 64) -> None:
        """Admit new sequences (uid -> prompt tokens)."""
        now = time.perf_counter()
        for uid, toks in zip(uids, tokens_list):
            toks = np.asarray(toks, np.int32).ravel()
            if not self.can_schedule(len(toks)):
                raise RuntimeError(f"cannot schedule uid={uid}: KV pool full")
            self.state.get_or_create(uid, toks, max_new_tokens)
            self._admit_time[uid] = now
            self._hub.counter_add("serve.requests")

    def step(self, temperature: float = 0.0, seed: int = 0,
             eos_token_id: Optional[int] = None) -> Dict[int, int]:
        """Run one SplitFuse step. Returns {uid: new_token} for sequences
        that produced a token this step."""
        t0 = time.perf_counter()
        scheduled = self.scheduler.schedule()
        self._release_finished()
        if not scheduled:
            # all live sequences starved for KV (pool exhausted mid-decode):
            # preempt the last-admitted sequence so the others can progress
            # — without this the engine deadlocks and leaks the pool
            live = [s for s in self.state.seqs.values() if not s.done]
            if live:
                victim = live[-1]
                log_dist(
                    f"KV pool exhausted: preempting uid={victim.uid} "
                    f"({len(victim.generated)} tokens generated)", ranks=[0])
                victim.done = True
                victim.truncated = True
                self.state.release(victim.uid)
            return {}
        batch = build_ragged_batch(scheduled, self.max_tokens, self.max_seqs,
                                   self.max_blocks_per_seq)
        # steady-state decode (one token per sequence): tokens line up
        # with slots, so the compact paged-kernel path applies
        decode_only = (self._use_paged_kernel
                       and all(len(nt) == 1 for _, nt, _ in scheduled))
        seg_plan = None
        if self._use_paged_kernel and not decode_only:
            seg_plan = self._plan_prefill_segments(scheduled)
            if seg_plan is None:
                n = self.stats["prefill_gather_fallbacks"] = \
                    self.stats["prefill_gather_fallbacks"] + 1
                if n == 1 or n % 100 == 0:
                    log_dist(
                        f"paged prefill fell back to the gather path "
                        f"({n}x: {self.stats['fallback_reasons']}) — "
                        "flat-layout serve step, no Pallas kernel; see "
                        "log_summary()", ranks=[0])
            else:
                self.stats["prefill_kernel_steps"] += 1
        elif decode_only:
            self.stats["decode_kernel_steps"] += 1
        with self.mesh:
            if seg_plan is not None:
                n_segs = seg_plan[0].shape[0]
                logits, new_kv = self._prefill_fn(
                    self.params, self.kv_cache.data, *seg_plan,
                    jnp.asarray(batch.block_table[:n_segs]))
            elif decode_only:
                # compact per-slot arrays: token i belongs to slot i; pad
                # out to max_seqs (token budget may be smaller than the
                # slot budget)
                n = batch.num_tokens
                d_tok = np.zeros(self.max_seqs, np.int32)
                d_pos = np.zeros(self.max_seqs, np.int32)
                d_tok[:n] = batch.token_ids[:n]
                d_pos[:n] = batch.token_pos[:n]
                logits, new_kv = self._decode_fn(
                    self.params, self.kv_cache.data,
                    jnp.asarray(d_tok), jnp.asarray(d_pos),
                    jnp.asarray(batch.block_table),
                    jnp.asarray(batch.ctx_lens))
            else:
                logits, new_kv = self._step_fn(
                    self.params, self.kv_cache.data,
                    jnp.asarray(batch.token_ids), jnp.asarray(batch.token_seq),
                    jnp.asarray(batch.token_pos), jnp.asarray(batch.block_table),
                    jnp.asarray(batch.num_tokens, jnp.int32))
        self.kv_cache.data = new_kv

        # Sample ON DEVICE and fetch only token ids (greedy) or just the
        # consumed rows (stochastic). Materializing the full [T, V]
        # logits host-side (131 MB/step at a 256-token budget x 128k
        # vocab) dominated step latency ~20:1 on a tunnel-attached host;
        # the ids are 4 bytes/sequence.
        stride = logits.shape[1] if logits.ndim == 3 else 1
        flat_idx = np.zeros(self.max_seqs, np.int32)
        consumers = []
        for slot, (seq, new_tokens, start_pos) in enumerate(scheduled):
            n = len(new_tokens)
            seq.seen_tokens = start_pos + n
            if seq.seen_tokens < len(seq.input_tokens):
                continue  # mid-prefill: no logits consumed
            if seg_plan is not None:
                flat_idx[slot] = slot * stride + (n - 1)
            elif decode_only:
                flat_idx[slot] = slot
            else:
                flat_idx[slot] = batch.last_token_index[slot]
            consumers.append((slot, seq))

        emitted: Dict[int, int] = {}
        if consumers:
            idx_dev = jnp.asarray(flat_idx)
            with self.mesh:
                if temperature == 0.0:
                    toks_np = np.asarray(self._pick_greedy(logits, idx_dev))
                else:
                    rows_np = np.asarray(self._take_rows(logits, idx_dev))
            for slot, seq in consumers:
                if temperature == 0.0:
                    tok = int(toks_np[slot])
                else:
                    tok = int(_sample_np(rows_np[slot], temperature,
                                         seed + slot + seq.seen_tokens))
                seq.generated.append(tok)
                emitted[seq.uid] = tok
                if eos_token_id is not None and tok == eos_token_id:
                    seq.done = True
                if len(seq.generated) >= seq.max_new_tokens:
                    seq.done = True
        now = time.perf_counter()
        self._step_hist.observe(now - t0)
        self._flight.record("serve_step", tokens=batch.num_tokens,
                            emitted=len(emitted),
                            wall_ms=round((now - t0) * 1000.0, 3))
        for uid in emitted:
            self._note_emitted(uid, 1, now)
        self._update_serve_gauges()
        self._release_finished()
        return emitted

    def _plan_prefill_segments(self, scheduled):
        """Per-slot padded chunk layout for the Pallas prefill kernel, or
        None when per-segment padding would outweigh the flat layout
        (then the gather path runs). Tq is bucketed to powers of two so
        jit compiles a handful of programs."""
        longest = max(len(nt) for _, nt, _ in scheduled)
        tq = 8
        while tq < longest:
            tq *= 2
        # kernel scratch is (Tq*num_heads) rows of (2*128 + head_dim) fp32
        # VMEM; keep it well under the ~16MB/core budget or the Mosaic
        # compile fails at serve time (gather path has no such limit)
        # per-shard head count under the tp shard_map
        scratch_bytes = (tq * (self.cfg.num_heads // self._tp)
                         * (256 + self.cfg.head_dim) * 4)
        if scratch_bytes > 4 * 1024 * 1024:
            self.stats["fallback_reasons"]["vmem"] += 1
            return None
        S = 1  # segment-count bucket: slots are ordered, so the forward
        while S < len(scheduled):  # runs on the leading S rows only
            S *= 2
        S = min(S, self.max_seqs)
        # the padded layout materializes S*tq token rows (incl. [S,tq,V]
        # fp32 logits); cap the blowup over the flat token budget
        if S * tq > 2 * self.max_tokens:
            self.stats["fallback_reasons"]["padding"] += 1
            return None
        toks = np.zeros((S, tq), np.int32)
        pos0 = np.zeros(S, np.int32)
        nreal = np.zeros(S, np.int32)
        for slot, (seq, nt, sp) in enumerate(scheduled):
            toks[slot, :len(nt)] = nt
            pos0[slot] = sp
            nreal[slot] = len(nt)
        return jnp.asarray(toks), jnp.asarray(pos0), jnp.asarray(nreal)

    def _release_finished(self) -> None:
        for uid in [s.uid for s in self.state.seqs.values() if s.done]:
            self.state.release(uid)
            self._admit_time.pop(uid, None)
            self._last_emit_time.pop(uid, None)

    def _note_emitted(self, uid: int, n_tokens: int, now: float) -> None:
        """Fold ``n_tokens`` just-emitted tokens of ``uid`` into the
        latency histograms: the first token of a request is its TTFT;
        later tokens record the gap since the previous emission (a burst
        spreads one device round trip evenly over its tokens)."""
        self._hub.counter_add("serve.tokens_emitted", n_tokens)
        admit = self._admit_time.pop(uid, None)
        last = self._last_emit_time.get(uid)
        if admit is not None:
            self._ttft_hist.observe(now - admit)
            n_tokens -= 1
            last = now
        if last is not None and n_tokens > 0:
            per_tok = (now - last) / n_tokens
            for _ in range(n_tokens):
                self._decode_hist.observe(per_tok)
        self._last_emit_time[uid] = now

    def _update_serve_gauges(self) -> None:
        live = [s for s in self.state.seqs.values() if not s.done]
        self._hub.gauge("serve.queue_depth", len(live))
        self._hub.gauge("serve.pending_prefill_tokens",
                        sum(s.pending_prefill for s in live))
        self._hub.gauge("serve.kv_free_blocks", self.kv_cache.free_blocks)
        self._hub.gauge("serve.batch_seq_occupancy",
                        self.scheduler.last_scheduled_seqs
                        / max(1, self.max_seqs))
        self._hub.gauge("serve.batch_token_occupancy",
                        self.scheduler.last_scheduled_tokens
                        / max(1, self.max_tokens))
        if self._burst_capacity > 0:
            self._hub.gauge("serve.burst_efficiency",
                            self._burst_tokens / self._burst_capacity)

    def _try_decode_burst(self, eos_token_id: Optional[int]
                          ) -> Optional[Dict[int, List[int]]]:
        """Run ``decode_steps`` greedy tokens in one device round trip.

        Applies only in steady state: every live sequence mid-decode, no
        prefill pending, and KV capacity for the whole burst (the block
        tables are frozen for its duration). Returns None when a single
        SplitFuse step should run instead."""
        live = [s for s in self.state.seqs.values() if not s.done]
        if (self.decode_steps <= 1 or not live or len(live) > self.max_seqs
                or any((not s.in_decode) or s.pending_prefill for s in live)):
            return None
        # clamp the burst to the shortest remaining budget: probing
        # capacity K tokens past a sequence that only needs 1 more would
        # trip ensure_capacity's per-seq-cap kill and truncate output
        # that per-token stepping would have finished
        K = min(self.decode_steps,
                max(1, min(s.max_new_tokens - len(s.generated)
                           for s in live)))
        if K <= 1:
            return None
        # side-effect-free capacity probe first: per-seq cap, then total
        # pool demand (a partial speculative grab would strand blocks
        # and push the fallback step into victim preemption)
        need_total = 0
        for s in live:
            blocks = self.kv_cache.blocks_needed(s.seen_tokens + K)
            if (self.state.max_blocks_per_seq is not None
                    and blocks > self.state.max_blocks_per_seq):
                return None  # near the per-seq cap: per-token tail
            need_total += max(0, blocks - len(s.kv_blocks))
        if need_total > self.kv_cache.free_blocks:
            return None
        for s in live:
            ok = self.state.ensure_capacity(s, s.seen_tokens + K)
            assert ok, "capacity probe said yes but allocation failed"
        t0 = time.perf_counter()
        S = self.max_seqs
        d_tok = np.zeros(S, np.int32)
        d_pos = np.zeros(S, np.int32)
        ctx = np.zeros(S, np.int32)
        bt = np.zeros((S, self.max_blocks_per_seq), np.int32)
        for i, s in enumerate(live):
            d_tok[i] = (s.generated[-1] if s.generated
                        else int(s.input_tokens[-1]))
            d_pos[i] = s.seen_tokens
            ctx[i] = s.seen_tokens + 1
            bt[i, :len(s.kv_blocks)] = s.kv_blocks
        with self.mesh:
            toks, new_kv = self._multi_decode_fn(
                self.params, self.kv_cache.data, jnp.asarray(d_tok),
                jnp.asarray(d_pos), jnp.asarray(bt), jnp.asarray(ctx),
                steps=K)
            toks_np = np.asarray(toks)  # [K, S] — one fetch per K tokens
        self.kv_cache.data = new_kv
        self.stats["decode_kernel_steps"] += K
        self.stats["burst_steps"] = self.stats.get("burst_steps", 0) + 1
        emitted: Dict[int, List[int]] = {}
        for i, s in enumerate(live):
            accepted = []
            for k in range(K):
                tok = int(toks_np[k, i])
                accepted.append(tok)
                if eos_token_id is not None and tok == eos_token_id:
                    s.done = True
                    break
                if len(s.generated) + len(accepted) >= s.max_new_tokens:
                    s.done = True
                    break
            s.generated.extend(accepted)
            s.seen_tokens += len(accepted)
            emitted[s.uid] = accepted
        now = time.perf_counter()
        self._step_hist.observe(now - t0)
        # burst efficiency: accepted tokens vs the K*len(live) the device
        # program computed (early-EOS/max-token exits waste the tail)
        self._burst_tokens += sum(len(v) for v in emitted.values())
        self._burst_capacity += K * len(live)
        for uid, toks in emitted.items():
            if toks:
                self._note_emitted(uid, len(toks), now)
        self._update_serve_gauges()
        self._release_finished()
        return emitted

    def generate_all(self, temperature: float = 0.0, seed: int = 0,
                     eos_token_id: Optional[int] = None,
                     max_steps: int = 10_000) -> Dict[int, List[int]]:
        """Drive steps until every admitted sequence finishes; returns
        {uid: generated tokens}. In steady greedy decode, bursts
        ``decode_steps`` tokens per device round trip."""
        results: Dict[int, List[int]] = {}
        for _ in range(max_steps):
            if not self.state.seqs:
                break
            if temperature == 0.0:
                burst = self._try_decode_burst(eos_token_id)
                if burst is not None:
                    for uid, toks in burst.items():
                        results.setdefault(uid, []).extend(toks)
                    continue
            # every step makes progress: emits tokens, advances a prefill,
            # or preempts a starved sequence — so this loop terminates
            emitted = self.step(temperature, seed, eos_token_id)
            for uid, tok in emitted.items():
                results.setdefault(uid, []).append(tok)
        return results

    def flush(self, uids: List[int]) -> None:
        """Drop sequences + free KV (reference engine_v2.py flush)."""
        for uid in uids:
            self.state.release(uid)

    def log_summary(self) -> Dict[str, Any]:
        """Serve-path telemetry (the comms-logger log_summary analog):
        kernel vs gather-fallback step counts, with fallback reasons.
        A nonzero ``prefill_gather_fallbacks`` means prefill ran the
        flat gather path — raise max_tokens_per_step or lower
        max_seqs_per_step/prompt chunking to restore the kernel path."""
        s = dict(self.stats)
        s["fallback_reasons"] = dict(self.stats["fallback_reasons"])
        log_dist(f"InferenceEngineV2 summary: {s}", ranks=[0])
        return s

    def snapshot(self) -> Dict[str, Any]:
        """Serving observability snapshot: request-latency percentiles
        (TTFT + per-decode-token, p50/p95/p99), queue/occupancy gauges
        and the kernel/fallback counters. The same histograms render on
        the hub's Prometheus page (docs/observability.md)."""
        live = [s for s in self.state.seqs.values() if not s.done]
        out: Dict[str, Any] = {
            "ttft": self._ttft_hist.snapshot(),
            "decode_token_latency": self._decode_hist.snapshot(),
            "step_latency": self._step_hist.snapshot(),
            "queue_depth": len(live),
            "pending_prefill_tokens": sum(s.pending_prefill for s in live),
            "kv_free_blocks": self.kv_cache.free_blocks,
            "batch_seq_occupancy": (self.scheduler.last_scheduled_seqs
                                    / max(1, self.max_seqs)),
            "batch_token_occupancy": (self.scheduler.last_scheduled_tokens
                                      / max(1, self.max_tokens)),
            "scheduler": dict(self.scheduler.stats),
            "stats": dict(self.stats,
                          fallback_reasons=dict(
                              self.stats["fallback_reasons"])),
        }
        if self._burst_capacity > 0:
            out["burst_efficiency"] = (self._burst_tokens
                                       / self._burst_capacity)
        return out


def _sample_np(logits_row: np.ndarray, temperature: float, seed: int) -> int:
    if temperature <= 0.0:
        return int(np.argmax(logits_row))
    rng = np.random.default_rng(seed)
    z = logits_row / temperature
    z = z - z.max()
    p = np.exp(z)
    p /= p.sum()
    return int(rng.choice(len(p), p=p))
