"""FastGen-style ragged continuous-batching engine.

Reference: ``InferenceEngineV2`` (inference/v2/engine_v2.py:30) — ``put``
(:107) runs a ragged forward over new tokens of many sequences and returns
next-token logits; ``query``/``can_schedule`` (:184) let a scheduler probe
admission; KV pages come from a blocked allocator.

TPU re-design: host-side state (StateManager/BlockedAllocator) assembles
dense int metadata per step (ragged_batch.py); ONE jitted program per
(max_tokens, max_seqs) bucket executes scatter-append KV + paged attention
(model_runner.ragged_forward). The SplitFuse scheduler keeps steps at a
near-constant token budget, so in steady state a single compiled program
serves the whole workload.
"""

from __future__ import annotations

import dataclasses
import math
import time
from collections import deque
from functools import partial
from typing import Any, Deque, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from deepspeed_tpu.inference import model_runner
from deepspeed_tpu.inference.ragged import (
    BlockedKVCache, KVCacheConfig, PrefixCache, RaggedBatch, StateManager)
from deepspeed_tpu.inference.ragged.ragged_batch import build_ragged_batch
from deepspeed_tpu.inference.scheduler import SplitFuseScheduler
from deepspeed_tpu.inference.spec_decode import PromptLookupDrafter
from deepspeed_tpu.models.transformer import TransformerLM
from deepspeed_tpu.observability.clocksync import wall_time
from deepspeed_tpu.observability.journal import get_journal
from deepspeed_tpu.parallel import topology as topo
from deepspeed_tpu.utils.logging import log_dist


@dataclasses.dataclass
class _QueuedRequest:
    """A request waiting for KV admission (FIFO). Requeued preemption
    victims carry their already-generated tokens inside ``tokens`` (for
    prefix recompute) and count them via ``prior_generated``."""
    uid: int
    tokens: np.ndarray
    max_new_tokens: int
    enqueue_time: float
    prior_generated: int = 0
    # original put() time while TTFT is still unmeasured; None once the
    # request has emitted its first token (pre-preemption)
    admit_time: Optional[float] = None
    # queue re-entry after a preemption (vs a fresh put()) — the trace
    # records the round trip's requeue wait on re-admission
    requeued: bool = False
    # the victim's KV is parked in the host tier (ragged/kv_tier.py):
    # admission restores blocks and resumes decode instead of
    # re-prefilling; ``tokens`` carries the folded history anyway as
    # the fallback if the tier spills the session before readmission
    paged: bool = False


# Process-level jit cache shared by every engine instance. A fleet of
# replicas (serving/) builds N engines over the SAME model config, and
# per-instance ``jax.jit(partial(...))`` wrappers would compile the
# identical step programs N times — key the wrapped callables by
# (config identity, kernel mesh) so replica N+1 reuses replica 0's
# executables. Entries hold a strong ref to the config, so an id()
# key can never alias a collected object.
_JIT_CACHE: Dict[Any, Tuple[Any, Dict[str, Any]]] = {}


def _shared_step_fns(cfg, kernel_mesh):
    key = (id(cfg), kernel_mesh)
    hit = _JIT_CACHE.get(key)
    if hit is not None and hit[0] is cfg:
        return hit[1]
    fns = {
        "step": jax.jit(partial(model_runner.ragged_forward, cfg)),
        "decode": jax.jit(partial(
            model_runner.ragged_decode_forward, cfg, mesh=kernel_mesh)),
        "prefill": jax.jit(partial(
            model_runner.ragged_prefill_forward, cfg, mesh=kernel_mesh)),
        "multi_decode": jax.jit(partial(
            model_runner.ragged_multi_decode, cfg, mesh=kernel_mesh),
            static_argnames=("steps",)),
    }
    _JIT_CACHE[key] = (cfg, fns)
    return fns


# device-side token picks are config-independent — one compiled copy
# per process, not per engine
_PICK_GREEDY = jax.jit(lambda lg, idx: jnp.argmax(
    lg.reshape(-1, lg.shape[-1])[idx].astype(jnp.float32),
    axis=-1).astype(jnp.int32))
_TAKE_ROWS = jax.jit(lambda lg, idx: lg.reshape(-1, lg.shape[-1])[idx])
_PICK_GREEDY_ALL = jax.jit(lambda lg: jnp.argmax(
    lg.reshape(-1, lg.shape[-1]).astype(jnp.float32),
    axis=-1).astype(jnp.int32))


class InferenceEngineV2:
    def __init__(self, model: TransformerLM, mesh: Optional[Mesh] = None,
                 params: Optional[Dict[str, Any]] = None,
                 kv_blocks: int = 256, kv_block_size: int = 16,
                 max_tokens_per_step: int = 128, max_seqs_per_step: int = 16,
                 max_blocks_per_seq: int = 32, dtype=jnp.bfloat16, seed: int = 0,
                 quantize_weights: Optional[str] = None,
                 decode_steps: int = 8,
                 prefix_cache: bool = True,
                 spec_decode: bool = False, spec_k: int = 4,
                 spec_ngram: int = 3, drafter: Optional[Any] = None,
                 max_queue_depth: Optional[int] = None,
                 kv_quant_bits: Optional[Any] = None,
                 handoff_wire: str = "auto",
                 host_kv_tier: bool = False, host_tier_mb: int = 256,
                 spec_adaptive_k: bool = False,
                 spec_accept_alpha: float = 0.25,
                 serving: Optional[Any] = None,
                 request_trace: Optional[Any] = None,
                 metric_labels: Optional[Dict[str, str]] = None):
        from deepspeed_tpu.inference.engine import InferenceEngine

        if serving is not None:
            # a config.ServingConfig block supplies the serving knobs;
            # explicit kwargs above keep their call-site values only when
            # the caller passed no block (the block is the source of
            # truth for config-driven deployments)
            prefix_cache = serving.prefix_cache
            spec_decode = serving.spec_decode
            spec_k = serving.spec_k
            spec_ngram = serving.spec_ngram
            decode_steps = serving.decode_steps
            max_queue_depth = serving.max_queue_depth
            kv_quant_bits = getattr(serving, "kv_quant_bits", None)
            handoff_wire = getattr(serving, "handoff_wire", "auto")
            host_kv_tier = getattr(serving, "host_kv_tier", False)
            host_tier_mb = getattr(serving, "host_tier_mb", 256)
            spec_adaptive_k = getattr(serving, "spec_adaptive_k", False)
            spec_accept_alpha = getattr(serving, "spec_accept_alpha", 0.25)

        # reuse v1's TP placement logic for params/mesh
        self._v1 = InferenceEngine(model, mesh=mesh, params=params,
                                   dtype=dtype, seed=seed,
                                   quantize_weights=quantize_weights)
        self.model, self.cfg = model, model.config
        self.mesh, self.params = self._v1.mesh, self._v1.params
        # kept for reload_params: a hot-swap routes replacement weights
        # through the same v1 placement/quantization path as boot
        self._param_dtype = dtype
        self._quantize_weights = quantize_weights

        kv_cfg = KVCacheConfig(
            num_layers=self.cfg.num_layers, kv_heads=self.cfg.kv_heads,
            head_dim=self.cfg.head_dim, block_size=kv_block_size,
            num_blocks=kv_blocks, dtype=dtype, quant_bits=kv_quant_bits)
        self.kv_cache = BlockedKVCache(kv_cfg, mesh=self.mesh)
        # disagg handoff wire codec mode ("auto"/"raw"/"int8"/"int4");
        # consumed by serving/disagg.py serialize_prefix
        self._handoff_wire = handoff_wire
        # the last block is the padding-token scratch target
        # (model_runner.ragged_forward routes padded writes there): shrink
        # the allocator so it is never handed out
        from deepspeed_tpu.inference.ragged import BlockedAllocator

        self.kv_cache.allocator = BlockedAllocator(kv_blocks - 1)
        self._scratch_block = kv_blocks - 1
        # shared-prefix KV reuse: full blocks whose content-hash chain
        # matches a cached prefix are shared by reference and skip
        # prefill (ragged/prefix_cache.py; docs/serving.md)
        # per-replica metric labels: a fleet of engines in one process
        # (serving/) tags every serve.* series with its replica id so
        # aggregation never collapses replicas into one series
        self._metric_labels = dict(metric_labels) if metric_labels else None
        if prefix_cache:
            self.kv_cache.prefix_cache = PrefixCache(
                kv_block_size, metric_labels=self._metric_labels)
        # host-memory KV tier (ragged/kv_tier.py): KV pressure PAGES
        # blocks out (through the pool's own compact storage format)
        # instead of evicting them — cold prefix chains and preempted
        # sessions come back without re-prefill
        if host_kv_tier:
            from deepspeed_tpu.inference.ragged.kv_tier import HostKVTier

            self.kv_cache.host_tier = HostKVTier(
                capacity_bytes=int(host_tier_mb) << 20,
                metric_labels=self._metric_labels)

        self.state = StateManager(self.kv_cache,
                                  max_tracked_sequences=4 * max_seqs_per_step,
                                  max_blocks_per_seq=max_blocks_per_seq)
        self.scheduler = SplitFuseScheduler(
            self.state, max_tokens_per_step, max_seqs_per_step)
        self.max_tokens = max_tokens_per_step
        self.max_seqs = max_seqs_per_step
        self.max_blocks_per_seq = max_blocks_per_seq
        # decode-only steps use the Pallas paged-attention kernel (no
        # per-token context gather). On any multi-device mesh the kernel
        # runs inside a shard_map — manual over tp (q heads / KV heads
        # co-sharded; needs tp | kv_heads for the GQA grouping), other
        # axes replicated. Pallas can't run under plain GSPMD, so a bare
        # multi-chip mesh without the wrap is NOT a kernel-path config.
        axes = {} if self.mesh is None else dict(self.mesh.shape)
        self._tp = axes.get("tp", 1)
        single = self.mesh is None or all(v == 1 for v in axes.values())
        # v1's constructor (run above) already raised unless tp divides
        # both head counts, which is exactly the GQA co-sharding the
        # shard_map wrap needs — every constructible config runs the
        # kernel path. The flag stays as a manual escape hatch (tests
        # flip it to compare against the gather path).
        self._use_paged_kernel = True
        # serve-path telemetry (VERDICT r2: the gather fallback is a perf
        # cliff users can't see — count it; reference analog: the comms
        # logger's op counts, utils/comms_logging.py)
        self._last_fallback_reason = "unknown"
        self.stats = {"decode_kernel_steps": 0, "prefill_kernel_steps": 0,
                      "prefill_gather_fallbacks": 0,
                      "fallback_reasons": {"vmem": 0, "padding": 0},
                      "queued": 0, "admitted": 0, "preempted": 0,
                      "preempt_reasons": {},
                      "requeued": 0, "truncated": 0,
                      "prefix_hit_tokens": 0,
                      "spec_steps": 0, "spec_proposed": 0,
                      "spec_accepted": 0, "spec_backoff_rounds": 0,
                      "paged_out": 0, "paged_in": 0,
                      "warm_resume_tokens": 0,
                      # live-migration ladder (serving/disagg.py
                      # serialize_session/install_session): warm resume /
                      # parked-in-tier / folded-recompute install rungs,
                      # plus the source-side captures
                      "migrated_out": 0, "migrated_in": 0,
                      "migrate_paged": 0, "migrate_recompute": 0,
                      "migrate_resume_tokens": 0}
        # admission queue: put() never raises on a full KV pool — requests
        # wait FIFO here and admit as blocks free up; preemption victims
        # requeue at the FRONT with their generated tokens preserved
        self._queue: Deque[_QueuedRequest] = deque()
        self._max_queue_depth = max_queue_depth
        # speculative decoding: model-free prompt-lookup drafts verified
        # through the ragged step (spec_decode.py; greedy acceptance is
        # token-identical to non-speculative greedy)
        self.spec_k = max(1, int(spec_k))
        self._drafter = drafter if drafter is not None else (
            PromptLookupDrafter(max_ngram=spec_ngram) if spec_decode
            else None)
        # adaptive draft length (ISSUE 17): per-request k chosen each
        # spec round from the measured acceptance EWMA and batch
        # occupancy — speculate hard when decode is memory-bound and the
        # batch is idle, back off toward k=0 under load. Off (the
        # default) is the bit-exact legacy fixed-k path; on changes only
        # HOW MANY drafts verify, never the accepted greedy chain.
        self._spec_adaptive = bool(spec_adaptive_k)
        self._spec_alpha = float(spec_accept_alpha)
        self._spec_accept_ewma: Optional[float] = None    # global
        self._seq_accept_ewma: Dict[int, float] = {}      # per request
        self._spec_wasted_verify_tokens = 0
        # backoff curve: the j-th draft's expected yield is a^j; draft
        # while a^j >= cut, where cut scales with batch occupancy (at
        # full occupancy verify rows crowd out real decode tokens)
        self._spec_cut_base = 0.25
        self._spec_load_gain = 3.0
        # request-latency observability (docs/observability.md): TTFT is
        # put()->first emitted token; decode latency is the gap between
        # consecutive emitted tokens of one sequence (a burst spreads its
        # round-trip evenly over the tokens it produced). Histograms live
        # in the process-wide hub so serving percentiles land on the same
        # Prometheus page as training metrics.
        from deepspeed_tpu.observability import get_hub
        from deepspeed_tpu.observability.flight_recorder import (
            get_flight_recorder, install_crash_handlers)

        self._hub = get_hub()
        lbl = self._metric_labels
        self._ttft_hist = self._hub.histogram("serve.ttft_seconds",
                                              labels=lbl)
        self._decode_hist = self._hub.histogram("serve.decode_token_seconds",
                                                labels=lbl)
        self._step_hist = self._hub.histogram("serve.step_seconds",
                                              labels=lbl)
        self._admission_hist = self._hub.histogram(
            "serve.admission_wait_seconds", labels=lbl)
        self._spec_hist = self._hub.histogram("serve.spec_accepted_len",
                                              labels=lbl)
        # serving shares the crash flight recorder: a wedged serve step
        # dumps the last admits/steps the same way a training hang does
        self._flight = get_flight_recorder()
        install_crash_handlers()
        # per-request flight paths (observability/request_trace.py):
        # every request gets a typed span timeline, tail-sampled at
        # FINISH (SLO violators always kept); the tracer registers the
        # in-flight request state as crash-dump context. ``request_trace``
        # takes the observability.request_trace config block (or a
        # dict); env: DSTPU_REQUEST_TRACE=0, DSTPU_REQ_TRACE_SAMPLE/
        # _RING/_SLO_MS.
        from deepspeed_tpu.observability.request_trace import RequestTracer

        self.tracer = RequestTracer.from_config(
            request_trace, hub=self._hub, flight=self._flight)
        self.scheduler.tracer = self.tracer
        self._admit_time: Dict[int, float] = {}
        self._last_emit_time: Dict[int, float] = {}
        self._burst_tokens = 0
        self._burst_capacity = 0
        kernel_mesh = None if single else self.mesh
        # all four step programs come from the process-level cache
        # (_shared_step_fns) so a fleet of same-config replicas compiles
        # each program once, not once per engine
        _fns = _shared_step_fns(self.cfg, kernel_mesh)
        self._step_fn = _fns["step"]
        self._decode_fn = _fns["decode"]
        self._prefill_fn = _fns["prefill"]
        # device-side token pick: the step fetches only sampled ids (or
        # the consumed rows when temperature > 0), never the full [T, V]
        # logits buffer (see step())
        self._pick_greedy = _PICK_GREEDY
        self._take_rows = _TAKE_ROWS
        # speculative verification consumes the greedy id of EVERY chunk
        # row (draft j is accepted iff it equals row j-1's argmax), so
        # fetch all T ids in one device round trip — still 4 bytes/row,
        # never the [T, V] logits
        self._pick_greedy_all = _PICK_GREEDY_ALL
        # multi-step greedy decode: one device program per `decode_steps`
        # tokens when every live sequence is in steady decode
        # (model_runner.ragged_multi_decode; decode_steps=1 restores
        # strict per-token SplitFuse admission)
        self.decode_steps = max(1, int(decode_steps))
        self._multi_decode_fn = _fns["multi_decode"]
        log_dist(
            f"InferenceEngineV2: kv_blocks={kv_blocks}x{kv_block_size} "
            f"budget={max_tokens_per_step}tok/{max_seqs_per_step}seq",
            ranks=[0])

    # -- admission (reference engine_v2.py:184 query/can_schedule) --------

    def can_schedule(self, prompt_len: int) -> bool:
        """Capacity probe: would a prompt of this length admit RIGHT NOW?
        KV blocks allocate lazily (the scheduler's ensure_capacity), so
        the free list alone over-admits — count the blocks already
        COMMITTED to live sequences: each sequence's private claim at its
        current length, plus every cache-shared block once. Idle
        prefix-cached blocks stay admissible (reclaimed on demand).
        Since the admission queue landed this is advisory only: put()
        enqueues regardless and admission happens as blocks free up.
        Admission also stops at ``max_seqs_per_step`` live sequences:
        the scheduler can't run more per step, and the fast multi-step
        decode/spec paths require every live sequence to fit one batch —
        over-admitting past the slots would silently degrade them to
        per-token steps for zero scheduling benefit."""
        blocks = self.kv_cache.blocks_needed(prompt_len + 1)
        if (blocks > self.max_blocks_per_seq
                or len(self.state.seqs) >= self.max_seqs
                or len(self.state.seqs)
                >= self.state.max_tracked_sequences):
            return False
        committed = 0
        for s in self.state.seqs.values():
            need = self.kv_cache.blocks_needed(s.total_tokens + 1)
            committed += max(need, len(s.kv_blocks)) - len(s.prefix_keys)
        cache = self.kv_cache.prefix_cache
        if cache is not None:
            committed += cache.referenced_blocks
        return blocks + committed <= self.kv_cache.allocator.total_blocks

    # -- core step (reference engine_v2.py:107 put) -----------------------

    @property
    def _journal_owner(self) -> str:
        """This engine's ingress-claim identity for the fleet journal
        (stable per instance; see FleetJournal.claim_ingress)."""
        return f"engine:{id(self)}"

    def put(self, uids: List[int], tokens_list: List[np.ndarray],
            max_new_tokens: int = 64) -> None:
        """Submit new sequences (uid -> prompt tokens). Requests enter a
        FIFO waiting queue and admit as KV blocks free up — a full pool
        means backpressure (``serve.queue_wait_depth``), never an error.
        (The pre-PR-8 contract — put() raised RuntimeError when the pool
        was full — is retired; see docs/serving.md.) Raises ValueError
        only for a prompt that can NEVER fit (per-seq block cap / total
        pool), and RuntimeError when ``max_queue_depth`` is configured
        and the queue is full (opt-in fail-fast backpressure)."""
        now = time.perf_counter()
        jr = get_journal()
        # a router-fronted engine defers ADMIT/EMIT journaling to the
        # router (which owns request identity); a standalone engine is
        # its own ingress and records admissions here
        journal_ingress = (jr is not None and jr.claim_ingress(
            self._journal_owner) == self._journal_owner)
        for uid, toks in zip(uids, tokens_list):
            toks = np.asarray(toks, np.int32).ravel()
            blocks = self.kv_cache.blocks_needed(len(toks) + 1)
            if (blocks > self.max_blocks_per_seq
                    or blocks > self.kv_cache.allocator.total_blocks):
                raise ValueError(
                    f"uid={uid}: prompt of {len(toks)} tokens needs "
                    f"{blocks} KV blocks and can never be scheduled "
                    f"(max_blocks_per_seq={self.max_blocks_per_seq}, "
                    f"pool={self.kv_cache.allocator.total_blocks})")
            if (self._max_queue_depth is not None
                    and len(self._queue) >= self._max_queue_depth):
                raise RuntimeError(
                    f"uid={uid}: admission queue full "
                    f"(max_queue_depth={self._max_queue_depth})")
            self._queue.append(_QueuedRequest(
                uid=uid, tokens=toks, max_new_tokens=max_new_tokens,
                enqueue_time=now, admit_time=now))
            if journal_ingress:
                jr.admit(uid, toks.tolist(), int(max_new_tokens))
            self.stats["queued"] += 1
            self._hub.counter_add("serve.requests", labels=self._metric_labels)
            self.tracer.on_enqueue(uid, len(toks),
                                   queue_depth=len(self._queue))
        self._admit_from_queue()
        self._hub.gauge("serve.queue_wait_depth", len(self._queue),
                        labels=self._metric_labels)

    def _admit_from_queue(self) -> None:
        """Admit waiting requests strictly FIFO while capacity lasts.
        Strict head-of-line order keeps big prompts from starving behind
        a stream of small ones; the rotation fairness lives in the
        scheduler's prefill scan instead."""
        now = time.perf_counter()
        while self._queue and self.can_schedule(len(self._queue[0].tokens)):
            req = self._queue.popleft()
            if req.paged:
                outcome = self._try_page_in(req, now)
                if outcome == "stall":
                    # the session's blocks don't fit RIGHT NOW (live
                    # pressure): keep FIFO order and retry next round
                    self._queue.appendleft(req)
                    break
                if outcome == "resumed":
                    continue
                # tier spilled the session: fall through — ``tokens``
                # carries the folded history for prefix recompute
            seq = self.state.get_or_create(req.uid, req.tokens,
                                           req.max_new_tokens)
            seq.prior_generated = req.prior_generated
            self.tracer.on_admit(req.uid, wait_s=now - req.enqueue_time,
                                 requeued=req.requeued)
            skipped = self.state.attach_prefix(seq)
            if skipped:
                self.stats["prefix_hit_tokens"] += skipped
                self._hub.counter_add("serve.prefix_hit_tokens", skipped,
                                       labels=self._metric_labels)
                self.tracer.on_prefix_hit(req.uid, skipped)
            if req.admit_time is not None:
                self._admit_time[req.uid] = req.admit_time
            self._admission_hist.observe(now - req.enqueue_time)
            self.stats["admitted"] += 1
        self._hub.gauge("serve.queue_wait_depth", len(self._queue),
                        labels=self._metric_labels)

    def _release_seq(self, uid: int) -> Optional[float]:
        """The ONE sequence-teardown path: frees state + KV and pops the
        latency maps (both the finished and the preempted path route
        here, so neither leaks ``_admit_time``/``_last_emit_time`` under
        sustained overload). Returns the pending admit time, if TTFT was
        still unmeasured, for requeue to carry forward."""
        self.state.release(uid)
        admit = self._admit_time.pop(uid, None)
        self._last_emit_time.pop(uid, None)
        self._seq_accept_ewma.pop(uid, None)
        return admit

    def _requeue(self, seq, reason: str = "pool_exhausted") -> None:
        """Preempt-and-requeue: park the victim back at the FRONT of the
        admission queue with its generated-so-far tokens folded into the
        prompt, so readmission recomputes the prefix (often straight
        from the prefix cache) and the request continues where it
        stopped — no work is discarded and nothing is dropped.
        ``reason`` tags the preemption (today only pool_exhausted; the
        disaggregated-router follow-ups add more) on the counter, the
        stats dict, and the victim's trace."""
        tokens = np.concatenate(
            [np.asarray(seq.input_tokens, np.int32),
             np.asarray(seq.generated, np.int32)])
        if (self.kv_cache.blocks_needed(len(tokens) + 1)
                > self.max_blocks_per_seq):
            # grown to the per-seq block cap: readmission could never
            # fit, so end it (the pre-existing cap-truncation contract)
            # instead of queueing it forever
            seq.done = True
            seq.truncated = True
            self.stats["truncated"] += 1
            self.tracer.on_finish(seq.uid, "truncated")
            self._release_seq(seq.uid)
            log_dist(f"uid={seq.uid} at per-seq KV cap on preemption: "
                     "truncated", ranks=[0])
            return
        self.tracer.on_preempt(seq.uid, reason=reason,
                               generated=len(seq.generated))
        jr = get_journal()
        if jr is not None:
            jr.decision("PREEMPT", uid=seq.uid, reason=reason,
                        generated=len(seq.generated),
                        free_blocks=self.kv_cache.free_blocks,
                        queue_depth=len(self._queue))
        prior = seq.prior_generated + len(seq.generated)
        admit = self._release_seq(seq.uid)
        self._queue.appendleft(_QueuedRequest(
            uid=seq.uid, tokens=tokens, max_new_tokens=seq.max_new_tokens,
            enqueue_time=time.perf_counter(), prior_generated=prior,
            admit_time=admit, requeued=True))
        self.stats["preempted"] += 1
        self.stats["preempt_reasons"][reason] = \
            self.stats["preempt_reasons"].get(reason, 0) + 1
        self.stats["requeued"] += 1
        self._hub.counter_add("serve.preempted", labels=self._metric_labels)
        self._hub.counter_add(f"serve.preempted_reason.{reason}",
                              labels=self._metric_labels)
        self._hub.gauge("serve.queue_wait_depth", len(self._queue),
                        labels=self._metric_labels)

    def _page_out(self, seq, reason: str = "paged_out") -> bool:
        """Preempt ``seq`` by PAGING its KV to the host tier instead of
        discarding it: block contents copy out in pool-native format (a
        pure byte copy — bit-exact round trip by construction) together
        with the descriptor state, and the request requeues at the queue
        front flagged ``paged``. Readmission restores the blocks and
        resumes *decode* — zero re-prefill FLOPs, token stream identical
        to a never-paged run. False when paging doesn't apply (no tier,
        mid-prefill, at the per-seq cap, or session oversize for the
        tier) — the caller falls back to ``_requeue`` recompute."""
        tier = getattr(self.kv_cache, "host_tier", None)
        if tier is None or seq.pending_prefill or seq.seen_tokens <= 0:
            return False
        if (self.kv_cache.blocks_needed(seq.total_tokens + 1)
                > self.max_blocks_per_seq):
            return False  # could never regrow: _requeue owns truncation
        # trim to the blocks holding real KV: rejected speculative
        # drafts may have grown the block list past the accepted
        # frontier, and those trailing blocks hold only draft garbage
        keep = self.kv_cache.blocks_needed(seq.seen_tokens)
        if keep <= 0 or keep > len(seq.kv_blocks):
            return False
        from deepspeed_tpu.inference.ragged.kv_tier import PagedSession

        payload, scales = self.kv_cache.read_blocks_host(
            np.asarray(seq.kv_blocks[:keep], np.int64))
        sess = PagedSession(
            uid=seq.uid,
            input_tokens=np.asarray(seq.input_tokens, np.int32),
            generated=list(seq.generated),
            seen_tokens=seq.seen_tokens,
            max_new_tokens=seq.max_new_tokens,
            prior_generated=seq.prior_generated,
            payload=payload, scales=scales,
            admit_time=self._admit_time.get(seq.uid),
            spec_accept_ewma=self._seq_accept_ewma.get(seq.uid))
        if not tier.put_session(sess):
            return False
        self.tracer.on_preempt(seq.uid, reason=reason,
                               generated=len(seq.generated))
        jr = get_journal()
        if jr is not None:
            jr.decision("PAGE_OUT", uid=seq.uid, reason=reason,
                        seen_tokens=int(seq.seen_tokens),
                        n_blocks=int(keep),
                        free_blocks=self.kv_cache.free_blocks,
                        queue_depth=len(self._queue))
        # folded history rides in the queued request as the fallback:
        # if the tier spills the session before readmission, admission
        # degrades to the ordinary prefix-recompute path
        tokens = np.concatenate(
            [np.asarray(seq.input_tokens, np.int32),
             np.asarray(seq.generated, np.int32)])
        prior = seq.prior_generated + len(seq.generated)
        admit = self._release_seq(seq.uid)
        self._queue.appendleft(_QueuedRequest(
            uid=seq.uid, tokens=tokens, max_new_tokens=seq.max_new_tokens,
            enqueue_time=time.perf_counter(), prior_generated=prior,
            admit_time=admit, requeued=True, paged=True))
        self.stats["preempted"] += 1
        self.stats["preempt_reasons"][reason] = \
            self.stats["preempt_reasons"].get(reason, 0) + 1
        self.stats["paged_out"] += 1
        self._hub.counter_add("serve.preempted", labels=self._metric_labels)
        self._hub.counter_add(f"serve.preempted_reason.{reason}",
                              labels=self._metric_labels)
        self._hub.gauge("serve.queue_wait_depth", len(self._queue),
                        labels=self._metric_labels)
        return True

    def _try_page_in(self, req: _QueuedRequest, now: float) -> str:
        """Warm-resume a ``paged`` queued request from the host tier.
        Returns ``"resumed"`` (decode continues, zero prefill),
        ``"stall"`` (session present but HBM can't take its blocks this
        round — keep queue order, retry later), or ``"recompute"`` (the
        tier spilled the session; the folded tokens re-prefill)."""
        tier = getattr(self.kv_cache, "host_tier", None)
        sess = tier.peek_session(req.uid) if tier is not None else None
        if sess is None:
            return "recompute"
        keep = sess.n_blocks
        if keep > self.kv_cache.free_blocks:
            self.kv_cache.reclaim(keep - self.kv_cache.free_blocks)
        if keep > self.kv_cache.free_blocks:
            return "stall"
        sess = tier.pop_session(req.uid)
        seq = self.state.get_or_create(sess.uid, sess.input_tokens,
                                       sess.max_new_tokens)
        seq.generated = list(sess.generated)
        seq.prior_generated = sess.prior_generated
        seq.seen_tokens = sess.seen_tokens
        blocks = self.kv_cache.allocator.allocate(keep)
        seq.kv_blocks = np.asarray(blocks, np.int64)
        self.kv_cache.write_blocks(blocks, sess.payload, sess.scales)
        seq.resumed_from_tier = keep
        if sess.spec_accept_ewma is not None:
            self._seq_accept_ewma[sess.uid] = float(sess.spec_accept_ewma)
        self.stats["paged_in"] += 1
        self.stats["admitted"] += 1
        self.stats["warm_resume_tokens"] += sess.seen_tokens
        self._hub.counter_add("serve.warm_resume_tokens", sess.seen_tokens,
                              labels=self._metric_labels)
        self.tracer.on_admit(req.uid, wait_s=now - req.enqueue_time,
                             requeued=True)
        if sess.admit_time is not None:
            self._admit_time[req.uid] = sess.admit_time
        elif req.admit_time is not None:
            self._admit_time[req.uid] = req.admit_time
        self._admission_hist.observe(now - req.enqueue_time)
        return "resumed"

    def page_out(self, uid: int) -> bool:
        """Explicitly park a live sequence's KV in the host tier (e.g. a
        session going idle between turns). The request re-enters the
        admission queue flagged ``paged`` and warm-resumes when capacity
        allows. False when paging doesn't apply — the sequence stays
        live."""
        seq = self.state.seqs.get(uid)
        if seq is None or seq.done:
            return False
        return self._page_out(seq, reason="explicit_page_out")

    # -- live session migration (serving/disagg.py owns the wire codec) --

    def migrate_out_session(self, uid: int) -> Optional[Dict[str, Any]]:
        """Destructively capture a mid-stream session for live migration:
        the committed KV blocks (partial tail block included, pool-native
        format), the descriptor state that rebuilds the sequence on the
        target, and the per-request spec-acceptance EWMA. The sequence is
        RELEASED here — the caller owns shipping the capture (or falling
        back to recompute on the target if the wire fails).

        A session already parked in the host tier migrates warm straight
        from host memory. Returns None when there is nothing warm to
        capture (unknown uid, mid-prefill, queued-but-never-admitted):
        the caller degrades to the legacy fold-and-resubmit path."""
        tier = getattr(self.kv_cache, "host_tier", None)
        seq = self.state.seqs.get(uid)
        if seq is None or seq.done:
            sess = tier.pop_session(uid) if tier is not None else None
            if sess is None:
                return None
            # drop the paged queue entry: ownership moves with the bytes
            if any(r.uid == uid for r in self._queue):
                self._queue = deque(r for r in self._queue
                                    if r.uid != uid)
            self._seq_accept_ewma.pop(uid, None)
            self.tracer.on_finish(uid, "migrated")
            self.stats["migrated_out"] += 1
            self._hub.counter_add("serve.migrated_out",
                                  labels=self._metric_labels)
            return {"uid": int(uid),
                    "input_tokens": np.asarray(sess.input_tokens, np.int32),
                    "generated": list(sess.generated),
                    "seen_tokens": int(sess.seen_tokens),
                    "max_new_tokens": int(sess.max_new_tokens),
                    "prior_generated": int(sess.prior_generated),
                    "payload": sess.payload, "scales": sess.scales,
                    "spec_accept_ewma": sess.spec_accept_ewma}
        if seq.pending_prefill or seq.seen_tokens <= 0:
            return None
        # trim to the blocks holding real KV (same rule as _page_out):
        # rejected speculative drafts leave garbage past the frontier
        keep = self.kv_cache.blocks_needed(seq.seen_tokens)
        if keep <= 0 or keep > len(seq.kv_blocks):
            return None
        payload, scales = self.kv_cache.read_blocks_host(
            np.asarray(seq.kv_blocks[:keep], np.int64))
        cap = {"uid": int(uid),
               "input_tokens": np.asarray(seq.input_tokens, np.int32),
               "generated": list(seq.generated),
               "seen_tokens": int(seq.seen_tokens),
               "max_new_tokens": int(seq.max_new_tokens),
               "prior_generated": int(seq.prior_generated),
               "payload": payload, "scales": scales,
               "spec_accept_ewma": self._seq_accept_ewma.get(uid)}
        self.tracer.on_finish(uid, "migrated")
        self._release_seq(uid)
        self.stats["migrated_out"] += 1
        self._hub.counter_add("serve.migrated_out",
                              labels=self._metric_labels)
        return cap

    def install_migrated_session(self, sess) -> str:
        """Install a migrated session whose ``payload`` is already in
        THIS pool's native storage format (serving/disagg.py
        install_session owns the wire→pool conversion). Walks the
        degradation ladder and NEVER raises:

        * ``"resumed"``    — blocks written, decode continues warm with
          zero re-prefill FLOPs;
        * ``"paged"``      — no HBM room right now: parked in the host
          tier + queued ``paged`` (still warm — readmission restores the
          blocks via the ordinary ``_try_page_in`` path);
        * ``"recompute"``  — no payload / no tier room: the folded token
          history queues for ordinary prefix-recompute admission;
        * ``"duplicate"``  — uid already live or queued here (a raced
          failover already owns it): installed nothing;
        * ``"truncated"``  — the folded history can never fit this
          engine (per-seq cap): counted and closed, mirroring
          ``_requeue``'s cap-truncation contract.
        """
        uid = int(sess.uid)
        if uid in self.state.seqs or any(r.uid == uid for r in self._queue):
            return "duplicate"
        tier = getattr(self.kv_cache, "host_tier", None)
        n = 0 if sess.payload is None else sess.n_blocks
        fold = np.concatenate(
            [np.asarray(sess.input_tokens, np.int32),
             np.asarray(sess.generated, np.int32)])
        prior = int(sess.prior_generated) + len(sess.generated)
        now = time.perf_counter()
        if (n > 0 and n <= self.max_blocks_per_seq
                and len(self.state.seqs) < self.max_seqs
                and len(self.state.seqs) < self.state.max_tracked_sequences):
            if n > self.kv_cache.free_blocks:
                self.kv_cache.reclaim(n - self.kv_cache.free_blocks)
            if n <= self.kv_cache.free_blocks:
                seq = self.state.get_or_create(
                    uid, np.asarray(sess.input_tokens, np.int32),
                    sess.max_new_tokens)
                seq.generated = list(sess.generated)
                seq.prior_generated = int(sess.prior_generated)
                seq.seen_tokens = int(sess.seen_tokens)
                blocks = self.kv_cache.allocator.allocate(n)
                seq.kv_blocks = np.asarray(blocks, np.int64)
                self.kv_cache.write_blocks(blocks, sess.payload,
                                           sess.scales)
                seq.resumed_from_tier = n
                if sess.spec_accept_ewma is not None:
                    self._seq_accept_ewma[uid] = float(
                        sess.spec_accept_ewma)
                self.tracer.on_enqueue(uid, len(fold),
                                       queue_depth=len(self._queue))
                self.tracer.on_admit(uid, wait_s=0.0, requeued=True)
                self.stats["migrated_in"] += 1
                self.stats["admitted"] += 1
                self.stats["migrate_resume_tokens"] += int(
                    sess.seen_tokens)
                self._hub.counter_add("serve.migrated_in",
                                      labels=self._metric_labels)
                self._hub.counter_add("serve.warm_resume_tokens",
                                      int(sess.seen_tokens),
                                      labels=self._metric_labels)
                return "resumed"
        if (n > 0 and tier is not None and n <= self.max_blocks_per_seq
                and tier.put_session(sess)):
            # target HBM is full RIGHT NOW: park the warm bytes in the
            # host tier — readmission warm-resumes with zero re-prefill
            self._queue.append(_QueuedRequest(
                uid=uid, tokens=fold,
                max_new_tokens=int(sess.max_new_tokens),
                enqueue_time=now, prior_generated=prior,
                requeued=True, paged=True))
            self.tracer.on_enqueue(uid, len(fold),
                                   queue_depth=len(self._queue))
            self.stats["migrate_paged"] += 1
            self.stats["queued"] += 1
            self._hub.counter_add("serve.migrate_paged",
                                  labels=self._metric_labels)
            self._admit_from_queue()
            return "paged"
        blocks_needed = self.kv_cache.blocks_needed(len(fold) + 1)
        if (blocks_needed > self.max_blocks_per_seq
                or blocks_needed > self.kv_cache.allocator.total_blocks):
            # can never fit this engine: close it loudly (the same
            # contract as _requeue's per-seq-cap truncation) instead of
            # wedging the admission queue head forever
            self.stats["truncated"] += 1
            self.tracer.on_finish(uid, "truncated")
            return "truncated"
        self._queue.append(_QueuedRequest(
            uid=uid, tokens=fold, max_new_tokens=int(sess.max_new_tokens),
            enqueue_time=now, prior_generated=prior, requeued=True))
        self.tracer.on_enqueue(uid, len(fold),
                               queue_depth=len(self._queue))
        self.stats["migrate_recompute"] += 1
        self.stats["queued"] += 1
        self._hub.counter_add("serve.migrate_recompute",
                              labels=self._metric_labels)
        self._admit_from_queue()
        return "recompute"

    def reload_params(self, params: Optional[Dict[str, Any]] = None,
                      seed: Optional[int] = None) -> None:
        """Hot-swap the serving weights in place. Replacement params
        route through the same v1 placement/quantization path as boot
        (``params=None`` re-derives them from ``model.init(seed)``).
        Every compiled step program takes params as an ARGUMENT, not a
        capture, so the swap costs zero recompilation and the next step
        serves the new weights — live KV blocks stay valid only if the
        caller quiesced the engine first (supervisor.rolling_swap drains
        and migrates sessions out before calling this)."""
        from deepspeed_tpu.inference.engine import InferenceEngine

        if params is None:
            params = self.model.init(
                jax.random.PRNGKey(int(seed or 0)))
        self._v1 = InferenceEngine(
            self.model, mesh=self.mesh, params=params,
            dtype=self._param_dtype,
            quantize_weights=self._quantize_weights)
        self.params = self._v1.params

    def holds_prefix_blocks(self, tokens) -> int:
        """How many full prefix blocks of ``tokens`` this engine can
        serve without prefill, counting BOTH the HBM prefix cache and
        the host tier behind it — the fleet router's session-affinity
        signal (serving/router.py prefers the replica already holding a
        returning session's blocks)."""
        cache = self.kv_cache.prefix_cache
        if cache is None:
            return 0
        toks = np.asarray(tokens, np.int32).ravel()
        tier = getattr(self.kv_cache, "host_tier", None)
        if tier is not None:
            return tier.holds_chain_prefix(cache, toks)
        keys, _ = cache.lookup(toks, max_tokens=max(0, len(toks) - 1))
        return len(keys)

    def step(self, temperature: float = 0.0, seed: int = 0,
             eos_token_id: Optional[int] = None) -> Dict[int, int]:
        """Run one SplitFuse step. Returns {uid: new_token} for sequences
        that produced a token this step."""
        t0 = time.perf_counter()
        self._admit_from_queue()
        scheduled = self.scheduler.schedule()
        self._release_finished()
        if not scheduled:
            # all live sequences starved for KV (pool exhausted mid-decode):
            # preempt the last-admitted sequence so the others can progress
            # — without this the engine deadlocks and leaks the pool. The
            # victim requeues at the queue front with its generated tokens
            # kept for prefix recompute; it is never silently dropped.
            live = [s for s in self.state.seqs.values() if not s.done]
            if len(live) > 1 or (live and self._queue):
                victim = live[-1]
                # page to the host tier when one is attached (decode
                # resumes without re-prefill); recompute-requeue is the
                # fallback when paging doesn't apply
                if self._page_out(victim):
                    log_dist(
                        f"KV pool exhausted: paged uid={victim.uid} to "
                        f"the host tier ({len(victim.generated)} tokens "
                        "generated) — warm resume on readmission",
                        ranks=[0])
                else:
                    log_dist(
                        f"KV pool exhausted: preempting uid={victim.uid} "
                        f"({len(victim.generated)} tokens generated) — "
                        "requeued for readmission", ranks=[0])
                    self._requeue(victim)
            elif live:
                # a lone sequence the pool cannot grow for: requeueing
                # would just readmit it into the same wall, so end it
                # (the only remaining truncation path)
                victim = live[0]
                log_dist(
                    f"KV pool exhausted by lone uid={victim.uid}: "
                    "truncated (pool smaller than one request)", ranks=[0])
                victim.done = True
                victim.truncated = True
                self.stats["truncated"] += 1
                self.tracer.on_finish(victim.uid, "truncated")
                self._release_seq(victim.uid)
            return {}
        batch = build_ragged_batch(scheduled, self.max_tokens, self.max_seqs,
                                   self.max_blocks_per_seq)
        # steady-state decode (one token per sequence): tokens line up
        # with slots, so the compact paged-kernel path applies
        decode_only = (self._use_paged_kernel
                       and all(len(nt) == 1 for _, nt, _ in scheduled))
        seg_plan = None
        if self._use_paged_kernel and not decode_only:
            seg_plan = self._plan_prefill_segments(scheduled)
            if seg_plan is None:
                self.stats["prefill_gather_fallbacks"] += 1
                # warn ONCE per reason (vmem/padding), then count
                # silently: the re-log-every-100 version flooded tier-1
                # output on CPU runs. Counts stay queryable in
                # log_summary() / telemetry.get().
                from deepspeed_tpu.utils import telemetry

                telemetry.count(
                    "serve.prefill_gather_fallback",
                    f"{self._last_fallback_reason}: paged prefill fell "
                    "back to the gather path — flat-layout serve step, "
                    "no Pallas kernel; see log_summary()")
            else:
                self.stats["prefill_kernel_steps"] += 1
            # fraction of mixed prefill steps that lost the Pallas
            # kernel to the gather path — per-replica on the Prometheus
            # page, so a fleet shows WHICH replica degraded, not a blur
            attempts = (self.stats["prefill_gather_fallbacks"]
                        + self.stats["prefill_kernel_steps"])
            self._hub.gauge(
                "serve.paged_fallback_ratio",
                self.stats["prefill_gather_fallbacks"] / max(1, attempts),
                labels=self._metric_labels)
        elif decode_only:
            self.stats["decode_kernel_steps"] += 1
        with self.mesh:
            if seg_plan is not None:
                n_segs = seg_plan[0].shape[0]
                logits, new_kv = self._prefill_fn(
                    self.params, self.kv_cache.kv_state, *seg_plan,
                    jnp.asarray(batch.block_table[:n_segs]))
            elif decode_only:
                # compact per-slot arrays: token i belongs to slot i; pad
                # out to max_seqs (token budget may be smaller than the
                # slot budget)
                n = batch.num_tokens
                d_tok = np.zeros(self.max_seqs, np.int32)
                d_pos = np.zeros(self.max_seqs, np.int32)
                d_tok[:n] = batch.token_ids[:n]
                d_pos[:n] = batch.token_pos[:n]
                logits, new_kv = self._decode_fn(
                    self.params, self.kv_cache.kv_state,
                    jnp.asarray(d_tok), jnp.asarray(d_pos),
                    jnp.asarray(batch.block_table),
                    jnp.asarray(batch.ctx_lens))
            else:
                logits, new_kv = self._step_fn(
                    self.params, self.kv_cache.kv_state,
                    jnp.asarray(batch.token_ids), jnp.asarray(batch.token_seq),
                    jnp.asarray(batch.token_pos), jnp.asarray(batch.block_table),
                    jnp.asarray(batch.num_tokens, jnp.int32))
        self.kv_cache.set_kv_state(new_kv)

        # Sample ON DEVICE and fetch only token ids (greedy) or just the
        # consumed rows (stochastic). Materializing the full [T, V]
        # logits host-side (131 MB/step at a 256-token budget x 128k
        # vocab) dominated step latency ~20:1 on a tunnel-attached host;
        # the ids are 4 bytes/sequence.
        stride = logits.shape[1] if logits.ndim == 3 else 1
        flat_idx = np.zeros(self.max_seqs, np.int32)
        consumers = []
        for slot, (seq, new_tokens, start_pos) in enumerate(scheduled):
            n = len(new_tokens)
            seq.seen_tokens = start_pos + n
            # prompt blocks the step just completed become shareable
            self.state.register_prefix_blocks(seq)
            if seq.seen_tokens < len(seq.input_tokens):
                continue  # mid-prefill: no logits consumed
            if seg_plan is not None:
                flat_idx[slot] = slot * stride + (n - 1)
            elif decode_only:
                flat_idx[slot] = slot
            else:
                flat_idx[slot] = batch.last_token_index[slot]
            consumers.append((slot, seq))

        emitted: Dict[int, int] = {}
        if consumers:
            idx_dev = jnp.asarray(flat_idx)
            with self.mesh:
                if temperature == 0.0:
                    toks_np = np.asarray(self._pick_greedy(logits, idx_dev))
                else:
                    rows_np = np.asarray(self._take_rows(logits, idx_dev))
            for slot, seq in consumers:
                if temperature == 0.0:
                    tok = int(toks_np[slot])
                else:
                    tok = int(_sample_np(rows_np[slot], temperature,
                                         seed + slot + seq.seen_tokens))
                seq.generated.append(tok)
                emitted[seq.uid] = tok
                if eos_token_id is not None and tok == eos_token_id:
                    seq.done = True
                if seq.gen_budget_left <= 0:
                    seq.done = True
        now = time.perf_counter()
        self._step_hist.observe(now - t0)
        self._flight.record("serve_step", tokens=batch.num_tokens,
                            emitted=len(emitted),
                            wall_ms=round((now - t0) * 1000.0, 3))
        if self.tracer.enabled:
            # one PREFILL span per prompt chunk this step advanced; the
            # span start backdates by the step wall so prefill lanes
            # line up with the step that computed them
            wall_ms = (now - t0) * 1e3
            # same clock domain as every other span (skew-aware wall
            # time): a stamp from the raw clock would rebase acausally
            t_start = wall_time() - (now - t0)
            for seq, new_tokens, start_pos in scheduled:
                if start_pos < len(seq.input_tokens):
                    self.tracer.on_prefill(seq.uid, t_start, wall_ms,
                                           tokens=len(new_tokens),
                                           start_pos=start_pos)
        for uid in emitted:
            self._note_emitted(uid, 1, now)
        self._update_serve_gauges()
        self._release_finished()
        return emitted

    def _plan_prefill_segments(self, scheduled):
        """Per-slot padded chunk layout for the Pallas prefill kernel, or
        None when per-segment padding would outweigh the flat layout
        (then the gather path runs). Tq is bucketed to powers of two so
        jit compiles a handful of programs."""
        longest = max(len(nt) for _, nt, _ in scheduled)
        tq = 8
        while tq < longest:
            tq *= 2
        # kernel scratch is (Tq*num_heads) rows of (2*128 + head_dim) fp32
        # VMEM; keep it well under the ~16MB/core budget or the Mosaic
        # compile fails at serve time (gather path has no such limit)
        # per-shard head count under the tp shard_map
        scratch_bytes = (tq * (self.cfg.num_heads // self._tp)
                         * (256 + self.cfg.head_dim) * 4)
        if scratch_bytes > 4 * 1024 * 1024:
            self.stats["fallback_reasons"]["vmem"] += 1
            self._last_fallback_reason = "vmem"
            return None
        S = 1  # segment-count bucket: slots are ordered, so the forward
        while S < len(scheduled):  # runs on the leading S rows only
            S *= 2
        S = min(S, self.max_seqs)
        # the padded layout materializes S*tq token rows (incl. [S,tq,V]
        # fp32 logits); cap the blowup over the flat token budget
        if S * tq > 2 * self.max_tokens:
            self.stats["fallback_reasons"]["padding"] += 1
            self._last_fallback_reason = "padding"
            return None
        toks = np.zeros((S, tq), np.int32)
        pos0 = np.zeros(S, np.int32)
        nreal = np.zeros(S, np.int32)
        for slot, (seq, nt, sp) in enumerate(scheduled):
            toks[slot, :len(nt)] = nt
            pos0[slot] = sp
            nreal[slot] = len(nt)
        return jnp.asarray(toks), jnp.asarray(pos0), jnp.asarray(nreal)

    def _release_finished(self) -> None:
        for seq in [s for s in self.state.seqs.values() if s.done]:
            self.tracer.on_finish(
                seq.uid, "truncated" if seq.truncated else "finished")
            self._release_seq(seq.uid)

    def _note_emitted(self, uid: int, n_tokens: int, now: float,
                      spec_overhead_ms: float = 0.0) -> None:
        """Fold ``n_tokens`` just-emitted tokens of ``uid`` into the
        latency histograms: the first token of a request is its TTFT;
        later tokens record the gap since the previous emission (a burst
        spreads one device round trip evenly over its tokens).
        ``spec_overhead_ms`` is this request's share of a speculative
        round's rejected-draft compute, attached to its DECODE_EMIT
        span for the phase decomposition."""
        self.tracer.on_emit(uid, n_tokens,
                            spec_overhead_ms=spec_overhead_ms)
        self._hub.counter_add("serve.tokens_emitted", n_tokens,
                              labels=self._metric_labels)
        admit = self._admit_time.pop(uid, None)
        last = self._last_emit_time.get(uid)
        if admit is not None:
            self._ttft_hist.observe(now - admit)
            n_tokens -= 1
            last = now
        if last is not None and n_tokens > 0:
            per_tok = (now - last) / n_tokens
            for _ in range(n_tokens):
                self._decode_hist.observe(per_tok)
        self._last_emit_time[uid] = now

    def _update_serve_gauges(self) -> None:
        live = [s for s in self.state.seqs.values() if not s.done]
        self._hub.gauge("serve.queue_depth", len(live),
                        labels=self._metric_labels)
        self._hub.gauge("serve.queue_wait_depth", len(self._queue),
                        labels=self._metric_labels)
        self._hub.gauge("serve.pending_prefill_tokens",
                        sum(s.pending_prefill for s in live),
                        labels=self._metric_labels)
        self._hub.gauge("serve.kv_free_blocks", self.kv_cache.free_blocks,
                        labels=self._metric_labels)
        if self.kv_cache.prefix_cache is not None:
            self._hub.gauge("serve.prefix_cached_blocks",
                            self.kv_cache.prefix_cache.cached_blocks,
                            labels=self._metric_labels)
        self._hub.gauge("serve.batch_seq_occupancy",
                        self.scheduler.last_scheduled_seqs
                        / max(1, self.max_seqs),
                        labels=self._metric_labels)
        self._hub.gauge("serve.batch_token_occupancy",
                        self.scheduler.last_scheduled_tokens
                        / max(1, self.max_tokens),
                        labels=self._metric_labels)
        if self._burst_capacity > 0:
            self._hub.gauge("serve.burst_efficiency",
                            self._burst_tokens / self._burst_capacity,
                            labels=self._metric_labels)

    def _try_decode_burst(self, eos_token_id: Optional[int]
                          ) -> Optional[Dict[int, List[int]]]:
        """Run ``decode_steps`` greedy tokens in one device round trip.

        Applies only in steady state: every live sequence mid-decode, no
        prefill pending, and KV capacity for the whole burst (the block
        tables are frozen for its duration). Returns None when a single
        SplitFuse step should run instead."""
        live = [s for s in self.state.seqs.values() if not s.done]
        if (self.decode_steps <= 1 or not live or len(live) > self.max_seqs
                or any((not s.in_decode) or s.pending_prefill for s in live)):
            return None
        # clamp the burst to the shortest remaining budget: probing
        # capacity K tokens past a sequence that only needs 1 more would
        # trip ensure_capacity's per-seq-cap kill and truncate output
        # that per-token stepping would have finished
        K = min(self.decode_steps,
                max(1, min(s.gen_budget_left for s in live)))
        if K <= 1:
            return None
        # side-effect-free capacity probe first: per-seq cap, then total
        # pool demand (a partial speculative grab would strand blocks
        # and push the fallback step into victim preemption)
        need_total = 0
        for s in live:
            blocks = self.kv_cache.blocks_needed(s.seen_tokens + K)
            if (self.state.max_blocks_per_seq is not None
                    and blocks > self.state.max_blocks_per_seq):
                return None  # near the per-seq cap: per-token tail
            need_total += max(0, blocks - len(s.kv_blocks))
        if need_total > self.kv_cache.free_blocks:
            return None
        for s in live:
            ok = self.state.ensure_capacity(s, s.seen_tokens + K)
            assert ok, "capacity probe said yes but allocation failed"
        t0 = time.perf_counter()
        S = self.max_seqs
        d_tok = np.zeros(S, np.int32)
        d_pos = np.zeros(S, np.int32)
        ctx = np.zeros(S, np.int32)
        bt = np.zeros((S, self.max_blocks_per_seq), np.int32)
        for i, s in enumerate(live):
            d_tok[i] = (s.generated[-1] if s.generated
                        else int(s.input_tokens[-1]))
            d_pos[i] = s.seen_tokens
            ctx[i] = s.seen_tokens + 1
            bt[i, :len(s.kv_blocks)] = s.kv_blocks
        with self.mesh:
            toks, new_kv = self._multi_decode_fn(
                self.params, self.kv_cache.kv_state, jnp.asarray(d_tok),
                jnp.asarray(d_pos), jnp.asarray(bt), jnp.asarray(ctx),
                steps=K)
            toks_np = np.asarray(toks)  # [K, S] — one fetch per K tokens
        self.kv_cache.set_kv_state(new_kv)
        self.stats["decode_kernel_steps"] += K
        self.stats["burst_steps"] = self.stats.get("burst_steps", 0) + 1
        emitted: Dict[int, List[int]] = {}
        for i, s in enumerate(live):
            accepted = []
            budget_left = s.gen_budget_left
            for k in range(K):
                tok = int(toks_np[k, i])
                accepted.append(tok)
                if eos_token_id is not None and tok == eos_token_id:
                    s.done = True
                    break
                if len(accepted) >= budget_left:
                    s.done = True
                    break
            s.generated.extend(accepted)
            s.seen_tokens += len(accepted)
            emitted[s.uid] = accepted
        now = time.perf_counter()
        self._step_hist.observe(now - t0)
        # burst efficiency: accepted tokens vs the K*len(live) the device
        # program computed (early-EOS/max-token exits waste the tail)
        self._burst_tokens += sum(len(v) for v in emitted.values())
        self._burst_capacity += K * len(live)
        for uid, toks in emitted.items():
            if toks:
                self._note_emitted(uid, len(toks), now)
        self._update_serve_gauges()
        self._release_finished()
        return emitted

    def _spec_round_k(self, seq, occ: float) -> int:
        """Draft length for ``seq`` this spec round. Fixed ``spec_k``
        unless adaptive speculation is on; then the controller models
        the j-th draft's expected yield as a^j (a = the request's
        measured acceptance EWMA, global EWMA as cold-start fallback)
        and drafts while a^j >= cut, where the cutoff rises with batch
        occupancy: an idle batch speculates hard (verify rows ride a
        memory-bound step for ~free), a full batch backs off toward k=0
        (verify rows crowd out real decode tokens). Only draft COUNT
        changes — accepted tokens are always the model's own greedy
        argmax chain, so bit-identity to fixed-k greedy holds."""
        if not self._spec_adaptive:
            return self.spec_k
        cut = min(0.95, self._spec_cut_base
                  * (1.0 + self._spec_load_gain * occ))
        a = self._seq_accept_ewma.get(seq.uid, self._spec_accept_ewma)
        if a is None:
            return self.spec_k  # no signal yet: speculate optimistically
        a = min(max(a, 0.0), 0.99)
        if a <= cut:
            return 0
        return max(0, min(self.spec_k,
                          int(math.log(cut) / math.log(a))))

    def _try_spec_step(self, eos_token_id: Optional[int]
                       ) -> Optional[Dict[int, List[int]]]:
        """One speculative greedy decode round: the drafter proposes up
        to ``spec_k`` tokens per sequence and ONE ragged forward verifies
        them (the SplitFuse chunk machinery doubles as the verifier —
        each chunk is [last real token, draft 1..k] and row j's argmax is
        the greedy token after prefix+drafts[:j]). The longest matching
        draft prefix is accepted plus one bonus token, so every emitted
        token is the model's own argmax chain — token-identical to
        non-speculative greedy. Returns None when a plain step should
        run instead (prefill pending, no drafts, or KV-starved)."""
        live = [s for s in self.state.seqs.values() if not s.done]
        if (self._drafter is None or not live or len(live) > self.max_seqs
                or len(live) > self.max_tokens
                or any((not s.in_decode) or s.pending_prefill for s in live)):
            return None
        # pass 1 — side-effect-free: propose drafts and probe capacity.
        # KV writes land for every chunk token (rejected drafts leave
        # garbage PAST the accepted frontier that the next real token
        # overwrites in place), so capacity must cover 1 + k per seq —
        # shrink a proposal rather than trip the per-seq-cap kill, and
        # bail to the plain step (which owns preemption) when the pool
        # cannot cover even the plain decode tokens.
        chunks: List[np.ndarray] = []
        total = 0
        need_total = 0
        n_drafted = 0
        occ = len(live) / max(1, self.max_seqs)
        adaptive_k_sum = 0
        for s in live:
            k_round = self._spec_round_k(s, occ)
            adaptive_k_sum += k_round
            k = min(k_round, s.gen_budget_left - 1,
                    self.max_tokens - total - 1)
            drafts: List[int] = []
            if k > 0:
                drafts = list(self._drafter.propose(
                    s.input_tokens.tolist() + s.generated, k))[:k]
            while drafts and (self.kv_cache.blocks_needed(
                    s.seen_tokens + 1 + len(drafts))
                    > self.max_blocks_per_seq):
                drafts.pop()
            blocks = self.kv_cache.blocks_needed(
                s.seen_tokens + 1 + len(drafts))
            if blocks > self.max_blocks_per_seq:
                return None  # at the per-seq cap: plain step decides
            need_total += max(0, blocks - len(s.kv_blocks))
            if drafts:
                n_drafted += 1
            t0 = (s.generated[-1] if s.generated
                  else int(s.input_tokens[-1]))
            chunks.append(np.asarray([t0] + drafts, np.int32))
            total += 1 + len(drafts)
        if n_drafted == 0:
            if self._spec_adaptive and adaptive_k_sum == 0:
                # the controller chose k=0 across the batch (load high
                # or acceptance low): deliberate backoff, not a drafter
                # miss — the burst path serves this round
                self.stats["spec_backoff_rounds"] += 1
            return None  # nothing proposed: the burst path is faster
        if need_total > self.kv_cache.available_blocks:
            return None
        sched: List[Tuple[Any, np.ndarray, int]] = []
        for s, chunk in zip(live, chunks):
            ok = self.state.ensure_capacity(s, s.seen_tokens + len(chunk))
            assert ok, "spec capacity probe said yes but allocation failed"
            sched.append((s, chunk, s.seen_tokens))
        t_start = time.perf_counter()
        batch = build_ragged_batch(sched, self.max_tokens, self.max_seqs,
                                   self.max_blocks_per_seq)
        with self.mesh:
            logits, new_kv = self._step_fn(
                self.params, self.kv_cache.kv_state,
                jnp.asarray(batch.token_ids), jnp.asarray(batch.token_seq),
                jnp.asarray(batch.token_pos), jnp.asarray(batch.block_table),
                jnp.asarray(batch.num_tokens, jnp.int32))
            greedy = np.asarray(self._pick_greedy_all(logits))
        self.kv_cache.set_kv_state(new_kv)
        emitted: Dict[int, List[int]] = {}
        wasted_rows: Dict[int, int] = {}
        cursor = 0
        for s, chunk, start_pos in sched:
            n = len(chunk)
            rows = greedy[cursor:cursor + n]
            cursor += n
            emit = [int(rows[0])]
            for j in range(1, n):
                if int(chunk[j]) != emit[-1]:
                    break  # draft j diverged from the greedy chain
                emit.append(int(rows[j]))
            self.stats["spec_proposed"] += n - 1
            self.stats["spec_accepted"] += len(emit) - 1
            # drafted/accepted COUNTERS (not just the accepted-len
            # histogram) so the acceptance *rate* is derivable on the
            # Prometheus page: accepted_tokens / drafted_tokens
            self._hub.counter_add("serve.spec_drafted_tokens", n - 1,
                                  labels=self._metric_labels)
            self._hub.counter_add("serve.spec_accepted_tokens",
                                  len(emit) - 1,
                                  labels=self._metric_labels)
            self.tracer.on_spec(s.uid, drafted=n - 1,
                                accepted=len(emit) - 1)
            self._spec_hist.observe(len(emit) - 1)
            if n > 1:
                # measured acceptance feeds the adaptive-k controller
                # (per-request EWMA, global EWMA as the cold-start
                # fallback) and the drafter's own counters
                rate = (len(emit) - 1) / (n - 1)
                a = self._spec_alpha
                prev = self._seq_accept_ewma.get(s.uid)
                self._seq_accept_ewma[s.uid] = (
                    rate if prev is None else a * rate + (1 - a) * prev)
                prev_g = self._spec_accept_ewma
                self._spec_accept_ewma = (
                    rate if prev_g is None else a * rate + (1 - a) * prev_g)
                note = getattr(self._drafter, "note_result", None)
                if note is not None:
                    note(n - 1, len(emit) - 1)
            # rows computed past the accepted frontier: the verify
            # round's wasted work (what adaptive-k minimizes under load)
            wasted = n - len(emit)
            if wasted:
                self._spec_wasted_verify_tokens += wasted
                self._hub.counter_add("serve.spec_wasted_verify_tokens",
                                      wasted, labels=self._metric_labels)
            budget_left = s.gen_budget_left
            final: List[int] = []
            for tok in emit:
                final.append(tok)
                if eos_token_id is not None and tok == eos_token_id:
                    s.done = True
                    break
                if len(final) >= budget_left:
                    s.done = True
                    break
            s.generated.extend(final)
            s.seen_tokens = start_pos + len(final)
            emitted[s.uid] = final
            wasted_rows[s.uid] = n - len(final)
        self.stats["spec_steps"] += 1
        if self._spec_accept_ewma is not None:
            self._hub.gauge("serve.spec_accept_ewma", self._spec_accept_ewma,
                            labels=self._metric_labels)
        now = time.perf_counter()
        self._step_hist.observe(now - t_start)
        round_wall_ms = (now - t_start) * 1e3
        self._flight.record("serve_step", tokens=batch.num_tokens,
                            emitted=sum(len(v) for v in emitted.values()),
                            spec=True,
                            wall_ms=round(round_wall_ms, 3))
        for uid, toks in emitted.items():
            if toks:
                # this request's share of the verify round spent on
                # rows past its accepted frontier — the spec_overhead
                # carve-out of its decode phase
                self._note_emitted(
                    uid, len(toks), now,
                    spec_overhead_ms=round_wall_ms * wasted_rows[uid]
                    / max(1, batch.num_tokens))
        self._update_serve_gauges()
        self._release_finished()
        return emitted

    def serve_step(self, temperature: float = 0.0, seed: int = 0,
                   eos_token_id: Optional[int] = None
                   ) -> Dict[int, List[int]]:
        """One serving round: admit from the waiting queue, then run the
        best step for the current mix — speculative decode (drafts
        available), multi-token burst (steady greedy decode), or a plain
        SplitFuse step. Returns {uid: tokens emitted this round}. The
        open-loop SLO harness (tools/serve_bench.py) drives this."""
        self._admit_from_queue()
        out: Optional[Dict[int, List[int]]] = None
        if temperature == 0.0:
            out = self._try_spec_step(eos_token_id)
            if out is None:
                out = self._try_decode_burst(eos_token_id)
        if out is None:
            emitted = self.step(temperature, seed, eos_token_id)
            out = {uid: [tok] for uid, tok in emitted.items()}
        jr = get_journal()
        if jr is not None and out and jr.claim_ingress(
                self._journal_owner) == self._journal_owner:
            for uid, toks in out.items():
                if toks:
                    jr.emit(uid, toks)
        return out

    def generate_all(self, temperature: float = 0.0, seed: int = 0,
                     eos_token_id: Optional[int] = None,
                     max_steps: int = 100_000) -> Dict[int, List[int]]:
        """Drive serve_step until every submitted sequence finishes
        (including requests still waiting in the admission queue);
        returns {uid: generated tokens}. In steady greedy decode, bursts
        ``decode_steps`` tokens (or verified speculative drafts) per
        device round trip."""
        results: Dict[int, List[int]] = {}
        for _ in range(max_steps):
            if not self.state.seqs and not self._queue:
                break
            # every round makes progress: emits tokens, advances a
            # prefill, admits from the queue, or preempts a starved
            # sequence — so this loop terminates
            for uid, toks in self.serve_step(
                    temperature, seed, eos_token_id).items():
                results.setdefault(uid, []).extend(toks)
        return results

    def flush(self, uids: List[int]) -> None:
        """Drop sequences + free KV (reference engine_v2.py flush);
        covers queued-but-unadmitted requests too."""
        tier = getattr(self.kv_cache, "host_tier", None)
        for uid in uids:
            self.tracer.on_finish(uid, "flushed")
            self._release_seq(uid)
            if tier is not None and tier.has_session(uid):
                tier.pop_session(uid)  # flushed sessions never resume
        drop = set(uids)
        if any(r.uid in drop for r in self._queue):
            self._queue = deque(r for r in self._queue if r.uid not in drop)

    def log_summary(self) -> Dict[str, Any]:
        """Serve-path telemetry (the comms-logger log_summary analog):
        kernel vs gather-fallback step counts, with fallback reasons.
        A nonzero ``prefill_gather_fallbacks`` means prefill ran the
        flat gather path — raise max_tokens_per_step or lower
        max_seqs_per_step/prompt chunking to restore the kernel path."""
        s = dict(self.stats)
        s["fallback_reasons"] = dict(self.stats["fallback_reasons"])
        s["preempt_reasons"] = dict(self.stats["preempt_reasons"])
        log_dist(f"InferenceEngineV2 summary: {s}", ranks=[0])
        return s

    def request_traces(self, last: int = 0):
        """Finished (tail-sampled) request traces — the input to
        ``slo_attribution`` and the per-request chrome-trace lanes."""
        return self.tracer.finished(last=last)

    def snapshot(self) -> Dict[str, Any]:
        """Serving observability snapshot: request-latency percentiles
        (TTFT + per-decode-token, p50/p95/p99), queue/occupancy gauges
        and the kernel/fallback counters. The same histograms render on
        the hub's Prometheus page (docs/observability.md)."""
        live = [s for s in self.state.seqs.values() if not s.done]
        out: Dict[str, Any] = {
            "ttft": self._ttft_hist.snapshot(),
            "decode_token_latency": self._decode_hist.snapshot(),
            "step_latency": self._step_hist.snapshot(),
            "admission_wait": self._admission_hist.snapshot(),
            "queue_depth": len(live),
            "queue_wait_depth": len(self._queue),
            "pending_prefill_tokens": sum(s.pending_prefill for s in live),
            "kv_free_blocks": self.kv_cache.free_blocks,
            "kv_quant_bits": self.kv_cache.quant_bits,
            "handoff_wire": self._handoff_wire,
            "batch_seq_occupancy": (self.scheduler.last_scheduled_seqs
                                    / max(1, self.max_seqs)),
            "batch_token_occupancy": (self.scheduler.last_scheduled_tokens
                                      / max(1, self.max_tokens)),
            "scheduler": dict(self.scheduler.stats),
            "stats": dict(self.stats,
                          fallback_reasons=dict(
                              self.stats["fallback_reasons"]),
                          preempt_reasons=dict(
                              self.stats["preempt_reasons"])),
            "request_trace": self.tracer.snapshot(),
        }
        if self._burst_capacity > 0:
            out["burst_efficiency"] = (self._burst_tokens
                                       / self._burst_capacity)
        if self.kv_cache.prefix_cache is not None:
            out["prefix_cache"] = self.kv_cache.prefix_cache.snapshot()
        if self.stats["spec_proposed"] > 0:
            # acceptance RATE next to the raw drafted/accepted counters
            # (the counters alone make it derivable across processes;
            # the line here makes it readable in one snapshot)
            out["spec_drafted_tokens"] = self.stats["spec_proposed"]
            out["spec_accepted_tokens"] = self.stats["spec_accepted"]
            out["spec_acceptance_rate"] = (self.stats["spec_accepted"]
                                           / self.stats["spec_proposed"])
            out["spec_accepted_len"] = self._spec_hist.snapshot()
        if self._spec_accept_ewma is not None:
            out["spec_accept_ewma"] = self._spec_accept_ewma
        if self._spec_wasted_verify_tokens:
            out["spec_wasted_verify_tokens"] = self._spec_wasted_verify_tokens
        tier = getattr(self.kv_cache, "host_tier", None)
        if tier is not None:
            out["host_tier"] = tier.snapshot()
        if self._drafter is not None and hasattr(self._drafter, "stats"):
            out["drafter"] = dict(self._drafter.stats)
        return out


def _sample_np(logits_row: np.ndarray, temperature: float, seed: int) -> int:
    if temperature <= 0.0:
        return int(np.argmax(logits_row))
    rng = np.random.default_rng(seed)
    z = logits_row / temperature
    z = z - z.max()
    p = np.exp(z)
    p /= p.sum()
    return int(rng.choice(len(p), p=p))
