"""Compiled inference forwards over the TransformerLM param tree.

Two paths, both reusing models/transformer.py weights unchanged:

  * ``forward_with_cache`` — dense per-batch KV cache, for the v1-style
    engine (reference: fused inference kernels consuming a contiguous
    cache, csrc/transformer/inference).
  * ``ragged_forward`` — paged/blocked KV with flat-token ragged batches,
    for the FastGen-style engine (reference: inference/v2 ragged kernels:
    blocked flash attention + fused rotary/KV-append,
    inference/v2/kernels/ragged_ops/). On TPU the KV append is an XLA
    scatter fused into the step, and attention runs over gathered pages;
    a Pallas paged-attention kernel can swap in behind the same signature.

Both are pure functions: (params, cache, metadata) -> (logits, cache'),
jitted once per shape bucket (the CUDA-graph analog, engine.py:497).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.models.transformer import (
    TransformerConfig, _norm, _rope, act_fn)
from deepspeed_tpu.ops.pallas.quantization import (kv_dequantize,
                                                   kv_pack, kv_quantize,
                                                   kv_unpack)
from deepspeed_tpu.runtime.sharding import (effective_dtype,
                                            vocab_parallel_lookup)
from deepspeed_tpu.utils import jaxcompat


def _kv_parts(kv_state):
    """Split the ragged KV pool pytree: a bare array (bf16 pool — today's
    program, traced verbatim) yields (data, None); a (payload, fp32
    scales) pair yields both. The quantized branch is chosen at trace
    time, so the unquantized lowering carries no quant ops at all."""
    if isinstance(kv_state, (tuple, list)):
        return kv_state[0], kv_state[1]
    return kv_state, None


def _kv_bits(kv_layer):
    """Storage width of a quantized pool, inferred at trace time from
    the payload dtype: int8 holds one value per byte; uint8 is the
    packed-nibble int4 pool (two values per byte, last dim head_dim//2
    — the codec PR 12 ships for the handoff wire, applied to storage);
    float8_e4m3fn is the fp8 quality-midpoint pool (ISSUE 17), which
    the codec passes through unpacked."""
    if kv_layer.dtype == jnp.float8_e4m3fn:
        return "fp8"
    return 4 if kv_layer.dtype == jnp.uint8 else 8


def _kernel_pages() -> int:
    """``kernels.pages_per_compute_block`` from the installed kernel
    config (ops.attention.set_kernel_config), resolved at trace time —
    same contract as the DSTPU_* env-at-construction knobs."""
    from deepspeed_tpu.ops import attention as attn_ops

    kcfg = attn_ops._KERNEL_CONFIG
    return int(getattr(kcfg, "pages_per_compute_block", 1) or 1) \
        if kcfg is not None else 1


def _qkv(cfg: TransformerConfig, layer_params, y, positions):
    """Project y [..., H] to q/k/v with rope applied. Returns q [.., nh, hd],
    k/v [.., nkv, hd] (GQA heads NOT repeated — cache stays small)."""
    ap = layer_params["attn"]
    dt = y.dtype
    q = jnp.einsum("...h,hnd->...nd", y, ap["wq"].astype(dt))
    k = jnp.einsum("...h,hnd->...nd", y, ap["wk"].astype(dt))
    v = jnp.einsum("...h,hnd->...nd", y, ap["wv"].astype(dt))
    if cfg.use_biases:
        q = q + ap["bq"].astype(dt)
        k = k + ap["bk"].astype(dt)
        v = v + ap["bv"].astype(dt)
    if cfg.pos_emb == "rope":
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
    return q, k, v


def _mlp(cfg: TransformerConfig, layer_params, x):
    if "moe" in layer_params:
        return _moe_mlp(cfg, layer_params, x)
    mp = layer_params["mlp"]
    dt = x.dtype
    y = _norm(x, layer_params["ln2"], cfg.norm, cfg.norm_eps)
    if cfg.activation == "swiglu":
        g = jnp.einsum("...h,hf->...f", y, mp["wg"].astype(dt))
        u = jnp.einsum("...h,hf->...f", y, mp["wi"].astype(dt))
        z = jax.nn.silu(g) * u
    else:
        act = act_fn(cfg.activation)
        pre = jnp.einsum("...h,hf->...f", y, mp["wi"].astype(dt))
        if cfg.use_biases:
            pre = pre + mp["bi"].astype(dt)
        z = act(pre)
    out = jnp.einsum("...f,fh->...h", z, mp["wo"].astype(dt))
    if cfg.use_biases:
        out = out + mp["bo"].astype(dt)
    return x + out


def _moe_mlp(cfg, layer_params, x):
    """MoE FFN for the inference runners (reference: inference/v2
    model_implementations mixtral/qwen_v2_moe — moe_gather/moe_scatter +
    top_k_gating ragged kernels). Token dropping is disabled: serving
    must route every token (capacity = tokens, the reference's
    no-drop inference dispatch)."""
    import dataclasses

    from deepspeed_tpu.parallel.moe import moe_ffn

    y = _norm(x, layer_params["ln2"], cfg.norm, cfg.norm_eps)
    flat = y[None] if y.ndim == 2 else y  # [1,T,H] / [S,Tq,H] groups
    gate = dataclasses.replace(cfg.gate, drop_tokens=False)
    out, _aux = moe_ffn(flat, layer_params["moe"]["router"],
                        layer_params["moe"]["experts"], gate,
                        activation=cfg.activation, train=False,
                        impl=getattr(cfg, "moe_impl", "auto"))
    return x + (out[0] if y.ndim == 2 else out)


def _unembed(cfg: TransformerConfig, params, x):
    if cfg.tie_embeddings:
        logits = jnp.einsum("...h,vh->...v", x,
                            params["embed"]["tokens"].astype(x.dtype))
    else:
        logits = jnp.einsum("...h,hv->...v", x,
                            params["unembed"]["kernel"].astype(x.dtype))
    return logits.astype(jnp.float32)


# ---------------------------------------------------------------------------
# dense-cache path (v1 engine)
# ---------------------------------------------------------------------------


def init_dense_cache(cfg: TransformerConfig, batch: int, max_len: int,
                     dtype=None):
    """cache: [L, B, max_len, 2, kv_heads, head_dim]."""
    dtype = dtype or effective_dtype(cfg.dtype)
    return jnp.zeros((cfg.num_layers, batch, max_len, 2, cfg.kv_heads,
                      cfg.head_dim), dtype)


def forward_with_cache(cfg: TransformerConfig, params, tokens: jax.Array,
                       cache: jax.Array, start_pos) -> Tuple[jax.Array, jax.Array]:
    """tokens [B, S] starting at absolute position start_pos (scalar);
    returns (logits [B, S, V] fp32, updated cache). Works for prefill
    (S = prompt len, start_pos = 0) and decode (S = 1)."""
    B, S = tokens.shape
    dt = effective_dtype(cfg.dtype)
    max_len = cache.shape[2]
    positions = start_pos + jnp.arange(S)[None, :]  # [1, S] broadcasts to B

    x = vocab_parallel_lookup(params["embed"]["tokens"].astype(dt), tokens)
    if cfg.pos_emb == "learned":
        x = x + params["embed"]["positions"].astype(dt)[positions]

    key_pos = jnp.arange(max_len)  # absolute position of each cache row
    rep = cfg.num_heads // cfg.kv_heads

    def layer_body(x, inputs):
        layer_params, kv_layer = inputs  # kv_layer [B, max_len, 2, nkv, hd]
        y = _norm(x, layer_params["ln1"], cfg.norm, cfg.norm_eps)
        q, k, v = _qkv(cfg, layer_params, y, positions)
        # append this step's kv at rows [start_pos, start_pos+S)
        kv_new = jnp.stack([k, v], axis=2).astype(kv_layer.dtype)  # [B,S,2,nkv,hd]
        kv_layer = lax.dynamic_update_slice(
            kv_layer, kv_new, (0, start_pos, 0, 0, 0))
        k_all = kv_layer[:, :, 0]  # [B, max_len, nkv, hd]
        v_all = kv_layer[:, :, 1]
        if rep > 1:
            k_all = jnp.repeat(k_all, rep, axis=2)
            v_all = jnp.repeat(v_all, rep, axis=2)
        scores = jnp.einsum("bsnd,bmnd->bnsm", q, k_all.astype(dt))
        scores = scores / jnp.sqrt(jnp.float32(cfg.head_dim)).astype(dt)
        mask = key_pos[None, None, None, :] <= positions[:, None, :, None]
        scores = jnp.where(mask, scores.astype(jnp.float32), -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(dt)
        attn = jnp.einsum("bnsm,bmnd->bsnd", probs, v_all.astype(dt))
        attn = jnp.einsum("bsnd,ndh->bsh", attn,
                          layer_params["attn"]["wo"].astype(dt))
        if cfg.use_biases:
            attn = attn + layer_params["attn"]["bo"].astype(dt)
        if cfg.parallel_block:  # Falcon: both branches read pre-attn x
            return _mlp(cfg, layer_params, x) + attn, kv_layer
        x = x + attn
        return _mlp(cfg, layer_params, x), kv_layer

    x, new_cache = lax.scan(layer_body, x, (params["layers"], cache))
    x = _norm(x, params["final_norm"], cfg.norm, cfg.norm_eps)
    return _unembed(cfg, params, x), new_cache


# ---------------------------------------------------------------------------
# ragged paged-KV path (v2 engine)
# ---------------------------------------------------------------------------


def ragged_forward(cfg: TransformerConfig, params, kv_data: jax.Array,
                   token_ids: jax.Array, token_seq: jax.Array,
                   token_pos: jax.Array, block_table: jax.Array,
                   num_tokens) -> Tuple[jax.Array, jax.Array]:
    """One ragged step over flat tokens.

    kv_data     [L, num_blocks, bs, 2, nkv, hd] — or, for a quantized
                pool, the (int8 payload, fp32 scales [L, nb, bs, 2, nkv])
                pair from ``BlockedKVCache.kv_state``
    token_ids   [T] int32 (padded); token_seq [T] slot ids; token_pos [T]
    block_table [S, Bm]; num_tokens scalar (true T, rest is padding)

    Returns (logits [T, V] fp32, kv_data'). Causal masking derives solely
    from token_pos: a query at position p attends cache rows 0..p of its
    sequence, which are exactly the rows written so far (plus this step's
    scatter, which lands before the attention reads). Padding tokens are
    routed to write into the reserved scratch block (last block id) so
    they never corrupt live pages.
    """
    kv_data, kv_scales = _kv_parts(kv_data)
    T = token_ids.shape[0]
    Smax, Bm = block_table.shape
    bs = kv_data.shape[2]
    dt = effective_dtype(cfg.dtype)
    rep = cfg.num_heads // cfg.kv_heads
    is_real = jnp.arange(T) < num_tokens  # [T]

    x = vocab_parallel_lookup(
        params["embed"]["tokens"].astype(dt), token_ids)  # [T, H]
    if cfg.pos_emb == "learned":
        x = x + params["embed"]["positions"].astype(dt)[token_pos]

    # destination page/offset per token; padded tokens write to the last
    # block's last row (block num_blocks-1 is reserved as scratch by the
    # engine) so they never corrupt live pages.
    page = block_table[token_seq, token_pos // bs]  # [T]
    offset = token_pos % bs
    scratch = kv_data.shape[1] - 1
    page = jnp.where(is_real, page, scratch)
    offset = jnp.where(is_real, offset, bs - 1)

    # context length per token's sequence, for causal masking
    max_ctx = Bm * bs
    key_pos = jnp.arange(max_ctx)  # [Lmax]

    def layer_body(x, inputs):
        if kv_scales is None:
            layer_params, kv_layer = inputs  # [num_blocks, bs, 2, nkv, hd]
            kv_sc = None
        else:
            layer_params, kv_layer, kv_sc = inputs
        y = _norm(x, layer_params["ln1"], cfg.norm, cfg.norm_eps)
        q, k, v = _qkv(cfg, layer_params, y, token_pos)  # q [T,nh,hd] k/v [T,nkv,hd]
        if kv_sc is None:
            kv_layer = kv_layer.at[page, offset, 0].set(
                k.astype(kv_layer.dtype))
            kv_layer = kv_layer.at[page, offset, 1].set(
                v.astype(kv_layer.dtype))
        else:
            bits = _kv_bits(kv_layer)
            qk, sk = kv_quantize(k, bits=bits)  # quantize-on-append
            qv, sv = kv_quantize(v, bits=bits)  # per head vector
            kv_layer = kv_layer.at[page, offset, 0].set(kv_pack(qk, bits))
            kv_layer = kv_layer.at[page, offset, 1].set(kv_pack(qv, bits))
            kv_sc = kv_sc.at[page, offset, 0].set(sk)
            kv_sc = kv_sc.at[page, offset, 1].set(sv)
        # gather each slot's pages into dense [S, Lmax, nkv, hd]
        gathered = kv_layer[block_table]  # [S, Bm, bs, 2, nkv, hd(/2)]
        if kv_sc is not None:
            # dequant-on-read: only the gathered pages, never the pool
            gathered = kv_dequantize(
                kv_unpack(gathered, _kv_bits(kv_layer)),
                kv_sc[block_table], dtype=dt)
        gathered = gathered.reshape(Smax, max_ctx, 2, cfg.kv_heads,
                                    cfg.head_dim)
        k_seq = gathered[:, :, 0][token_seq]  # [T, Lmax, nkv, hd]
        v_seq = gathered[:, :, 1][token_seq]
        if rep > 1:
            k_seq = jnp.repeat(k_seq, rep, axis=2)
            v_seq = jnp.repeat(v_seq, rep, axis=2)
        scores = jnp.einsum("tnd,tmnd->tnm", q, k_seq.astype(dt))
        scores = scores / jnp.sqrt(jnp.float32(cfg.head_dim)).astype(dt)
        mask = key_pos[None, None, :] <= token_pos[:, None, None]
        scores = jnp.where(mask, scores.astype(jnp.float32), -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(dt)
        attn = jnp.einsum("tnm,tmnd->tnd", probs, v_seq.astype(dt))
        attn = jnp.einsum("tnd,ndh->th", attn,
                          layer_params["attn"]["wo"].astype(dt))
        if cfg.use_biases:
            attn = attn + layer_params["attn"]["bo"].astype(dt)
        kv_out = kv_layer if kv_sc is None else (kv_layer, kv_sc)
        if cfg.parallel_block:  # Falcon: both branches read pre-attn x
            return _mlp(cfg, layer_params, x) + attn, kv_out
        x = x + attn
        return _mlp(cfg, layer_params, x), kv_out

    xs = ((params["layers"], kv_data) if kv_scales is None
          else (params["layers"], kv_data, kv_scales))
    x, new_kv = lax.scan(layer_body, x, xs)
    x = _norm(x, params["final_norm"], cfg.norm, cfg.norm_eps)
    return _unembed(cfg, params, x), new_kv


# ---------------------------------------------------------------------------
# segmented prefill path: Pallas chunked-prefill kernel (v2 engine)
# ---------------------------------------------------------------------------




def _tp_shard_map(kernel, mesh, q_spec, n_extra: int):
    """Wrap a Pallas paged-attention kernel for a multi-device mesh.

    Pallas calls can't run under plain GSPMD partitioning; shard_map
    makes the mesh manual so each shard runs the kernel on its local
    heads: q sharded on num_heads over tp, the KV pool sharded on
    kv_heads over tp (contiguous GQA grouping keeps q-head i's kv head
    on the same shard whenever tp divides kv_heads — the engine gates
    on that), metadata replicated. Axes other than tp are unmentioned =
    replicated (the default inference mesh absorbs spare chips into dp).
    Reference: the TP-sharded ragged kernels of inference/v2
    (kernels/ragged_ops + TP sharding).
    """
    from jax.sharding import PartitionSpec as PS

    kv_spec = PS(None, None, None, "tp", None)
    in_specs = (q_spec, kv_spec) + (PS(),) * n_extra
    return jaxcompat.shard_map(kernel, mesh=mesh, in_specs=in_specs,
                         out_specs=q_spec, check_vma=False)


def _paged_decode(mesh, q, kv_layer, block_table, context_lens):
    from deepspeed_tpu.ops.pallas.paged_attention import \
        paged_decode_attention

    kernel = partial(paged_decode_attention,
                     pages_per_compute_block=_kernel_pages())
    if mesh is None:
        return kernel(q, kv_layer, block_table, context_lens)
    from jax.sharding import PartitionSpec as PS

    fn = _tp_shard_map(kernel, mesh, PS(None, "tp", None), 2)
    return fn(q, kv_layer, block_table, context_lens)


def _paged_prefill(mesh, q, kv_layer, block_table, seg_pos0, ctx_lens):
    from deepspeed_tpu.ops.pallas.paged_attention import \
        paged_prefill_attention

    kernel = partial(paged_prefill_attention,
                     pages_per_compute_block=_kernel_pages())
    if mesh is None:
        return kernel(q, kv_layer, block_table, seg_pos0, ctx_lens)
    from jax.sharding import PartitionSpec as PS

    fn = _tp_shard_map(kernel, mesh, PS(None, None, "tp", None), 3)
    return fn(q, kv_layer, block_table, seg_pos0, ctx_lens)


def ragged_prefill_forward(cfg: TransformerConfig, params,
                           kv_data: jax.Array, seg_tokens: jax.Array,
                           seg_pos0: jax.Array, seg_nreal: jax.Array,
                           block_table: jax.Array, *, mesh=None
                           ) -> Tuple[jax.Array, jax.Array]:
    """Prefill chunks, one segment per sequence slot.

    Reference: the SplitFuse prefill path of inference/v2 (blocked flash
    over new chunks + paged history). Each segment s runs ``nreal[s]``
    new tokens at absolute positions pos0[s].. through the paged cache;
    padded rows (qi >= nreal) and dead segments (nreal == 0) write to the
    scratch page and emit garbage logits the engine never reads.

    seg_tokens [S, Tq] int32; seg_pos0/seg_nreal [S]; block_table [S, Bm]
    Returns (logits [S, Tq, V] fp32, kv_data').
    """
    kv_data, kv_scales = _kv_parts(kv_data)
    S, Tq = seg_tokens.shape
    bs = kv_data.shape[2]
    dt = effective_dtype(cfg.dtype)

    qi = jnp.arange(Tq)[None, :]                      # [1, Tq]
    pos = seg_pos0[:, None] + qi                      # [S, Tq]
    real = qi < seg_nreal[:, None]                    # [S, Tq]
    ctx_lens = seg_pos0 + seg_nreal                   # [S]

    x = vocab_parallel_lookup(
        params["embed"]["tokens"].astype(dt), seg_tokens)  # [S, Tq, H]
    if cfg.pos_emb == "learned":
        x = x + params["embed"]["positions"].astype(dt)[pos]

    scratch = kv_data.shape[1] - 1
    page = jnp.take_along_axis(block_table, pos // bs, axis=1)  # [S, Tq]
    page = jnp.where(real, page, scratch)
    offset = jnp.where(real, pos % bs, bs - 1)

    def layer_body(x, inputs):
        if kv_scales is None:
            layer_params, kv_layer = inputs
            kv_sc = None
        else:
            layer_params, kv_layer, kv_sc = inputs
        y = _norm(x, layer_params["ln1"], cfg.norm, cfg.norm_eps)
        q, k, v = _qkv(cfg, layer_params, y, pos)  # q [S,Tq,nh,hd]
        if kv_sc is None:
            kv_layer = kv_layer.at[page, offset, 0].set(
                k.astype(kv_layer.dtype))
            kv_layer = kv_layer.at[page, offset, 1].set(
                v.astype(kv_layer.dtype))
            kv_read = kv_layer
        else:
            bits = _kv_bits(kv_layer)
            qk, sk = kv_quantize(k, bits=bits)
            qv, sv = kv_quantize(v, bits=bits)
            kv_layer = kv_layer.at[page, offset, 0].set(kv_pack(qk, bits))
            kv_layer = kv_layer.at[page, offset, 1].set(kv_pack(qv, bits))
            kv_sc = kv_sc.at[page, offset, 0].set(sk)
            kv_sc = kv_sc.at[page, offset, 1].set(sv)
            # the Pallas kernel reads a dense layer pool; dequantize the
            # per-layer slice (transient, 1/L of the bf16 pool) — the
            # persistent pool stays int8/packed-int4
            kv_read = kv_dequantize(kv_unpack(kv_layer, bits), kv_sc,
                                    dtype=dt)
        attn = _paged_prefill(mesh, q.astype(dt), kv_read, block_table,
                              seg_pos0, ctx_lens)
        attn = jnp.einsum("stnd,ndh->sth", attn.astype(dt),
                          layer_params["attn"]["wo"].astype(dt))
        if cfg.use_biases:
            attn = attn + layer_params["attn"]["bo"].astype(dt)
        kv_out = kv_layer if kv_sc is None else (kv_layer, kv_sc)
        if cfg.parallel_block:  # Falcon: both branches read pre-attn x
            return _mlp(cfg, layer_params, x) + attn, kv_out
        x = x + attn
        return _mlp(cfg, layer_params, x), kv_out

    xs = ((params["layers"], kv_data) if kv_scales is None
          else (params["layers"], kv_data, kv_scales))
    x, new_kv = lax.scan(layer_body, x, xs)
    x = _norm(x, params["final_norm"], cfg.norm, cfg.norm_eps)
    return _unembed(cfg, params, x), new_kv


# ---------------------------------------------------------------------------
# decode-only ragged path: Pallas paged-attention kernel (v2 engine)
# ---------------------------------------------------------------------------


def ragged_decode_forward(cfg: TransformerConfig, params, kv_data: jax.Array,
                          token_ids: jax.Array, token_pos: jax.Array,
                          block_table: jax.Array, context_lens: jax.Array,
                          *, mesh=None) -> Tuple[jax.Array, jax.Array]:
    """One decode step: exactly one new token per live slot.

    Reference: the blocked-flash decode kernels of inference/v2
    (ragged_ops/blocked_flash + linear_blocked_kv_rotary) — here the KV
    append is an XLA scatter and attention is the Pallas paged kernel
    (ops/pallas/paged_attention.py), so no per-token context is ever
    gathered. Dead slots have context_lens == 0: their K/V writes are
    routed to the scratch page and their logits are zeros.

    kv_data      [L, num_blocks, bs, 2, nkv, hd]
    token_ids    [S] int32;  token_pos [S];  block_table [S, Bm]
    context_lens [S] = token_pos + 1 for live slots, 0 for dead

    Returns (logits [S, V] fp32, kv_data').
    """
    kv_data, kv_scales = _kv_parts(kv_data)
    S = token_ids.shape[0]
    bs = kv_data.shape[2]
    dt = effective_dtype(cfg.dtype)
    alive = context_lens > 0

    x = vocab_parallel_lookup(
        params["embed"]["tokens"].astype(dt), token_ids)  # [S, H]
    if cfg.pos_emb == "learned":
        x = x + params["embed"]["positions"].astype(dt)[token_pos]

    scratch = kv_data.shape[1] - 1
    page = block_table[jnp.arange(S), token_pos // bs]
    page = jnp.where(alive, page, scratch)
    offset = jnp.where(alive, token_pos % bs, bs - 1)

    def layer_body(x, inputs):
        if kv_scales is None:
            layer_params, kv_layer = inputs
            kv_sc = None
        else:
            layer_params, kv_layer, kv_sc = inputs
        y = _norm(x, layer_params["ln1"], cfg.norm, cfg.norm_eps)
        q, k, v = _qkv(cfg, layer_params, y, token_pos)  # q [S,nh,hd]
        if kv_sc is None:
            kv_layer = kv_layer.at[page, offset, 0].set(
                k.astype(kv_layer.dtype))
            kv_layer = kv_layer.at[page, offset, 1].set(
                v.astype(kv_layer.dtype))
            kv_read = kv_layer
        else:
            bits = _kv_bits(kv_layer)
            qk, sk = kv_quantize(k, bits=bits)
            qv, sv = kv_quantize(v, bits=bits)
            kv_layer = kv_layer.at[page, offset, 0].set(kv_pack(qk, bits))
            kv_layer = kv_layer.at[page, offset, 1].set(kv_pack(qv, bits))
            kv_sc = kv_sc.at[page, offset, 0].set(sk)
            kv_sc = kv_sc.at[page, offset, 1].set(sv)
            kv_read = kv_dequantize(kv_unpack(kv_layer, bits), kv_sc,
                                    dtype=dt)
        attn = _paged_decode(mesh, q.astype(dt), kv_read, block_table,
                             context_lens)
        attn = jnp.einsum("snd,ndh->sh", attn.astype(dt),
                          layer_params["attn"]["wo"].astype(dt))
        if cfg.use_biases:
            attn = attn + layer_params["attn"]["bo"].astype(dt)
        kv_out = kv_layer if kv_sc is None else (kv_layer, kv_sc)
        if cfg.parallel_block:  # Falcon: both branches read pre-attn x
            return _mlp(cfg, layer_params, x) + attn, kv_out
        x = x + attn
        return _mlp(cfg, layer_params, x), kv_out

    xs = ((params["layers"], kv_data) if kv_scales is None
          else (params["layers"], kv_data, kv_scales))
    x, new_kv = lax.scan(layer_body, x, xs)
    x = _norm(x, params["final_norm"], cfg.norm, cfg.norm_eps)
    return _unembed(cfg, params, x), new_kv


def ragged_multi_decode(cfg: TransformerConfig, params, kv_data: jax.Array,
                        token_ids: jax.Array, token_pos: jax.Array,
                        block_table: jax.Array, context_lens: jax.Array,
                        *, steps: int, mesh=None
                        ) -> Tuple[jax.Array, jax.Array]:
    """``steps`` greedy decode steps in ONE device program.

    The autoregressive loop runs as a ``lax.scan`` over
    :func:`ragged_decode_forward` with the argmax token fed back on
    device, so the host pays ONE dispatch + fetch round trip per
    ``steps`` tokens instead of per token. On a tunnel-attached host
    (~90ms RTT per sync) this is the difference between the engine being
    latency-bound and compute-bound; it is also the right shape on a
    co-located host — the per-step host work (metadata assembly, sync)
    amortizes ``steps``-fold. TPU-serving analog of the reference's
    CUDA-graphed decode loop (inference/v2 runs one graph per step; XLA
    gives us the whole loop as one program).

    The caller must have allocated KV blocks for ``steps`` more tokens
    per live slot (the block tables are fixed for the whole burst) and
    trims tokens past eos/max_new_tokens host-side — dead slots
    (context_lens == 0) stay dead, their writes going to the scratch
    page inside :func:`ragged_decode_forward`.

    Returns (tokens [steps, S] int32, kv_data').
    """
    def body(carry, _):
        kv, tok, pos, ctx = carry
        logits, kv = ragged_decode_forward(
            cfg, params, kv, tok, pos, block_table, ctx, mesh=mesh)
        nxt = jnp.argmax(logits.astype(jnp.float32), axis=-1).astype(jnp.int32)
        alive = ctx > 0
        nxt = jnp.where(alive, nxt, 0)
        return (kv, nxt, pos + 1, jnp.where(alive, ctx + 1, 0)), nxt

    (kv_data, *_), toks = lax.scan(
        body, (kv_data, token_ids, token_pos, context_lens), length=steps)
    return toks, kv_data
