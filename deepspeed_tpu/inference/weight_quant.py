"""Weight-only quantized serving: int8 weights resident in HBM.

Reference: the inference quantization stack — v1 MoQ/GroupQuantizer
(``module_inject/replace_module.py:44``), the INT4/INT8 weight paths of
inference/v2 (``quantization kernels`` csrc/quantization/, fp6
``cuda_linear``). The reference swaps modules for kernel-injected
quantized linears; here the params TREE is quantized instead: each
eligible weight becomes a ``QuantizedTensor`` pytree node holding int8
values + per-block fp32 scales, whose ``.astype(dt)`` dequantizes
lazily INSIDE the compiled step. Model/runner code is untouched — every
use site already reads ``w.astype(dt)`` — and HBM holds ~4x less weight
(bf16 → int8 + 1/block scales), which is KV-cache/batch headroom for
the serving engines.

XLA fuses the dequant (elementwise multiply) into the consuming matmul
epilogue-side, so the wire cost is one int8→bf16 widening per use.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

QUANT_BLOCK = 128


@jax.tree_util.register_pytree_node_class
class QuantizedTensor:
    """int8 blockwise-quantized stand-in for a weight array.

    Ducks the slice of the jax.Array API the model runners use
    (``astype``, ``shape``, ``ndim``, ``dtype``) — ``astype`` is the
    dequantization point. Shape derives from the payload (the layer
    scan slices pytree leaves through this node, so stored metadata
    would go stale): q [..., nblocks, block] stands for a logical
    [..., nblocks*block] array.
    """

    def __init__(self, q: jax.Array, scale: jax.Array, like_dtype=None):
        self.q = q              # int8 [..., nblocks, block]
        self.scale = scale      # fp32 [..., nblocks, 1]
        self._dtype = like_dtype if like_dtype is not None else jnp.bfloat16

    # -- pytree ---------------------------------------------------------
    def tree_flatten(self):
        return (self.q, self.scale), (self._dtype,)

    @classmethod
    def tree_unflatten(cls, aux, children):
        q, scale = children
        return cls(q, scale, aux[0])

    # -- array duck-typing ---------------------------------------------
    @property
    def shape(self):
        qs = self.q.shape
        return qs[:-2] + (qs[-2] * qs[-1],)

    @property
    def ndim(self):
        return len(self.q.shape) - 1

    @property
    def dtype(self):
        return self._dtype

    @property
    def nbytes(self):
        return int(self.q.size * 1 + self.scale.size * 4)

    def astype(self, dtype):
        """Dequantize: the compiled step widens int8 on use."""
        full = (self.q.astype(jnp.float32) * self.scale).reshape(self.shape)
        return full.astype(dtype)


MIN_BLOCK = 16  # below this the fp32 scales eat the int8 savings


def pick_block(n: int, block: int = QUANT_BLOCK):
    """Largest power-of-2 divisor of n up to ``block``; None when the
    result would be so small that int8 + per-block fp32 scales exceed
    the original bf16 bytes (then the leaf stays exact)."""
    b = block
    while n % b:
        b //= 2
    return b if b >= MIN_BLOCK else None


def quantize_weight(w: jax.Array, block: int = QUANT_BLOCK
                    ) -> QuantizedTensor:
    """Blockwise symmetric int8 over the last dim (one shared formula:
    ops/pallas/quantization._quantize_ref)."""
    from deepspeed_tpu.ops.pallas.quantization import _quantize_ref

    b = pick_block(w.shape[-1], block)
    if b is None:
        raise ValueError(
            f"last dim {w.shape[-1]} has no >= {MIN_BLOCK} power-of-2 "
            "block divisor; leaf is not worth quantizing")
    q, scale = _quantize_ref(jnp.asarray(w, jnp.float32), 8, b)
    q = q.reshape(*w.shape[:-1], w.shape[-1] // b, b)
    return QuantizedTensor(q, scale[..., None], w.dtype)


def _eligible(path: str, leaf) -> bool:
    """Quantize the big matmul weights; embeddings (lookup tables),
    norms, biases and scalars stay exact (the reference's MoQ scope)."""
    if getattr(leaf, "ndim", 0) < 2:
        return False
    if pick_block(leaf.shape[-1]) is None:
        return False  # degenerate blocks would GROW the leaf
    if "embed" in path and "unembed" not in path:
        return False  # token/position lookup tables stay exact
    # experts excluded: moe_ffn consumes expert weights without astype
    for skip in ("ln1", "ln2", "norm", "['b", "router", "experts"):
        if skip in path:
            return False
    return True


def quantize_params(params: Any, block: int = QUANT_BLOCK) -> Any:
    """Params tree → tree with eligible weights as QuantizedTensor."""
    from jax.tree_util import keystr, tree_map_with_path

    return tree_map_with_path(
        lambda kp, p: (quantize_weight(p, block)
                       if _eligible(keystr(kp), p) else p), params)


def quantized_fraction(params: Any) -> float:
    """Fraction of the ORIGINAL weight bytes now held as int8 (coverage
    observability — post-compression bytes would understate ~4x)."""
    import numpy as np

    qb = tb = 0
    for leaf in jax.tree.leaves(
            params, is_leaf=lambda x: isinstance(x, QuantizedTensor)):
        if isinstance(leaf, QuantizedTensor):
            orig = int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
            qb += orig
            tb += orig
        elif hasattr(leaf, "nbytes"):
            tb += leaf.nbytes
    return qb / tb if tb else 0.0
