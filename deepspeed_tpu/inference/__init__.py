"""Inference stack: v1-style TP engine + FastGen-style ragged engine.

Reference: deepspeed/inference/ (engine.py:40 InferenceEngine,
v2/engine_v2.py:30 InferenceEngineV2).
"""

from deepspeed_tpu.inference.engine import InferenceEngine, init_inference
from deepspeed_tpu.inference.engine_v2 import InferenceEngineV2
from deepspeed_tpu.inference.spec_decode import (Drafter,
                                                 PromptLookupDrafter,
                                                 TransformerDrafter)

__all__ = ["Drafter", "InferenceEngine", "InferenceEngineV2",
           "PromptLookupDrafter", "TransformerDrafter", "init_inference"]
