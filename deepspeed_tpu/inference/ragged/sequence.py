"""Sequence descriptors + state manager for ragged batching.

Reference: ``DSSequenceDescriptor`` / ``DSStateManager``
(inference/v2/ragged/{sequence_descriptor,ragged_manager}.py). Tracks each
live sequence's token history, KV blocks, and scheduling state. All host
side — the compiled step only sees the dense metadata RaggedBatch builds.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.inference.ragged.kv_cache import BlockedKVCache


@dataclasses.dataclass
class SequenceDescriptor:
    uid: int
    input_tokens: np.ndarray            # full prompt
    seen_tokens: int = 0                # tokens already in the KV cache
    kv_blocks: np.ndarray = dataclasses.field(
        default_factory=lambda: np.empty(0, dtype=np.int64))
    generated: List[int] = dataclasses.field(default_factory=list)
    max_new_tokens: int = 64
    done: bool = False
    truncated: bool = False  # ended early (per-seq KV cap or preemption)

    @property
    def total_tokens(self) -> int:
        return len(self.input_tokens) + len(self.generated)

    @property
    def pending_prefill(self) -> int:
        """Prompt tokens not yet through the model."""
        return max(0, len(self.input_tokens) - self.seen_tokens)

    @property
    def in_decode(self) -> bool:
        return self.pending_prefill == 0 and not self.done


class StateManager:
    """Owns live sequences + their KV blocks (reference
    ragged_manager.py:19: tracks sequences, allocates KV on demand)."""

    def __init__(self, kv_cache: BlockedKVCache, max_tracked_sequences: int = 64,
                 max_blocks_per_seq: Optional[int] = None):
        self.kv_cache = kv_cache
        self.max_tracked_sequences = max_tracked_sequences
        self.max_blocks_per_seq = max_blocks_per_seq
        self.seqs: Dict[int, SequenceDescriptor] = {}

    def get_or_create(self, uid: int, tokens: np.ndarray,
                      max_new_tokens: int = 64) -> SequenceDescriptor:
        if uid in self.seqs:
            return self.seqs[uid]
        if len(self.seqs) >= self.max_tracked_sequences:
            raise RuntimeError("max_tracked_sequences exceeded")
        seq = SequenceDescriptor(uid=uid,
                                 input_tokens=np.asarray(tokens, np.int32),
                                 max_new_tokens=max_new_tokens)
        self.seqs[uid] = seq
        return seq

    def ensure_capacity(self, seq: SequenceDescriptor, new_total: int) -> bool:
        """Grow seq's block list to fit new_total tokens. False if the pool
        is exhausted. A sequence that hits the per-seq block cap is ENDED
        (truncated) rather than grown — growing past the cap would crash
        the dense batch metadata (build_ragged_batch bucket bound)."""
        total_needed = self.kv_cache.blocks_needed(new_total)
        need = total_needed - len(seq.kv_blocks)
        if need <= 0:
            return True
        if (self.max_blocks_per_seq is not None
                and total_needed > self.max_blocks_per_seq):
            seq.done = True
            seq.truncated = True
            return False
        if need > self.kv_cache.free_blocks:
            return False
        new_blocks = self.kv_cache.allocator.allocate(need)
        seq.kv_blocks = np.concatenate([seq.kv_blocks, new_blocks])
        return True

    def release(self, uid: int) -> None:
        seq = self.seqs.pop(uid, None)
        if seq is not None and len(seq.kv_blocks):
            self.kv_cache.free(seq.kv_blocks)

    def live_uids(self) -> List[int]:
        return list(self.seqs)
