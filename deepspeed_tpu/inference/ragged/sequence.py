"""Sequence descriptors + state manager for ragged batching.

Reference: ``DSSequenceDescriptor`` / ``DSStateManager``
(inference/v2/ragged/{sequence_descriptor,ragged_manager}.py). Tracks each
live sequence's token history, KV blocks, and scheduling state. All host
side — the compiled step only sees the dense metadata RaggedBatch builds.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from deepspeed_tpu.inference.ragged.kv_cache import BlockedKVCache


@dataclasses.dataclass
class SequenceDescriptor:
    uid: int
    input_tokens: np.ndarray            # full prompt
    seen_tokens: int = 0                # tokens already in the KV cache
    kv_blocks: np.ndarray = dataclasses.field(
        default_factory=lambda: np.empty(0, dtype=np.int64))
    generated: List[int] = dataclasses.field(default_factory=list)
    max_new_tokens: int = 64
    done: bool = False
    truncated: bool = False  # ended early (per-seq KV cap or preemption)
    # shared-prefix bookkeeping: prefix_keys[i] is the PrefixCache key of
    # kv_blocks[i] for the cache-managed head run; those blocks are
    # unref'd (not freed) at release. Always a prefix of kv_blocks.
    prefix_keys: List[str] = dataclasses.field(default_factory=list)
    # tokens already emitted to the caller before a preempt-and-requeue
    # round trip (they ride back in via input_tokens for KV recompute
    # and must still count against max_new_tokens)
    prior_generated: int = 0
    # a registration conflict (identical content cached under another
    # block) ends this seq's registrable run for good
    prefix_reg_stopped: bool = False
    # warm resume (ragged/kv_tier.py): nonzero when admission restored
    # this sequence's KV from the host tier — blocks paged in instead
    # of prefilled (the scheduler reports resumed decode separately)
    resumed_from_tier: int = 0

    @property
    def total_tokens(self) -> int:
        return len(self.input_tokens) + len(self.generated)

    @property
    def pending_prefill(self) -> int:
        """Prompt tokens not yet through the model."""
        return max(0, len(self.input_tokens) - self.seen_tokens)

    @property
    def in_decode(self) -> bool:
        return self.pending_prefill == 0 and not self.done

    @property
    def gen_budget_left(self) -> int:
        """New tokens this sequence may still emit (counts tokens
        emitted before any preemption round trip)."""
        return max(0, self.max_new_tokens
                   - self.prior_generated - len(self.generated))


class StateManager:
    """Owns live sequences + their KV blocks (reference
    ragged_manager.py:19: tracks sequences, allocates KV on demand)."""

    def __init__(self, kv_cache: BlockedKVCache, max_tracked_sequences: int = 64,
                 max_blocks_per_seq: Optional[int] = None):
        self.kv_cache = kv_cache
        self.max_tracked_sequences = max_tracked_sequences
        self.max_blocks_per_seq = max_blocks_per_seq
        self.seqs: Dict[int, SequenceDescriptor] = {}

    def get_or_create(self, uid: int, tokens: np.ndarray,
                      max_new_tokens: int = 64) -> SequenceDescriptor:
        if uid in self.seqs:
            return self.seqs[uid]
        if len(self.seqs) >= self.max_tracked_sequences:
            raise RuntimeError("max_tracked_sequences exceeded")
        seq = SequenceDescriptor(uid=uid,
                                 input_tokens=np.asarray(tokens, np.int32),
                                 max_new_tokens=max_new_tokens)
        self.seqs[uid] = seq
        return seq

    def ensure_capacity(self, seq: SequenceDescriptor, new_total: int) -> bool:
        """Grow seq's block list to fit new_total tokens. False if the pool
        is exhausted (after reclaiming idle prefix-cached blocks). A
        sequence that hits the per-seq block cap is ENDED (truncated)
        rather than grown — growing past the cap would crash the dense
        batch metadata (build_ragged_batch bucket bound)."""
        total_needed = self.kv_cache.blocks_needed(new_total)
        need = total_needed - len(seq.kv_blocks)
        if need <= 0:
            return True
        if (self.max_blocks_per_seq is not None
                and total_needed > self.max_blocks_per_seq):
            seq.done = True
            seq.truncated = True
            return False
        if need > self.kv_cache.free_blocks:
            self.kv_cache.reclaim(need - self.kv_cache.free_blocks)
        if need > self.kv_cache.free_blocks:
            return False
        new_blocks = self.kv_cache.allocator.allocate(need)
        seq.kv_blocks = np.concatenate([seq.kv_blocks, new_blocks])
        return True

    def attach_prefix(self, seq: SequenceDescriptor) -> int:
        """Seed a freshly-created sequence's block list from the prefix
        cache: the longest cached full-block chain matching its prompt
        is shared by reference and those tokens skip prefill. The final
        prompt token is always left uncached so the step still computes
        first-token logits. With a host tier attached
        (ragged/kv_tier.py) the chain walk continues PAST the HBM cache
        into host memory: matching paged-out blocks page back in,
        re-register, and extend the skip — a returning session resumes
        without re-prefilling what the tier kept. Returns the number of
        prefill tokens skipped."""
        cache = self.kv_cache.prefix_cache
        if (cache is None or seq.seen_tokens or len(seq.kv_blocks)
                or len(seq.input_tokens) <= cache.block_size):
            return 0
        limit = len(seq.input_tokens) - 1
        if self.max_blocks_per_seq is not None:
            # leave room for at least one private (tail/generation) block
            limit = min(limit,
                        (self.max_blocks_per_seq - 1) * cache.block_size)
        keys, blocks = cache.lookup(seq.input_tokens, max_tokens=limit)
        tier = getattr(self.kv_cache, "host_tier", None)
        if tier is not None:
            paged = self._page_in_chain(seq, cache, tier, keys, blocks,
                                        limit)
            seq.resumed_from_tier += paged
        if not keys:
            return 0
        cache.ref(keys)
        seq.kv_blocks = np.asarray(blocks, np.int64)
        seq.prefix_keys = list(keys)
        seq.seen_tokens = len(keys) * cache.block_size
        return seq.seen_tokens

    def _page_in_chain(self, seq: SequenceDescriptor, cache, tier,
                       keys: List[str], blocks: List[int],
                       limit: int) -> int:
        """Continue the prefix chain walk into the host tier: page
        matching blocks back into freshly-allocated HBM blocks and
        register them in the prefix cache, extending ``keys``/``blocks``
        in place. Stops at the first tier miss, allocation failure, or
        registration conflict — chain-prefix semantics hold because
        installs happen strictly in chain order. Returns the number of
        blocks paged in."""
        bs = cache.block_size
        toks = seq.input_tokens
        paged = 0
        while (len(keys) + 1) * bs <= limit:
            i = len(keys)
            key = cache.chain_key(keys[-1] if keys else None,
                                  toks[i * bs:(i + 1) * bs])
            if not tier.has_block(key):
                break
            if self.kv_cache.free_blocks < 1:
                self.kv_cache.reclaim(1)
            if self.kv_cache.free_blocks < 1:
                break  # pool under live pressure: keep what we got
            ent = tier.take_block(key)
            if ent is None:
                break
            blk = int(self.kv_cache.allocator.allocate(1)[0])
            self.kv_cache.write_blocks([blk], ent[0][:, None],
                                       None if ent[1] is None
                                       else ent[1][:, None])
            if not cache.register(key, blk):
                # identical content raced in under another block: theirs
                # wins, and lookup would have found it — stop here
                self.kv_cache.free([blk])
                break
            cache.unref([key])  # park idle; ref'd with the chain below
            keys.append(key)
            blocks.append(blk)
            paged += 1
        return paged

    def register_prefix_blocks(self, seq: SequenceDescriptor) -> None:
        """Publish seq's write-complete full prompt blocks into the
        prefix cache (idempotent; call after each step). Only blocks
        strictly before the prompt's append frontier qualify — the
        partial tail block and every generated-token block are written
        in place as the sequence grows and stay private (copy-on-write
        by construction)."""
        cache = self.kv_cache.prefix_cache
        if cache is None or seq.prefix_reg_stopped:
            return
        bs = cache.block_size
        done_tokens = min(seq.seen_tokens, len(seq.input_tokens))
        n_reg = min(len(seq.input_tokens) // bs, done_tokens // bs,
                    len(seq.kv_blocks))
        while len(seq.prefix_keys) < n_reg:
            i = len(seq.prefix_keys)
            key = cache.chain_key(seq.prefix_keys[-1] if i else None,
                                  seq.input_tokens[i * bs:(i + 1) * bs])
            if not cache.register(key, int(seq.kv_blocks[i])):
                # same content cached under another block: stop for good —
                # keys must chain over THIS seq's own block run
                seq.prefix_reg_stopped = True
                break
            seq.prefix_keys.append(key)

    def release(self, uid: int) -> None:
        seq = self.seqs.pop(uid, None)
        if seq is None:
            return
        n_shared = len(seq.prefix_keys)
        if n_shared:
            self.kv_cache.prefix_cache.unref(seq.prefix_keys)
            seq.prefix_keys = []
        if len(seq.kv_blocks) > n_shared:
            self.kv_cache.free(seq.kv_blocks[n_shared:])
        seq.kv_blocks = np.empty(0, dtype=np.int64)

    def live_uids(self) -> List[int]:
        return list(self.seqs)
