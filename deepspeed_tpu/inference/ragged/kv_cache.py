"""Blocked (paged) KV cache.

Reference: ``BlockedKVCache`` (inference/v2/ragged/kv_cache.py:40) backs a
paged KV pool consumed by CUDA blocked-flash kernels. TPU re-design: the
pool is ONE jax array per model,

    kv[L, num_blocks, block_size, 2, kv_heads, head_dim]

sharded over the tp axis on ``kv_heads``. Pages are appended inside the
compiled step via scatter (see inference/model_runner.py); the host only
manages block ids (blocked_allocator.py). Static pool shape keeps every
step the same compiled program — the XLA analog of the reference
preallocating the cache up front.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.ragged.blocked_allocator import BlockedAllocator


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    num_layers: int
    kv_heads: int
    head_dim: int
    block_size: int = 16
    num_blocks: int = 256
    dtype: object = jnp.bfloat16

    @property
    def bytes_per_block(self) -> int:
        itemsize = jnp.dtype(self.dtype).itemsize
        return (self.num_layers * self.block_size * 2 * self.kv_heads
                * self.head_dim * itemsize)


class BlockedKVCache:
    """Device pool + host allocator (reference kv_cache.py:40 contract:
    reserve/free by block count; here also owns the device buffer).

    When a :class:`~deepspeed_tpu.inference.ragged.prefix_cache.PrefixCache`
    is attached (``prefix_cache`` attr), idle cached blocks are parked
    outside the allocator free list; :meth:`reclaim` evicts them back
    under memory pressure, so shared-prefix reuse never shrinks the pool
    a live sequence can reach."""

    def __init__(self, config: KVCacheConfig, mesh=None, tp_axis: str = "tp"):
        self.config = config
        self.allocator = BlockedAllocator(config.num_blocks)
        self.prefix_cache = None  # Optional[PrefixCache], attached by owner
        shape = (config.num_layers, config.num_blocks, config.block_size,
                 2, config.kv_heads, config.head_dim)
        if mesh is not None and tp_axis in mesh.axis_names and (
                mesh.shape[tp_axis] > 1):
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharding = NamedSharding(
                mesh, P(None, None, None, None, tp_axis, None))
            self.data = jax.device_put(
                jnp.zeros(shape, config.dtype), sharding)
        else:
            self.data = jnp.zeros(shape, config.dtype)

    def blocks_needed(self, num_tokens: int) -> int:
        bs = self.config.block_size
        return (num_tokens + bs - 1) // bs

    def free(self, blocks) -> None:
        if len(blocks):
            self.allocator.free(blocks)

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    @property
    def available_blocks(self) -> int:
        """Free blocks plus idle prefix-cached blocks reclaimable via
        :meth:`reclaim` — the admission-control capacity number."""
        extra = (self.prefix_cache.evictable_blocks
                 if self.prefix_cache is not None else 0)
        return self.allocator.free_blocks + extra

    def reclaim(self, n: int) -> int:
        """Evict up to ``n`` idle prefix-cached blocks back into the
        allocator free list; returns how many were reclaimed."""
        if n <= 0 or self.prefix_cache is None:
            return 0
        evicted = self.prefix_cache.evict(n)
        if evicted:
            self.allocator.free(np.asarray(evicted, np.int64))
        return len(evicted)
