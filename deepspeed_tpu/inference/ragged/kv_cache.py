"""Blocked (paged) KV cache.

Reference: ``BlockedKVCache`` (inference/v2/ragged/kv_cache.py:40) backs a
paged KV pool consumed by CUDA blocked-flash kernels. TPU re-design: the
pool is ONE jax array per model,

    kv[L, num_blocks, block_size, 2, kv_heads, head_dim]

sharded over the tp axis on ``kv_heads``. Pages are appended inside the
compiled step via scatter (see inference/model_runner.py); the host only
manages block ids (blocked_allocator.py). Static pool shape keeps every
step the same compiled program — the XLA analog of the reference
preallocating the cache up front.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.inference.ragged.blocked_allocator import BlockedAllocator


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    num_layers: int
    kv_heads: int
    head_dim: int
    block_size: int = 16
    num_blocks: int = 256
    dtype: object = jnp.bfloat16
    # None = bf16 pool (bit-exact legacy program); 8 = int8 payload with one
    # fp32 scale per (layer, block, row, k/v, head) vector; 4 = packed-nibble
    # uint8 payload (two values per byte, ~1.9x more sessions at head_dim
    # 128) with the same per-vector fp32 scale; "fp8" = e4m3 payload (the
    # quality midpoint between int8 and int4) with the same per-vector scale.
    quant_bits: Optional[object] = None

    def __post_init__(self):
        if self.quant_bits not in (None, 4, 8, "fp8"):
            raise ValueError(f"kv quant_bits must be None, 4, 8 or 'fp8', "
                             f"got {self.quant_bits}")
        if self.quant_bits == 4 and self.head_dim % 2:
            raise ValueError(
                f"int4 KV storage packs two values per byte and needs an "
                f"even head_dim, got {self.head_dim}")

    @property
    def payload_width(self) -> int:
        """Last-dim extent of the pool payload: head_dim values, packed
        two-per-byte under int4."""
        return self.head_dim // 2 if self.quant_bits == 4 else self.head_dim

    @property
    def bytes_per_block(self) -> int:
        vecs = self.num_layers * self.block_size * 2 * self.kv_heads
        if self.quant_bits is not None:
            # int8/fp8/packed-int4 payload + fp32 scale per head vector
            return vecs * (self.payload_width + 4)
        itemsize = jnp.dtype(self.dtype).itemsize
        return vecs * self.head_dim * itemsize


class BlockedKVCache:
    """Device pool + host allocator (reference kv_cache.py:40 contract:
    reserve/free by block count; here also owns the device buffer).

    When a :class:`~deepspeed_tpu.inference.ragged.prefix_cache.PrefixCache`
    is attached (``prefix_cache`` attr), idle cached blocks are parked
    outside the allocator free list; :meth:`reclaim` evicts them back
    under memory pressure, so shared-prefix reuse never shrinks the pool
    a live sequence can reach."""

    def __init__(self, config: KVCacheConfig, mesh=None, tp_axis: str = "tp"):
        self.config = config
        self.allocator = BlockedAllocator(config.num_blocks)
        self.prefix_cache = None  # Optional[PrefixCache], attached by owner
        self.host_tier = None     # Optional[HostKVTier], attached by owner
        shape = (config.num_layers, config.num_blocks, config.block_size,
                 2, config.kv_heads, config.payload_width)
        quantized = config.quant_bits is not None
        # int4 packs nibbles into uint8 (the runner infers the width from
        # the pool dtype at trace time: int8 → 8, uint8 → 4, e4m3 → fp8)
        pool_dtype = (jnp.uint8 if config.quant_bits == 4
                      else jnp.float8_e4m3fn if config.quant_bits == "fp8"
                      else jnp.int8 if quantized else config.dtype)
        self.scales = None
        if mesh is not None and tp_axis in mesh.axis_names and (
                mesh.shape[tp_axis] > 1):
            from jax.sharding import NamedSharding, PartitionSpec as P

            sharding = NamedSharding(
                mesh, P(None, None, None, None, tp_axis, None))
            self.data = jax.device_put(jnp.zeros(shape, pool_dtype), sharding)
            if quantized:
                s_sharding = NamedSharding(
                    mesh, P(None, None, None, None, tp_axis))
                self.scales = jax.device_put(
                    jnp.ones(shape[:-1], jnp.float32), s_sharding)
        else:
            self.data = jnp.zeros(shape, pool_dtype)
            if quantized:
                self.scales = jnp.ones(shape[:-1], jnp.float32)

    @property
    def quant_bits(self) -> Optional[int]:
        return self.config.quant_bits

    @property
    def kv_state(self):
        """Device pool as the pytree the ragged forwards consume: the bare
        bf16 array when unquantized (today's program, verbatim), or a
        (payload, fp32 scales) pair when ``quant_bits`` is set (int8
        payload, or packed-nibble uint8 for 4-bit storage)."""
        if self.scales is None:
            return self.data
        return (self.data, self.scales)

    def set_kv_state(self, state) -> None:
        """Store the pool returned by a compiled step (inverse of
        :attr:`kv_state`)."""
        if self.scales is None:
            self.data = state
        else:
            self.data, self.scales = state

    def blocks_needed(self, num_tokens: int) -> int:
        bs = self.config.block_size
        return (num_tokens + bs - 1) // bs

    # -- host-tier block I/O (ragged/kv_tier.py) -----------------------

    def read_blocks_host(self, block_ids):
        """Device→host copy of the pool contents at ``block_ids``:
        ``(payload [L, n, bs, 2, H, W], scales [L, n, bs, 2, H] | None)``
        in the pool's native storage format — for a quantized pool this
        IS the compact kv_pack wire format, so paging it out costs no
        conversion (the disagg serialize idiom applied to the tier)."""
        idx = np.asarray(block_ids, np.int64)
        payload = np.asarray(self.data[:, idx])
        scales = (np.asarray(self.scales[:, idx])
                  if self.scales is not None else None)
        return payload, scales

    def write_blocks(self, block_ids, payload, scales=None) -> None:
        """Host→device restore of pool contents at ``block_ids`` —
        the inverse of :meth:`read_blocks_host`, bit-exact when the
        payload is pool-native."""
        idx = jnp.asarray(np.asarray(block_ids, np.int64))
        self.data = self.data.at[:, idx].set(
            jnp.asarray(payload, self.data.dtype))
        if self.scales is not None and scales is not None:
            self.scales = self.scales.at[:, idx].set(
                jnp.asarray(scales, jnp.float32))

    def free(self, blocks) -> None:
        if len(blocks):
            self.allocator.free(blocks)

    @property
    def free_blocks(self) -> int:
        return self.allocator.free_blocks

    @property
    def available_blocks(self) -> int:
        """Free blocks plus idle prefix-cached blocks reclaimable via
        :meth:`reclaim` — the admission-control capacity number."""
        extra = (self.prefix_cache.evictable_blocks
                 if self.prefix_cache is not None else 0)
        return self.allocator.free_blocks + extra

    def reclaim(self, n: int) -> int:
        """Evict up to ``n`` idle prefix-cached blocks back into the
        allocator free list; returns how many were reclaimed. With a
        host tier attached, cold chains page OUT (contents parked in
        host memory under the same chain keys) instead of being
        dropped — a returning session pages back in without
        re-prefill."""
        if n <= 0 or self.prefix_cache is None:
            return 0
        if self.host_tier is not None:
            entries = self.prefix_cache.evict_entries(n)
            if entries:
                keys = [k for k, _ in entries]
                blocks = [b for _, b in entries]
                payload, scales = self.read_blocks_host(blocks)
                self.host_tier.put_chain(keys, payload, scales)
                self.allocator.free(np.asarray(blocks, np.int64))
            return len(entries)
        evicted = self.prefix_cache.evict(n)
        if evicted:
            self.allocator.free(np.asarray(evicted, np.int64))
        return len(evicted)
