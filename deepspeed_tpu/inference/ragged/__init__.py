"""Ragged-batching state management (reference: inference/v2/ragged/)."""

from deepspeed_tpu.inference.ragged.blocked_allocator import BlockedAllocator
from deepspeed_tpu.inference.ragged.kv_cache import BlockedKVCache, KVCacheConfig
from deepspeed_tpu.inference.ragged.kv_tier import HostKVTier, PagedSession
from deepspeed_tpu.inference.ragged.prefix_cache import PrefixCache
from deepspeed_tpu.inference.ragged.sequence import (
    SequenceDescriptor, StateManager)
from deepspeed_tpu.inference.ragged.ragged_batch import RaggedBatch

__all__ = [
    "BlockedAllocator",
    "BlockedKVCache",
    "HostKVTier",
    "KVCacheConfig",
    "PagedSession",
    "PrefixCache",
    "SequenceDescriptor",
    "StateManager",
    "RaggedBatch",
]
