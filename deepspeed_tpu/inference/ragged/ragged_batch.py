"""Dense metadata for a ragged forward step.

Reference: ``RaggedBatchWrapper`` (inference/v2/ragged/ragged_wrapper.py)
plus the native atom-builder (inference/v2/ragged/csrc/) that packs batch
metadata for the CUDA kernels. XLA needs static shapes, so the TPU design
pads every step to a (max_tokens, max_seqs) *bucket*: one compiled program
per bucket serves every batch composition (the reference's CUDA-graph-like
replay falls out of jit caching).

Layout (all int32, device-bound each step):
  token_ids   [T]     flattened new tokens across sequences
  token_seq   [T]     local slot (0..S-1) of the owning sequence
  token_pos   [T]     absolute position of the token in its sequence
  block_table [S, Bm] KV block ids per slot (padded with 0)
  ctx_lens    [S]     tokens in cache *after* this step per slot
  num_tokens  []      true token count (rest is padding)
  slot_uid    host-side: uid per slot (for gathering logits)
  last_token_index [S] index into [T] of each slot's final token (for
                      next-token logits), 0 for empty slots
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from deepspeed_tpu.inference.ragged.sequence import SequenceDescriptor


@dataclasses.dataclass
class RaggedBatch:
    token_ids: np.ndarray
    token_seq: np.ndarray
    token_pos: np.ndarray
    block_table: np.ndarray
    ctx_lens: np.ndarray
    num_tokens: int
    last_token_index: np.ndarray
    slot_uids: List[Optional[int]]
    slot_is_live: np.ndarray  # bool [S]: slot has a real sequence

    @property
    def max_tokens(self) -> int:
        return len(self.token_ids)

    @property
    def max_seqs(self) -> int:
        return len(self.ctx_lens)


def build_ragged_batch(
    scheduled: List[Tuple[SequenceDescriptor, np.ndarray, int]],
    max_tokens: int,
    max_seqs: int,
    max_blocks_per_seq: int,
) -> RaggedBatch:
    """Pack (sequence, new_tokens, start_pos) triples into dense arrays.

    ``scheduled`` comes from the SplitFuse scheduler: each entry is a chunk
    of a sequence's tokens to run this step (full/partial prefill or a
    single decode token).
    """
    if len(scheduled) > max_seqs:
        raise ValueError(f"{len(scheduled)} sequences > bucket max {max_seqs}")
    token_ids = np.zeros(max_tokens, np.int32)
    token_seq = np.zeros(max_tokens, np.int32)
    token_pos = np.zeros(max_tokens, np.int32)
    block_table = np.zeros((max_seqs, max_blocks_per_seq), np.int32)
    ctx_lens = np.zeros(max_seqs, np.int32)
    last_token_index = np.zeros(max_seqs, np.int32)
    slot_uids: List[Optional[int]] = [None] * max_seqs
    slot_is_live = np.zeros(max_seqs, bool)

    cursor = 0
    for slot, (seq, new_tokens, start_pos) in enumerate(scheduled):
        n = len(new_tokens)
        if cursor + n > max_tokens:
            raise ValueError("token budget overflow; scheduler bug")
        token_ids[cursor:cursor + n] = new_tokens
        token_seq[cursor:cursor + n] = slot
        token_pos[cursor:cursor + n] = np.arange(start_pos, start_pos + n)
        nb = len(seq.kv_blocks)
        if nb > max_blocks_per_seq:
            raise ValueError(
                f"sequence needs {nb} blocks > bucket max {max_blocks_per_seq}")
        block_table[slot, :nb] = seq.kv_blocks
        ctx_lens[slot] = start_pos + n
        last_token_index[slot] = cursor + n - 1
        slot_uids[slot] = seq.uid
        slot_is_live[slot] = True
        cursor += n

    # padding tokens point at slot 0 with pos 0; they are masked out by
    # comparing token index against num_tokens in the runner.
    return RaggedBatch(
        token_ids=token_ids, token_seq=token_seq, token_pos=token_pos,
        block_table=block_table, ctx_lens=ctx_lens, num_tokens=cursor,
        last_token_index=last_token_index, slot_uids=slot_uids,
        slot_is_live=slot_is_live)
