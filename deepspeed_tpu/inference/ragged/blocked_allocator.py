"""KV-block free-list allocator.

TPU-native re-design of the reference's ``BlockedAllocator``
(inference/v2/ragged/blocked_allocator.py:11): the reference keeps the
free list as a device tensor next to the CUDA kernels that consume it; on
TPU the block table is host-side metadata fed to the compiled step as a
dense int array, so a plain numpy free list is the right shape — zero
device traffic to allocate/free.
"""

from __future__ import annotations

import numpy as np


class BlockedAllocator:
    """Fixed pool of equal-size blocks; O(1) allocate/free via a linked
    free list (same contract as the reference: allocate(n) -> block ids,
    free(ids))."""

    def __init__(self, num_blocks: int):
        if num_blocks < 1:
            raise ValueError(f"need at least 1 block, got {num_blocks}")
        self._num_blocks = num_blocks
        # _next[i] = next free block after i (linked list threaded through
        # a dense array, as in the reference)
        self._next = np.arange(1, num_blocks + 1, dtype=np.int64)
        self._head = 0
        self._free_count = num_blocks

    @property
    def free_blocks(self) -> int:
        return self._free_count

    @property
    def total_blocks(self) -> int:
        return self._num_blocks

    def allocate(self, num_blocks: int) -> np.ndarray:
        if num_blocks > self._free_count:
            raise MemoryError(
                f"requested {num_blocks} blocks, only {self._free_count} free")
        out = np.empty(num_blocks, dtype=np.int64)
        for i in range(num_blocks):
            out[i] = self._head
            self._head = self._next[self._head]
        self._free_count -= num_blocks
        return out

    def free(self, blocks) -> None:
        blocks = np.atleast_1d(np.asarray(blocks, dtype=np.int64))
        seen = set()
        for b in blocks:
            bi = int(b)
            if not 0 <= bi < self._num_blocks:
                raise ValueError(f"block id {bi} out of range")
            if bi in seen:
                raise ValueError(f"double free of block {bi}")
            seen.add(bi)
        for b in blocks:
            bi = int(b)
            self._next[bi] = self._head
            self._head = bi
        self._free_count += len(blocks)
