"""Host-memory KV tier: page blocks out instead of evicting them.

The HBM pool (kv_cache.py) is HBM-or-nothing: under pressure, idle
prefix-cached chains are dropped and a returning session pays full
re-prefill. DeepSpeed's ZeRO-Infinity/host-offload lineage (PAPER.md)
shows host memory is a usable tier below HBM when the wire format is
compact — and the int8/int4 ``kv_pack`` codec (PRs 12/14) already IS
that wire format: a quantized pool's payload + per-vector scales page
to host as plain byte copies, no conversion on either side, so the
round trip is bit-exact by construction (bf16 pools page their raw
payload — also bit-exact, just 2 bytes/value).

Two record kinds, mirroring the two ways KV goes cold:

* **Chains** — cold prefix-cache entries. ``BlockedKVCache.reclaim``
  pages evicted chains here under their content-hash chain keys
  (prefix_cache.py), and ``StateManager.attach_prefix`` continues its
  chain walk into this tier on an HBM miss: matching blocks page back
  in, re-register in the HBM prefix cache, and the request skips that
  much prefill — the disagg.py serialize/install chain-walk turned
  inward.
* **Sessions** — paged-out live sequences ("paged-out" is a
  first-class engine state, engine_v2.py ``_page_out``/``_page_in``):
  a preemption victim's full block contents (including the partial
  tail block) park here with its descriptor state; readmission
  restores the blocks and resumes *decode* directly — zero prefill
  FLOPs, token stream bit-identical to a never-paged run.

The tier is byte-budgeted with LRU eviction (chains first — a paged
session is a parked live request; a chain is an optimization). Spilling
either kind is safe: chains degrade to re-prefill via the ordinary
cache-miss path, sessions degrade to the preemption requeue's
prefix-recompute path.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

DEFAULT_CAPACITY_MB = 256


@dataclasses.dataclass
class PagedSession:
    """A live sequence parked in host memory: the descriptor state that
    rebuilds its ``SequenceDescriptor`` plus the full contents of its KV
    blocks (pool-native format, partial tail block included — restore
    is bit-exact and decode continues with zero recompute)."""

    uid: int
    input_tokens: np.ndarray
    generated: List[int]
    seen_tokens: int
    max_new_tokens: int
    prior_generated: int
    payload: np.ndarray               # [L, n_blocks, bs, 2, H, W]
    scales: Optional[np.ndarray]      # [L, n_blocks, bs, 2, H] | None
    admit_time: Optional[float] = None  # pending-TTFT stamp, if any
    # per-request spec-decode acceptance EWMA: the adaptive-k controller's
    # learned signal survives page-out AND live migration — a resumed
    # session speculates at its measured rate instead of cold-starting
    spec_accept_ewma: Optional[float] = None

    @property
    def n_blocks(self) -> int:
        return int(self.payload.shape[1])

    @property
    def nbytes(self) -> int:
        n = int(self.payload.nbytes)
        if self.scales is not None:
            n += int(self.scales.nbytes)
        return n


class HostKVTier:
    """Byte-budgeted host store of paged-out KV blocks (chains by
    content-hash key, sessions by uid), LRU within each kind."""

    def __init__(self, capacity_bytes: int = DEFAULT_CAPACITY_MB << 20,
                 metric_labels: Optional[Dict[str, str]] = None):
        self.capacity_bytes = int(capacity_bytes)
        self._metric_labels = dict(metric_labels) if metric_labels else None
        # chain key -> (payload [L, bs, 2, H, W], scales [L, bs, 2, H]|None)
        self._chains: "OrderedDict[str, Tuple[np.ndarray, Optional[np.ndarray]]]" = OrderedDict()
        self._sessions: "OrderedDict[int, PagedSession]" = OrderedDict()
        self._bytes = 0
        self.stats = {"chain_blocks_out": 0, "chain_blocks_in": 0,
                      "sessions_out": 0, "sessions_in": 0,
                      "evicted_chain_blocks": 0, "evicted_sessions": 0,
                      "rejected_oversize": 0}
        from deepspeed_tpu.observability.hub import get_hub

        self._hub = get_hub()

    # -- accounting ----------------------------------------------------

    @property
    def used_bytes(self) -> int:
        return self._bytes

    @property
    def chain_blocks(self) -> int:
        return len(self._chains)

    @property
    def session_count(self) -> int:
        return len(self._sessions)

    @property
    def total_blocks(self) -> int:
        return len(self._chains) + sum(s.n_blocks
                                       for s in self._sessions.values())

    def _entry_bytes(self, payload: np.ndarray,
                     scales: Optional[np.ndarray]) -> int:
        n = int(payload.nbytes)
        if scales is not None:
            n += int(scales.nbytes)
        return n

    def _gauges(self) -> None:
        self._hub.gauge("serve.host_tier_bytes", self._bytes,
                        labels=self._metric_labels)
        self._hub.gauge("serve.host_tier_blocks", self.total_blocks,
                        labels=self._metric_labels)
        self._hub.gauge("serve.host_tier_sessions", len(self._sessions),
                        labels=self._metric_labels)

    def _evict_to_fit(self, incoming: int) -> None:
        """Make room for ``incoming`` bytes: drop LRU chains first (they
        degrade to re-prefill), then the oldest paged sessions (they
        degrade to the requeue recompute path)."""
        while (self._bytes + incoming > self.capacity_bytes
               and self._chains):
            _, (p, s) = self._chains.popitem(last=False)
            self._bytes -= self._entry_bytes(p, s)
            self.stats["evicted_chain_blocks"] += 1
        while (self._bytes + incoming > self.capacity_bytes
               and self._sessions):
            _, sess = self._sessions.popitem(last=False)
            self._bytes -= sess.nbytes
            self.stats["evicted_sessions"] += 1

    # -- chains (cold prefix-cache entries) ----------------------------

    def put_chain(self, keys: List[str], payload: np.ndarray,
                  scales: Optional[np.ndarray]) -> None:
        """Park evicted chain blocks: ``payload`` is the pool slice
        ``[L, len(keys), bs, 2, H, W]`` in chain order (pool-native
        format, i.e. already through the kv_pack codec for quantized
        pools)."""
        for i, key in enumerate(keys):
            p = np.ascontiguousarray(payload[:, i])
            s = (np.ascontiguousarray(scales[:, i])
                 if scales is not None else None)
            nb = self._entry_bytes(p, s)
            if nb > self.capacity_bytes:
                self.stats["rejected_oversize"] += 1
                continue
            old = self._chains.pop(key, None)
            if old is not None:
                self._bytes -= self._entry_bytes(*old)
            self._evict_to_fit(nb)
            self._chains[key] = (p, s)
            self._bytes += nb
            self.stats["chain_blocks_out"] += 1
        self._hub.counter_add("serve.host_tier_pages_out", len(keys),
                              labels=self._metric_labels)
        self._gauges()

    def has_block(self, key: str) -> bool:
        return key in self._chains

    def take_block(self, key: str
                   ) -> Optional[Tuple[np.ndarray, Optional[np.ndarray]]]:
        """Remove and return a chain block's contents for page-in (move
        semantics: once re-registered in the HBM prefix cache the host
        copy is redundant; re-eviction pages it out again)."""
        ent = self._chains.pop(key, None)
        if ent is None:
            return None
        self._bytes -= self._entry_bytes(*ent)
        self.stats["chain_blocks_in"] += 1
        self._hub.counter_add("serve.host_tier_pages_in",
                              labels=self._metric_labels)
        self._gauges()
        return ent

    # -- sessions (paged-out live sequences) ---------------------------

    def put_session(self, sess: PagedSession) -> bool:
        """Park a paged-out session; False when it can never fit (the
        caller then falls back to preempt-and-requeue recompute)."""
        nb = sess.nbytes
        if nb > self.capacity_bytes:
            self.stats["rejected_oversize"] += 1
            return False
        old = self._sessions.pop(sess.uid, None)
        if old is not None:
            self._bytes -= old.nbytes
        self._evict_to_fit(nb)
        self._sessions[sess.uid] = sess
        self._bytes += nb
        self.stats["sessions_out"] += 1
        self._hub.counter_add("serve.host_tier_pages_out", sess.n_blocks,
                              labels=self._metric_labels)
        self._gauges()
        return True

    def has_session(self, uid: int) -> bool:
        return uid in self._sessions

    def peek_session(self, uid: int) -> Optional[PagedSession]:
        """Inspect a parked session without moving it (no LRU touch, no
        page-in accounting) — admission sizes its HBM reclaim against
        ``n_blocks`` before committing to the pop."""
        return self._sessions.get(uid)

    def pop_session(self, uid: int) -> Optional[PagedSession]:
        sess = self._sessions.pop(uid, None)
        if sess is None:
            return None
        self._bytes -= sess.nbytes
        self.stats["sessions_in"] += 1
        self._hub.counter_add("serve.host_tier_pages_in", sess.n_blocks,
                              labels=self._metric_labels)
        self._gauges()
        return sess

    # -- introspection -------------------------------------------------

    def holds_chain_prefix(self, cache, tokens) -> int:
        """How many full blocks of ``tokens``'s prefix this tier (or the
        HBM cache it backs) can serve without prefill — the fleet
        router's placement signal: prefer the replica already holding a
        returning session's blocks. ``cache`` is the engine's
        PrefixCache (owns the chain-key function)."""
        toks = np.asarray(tokens, np.int32).ravel()
        bs = cache.block_size
        prev: Optional[str] = None
        hits = 0
        for i in range(max(0, (len(toks) - 1) // bs)):
            key = cache.chain_key(prev, toks[i * bs:(i + 1) * bs])
            if cache.get(key) is None and key not in self._chains:
                break
            hits += 1
            prev = key
        return hits

    def snapshot(self) -> Dict[str, int]:
        return dict(self.stats, used_bytes=self._bytes,
                    capacity_bytes=self.capacity_bytes,
                    chain_blocks=len(self._chains),
                    sessions=len(self._sessions),
                    total_blocks=self.total_blocks)
