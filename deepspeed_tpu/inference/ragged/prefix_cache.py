"""Shared-prefix KV block cache (content-hash prefix matching).

Repeated system prompts are the dominant prefill cost in production
serving: every request carries the same first N tokens, and the KV for
those tokens is identical across requests (the forward for token t
depends only on tokens <= t). vLLM calls this automatic prefix caching;
the reference's FastGen leaves it to MII's replica router. Here it lives
next to the blocked allocator: *full* KV blocks whose token content
matches a cached chain are shared by block id instead of re-prefilled.

Design:

- Keys form a hash chain: ``key_i = H(key_{i-1}, tokens[i*bs:(i+1)*bs])``
  so a block is only reusable when the ENTIRE prefix up to it matches —
  positional KV content depends on everything before it.
- Only full, write-complete blocks are ever shared. The partial tail
  block of a prompt (and every generated-token block) is written in
  place as the sequence grows, so it is always freshly allocated per
  sequence — copy-on-write by construction: a shared block is never the
  append target.
- Per-block refcounts track live sequences holding the block. At
  refcount 0 the block moves to an LRU idle list: still cached (a new
  request can revive it) but evictable, so KV-pool pressure reclaims
  idle cached blocks back to the allocator free list before any live
  sequence is preempted.

The cache owns no device memory: block ids index the one static KV pool
array (kv_cache.py), and eviction is pure host bookkeeping.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def _chain_key(prev_key: Optional[str], tokens: np.ndarray) -> str:
    h = hashlib.sha1()
    if prev_key is not None:
        h.update(prev_key.encode())
    h.update(np.ascontiguousarray(tokens, np.int32).tobytes())
    return h.hexdigest()


class PrefixCache:
    """Content-addressed registry of full KV blocks with refcounts and
    LRU eviction of idle entries."""

    def __init__(self, block_size: int,
                 metric_labels: Optional[Dict[str, str]] = None):
        self.block_size = int(block_size)
        self._metric_labels = dict(metric_labels) if metric_labels else None
        self._block_of: Dict[str, int] = {}      # key -> block id
        self._refs: Dict[str, int] = {}          # key -> live holders
        self._idle: "OrderedDict[str, int]" = OrderedDict()  # LRU, ref==0
        self.stats = {"hits": 0, "hit_tokens": 0, "misses": 0,
                      "registered": 0, "evicted": 0, "conflicts": 0}
        # hit RATIO and eviction pressure on the shared dashboard, not
        # just serve.prefix_hit_tokens: every lookup/miss/eviction also
        # lands as a hub counter (docs/observability.md serving metrics)
        from deepspeed_tpu.observability.hub import get_hub

        self._hub = get_hub()

    # -- lookup / ref lifecycle ---------------------------------------

    def chain_key(self, prev_key: Optional[str], tokens) -> str:
        return _chain_key(prev_key, np.asarray(tokens, np.int32))

    def lookup(self, tokens, max_tokens: Optional[int] = None
               ) -> Tuple[List[str], List[int]]:
        """Longest cached full-block chain covering a prefix of
        ``tokens`` (capped at ``max_tokens``). Returns (keys, block ids)
        WITHOUT taking references — call :meth:`ref` to hold them."""
        toks = np.asarray(tokens, np.int32).ravel()
        bs = self.block_size
        limit = len(toks) if max_tokens is None else min(len(toks),
                                                         int(max_tokens))
        keys: List[str] = []
        blocks: List[int] = []
        prev: Optional[str] = None
        for i in range(limit // bs):
            key = _chain_key(prev, toks[i * bs:(i + 1) * bs])
            blk = self._block_of.get(key)
            if blk is None:
                break
            keys.append(key)
            blocks.append(blk)
            prev = key
        self._hub.counter_add("serve.prefix_lookups",
                              labels=self._metric_labels)
        if keys:
            self.stats["hits"] += 1
            self.stats["hit_tokens"] += len(keys) * bs
        else:
            self.stats["misses"] += 1
            self._hub.counter_add("serve.prefix_misses",
                                  labels=self._metric_labels)
        return keys, blocks

    def get(self, key: str) -> Optional[int]:
        """Block id cached under ``key`` (no ref taken), or None. The
        disaggregation handoff codec (serving/disagg.py) uses this to
        skip installing blocks the target replica already holds."""
        return self._block_of.get(key)

    def ref(self, keys: Sequence[str]) -> None:
        for key in keys:
            if key not in self._block_of:
                raise KeyError(f"prefix key {key[:12]} not cached")
            self._refs[key] = self._refs.get(key, 0) + 1
            self._idle.pop(key, None)

    def unref(self, keys: Sequence[str]) -> None:
        for key in keys:
            n = self._refs.get(key, 0) - 1
            if n < 0:
                raise ValueError(f"unref of unheld prefix key {key[:12]}")
            if n == 0:
                self._refs.pop(key)
                # most-recently-released = last evicted
                self._idle[key] = self._block_of[key]
                self._idle.move_to_end(key)
            else:
                self._refs[key] = n

    # -- registration / eviction --------------------------------------

    def register(self, key: str, block_id: int) -> bool:
        """Adopt ``block_id`` (owned and already write-complete by the
        caller's sequence) into the cache under ``key``, with one
        reference held by the caller. False when the key is already
        cached under a different block (two identical prompts prefilled
        concurrently) — the caller's block then stays private."""
        existing = self._block_of.get(key)
        if existing is not None:
            if existing != int(block_id):
                self.stats["conflicts"] += 1
                return False
            # re-register of the caller's own block: just take the ref
            self._refs[key] = self._refs.get(key, 0) + 1
            self._idle.pop(key, None)
            return True
        self._block_of[key] = int(block_id)
        self._refs[key] = 1
        self.stats["registered"] += 1
        return True

    @property
    def evictable_blocks(self) -> int:
        return len(self._idle)

    @property
    def referenced_blocks(self) -> int:
        """Distinct cached blocks currently held by live sequences."""
        return len(self._refs)

    @property
    def cached_blocks(self) -> int:
        return len(self._block_of)

    def evict(self, n: int) -> List[int]:
        """Drop up to ``n`` least-recently-idle entries; returns their
        block ids for the caller to hand back to the allocator."""
        return [blk for _, blk in self.evict_entries(n)]

    def evict_entries(self, n: int) -> List[Tuple[str, int]]:
        """Like :meth:`evict`, but returns ``(chain_key, block_id)``
        pairs — the host tier (ragged/kv_tier.py) needs the keys to
        page the evicted contents out instead of dropping them."""
        out: List[Tuple[str, int]] = []
        while self._idle and len(out) < n:
            key, blk = self._idle.popitem(last=False)
            del self._block_of[key]
            out.append((key, blk))
        self.stats["evicted"] += len(out)
        if out:
            self._hub.counter_add("serve.prefix_evicted_blocks", len(out),
                                  labels=self._metric_labels)
        return out

    def snapshot(self) -> Dict[str, int]:
        return dict(self.stats, cached_blocks=self.cached_blocks,
                    evictable_blocks=self.evictable_blocks,
                    referenced_blocks=len(self._refs))
