"""Profiling subsystem (reference: deepspeed/profiling/)."""

from deepspeed_tpu.profiling.flops_profiler import (  # noqa: F401
    FlopsProfiler,
    get_model_profile,
    profile_compiled,
)
