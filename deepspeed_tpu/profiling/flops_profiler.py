"""FLOPS profiler — XLA cost-analysis based.

Reference parity: ``deepspeed/profiling/flops_profiler/profiler.py:30``
(``FlopsProfiler``) and ``get_model_profile`` there. The reference
monkey-patches ``torch.nn.functional`` to count MACs module-by-module while
eager ops execute; on TPU the whole step is one compiled XLA program, so the
idiomatic source of truth is the compiler itself: ``jax.jit(fn).lower(...)
.compile().cost_analysis()`` reports exact flops / bytes-accessed for the
program XLA actually runs (post-fusion), and ``memory_analysis()`` reports
live-memory. Per-module breakdown comes from the parameter pytree (params per
top-level module) plus the analytic transformer cost model — the same
decomposition the reference prints, without perturbing the hot path.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import numpy as np

from deepspeed_tpu.utils.logging import logger

__all__ = [
    "FlopsProfiler",
    "get_model_profile",
    "profile_compiled",
    "number_to_string",
    "flops_to_string",
    "macs_to_string",
    "params_to_string",
    "duration_to_string",
]


# ---------------------------------------------------------------------------
# formatting helpers (reference profiler.py number_to_string family)
# ---------------------------------------------------------------------------

def number_to_string(num: float, units: Optional[str] = None,
                     precision: int = 2) -> str:
    if units is None:
        if num >= 1e12:
            return f"{num / 1e12:.{precision}f} T"
        if num >= 1e9:
            return f"{num / 1e9:.{precision}f} G"
        if num >= 1e6:
            return f"{num / 1e6:.{precision}f} M"
        if num >= 1e3:
            return f"{num / 1e3:.{precision}f} K"
        return f"{num:.{precision}f} "
    scale = {"T": 1e12, "G": 1e9, "M": 1e6, "K": 1e3, "": 1.0}[units]
    return f"{num / scale:.{precision}f} {units}"


def flops_to_string(flops: float, units=None, precision: int = 2) -> str:
    return number_to_string(flops, units, precision) + "FLOPS"


def macs_to_string(macs: float, units=None, precision: int = 2) -> str:
    return number_to_string(macs, units, precision) + "MACs"


def params_to_string(n: float, units=None, precision: int = 2) -> str:
    return number_to_string(n, units, precision).rstrip()


def bytes_to_string(n: float, precision: int = 2) -> str:
    return number_to_string(n, None, precision) + "B"


def duration_to_string(seconds: float, precision: int = 2) -> str:
    if seconds >= 1:
        return f"{seconds:.{precision}f} s"
    if seconds >= 1e-3:
        return f"{seconds * 1e3:.{precision}f} ms"
    return f"{seconds * 1e6:.{precision}f} us"


# ---------------------------------------------------------------------------
# compiled-program cost extraction
# ---------------------------------------------------------------------------

def profile_compiled(fn: Callable, *args, static_argnums=(),
                     **kwargs) -> Dict[str, float]:
    """Lower+compile ``fn`` and return XLA's cost analysis.

    Returns dict with keys ``flops``, ``bytes_accessed``, ``transcendentals``,
    ``peak_bytes`` (generated-code temp + output, when the backend reports
    memory analysis). Works on jitted or plain callables.
    """
    jitted = fn if hasattr(fn, "lower") else jax.jit(
        fn, static_argnums=static_argnums)
    lowered = jitted.lower(*args, **kwargs)
    compiled = lowered.compile()
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, (list, tuple)):  # older jax returns [dict]
        cost = cost[0] if cost else {}
    out = {
        "flops": float(cost.get("flops", 0.0)),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
        "transcendentals": float(cost.get("transcendentals", 0.0)),
        "peak_bytes": 0.0,
    }
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            # donated inputs alias their outputs — counting both sides
            # double-books every donated buffer (ZeRO state is donated)
            out["peak_bytes"] = float(
                getattr(mem, "temp_size_in_bytes", 0)
                + getattr(mem, "output_size_in_bytes", 0)
                + getattr(mem, "argument_size_in_bytes", 0)
                - getattr(mem, "alias_size_in_bytes", 0))
    except Exception:  # backend may not implement memory analysis
        pass
    return out


def _count_params(tree) -> int:
    return int(sum(np.prod(x.shape) for x in jax.tree.leaves(tree)
                   if hasattr(x, "shape")))


def _per_module_params(params) -> Dict[str, int]:
    """Params per top-level pytree key (the 'module' granularity)."""
    if isinstance(params, dict):
        return {k: _count_params(v) for k, v in params.items()}
    return {"params": _count_params(params)}


# ---------------------------------------------------------------------------
# profiler
# ---------------------------------------------------------------------------

class FlopsProfiler:
    """Reference-parity profiler (profiler.py:30): ``start_profile`` /
    ``stop_profile`` / ``get_total_*`` / ``print_model_profile`` /
    ``end_profile``.

    Attach to an engine (``FlopsProfiler(engine=engine)``) to profile its
    compiled train step, or use standalone around any jittable fn via
    :func:`get_model_profile`.
    """

    def __init__(self, model=None, engine=None, config=None):
        self.model = model
        self.engine = engine
        self.config = config or (engine.config.flops_profiler
                                 if engine is not None else None)
        self.started = False
        self._t0 = 0.0
        self._duration = 0.0
        self._cost: Dict[str, float] = {}
        self._params_total = 0
        self._params_by_module: Dict[str, int] = {}

    # -- lifecycle ---------------------------------------------------------
    def start_profile(self, ignore_list=None):
        self.started = True
        self._t0 = time.time()
        if self.engine is not None:
            self._analyze_engine()
        elif self.model is not None and hasattr(self.model, "init"):
            params = self.model.abstract_params() if hasattr(
                self.model, "abstract_params") else None
            if params is not None:
                self._params_total = _count_params(params)
                self._params_by_module = _per_module_params(params)

    def stop_profile(self):
        if self.started:
            self._duration = time.time() - self._t0

    def end_profile(self):
        self.started = False

    def reset_profile(self):
        self._cost = {}
        self._duration = 0.0

    # -- engine analysis ---------------------------------------------------
    def _analyze_engine(self):
        eng = self.engine
        self._params_total = _count_params(eng.params)
        self._params_by_module = _per_module_params(eng.params)
        # cost of the compiled train step over one GAS window
        try:
            gas = eng.gradient_accumulation_steps
            batch = self._example_batch(gas)
            if batch is not None:
                self._cost = profile_compiled(
                    eng._jit_train_step, eng.params, eng.opt_state,
                    eng.loss_scale_state, eng.step_count, batch)
        except Exception as e:
            logger.debug(f"flops profiler: cost_analysis unavailable ({e})")

    def _example_batch(self, gas: int):
        eng = self.engine
        model = getattr(eng, "model", None)
        cfg = getattr(model, "config", None)
        if cfg is None or not hasattr(cfg, "max_seq_len"):
            return None
        import jax.numpy as jnp
        micro = eng.micro_batch_size * eng.dp_world_size  # global micro batch
        seq = min(cfg.max_seq_len, 512)
        tokens = jnp.zeros((gas, micro, seq), jnp.int32)
        batch = {"input_ids": tokens}
        return jax.device_put(batch, eng._batch_sharding(leading_dims=2))

    # -- totals (reference get_total_* API) --------------------------------
    def get_total_flops(self, as_string: bool = False):
        f = self._cost.get("flops", 0.0)
        return flops_to_string(f) if as_string else f

    def get_total_macs(self, as_string: bool = False):
        m = self._cost.get("flops", 0.0) / 2.0
        return macs_to_string(m) if as_string else m

    def get_total_params(self, as_string: bool = False):
        return (params_to_string(self._params_total) if as_string
                else self._params_total)

    def get_total_duration(self, as_string: bool = False):
        return (duration_to_string(self._duration) if as_string
                else self._duration)

    # -- report ------------------------------------------------------------
    def print_model_profile(self, profile_step: int = 1, module_depth: int = -1,
                            top_modules: int = 1, detailed: bool = True,
                            output_file: Optional[str] = None):
        lines = self._render(profile_step, detailed)
        text = "\n".join(lines)
        if output_file:
            with open(output_file, "w") as f:
                f.write(text + "\n")
        else:
            print(text)

    def _render(self, profile_step: int, detailed: bool):
        lines = [
            "-" * 72,
            "DeepSpeed-TPU Flops Profiler",
            "-" * 72,
            f"Profile step:                   {profile_step}",
            f"Params:                         "
            f"{params_to_string(self._params_total)}",
        ]
        if self._cost:
            flops = self._cost["flops"]
            lines += [
                f"FLOPs per train step (XLA):     {flops_to_string(flops)}",
                f"MACs per train step:            "
                f"{macs_to_string(flops / 2)}",
                f"HBM bytes accessed:             "
                f"{bytes_to_string(self._cost['bytes_accessed'])}",
                f"Arithmetic intensity:           "
                f"{flops / max(self._cost['bytes_accessed'], 1):.1f} "
                f"FLOP/byte",
            ]
            if self._cost.get("peak_bytes"):
                lines.append(f"Compiled memory footprint:      "
                             f"{bytes_to_string(self._cost['peak_bytes'])}")
        if self._duration:
            lines.append(f"Profile duration:               "
                         f"{duration_to_string(self._duration)}")
            if self._cost:
                lines.append(
                    f"Achieved:                       "
                    f"{flops_to_string(self._cost['flops'] / self._duration)}")
        if detailed and self._params_by_module:
            lines.append("")
            lines.append("Per-module parameters:")
            total = max(self._params_total, 1)
            for name, n in sorted(self._params_by_module.items(),
                                  key=lambda kv: -kv[1]):
                lines.append(f"  {name:<28} {params_to_string(n):>10}  "
                             f"({100.0 * n / total:.1f}%)")
        lines.append("-" * 72)
        return lines


# ---------------------------------------------------------------------------
# standalone convenience (reference get_model_profile)
# ---------------------------------------------------------------------------

def get_model_profile(model, input_shape: Optional[Tuple[int, ...]] = None,
                      args=None, print_profile: bool = True,
                      detailed: bool = True, as_string: bool = True,
                      output_file: Optional[str] = None,
                      warm_up: int = 1) -> Tuple[Any, Any, Any]:
    """Profile one forward pass of ``model`` (reference
    ``flops_profiler/profiler.py`` ``get_model_profile``): returns
    ``(flops, macs, params)``.

    ``model`` is anything with ``.init(rng)`` + ``.apply(params, tokens)``
    (our zoo contract), or a plain callable when ``args`` is given.
    """
    import jax.numpy as jnp

    if hasattr(model, "init") and input_shape is not None:
        params = model.init(jax.random.PRNGKey(0))
        tokens = jnp.zeros(input_shape, jnp.int32)
        fn = lambda p, t: model.apply(p, t)
        cost = profile_compiled(fn, params, tokens)
        n_params = _count_params(params)
        by_module = _per_module_params(params)
    elif args is not None:
        cost = profile_compiled(model, *args)
        n_params = 0
        by_module = {}
    else:
        raise ValueError("need input_shape (zoo model) or args (callable)")

    prof = FlopsProfiler()
    prof._cost = cost
    prof._params_total = n_params
    prof._params_by_module = by_module
    if print_profile:
        prof.print_model_profile(detailed=detailed, output_file=output_file)
    flops, macs, n = cost["flops"], cost["flops"] / 2, n_params
    if as_string:
        return (flops_to_string(flops), macs_to_string(macs),
                params_to_string(n))
    return flops, macs, n
