"""PreemptionGuard: turn SIGTERM into a durable checkpoint, not a corpse.

Pod schedulers (and the OOM killer's politer cousins) deliver SIGTERM
with a grace window before SIGKILL. The flight recorder already chains a
SIGTERM handler that dumps the ring and re-kills the process — correct
for a crash post-mortem, wrong for preemption: we want the run to *keep
going* just long enough to reach the next GAS boundary, drain the
dispatch-ahead window, and commit an emergency checkpoint.

So the guard deliberately does NOT chain previous handlers on the first
signal: it flips a flag, records the event in the flight ring, and
returns, letting the training loop notice at its next ``train_batch``
boundary (``Engine`` checks :meth:`should_checkpoint` there, drains via
``synchronize()``, saves, and commits under :attr:`save_deadline_s`).
A second signal means the grace window is closing faster than we can
drain — it escalates: flight dump, then the previously-installed
handler (or the default disposition) runs, preserving "killed by
SIGTERM" exit semantics.

The guard is also the programmatic preemption entry point:
:meth:`request` lets the chaos harness and tests trigger the same path
without a real signal.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Optional

from deepspeed_tpu.utils.logging import logger


class PreemptionGuard:
    """Listens for preemption notice and arranges an emergency save.

    Args:
      save_deadline_s: budget for the emergency save+commit once the
        engine reaches a GAS boundary. The engine passes it to the
        checkpoint commit wait; a blown deadline logs and proceeds to
        exit (a partial save is invisible to resume thanks to the
        manifest — see resilience/manifest.py).
      signals: which signals mean "preemption notice". SIGTERM by
        default; tests add SIGUSR1 to avoid racing the test runner.
    """

    def __init__(self, save_deadline_s: float = 60.0,
                 signals=(signal.SIGTERM,)):
        self.save_deadline_s = float(save_deadline_s)
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._handled = False
        self._installed = False
        self._prev = {}
        self._requested_at: Optional[float] = None
        self.reason: Optional[str] = None

    # -- state ---------------------------------------------------------
    @property
    def requested(self) -> bool:
        """True once a preemption notice has arrived."""
        return self._event.is_set()

    @property
    def requested_at(self) -> Optional[float]:
        return self._requested_at

    def should_checkpoint(self) -> bool:
        """True exactly once: the first boundary check after a notice.
        The engine calls this at each train_batch GAS boundary."""
        if self._event.is_set() and not self._handled:
            self._handled = True
            return True
        return False

    def reset(self) -> None:
        """Forget a handled notice (tests / multi-notice runs)."""
        self._event.clear()
        self._handled = False
        self._requested_at = None
        self.reason = None

    # -- triggering ----------------------------------------------------
    def request(self, reason: str = "programmatic") -> None:
        """Raise the preemption flag without a signal (chaos harness,
        cloud preemption-notice pollers)."""
        if self._event.is_set():
            return
        self.reason = reason
        self._requested_at = time.time()
        self._event.set()
        self._record("preempt_notice", reason=reason)
        logger.warning(
            f"resilience: preemption notice ({reason}); will drain "
            f"in-flight steps and checkpoint at the next GAS boundary "
            f"(deadline {self.save_deadline_s:g}s)")

    # -- signal plumbing -----------------------------------------------
    def install(self) -> bool:
        """Install signal handlers (idempotent; main thread only —
        ``signal.signal`` raises elsewhere, in which case the guard still
        works via :meth:`request`). Returns True if handlers went in."""
        if self._installed:
            return True
        if threading.current_thread() is not threading.main_thread():
            logger.debug("resilience: PreemptionGuard signal install "
                         "skipped off the main thread")
            return False
        try:
            for sig in self.signals:
                self._prev[sig] = signal.getsignal(sig)
                signal.signal(sig, self._on_signal)
        except (ValueError, OSError) as e:
            logger.debug(f"resilience: PreemptionGuard install failed: {e}")
            self._prev.clear()
            return False
        self._installed = True
        return True

    def uninstall(self) -> None:
        """Restore previous handlers (tests)."""
        if not self._installed:
            return
        try:
            for sig, prev in self._prev.items():
                signal.signal(sig, prev)
        except (ValueError, OSError):
            pass
        self._prev.clear()
        self._installed = False

    def _on_signal(self, signum, frame) -> None:
        if not self._event.is_set():
            # first notice: flag it and RETURN — no chaining, the run
            # must survive to the next GAS boundary to save.
            self.request(reason=f"signal {signum}")
            return
        # second notice: the grace window is closing — escalate through
        # the previous handler (flight recorder dump + kill) or default.
        logger.error("resilience: second preemption signal — escalating "
                     "to immediate shutdown")
        self._record("preempt_escalate", signum=signum)
        self._dump_flight("preempt_escalate")
        prev = self._prev.get(signum)
        if callable(prev):
            prev(signum, frame)
        else:
            try:
                signal.signal(signum, signal.SIG_DFL)
            except (ValueError, OSError):
                pass
            os.kill(os.getpid(), signum)

    # -- flight recorder (best-effort, jax-free) -----------------------
    @staticmethod
    def _record(kind: str, **fields) -> None:
        try:
            from deepspeed_tpu.observability.flight_recorder import \
                get_flight_recorder

            get_flight_recorder().record(kind, **fields)
        except Exception:
            pass

    @staticmethod
    def _dump_flight(reason: str) -> None:
        try:
            from deepspeed_tpu.observability.flight_recorder import \
                dump_flight_recorder

            dump_flight_recorder(reason)
        except Exception:
            pass
