"""Atomic checkpoint manifests: no silent bad restore, ever.

A preempted host can die mid-write; orbax's own commit protocol protects
the tensor payload directory, but the *checkpoint as a unit* (payload +
per-rank host blobs + metadata + the ``latest`` pointer) had no
durability witness — ``load_checkpoint`` would happily restore whatever
the filesystem held. The manifest closes that hole:

* written LAST, via tmp+rename, only after every rank's payload is
  durable (the publish barrier in checkpoint/state.py), so its presence
  certifies a complete save;
* records the tag, step, world topology, the data-pipeline cursor, and a
  per-file (size, crc32) table over the whole checkpoint dir, so torn or
  bit-rotted files are detected at load;
* :func:`validate_manifest` raises :class:`CheckpointCorruptError` with
  the concrete reason (missing file, size mismatch, checksum mismatch);
* :func:`find_latest_valid_tag` walks candidate tags newest-first so a
  corrupt latest falls back to the previous good tag instead of a torn
  restore.

Checksums stream with crc32 (zlib) — fast enough to run over multi-GB
payloads at save time without showing up next to the actual device→host
copy, and strong enough for the failure modes that matter here
(truncation, partial writes, zeroed pages). Paths under ``state/`` are
the orbax payload; everything is checksummed uniformly.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

MANIFEST_FILE = "manifest.json"
MANIFEST_VERSION = 1

_SKIP_SUFFIXES = (".tmp",)
_CHUNK = 1 << 20


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed manifest validation (torn/corrupt save)."""

    def __init__(self, ckpt_dir: str, reason: str):
        self.ckpt_dir = ckpt_dir
        self.reason = reason
        super().__init__(
            f"checkpoint at {ckpt_dir} failed manifest validation: "
            f"{reason}. Refusing to restore a torn/corrupt save — "
            "pass an older tag, or delete the directory so auto-resume "
            "falls back to the previous good tag.")


def _file_crc32(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


def _is_tmp(name: str) -> bool:
    return any(s in name for s in _SKIP_SUFFIXES)


def _walk_files(ckpt_dir: str) -> List[str]:
    out = []
    for root, _dirs, files in os.walk(ckpt_dir):
        for name in files:
            if name == MANIFEST_FILE or _is_tmp(name):
                continue
            out.append(os.path.relpath(os.path.join(root, name), ckpt_dir))
    return sorted(out)


def write_manifest(ckpt_dir: str, tag: str, *,
                   global_steps: int = 0,
                   world: Optional[Dict[str, Any]] = None,
                   data_cursor: Optional[Dict[str, Any]] = None,
                   extra: Optional[Dict[str, Any]] = None) -> str:
    """Checksum every file under ``ckpt_dir`` and publish the manifest
    atomically (tmp+rename). Call only after all payloads are durable."""
    files = {}
    for rel in _walk_files(ckpt_dir):
        p = os.path.join(ckpt_dir, rel)
        files[rel] = {"size": os.path.getsize(p),
                      "crc32": _file_crc32(p)}
    doc = {
        "kind": "dstpu_checkpoint_manifest",
        "version": MANIFEST_VERSION,
        "tag": str(tag),
        "global_steps": int(global_steps),
        "saved_at": time.time(),
        "world": dict(world or {}),
        "data_cursor": dict(data_cursor or {}),
        "n_files": len(files),
        "files": files,
    }
    if extra:
        doc.update(extra)
    path = os.path.join(ckpt_dir, MANIFEST_FILE)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1, default=str)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def read_manifest(ckpt_dir: str) -> Optional[Dict[str, Any]]:
    """Parsed manifest, or None when absent (pre-resilience checkpoint).
    An unparseable manifest raises CheckpointCorruptError — a torn
    manifest write means the save did not complete."""
    path = os.path.join(ckpt_dir, MANIFEST_FILE)
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(ckpt_dir, f"unreadable manifest: {e}")
    if doc.get("kind") != "dstpu_checkpoint_manifest":
        raise CheckpointCorruptError(ckpt_dir, "not a checkpoint manifest")
    return doc


def validate_manifest(ckpt_dir: str,
                      check_checksums: bool = True
                      ) -> Optional[Dict[str, Any]]:
    """Validate ``ckpt_dir`` against its manifest.

    Returns the manifest dict, or None when no manifest exists (legacy
    checkpoint — callers decide whether to accept). Raises
    :class:`CheckpointCorruptError` naming the first defect found."""
    doc = read_manifest(ckpt_dir)
    if doc is None:
        return None
    files = doc.get("files", {})
    for rel, ent in files.items():
        p = os.path.join(ckpt_dir, rel)
        if not os.path.exists(p):
            raise CheckpointCorruptError(ckpt_dir, f"missing file: {rel}")
        size = os.path.getsize(p)
        if size != ent.get("size"):
            raise CheckpointCorruptError(
                ckpt_dir, f"size mismatch for {rel}: manifest says "
                f"{ent.get('size')} bytes, found {size} (truncated/torn "
                "write)")
        if check_checksums and _file_crc32(p) != ent.get("crc32"):
            raise CheckpointCorruptError(
                ckpt_dir, f"checksum mismatch for {rel} (corrupt data)")
    return doc


def _candidate_tags(load_dir: str) -> List[str]:
    """Tag directories under ``load_dir`` sorted newest-first by manifest
    saved_at (manifest-less dirs sort last, by mtime)."""
    entries = []
    try:
        names = os.listdir(load_dir)
    except OSError:
        return []
    for name in names:
        d = os.path.join(load_dir, name)
        if not os.path.isdir(d):
            continue
        mpath = os.path.join(d, MANIFEST_FILE)
        order = (0.0, os.path.getmtime(d))
        if os.path.exists(mpath):
            try:
                with open(mpath) as f:
                    order = (1.0, float(json.load(f).get("saved_at", 0.0)))
            except (OSError, ValueError):
                order = (1.0, 0.0)  # torn manifest: still a candidate slot
        entries.append((order, name))
    entries.sort(reverse=True)
    return [name for _o, name in entries]


def find_latest_valid_tag(load_dir: str,
                          exclude: Optional[List[str]] = None,
                          check_checksums: bool = True) -> Optional[str]:
    """Newest tag under ``load_dir`` that passes manifest validation
    (manifest-less legacy dirs do NOT qualify — a fallback must be
    provably good). ``exclude`` lists tags already known bad."""
    exclude = set(exclude or [])
    for tag in _candidate_tags(load_dir):
        if tag in exclude:
            continue
        d = os.path.join(load_dir, tag)
        try:
            doc = validate_manifest(d, check_checksums=check_checksums)
        except CheckpointCorruptError as e:
            logger.warning(f"resilience: skipping corrupt checkpoint "
                           f"candidate {tag!r}: {e.reason}")
            continue
        if doc is not None:
            return tag
    return None
