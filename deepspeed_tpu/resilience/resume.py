"""Deterministic auto-resume of the data pipeline.

The hard part of resuming a killed run is not the tensors (orbax owns
those) but the *batch stream*: the resumed run must see exactly the
batches the dead run never consumed, in the same order, or the loss
trajectories diverge and "resumed" silently means "different run".

The cursor that makes this work counts **consumed GAS boundaries**, not
pulled batches. ``Engine._next_batches`` hands exactly one boundary per
``train_batch`` call, so ``boundaries_consumed == engine.global_steps``
— and batches a ``PrefetchingIterator`` worker pulled ahead but the
training loop never consumed are automatically *excluded* from the
cursor. On resume the fresh iterator replays them first, which is
exactly right: the dead run's prefetch buffer died with it.

Restore strategies, in order of preference:

1. the data source exposes ``load_state_dict`` (``DeepSpeedDataSampler``,
   ``DeepSpeedDataLoader``, ``RepeatingLoader``): O(1) state restore plus
   a bounded fast-forward for the intra-epoch offset;
2. plain iterator: fast-forward by ``microbatches_consumed`` pulls.
   Deterministic loaders (rng seeded from ``seed + epoch`` / ``seed +
   step``) replay identically, so discard-and-count is exact. O(consumed
   batches) — fine for tier-1 shapes and short runs; production data
   pipelines should carry a sampler with ``state_dict``.

Both paths produce a stream positioned so the next pull is the first
batch the dead run never trained on.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from deepspeed_tpu.utils.logging import logger

CURSOR_VERSION = 1


def data_cursor(engine) -> Dict[str, Any]:
    """Snapshot the engine's data-pipeline position for the checkpoint
    manifest. Call only at a drained GAS boundary (save_checkpoint does:
    it runs synchronize() first)."""
    gas = int(engine.gradient_accumulation_steps)
    cursor: Dict[str, Any] = {
        "version": CURSOR_VERSION,
        "boundaries_consumed": int(engine.global_steps),
        "gas": gas,
        "microbatches_consumed": int(engine.global_steps) * gas,
        "global_samples": int(engine.global_samples),
    }
    # loader state: prefer the stream train_batch actually consumed (a
    # RepeatingLoader is its own iterator, so the engine's last data_iter
    # often IS the stateful loader); fall back to the engine-owned one.
    # NOT captured while a prefetcher is active: the worker has pulled
    # ahead of consumption, so the loader's epoch/offset are "future"
    # values — the consumed-boundary counts above are the only truthful
    # cursor there, and the fast-forward path replays from them exactly.
    if getattr(engine, "_prefetcher", None) is not None:
        return cursor
    for source in (getattr(engine, "_last_data_iter", None),
                   getattr(engine, "training_dataloader", None)):
        state_fn = getattr(source, "state_dict", None)
        if callable(state_fn):
            try:
                cursor["loader"] = state_fn()
            except Exception as e:  # cursor must never block a save
                logger.warning(
                    f"resilience: loader state_dict failed ({e}); cursor "
                    "falls back to fast-forward counts")
            break
    return cursor


def _fast_forward(data_iter, n: int) -> int:
    """Pull and discard ``n`` items; returns how many were skipped (may
    be short if the stream ends — RepeatingLoader never does)."""
    skipped = 0
    for _ in range(n):
        try:
            next(data_iter)
        except StopIteration:
            break
        skipped += 1
    return skipped


def resume_data_iter(data_iter, cursor: Optional[Dict[str, Any]],
                     source=None):
    """Position ``data_iter`` at the first unconsumed microbatch.

    ``cursor`` is the manifest's ``data_cursor`` (None/empty = fresh run,
    returned untouched). ``source`` optionally names the loader object
    backing ``data_iter`` (e.g. the ``RepeatingLoader`` itself) so its
    ``load_state_dict`` can restore epoch/offset state that a bare
    iterator cannot carry.

    IMPORTANT: call before the first ``train_batch`` — the engine's
    prefetch promotion must only ever see the already-positioned stream.
    """
    if not cursor:
        return data_iter
    n = int(cursor.get("microbatches_consumed", 0))
    if n <= 0:
        return data_iter
    loader_state = cursor.get("loader")
    target = source if source is not None else data_iter
    load_fn = getattr(target, "load_state_dict", None)
    if loader_state is not None and callable(load_fn):
        load_fn(loader_state)
        # state restore covers epoch/rng; the intra-epoch offset (batches
        # consumed since the last epoch boundary) still replays here
        n = int(loader_state.get("offset_batches", n))
        if n:
            skipped = _fast_forward(data_iter, n)
            logger.info(f"resilience: resumed loader state + fast-forward "
                        f"{skipped} intra-epoch batch(es)")
        else:
            logger.info("resilience: resumed loader state (no intra-epoch "
                        "offset)")
        return data_iter
    skipped = _fast_forward(data_iter, n)
    if skipped < n:
        logger.warning(
            f"resilience: data stream ended during resume fast-forward "
            f"({skipped}/{n} microbatches) — the resumed run will see a "
            "shorter stream than the original (wrap the loader in "
            "RepeatingLoader for epoch restarts)")
    else:
        logger.info(f"resilience: fast-forwarded data stream by {n} "
                    "consumed microbatch(es)")
    return data_iter
