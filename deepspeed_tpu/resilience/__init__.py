"""Fault tolerance: survive preemption, rank death, and flaky collectives.

The production analog of the reference's DecoupledCheckpointEngine +
DSElasticAgent split, grown into a subsystem (docs/resilience.md):

* :mod:`policy`     — deadline / exponential-backoff / jitter retry policy
  for control-plane collectives; typed :class:`CommTimeoutError` carrying
  the flight-ring tail so the elastic agent can tell "peer dead" from
  "transient".
* :mod:`manifest`   — atomic per-checkpoint manifest (tag, step, world
  topology, per-file checksums, data-pipeline cursor) written tmp+rename;
  validation refuses torn/corrupt saves and falls back to the previous
  good tag.
* :mod:`preemption` — :class:`PreemptionGuard`: SIGTERM/preemption-notice
  listener that drains in-flight dispatch-ahead steps and forces an
  emergency save+commit at the next GAS boundary under a bounded deadline.
* :mod:`resume`     — deterministic auto-resume of the data pipeline: the
  checkpointed cursor counts *consumed* boundaries (snapshotted before any
  prefetched-but-unconsumed batches), so a killed-and-resumed run replays
  the exact remaining batch stream.
* :mod:`chaos`      — env/config-driven fault injection (kill a rank at
  step N, delay/fail the Kth collective, corrupt a checkpoint, stall the
  input pipeline) powering ``make chaos`` and the tier-1 chaos tests.
"""

from deepspeed_tpu.resilience.chaos import (ChaosInjector, ChaosSpec,
                                            corrupt_checkpoint,
                                            get_chaos_injector)
from deepspeed_tpu.resilience.manifest import (MANIFEST_FILE,
                                               CheckpointCorruptError,
                                               find_latest_valid_tag,
                                               validate_manifest,
                                               write_manifest)
from deepspeed_tpu.resilience.policy import (TRANSIENT_EXIT_CODE,
                                             CommTimeoutError, RetryPolicy)
from deepspeed_tpu.resilience.preemption import PreemptionGuard
from deepspeed_tpu.resilience.resume import data_cursor, resume_data_iter

__all__ = [
    "ChaosInjector", "ChaosSpec", "CheckpointCorruptError",
    "CommTimeoutError", "MANIFEST_FILE", "PreemptionGuard", "RetryPolicy",
    "TRANSIENT_EXIT_CODE", "corrupt_checkpoint", "data_cursor",
    "find_latest_valid_tag", "get_chaos_injector", "resume_data_iter",
    "validate_manifest", "write_manifest",
]
