"""Chaos harness: injected faults with assertable outcomes.

Fault tolerance that has never met a fault is a hypothesis. This module
injects the failures the resilience subsystem claims to survive — a rank
killed mid-run, a wedged or failing collective, a corrupted checkpoint,
a stalled input pipeline — deterministically enough that a test can
assert the *outcome*: elastic agent restarts the group, auto-resume
lands on the latest valid manifest, and the final losses are
bit-identical to a fault-free run (``make chaos``,
tests/test_resilience.py, tools/chaos_run.py).

Faults are declared in a :class:`ChaosSpec`, normally parsed from the
``DSTPU_CHAOS`` env var so the launcher's child processes inherit them
without config plumbing::

    DSTPU_CHAOS="kill_rank=1,kill_step=3,kill_signal=SIGKILL"
    DSTPU_CHAOS="collective_k=5,collective_mode=delay,collective_delay_s=2"
    DSTPU_CHAOS="stall_input_step=2,stall_input_s=1.5"
    DSTPU_CHAOS="net_drop_frac=0.05,net_seed=7"
    DSTPU_CHAOS="net_partition=r1:20"

The injector is process-global (:func:`get_chaos_injector`) and inert
unless a spec is armed — the hooks in the engine/comm hot paths cost one
``is None`` check when chaos is off. Every injected fault is recorded in
the flight ring first, so post-mortems show "chaos_kill step=3" instead
of an unexplained death.
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

CHAOS_ENV = "DSTPU_CHAOS"

_SIGNALS = {
    "SIGKILL": signal.SIGKILL,
    "SIGTERM": signal.SIGTERM,
    "KILL": signal.SIGKILL,
    "TERM": signal.SIGTERM,
}


class ChaosCollectiveError(RuntimeError):
    """Injected collective failure (chaos harness, not a real fault)."""


@dataclass
class ChaosSpec:
    """One process's fault plan. All fields optional; unset = no fault.

    kill_rank/kill_step/kill_signal: send ``kill_signal`` to self when
      this rank enters training step ``kill_step`` (1-based, the step
      about to run). SIGKILL models preemption without grace; SIGTERM
      exercises the PreemptionGuard drain path.
    collective_k/collective_mode: on the Kth traced collective (1-based)
      either ``fail`` (raise :class:`ChaosCollectiveError`) or ``delay``
      (sleep ``collective_delay_s`` — a straggler/wedge, which a
      configured ``collective_timeout_s`` should catch).
    stall_input_step/stall_input_s: sleep inside the input pipeline at
      the given batch pull (1-based) — models a slow data source.
    net_*: the transport fault family, evaluated inside the serving
      channels (serving/transport/channel.py) so faults hit real bytes
      on the wire. ``net_drop_frac`` drops that fraction of outbound
      frames (seeded by ``net_seed``); ``net_delay_ms`` sleeps before
      each outbound frame; ``net_dup`` duplicates every Nth frame;
      ``net_corrupt`` flips one payload byte of every Nth frame (the
      CRC catches it at the receiver); ``net_partition=rN:K`` blackholes
      both directions of peer N's link for its first K wire ops, then
      heals — the receiver's per-channel sequence numbers turn silent
      drops into a detectable gap.
    """

    kill_rank: Optional[int] = None
    kill_step: Optional[int] = None
    kill_signal: str = "SIGKILL"
    collective_k: Optional[int] = None
    collective_mode: str = "fail"
    collective_delay_s: float = 2.0
    collective_op: Optional[str] = None
    stall_input_step: Optional[int] = None
    stall_input_s: float = 0.0
    net_drop_frac: float = 0.0
    net_delay_ms: float = 0.0
    net_dup: Optional[int] = None
    net_corrupt: Optional[int] = None
    net_partition: Optional[str] = None
    net_seed: Optional[int] = None

    _INT_FIELDS = ("kill_rank", "kill_step", "collective_k",
                   "stall_input_step", "net_dup", "net_corrupt",
                   "net_seed")
    _FLOAT_FIELDS = ("collective_delay_s", "stall_input_s",
                     "net_drop_frac", "net_delay_ms")

    @classmethod
    def parse(cls, text: str) -> "ChaosSpec":
        """Parse ``k=v,k=v`` (the DSTPU_CHAOS format). Unknown keys are
        an error — a typoed fault that silently no-ops would make a
        chaos test pass vacuously."""
        spec = cls()
        valid = set(cls.__dataclass_fields__)
        for part in text.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(
                    f"{CHAOS_ENV}: expected k=v, got {part!r}")
            key, val = (s.strip() for s in part.split("=", 1))
            if key not in valid or key.startswith("_"):
                raise ValueError(
                    f"{CHAOS_ENV}: unknown chaos key {key!r} "
                    f"(valid: {sorted(k for k in valid if not k.startswith('_'))})")
            if key in cls._INT_FIELDS:
                setattr(spec, key, int(val))
            elif key in cls._FLOAT_FIELDS:
                setattr(spec, key, float(val))
            else:
                setattr(spec, key, val)
        if spec.kill_signal.upper() not in _SIGNALS:
            raise ValueError(
                f"{CHAOS_ENV}: kill_signal must be SIGKILL or SIGTERM, "
                f"got {spec.kill_signal!r}")
        if spec.collective_mode not in ("fail", "delay"):
            raise ValueError(
                f"{CHAOS_ENV}: collective_mode must be fail|delay, got "
                f"{spec.collective_mode!r}")
        if not 0.0 <= spec.net_drop_frac < 1.0:
            raise ValueError(
                f"{CHAOS_ENV}: net_drop_frac must be in [0, 1), got "
                f"{spec.net_drop_frac}")
        spec.partition_target()  # validate rN:K early, not on the wire
        return spec

    def partition_target(self) -> Optional[tuple]:
        """``net_partition="rN:K"`` → (peer N, K wire ops blackholed)."""
        if not self.net_partition:
            return None
        text = self.net_partition.strip()
        try:
            peer_s, rounds_s = text.split(":", 1)
            if not peer_s.startswith("r"):
                raise ValueError
            peer, rounds = int(peer_s[1:]), int(rounds_s)
        except ValueError:
            raise ValueError(
                f"{CHAOS_ENV}: net_partition must look like rN:K "
                f"(e.g. r1:20), got {self.net_partition!r}") from None
        if rounds < 1:
            raise ValueError(
                f"{CHAOS_ENV}: net_partition rounds must be >= 1, got "
                f"{rounds}")
        return peer, rounds

    @property
    def has_net_faults(self) -> bool:
        return (self.net_drop_frac > 0.0 or self.net_delay_ms > 0.0
                or self.net_dup is not None
                or self.net_corrupt is not None
                or self.net_partition is not None)

    @classmethod
    def from_env(cls, env=None) -> Optional["ChaosSpec"]:
        text = (env or os.environ).get(CHAOS_ENV, "").strip()
        return cls.parse(text) if text else None

    def to_env(self) -> str:
        """Inverse of parse — for launchers exporting to children."""
        parts = []
        for key in self.__dataclass_fields__:
            if key.startswith("_"):
                continue
            val = getattr(self, key)
            default = self.__dataclass_fields__[key].default
            if val != default:
                parts.append(f"{key}={val}")
        return ",".join(parts)


class ChaosInjector:
    """Evaluates a :class:`ChaosSpec` at the engine/comm hook points."""

    def __init__(self, spec: Optional[ChaosSpec] = None,
                 rank: Optional[int] = None):
        self.spec = spec
        self.rank = rank
        self._collective_n = 0
        self._input_n = 0
        self._wire_n = 0
        self._partition_n = 0
        self._net_rng = random.Random(
            spec.net_seed if spec is not None
            and spec.net_seed is not None else 0)
        self.net_stats = {"dropped": 0, "duplicated": 0, "corrupted": 0,
                          "delayed": 0, "partitioned": 0}
        self._lock = threading.Lock()

    @property
    def armed(self) -> bool:
        return self.spec is not None

    def _resolve_rank(self) -> int:
        if self.rank is not None:
            return self.rank
        for var in ("RANK", "PROCESS_ID"):
            v = os.environ.get(var)
            if v is not None:
                try:
                    return int(v)
                except ValueError:
                    pass
        return 0

    # -- hooks ---------------------------------------------------------
    def on_step(self, step: int) -> None:
        """Engine calls at step entry (before dispatch)."""
        s = self.spec
        if s is None or s.kill_step is None:
            return
        if s.kill_rank is not None and self._resolve_rank() != s.kill_rank:
            return
        if step != s.kill_step:
            return
        sig = _SIGNALS[s.kill_signal.upper()]
        self._record("chaos_kill", step=step, sig=s.kill_signal,
                     rank=self._resolve_rank())
        logger.warning(f"chaos: killing rank {self._resolve_rank()} with "
                       f"{s.kill_signal} at step {step}")
        if sig == signal.SIGKILL:
            self._dump_flight("chaos_kill")  # SIGKILL leaves no handler
        os.kill(os.getpid(), sig)
        if sig == signal.SIGTERM:
            # SIGTERM is deliverable but deferred until the interpreter
            # checks — with a PreemptionGuard installed the handler just
            # flags; the step proceeds and the drain happens at the next
            # boundary, which is exactly the production sequence.
            time.sleep(0)

    def on_collective(self, op: str) -> None:
        """comm layer calls per traced collective."""
        s = self.spec
        if s is None or s.collective_k is None:
            return
        if s.collective_op and s.collective_op != op:
            return
        with self._lock:
            self._collective_n += 1
            n = self._collective_n
        if n != s.collective_k:
            return
        if s.collective_mode == "delay":
            self._record("chaos_collective_delay", op=op, k=n,
                         delay_s=s.collective_delay_s)
            logger.warning(f"chaos: delaying collective #{n} ({op}) by "
                           f"{s.collective_delay_s}s")
            time.sleep(s.collective_delay_s)
            return
        self._record("chaos_collective_fail", op=op, k=n)
        raise ChaosCollectiveError(
            f"chaos: injected failure of collective #{n} ({op})")

    def on_input_batch(self) -> None:
        """Input pipeline calls per microbatch pull."""
        s = self.spec
        if s is None or s.stall_input_step is None:
            return
        with self._lock:
            self._input_n += 1
            n = self._input_n
        if n != s.stall_input_step or s.stall_input_s <= 0:
            return
        self._record("chaos_input_stall", pull=n, stall_s=s.stall_input_s)
        logger.warning(f"chaos: stalling input pull #{n} by "
                       f"{s.stall_input_s}s")
        time.sleep(s.stall_input_s)

    # -- transport wire hooks ------------------------------------------
    def _partition_drops(self, peer: Optional[int]) -> bool:
        """True while ``peer``'s link is blackholed (counts one wire op
        against the partition window)."""
        target = self.spec.partition_target()
        if target is None or peer is None or peer != target[0]:
            return False
        with self._lock:
            if self._partition_n >= target[1]:
                return False
            self._partition_n += 1
        self.net_stats["partitioned"] += 1
        self._record("chaos_net_partition", peer=peer,
                     op=self._partition_n, window=target[1])
        return True

    def on_wire_tx(self, frame: bytes,
                   peer: Optional[int] = None) -> List[bytes]:
        """Channel send hook: one encoded frame in, the frames that
        actually hit the wire out ([] = dropped, two = duplicated)."""
        s = self.spec
        if s is None or not s.has_net_faults:
            return [frame]
        if self._partition_drops(peer):
            return []
        with self._lock:
            self._wire_n += 1
            n = self._wire_n
            dropped = (s.net_drop_frac > 0.0
                       and self._net_rng.random() < s.net_drop_frac)
        if dropped:
            self.net_stats["dropped"] += 1
            self._record("chaos_net_drop", peer=peer, frame=n)
            return []
        out = [frame]
        if s.net_dup and n % s.net_dup == 0:
            self.net_stats["duplicated"] += 1
            self._record("chaos_net_dup", peer=peer, frame=n)
            out = [frame, frame]
        if s.net_corrupt and n % s.net_corrupt == 0:
            from deepspeed_tpu.serving.transport.framing import \
                HEADER_BYTES
            body = len(frame) - HEADER_BYTES
            if body > 0:
                i = HEADER_BYTES + body // 2
                out = [fr[:i] + bytes([fr[i] ^ 0xFF]) + fr[i + 1:]
                       for fr in out]
                self.net_stats["corrupted"] += 1
                self._record("chaos_net_corrupt", peer=peer, frame=n)
        if s.net_delay_ms > 0.0:
            self.net_stats["delayed"] += 1
            time.sleep(s.net_delay_ms / 1e3)
        return out

    def on_wire_rx(self, chunk: bytes,
                   peer: Optional[int] = None) -> Optional[bytes]:
        """Channel recv hook: raw bytes in, bytes to feed the frame
        reader out (None = blackholed by a partition)."""
        s = self.spec
        if s is None or s.net_partition is None:
            return chunk
        return None if self._partition_drops(peer) else chunk

    # -- flight recorder + fleet journal (best-effort) ------------------
    def _record(self, kind: str, **fields) -> None:
        try:
            from deepspeed_tpu.observability.flight_recorder import \
                get_flight_recorder

            get_flight_recorder().record(kind, **fields)
        except Exception:
            pass
        try:
            from deepspeed_tpu.observability.journal import get_journal

            jr = get_journal()
            if jr is not None:
                # fault kind + seed + sequence position: everything a
                # replay needs to re-arm the injector and line the
                # injection up against the decisions around it
                spec = self.spec
                seed = (spec.net_seed if spec is not None else None)
                jr.chaos(kind, seed=seed, rank=self.rank, **fields)
        except Exception:
            pass

    @staticmethod
    def _dump_flight(reason: str) -> None:
        try:
            from deepspeed_tpu.observability.flight_recorder import \
                dump_flight_recorder

            dump_flight_recorder(reason)
        except Exception:
            pass


_INJECTOR: Optional[ChaosInjector] = None
_INJECTOR_LOCK = threading.Lock()


def get_chaos_injector() -> ChaosInjector:
    """Process-global injector; arms itself from DSTPU_CHAOS on first
    use. Inert (spec=None) when the env var is unset."""
    global _INJECTOR
    with _INJECTOR_LOCK:
        if _INJECTOR is None:
            _INJECTOR = ChaosInjector(spec=ChaosSpec.from_env())
        return _INJECTOR


def set_chaos_injector(inj: Optional[ChaosInjector]) -> None:
    """Arm (or disarm with None) the process-global injector directly —
    the in-process alternative to DSTPU_CHAOS for harnesses that inject
    transport faults on their own side of the wire (run_chaos_fleet)."""
    global _INJECTOR
    with _INJECTOR_LOCK:
        _INJECTOR = inj


def reset_chaos_injector() -> None:
    """Drop the singleton so the next access re-reads DSTPU_CHAOS
    (tests)."""
    global _INJECTOR
    with _INJECTOR_LOCK:
        _INJECTOR = None


# -- checkpoint corruption ---------------------------------------------------

def corrupt_checkpoint(ckpt_dir: str, mode: str = "flip",
                       target: Optional[str] = None) -> str:
    """Damage a checkpoint tag directory for corruption tests.

    mode:
      flip      — XOR one byte in the middle of the target file
      truncate  — drop the second half of the target file
      manifest  — overwrite the manifest with syntactically-broken JSON

    ``target`` is a path relative to ``ckpt_dir``; default picks the
    largest non-manifest file (the payload most likely to be torn).
    Returns the path of the damaged file."""
    from deepspeed_tpu.resilience.manifest import MANIFEST_FILE

    if mode == "manifest":
        path = os.path.join(ckpt_dir, MANIFEST_FILE)
        with open(path, "w") as f:
            f.write('{"kind": "dstpu_checkpoint_manifest", "truncated')
        return path
    if target is None:
        best, best_size = None, -1
        for root, _dirs, files in os.walk(ckpt_dir):
            for name in files:
                if name == MANIFEST_FILE:
                    continue
                p = os.path.join(root, name)
                size = os.path.getsize(p)
                if size > best_size:
                    best, best_size = p, size
        if best is None:
            raise FileNotFoundError(f"no files to corrupt in {ckpt_dir}")
        path = best
    else:
        path = os.path.join(ckpt_dir, target)
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(0, size // 2))
    elif mode == "flip":
        with open(path, "r+b") as f:
            f.seek(size // 2)
            b = f.read(1)
            f.seek(size // 2)
            f.write(bytes([(b[0] ^ 0xFF) if b else 0xFF]))
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    return path
