"""Deadline + retry/backoff policy for control-plane operations.

At pod scale a slow or wedged reduction is indistinguishable from a dead
peer ("The Big Send-off", PAPERS.md): the process-level control-plane ops
(rendezvous init, barrier, cross-process asserts, heartbeat I/O) are the
places a single sick host turns into a silent fleet-wide hang. This
module bounds them:

* :class:`RetryPolicy` — configurable deadline per attempt, exponential
  backoff with jitter between attempts (``resilience`` config block:
  ``init_timeout_s``, ``collective_timeout_s``, ``max_retries``,
  ``backoff_base_s``).
* :class:`CommTimeoutError` — the typed exhaustion error. It carries the
  flight-ring tail (the last seconds of runtime events) so whoever
  catches it — the elastic agent, a human reading the worker log — can
  distinguish "peer dead → restart group" from "transient → retry".
  Workers that die of it exit with :data:`TRANSIENT_EXIT_CODE` so the
  elastic agent classifies the restart without parsing logs.

Deadlines run the wrapped callable on a worker thread and abandon it on
expiry (Python cannot safely interrupt a blocked C extension call); the
leaked thread is daemonic and the caller is expected to tear the process
down — that is the point: a *diagnosed* restart instead of a hang.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from deepspeed_tpu.utils.logging import logger

#: sysexits.h EX_TEMPFAIL — the exit code a worker uses when it dies of a
#: CommTimeoutError, letting the elastic agent classify the failure as
#: transient (retry with backoff) without parsing stderr.
TRANSIENT_EXIT_CODE = 75


class CommTimeoutError(RuntimeError):
    """A control-plane op exhausted its deadline/retry budget.

    Attributes:
      op:           operation name ("init_distributed", "barrier", ...)
      timeout_s:    per-attempt deadline that expired
      attempts:     how many attempts were made
      flight_tail:  formatted tail of the flight-recorder ring at raise
                    time (what the worker was doing when it wedged)
    """

    exit_code = TRANSIENT_EXIT_CODE

    def __init__(self, op: str, timeout_s: Optional[float] = None,
                 attempts: int = 1, flight_tail: str = "",
                 cause: Optional[BaseException] = None):
        self.op = op
        self.timeout_s = timeout_s
        self.attempts = attempts
        self.flight_tail = flight_tail
        msg = (f"control-plane op {op!r} failed after {attempts} "
               f"attempt(s)"
               + (f" (deadline {timeout_s:g}s per attempt)"
                  if timeout_s else "")
               + (f": {cause}" if cause is not None else ""))
        if flight_tail:
            msg += f"\nflight-recorder tail:\n{flight_tail}"
        super().__init__(msg)


def _flight_tail(last: int = 24) -> str:
    """Best-effort flight-ring tail; never raises (the recorder import is
    jax-free, but a half-torn process must still be able to raise)."""
    try:
        from deepspeed_tpu.observability.flight_recorder import \
            get_flight_recorder

        return get_flight_recorder().tail_lines(last=last)
    except Exception:
        return ""


class _DeadlineExpired(Exception):
    pass


def run_with_deadline(fn: Callable[[], Any], timeout_s: Optional[float],
                      name: str = "op") -> Any:
    """Run ``fn`` bounded by ``timeout_s`` (None/<=0 = unbounded, called
    inline). On expiry raises :class:`_DeadlineExpired`; the worker
    thread is abandoned (daemon) — see module docstring."""
    if not timeout_s or timeout_s <= 0:
        return fn()
    result: list = []
    error: list = []
    done = threading.Event()

    def target():
        try:
            result.append(fn())
        except BaseException as e:  # re-raised on the caller thread
            error.append(e)
        finally:
            done.set()

    t = threading.Thread(target=target, name=f"deadline-{name}",
                         daemon=True)
    t.start()
    if not done.wait(timeout=timeout_s):
        raise _DeadlineExpired(name)
    if error:
        raise error[0]
    return result[0]


@dataclass
class RetryPolicy:
    """Deadline + exponential backoff + jitter for control-plane ops.

    ``collective_timeout_s`` / ``init_timeout_s`` of ``None`` (the
    defaults) leave the corresponding ops unbounded — zero behavior
    change until the ``resilience`` config block opts in.
    """

    init_timeout_s: Optional[float] = None
    collective_timeout_s: Optional[float] = None
    max_retries: int = 2
    backoff_base_s: float = 1.0
    backoff_max_s: float = 30.0
    jitter: float = 0.25

    @classmethod
    def from_config(cls, rcfg) -> "RetryPolicy":
        """Build from a ResilienceConfig block (or anything duck-typed)."""
        if rcfg is None:
            return cls()
        return cls(
            init_timeout_s=getattr(rcfg, "init_timeout_s", None),
            collective_timeout_s=getattr(rcfg, "collective_timeout_s",
                                         None),
            max_retries=int(getattr(rcfg, "max_retries", 2)),
            backoff_base_s=float(getattr(rcfg, "backoff_base_s", 1.0)),
            backoff_max_s=float(getattr(rcfg, "backoff_max_s", 30.0)),
            jitter=float(getattr(rcfg, "jitter", 0.25)))

    def backoff_s(self, attempt: int) -> float:
        """Sleep before retry ``attempt`` (1-based): exponential with
        multiplicative jitter, capped at ``backoff_max_s``."""
        base = min(self.backoff_max_s,
                   self.backoff_base_s * (2.0 ** max(0, attempt - 1)))
        return base * (1.0 + self.jitter * random.random())

    def run(self, op: str, fn: Callable[[], Any],
            timeout_s: Optional[float] = None,
            retryable: Callable[[BaseException], bool] = None) -> Any:
        """Run ``fn`` under the policy: each attempt bounded by
        ``timeout_s`` (default ``collective_timeout_s``), up to
        ``max_retries`` retries with backoff between. Exhaustion (or a
        non-retryable error after a timeout was configured) raises
        :class:`CommTimeoutError` with the flight tail attached; with no
        timeout configured the call is a plain passthrough."""
        timeout_s = (self.collective_timeout_s if timeout_s is None
                     else timeout_s)
        if not timeout_s or timeout_s <= 0:
            return fn()
        attempts = self.max_retries + 1
        last: Optional[BaseException] = None
        for attempt in range(1, attempts + 1):
            try:
                return run_with_deadline(fn, timeout_s, name=op)
            except _DeadlineExpired:
                last = None
                logger.warning(
                    f"resilience: {op} exceeded {timeout_s:g}s deadline "
                    f"(attempt {attempt}/{attempts})")
            except Exception as e:  # noqa: BLE001 — classified below
                if retryable is not None and not retryable(e):
                    raise
                last = e
                logger.warning(
                    f"resilience: {op} failed (attempt "
                    f"{attempt}/{attempts}): {e}")
            if attempt < attempts:
                delay = self.backoff_s(attempt)
                logger.info(f"resilience: retrying {op} in {delay:.2f}s")
                time.sleep(delay)
        raise CommTimeoutError(op, timeout_s=timeout_s, attempts=attempts,
                               flight_tail=_flight_tail(), cause=last)
