"""DeepCompile-analog pass pipeline.

Reference: ``deepspeed/compile/`` (``make_backend`` backend.py:246 with
passes ``zero1_compile / zero3_compile / prefetch / selective_gather /
offload_parameters / offload_adam_states / offload_activation /
sp_compile / long_context_checkpointing``) + ``csrc/compile/`` native
helpers — graph passes that rewrite a captured fx graph to insert
gather/reduce/offload scheduling.

TPU mapping: most of what DeepCompile inserts by graph surgery is what
XLA/GSPMD *already does* given the right declarations — so the passes
here operate on the *declarations* (model config + engine config) before
``initialize``, not on a captured graph:

  | reference pass               | this pipeline                          |
  |------------------------------|----------------------------------------|
  | zero1/zero3_compile          | sharding plan from zero stage (native: |
  |                              | runtime/sharding.py; pass validates)   |
  | prefetch / selective_gather  | XLA latency-hiding scheduler (no-op,   |
  |                              | reported)                              |
  | offload_parameters           | zero_optimization.offload_param check  |
  | offload_adam_states          | offload_optimizer → host tier          |
  | offload_activation           | remat policy 'offload_dots_host'       |
  | sp_compile                   | AutoSP strategy selection              |
  | long_context_checkpointing   | enable remat + tiled/chunked compute   |
  |                              | above a sequence-length threshold      |

Usage (before building the engine)::

    model, report = compile_model(model, config, mesh)
    engine, *_ = dstpu.initialize(model=model, config=config)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import log_dist, logger

LONG_CONTEXT_THRESHOLD = 32768


@dataclasses.dataclass
class PassResult:
    name: str
    applied: bool
    note: str = ""


PASSES: List[Tuple[str, Callable]] = []


def register_pass(name: str):
    """Register a pass fn(model, config, mesh) → (model, PassResult)."""
    def deco(fn):
        PASSES.append((name, fn))
        return fn

    return deco


def _model_cfg(model):
    return getattr(model, "config", None)


@register_pass("zero_compile")
def _zero_compile(model, config, mesh):
    """zero1/zero3_compile analog: the sharding plan IS the compiled
    gather/reduce schedule; validate stage vs mesh so misdeclarations
    surface at compile time, not step time."""
    stage = config.zero_optimization.stage
    note = f"stage {stage} → declarative sharding plan (GSPMD collectives)"
    if mesh is not None and stage >= 1:
        data = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
        if data == 1:
            note += "; WARNING: no data-parallel extent, nothing to shard"
    return model, PassResult("zero_compile", True, note)


@register_pass("prefetch")
def _prefetch(model, config, mesh):
    return model, PassResult(
        "prefetch", False,
        "no-op on TPU: XLA's latency-hiding scheduler overlaps the "
        "param all-gathers DeepCompile prefetches by hand")


@register_pass("selective_gather")
def _selective_gather(model, config, mesh):
    thresh = config.zero_optimization.param_persistence_threshold
    return model, PassResult(
        "selective_gather", bool(thresh),
        f"persistence threshold {thresh}: small params stay replicated"
        if thresh else "off (param_persistence_threshold=0)")


@register_pass("offload_parameters")
def _offload_parameters(model, config, mesh):
    off = config.zero_optimization.offload_param
    on = off is not None and (off.device or "none") != "none"
    return model, PassResult(
        "offload_parameters", on,
        f"param offload tier ({off.device})" if on else "off")


@register_pass("offload_adam_states")
def _offload_adam(model, config, mesh):
    off = config.zero_optimization.offload_optimizer
    on = off is not None and (off.device or "none") != "none"
    return model, PassResult(
        "offload_adam_states", on,
        f"host optimizer tier ({off.device})" if on else "off")


@register_pass("offload_activation")
def _offload_activation(model, config, mesh):
    on = (config.activation_checkpointing.cpu_checkpointing
          or config.activation_checkpointing.policy == "offload_dots_host")
    return model, PassResult(
        "offload_activation", on,
        "checkpointed dots spill to pinned host memory" if on else "off")


@register_pass("sp_compile")
def _sp_compile(model, config, mesh):
    """AutoSP (reference compile/passes/sp_compile.py + sequence/auto_sp)."""
    from deepspeed_tpu.parallel.auto_sp import auto_wrap_model_for_sp

    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    if sp <= 1 or _model_cfg(model) is None:
        return model, PassResult("sp_compile", False, "no sp axis")
    new = auto_wrap_model_for_sp(model, mesh)
    mode = getattr(_model_cfg(new), "sp_mode", None)
    return new, PassResult("sp_compile", True, f"sp={sp} → {mode}")


@register_pass("long_context_checkpointing")
def _long_context(model, config, mesh):
    """Reference compile/passes/long_context_checkpointing.py: auto-insert
    activation checkpointing (+ tiled compute) for long sequences."""
    cfg = _model_cfg(model)
    if cfg is None or getattr(cfg, "max_seq_len", 0) < LONG_CONTEXT_THRESHOLD:
        return model, PassResult("long_context_checkpointing", False,
                                 "sequence below threshold")
    changes = {}
    if not getattr(cfg, "remat", True):
        changes["remat"] = True
    if getattr(cfg, "tiled_logits", 0) <= 1:
        changes["tiled_logits"] = max(8, cfg.max_seq_len // 4096)
    if getattr(cfg, "attn_chunks", 0) <= 1 and cfg.max_seq_len >= 131072:
        changes["attn_chunks"] = cfg.max_seq_len // 16384
    if not changes:
        return model, PassResult("long_context_checkpointing", False,
                                 "already configured")
    new_cfg = dataclasses.replace(cfg, **changes)
    return type(model)(new_cfg), PassResult(
        "long_context_checkpointing", True,
        f"seq={cfg.max_seq_len}: set {sorted(changes)}")


def compile_model(model, config, mesh=None,
                  passes: Optional[List[str]] = None
                  ) -> Tuple[Any, List[PassResult]]:
    """Run the pass pipeline (reference make_backend compile/backend.py:246
    — there a torch.compile backend, here a pre-initialize transform).

    ``passes``: subset of pass names to run (default: all registered).
    Returns (possibly-rebuilt model, per-pass report).
    """
    report: List[PassResult] = []
    selected = set(passes) if passes is not None else None
    if selected is not None:
        known = {name for name, _ in PASSES}
        unknown = selected - known
        if unknown:
            raise ValueError(f"unknown compile passes {sorted(unknown)}; "
                             f"registered: {sorted(known)}")
    for name, fn in PASSES:
        if selected is not None and name not in selected:
            continue
        try:
            model, res = fn(model, config, mesh)
        except Exception as e:  # a pass must never break the build
            logger.warning(f"compile pass '{name}' failed: {e}")
            res = PassResult(name, False, f"error: {e}")
        report.append(res)
    applied = [r.name for r in report if r.applied]
    log_dist(f"compile passes applied: {applied or 'none'}", ranks=[0])
    return model, report
