"""Compile-time model transformation passes (reference: deepspeed/compile/)."""

from deepspeed_tpu.compile.passes import (  # noqa: F401
    PASSES,
    compile_model,
    register_pass,
)
