"""Paged (blocked-KV) decode attention kernel.

Reference: the ragged inference ops in
``inference/v2/kernels/ragged_ops/blocked_flash`` — CUDA flash attention
reading K/V directly from paged cache blocks via a block table, so decode
never materializes a per-token contiguous context.

TPU re-design: one Pallas kernel per sequence walks that sequence's pages
(innermost grid dim) with the block table as a scalar-prefetch operand —
the page id feeds the BlockSpec index_map, so the next page's DMA is
issued ahead of the body (the TPU analog of the reference's async-copy
pipeline). Online-softmax accumulation over pages in fp32 scratch; GQA
handled by grouping query heads per kv head (static in-kernel loop, since
Mosaic block shapes cannot tile the kv-head axis independently).

Layout matches inference/ragged/kv_cache.py: one layer's pool is
``kv[num_blocks, block_size, 2, kv_heads, head_dim]`` — the same array is
fetched one page per grid step; the kernel reads K from plane 0 and V
from plane 1 of the same block.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _fold_page(q, k, v, visible, m_ref, l_ref, acc_ref, rows: slice,
               nrows: int):
    """Fold one K/V page into the online-softmax state for one kv head.

    q [nrows, hd] fp32 (pre-scaled); k/v [bs, hd] fp32; visible
    [nrows, bs]; scratch refs indexed at ``rows``.
    """
    sc = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    sc = jax.lax.select(visible, sc, jnp.full_like(sc, NEG_INF))

    m_prev = m_ref[rows, :1]                      # [nrows, 1]
    m_cur = jnp.max(sc, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    # explicit zero for masked columns: when every score so far is
    # the NEG_INF sentinel, exp(sc - m_new) == exp(0) would count them
    e = jnp.exp(sc - m_new)
    p = jax.lax.select(visible, e, jnp.zeros_like(e))

    l_new = alpha * l_ref[rows, :1] + jnp.sum(p, axis=1, keepdims=True)
    acc_ref[rows, :] = acc_ref[rows, :] * alpha + jax.lax.dot_general(
        p, v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    m_ref[rows, :] = jnp.broadcast_to(m_new, (nrows, m_ref.shape[1]))
    l_ref[rows, :] = jnp.broadcast_to(l_new, (nrows, l_ref.shape[1]))


def _visit(q_ref, kv_ref, m_ref, l_ref, acc_ref, visible, *, bs: int,
           nkv: int, gp: int, scale: float):
    """Fold one K/V page into the online-softmax state (decode)."""
    for n in range(nkv):  # static unroll over kv heads
        q = q_ref[0, n].astype(jnp.float32) * scale   # [gp, hd]
        k = kv_ref[0, :, 0, n].astype(jnp.float32)    # [bs, hd]
        v = kv_ref[0, :, 1, n].astype(jnp.float32)    # [bs, hd]
        _fold_page(q, k, v, visible, m_ref, l_ref, acc_ref,
                   slice(n * gp, (n + 1) * gp), gp)


def _kernel(bt_ref, ctx_ref, q_ref, *refs, bs: int, nkv: int, gp: int,
            scale: float, pages: int):
    # refs = pages kv page blocks, then out_ref + 3 scratch refs. The
    # pages fold sequentially in ascending page order — the identical
    # op sequence for every pages_per_compute_block, so outputs stay
    # bit-identical across the autotuner's geometry candidates.
    kv_refs = refs[:pages]
    out_ref, m_ref, l_ref, acc_ref = refs[pages:]
    s = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    ctx = ctx_ref[s]
    for i, kv_ref in enumerate(kv_refs):
        cols = ((j * pages + i) * bs
                + jax.lax.broadcasted_iota(jnp.int32, (gp, bs), 1))
        visible = cols < ctx

        # pages past the context: no compute (and the index_map
        # re-requests the same page: no DMA)
        @pl.when((j * pages + i) * bs < ctx)
        def _visit_page(kv_ref=kv_ref, visible=visible):
            _visit(q_ref, kv_ref, m_ref, l_ref, acc_ref, visible,
                   bs=bs, nkv=nkv, gp=gp, scale=scale)

    @pl.when(j == nj - 1)
    def _finalize():
        for n in range(nkv):
            rows = slice(n * gp, (n + 1) * gp)
            l = l_ref[rows, :1]
            l = jax.lax.select(l == 0.0, jnp.ones_like(l), l)  # dead slots
            out_ref[0, n] = (acc_ref[rows, :] / l).astype(out_ref.dtype)


def _prefill_kernel(pos0_ref, ctx_ref, bt_ref, q_ref, *refs, bs: int,
                    nkv: int, g: int, tq: int, scale: float, pages: int):
    kv_refs = refs[:pages]
    out_ref, m_ref, l_ref, acc_ref = refs[pages:]
    s = pl.program_id(0)
    j = pl.program_id(1)
    nj = pl.num_programs(1)
    rows = tq * g  # row layout per kv head: query-major, group-minor

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    pos0 = pos0_ref[s]
    ctx = ctx_ref[s]
    # query absolute position per row (row r = query r // g, group r % g)
    qpos = pos0 + jax.lax.broadcasted_iota(jnp.int32, (rows, bs), 0) // g
    for i, kv_ref in enumerate(kv_refs):
        cols = ((j * pages + i) * bs
                + jax.lax.broadcasted_iota(jnp.int32, (rows, bs), 1))
        # causal within the segment + bounded by the segment's total
        # context; dead/padded segments have ctx == 0 -> nothing visible
        visible = jnp.logical_and(cols <= qpos, cols < ctx)

        @pl.when((j * pages + i) * bs < ctx)
        def _visit_page(kv_ref=kv_ref, visible=visible):
            for n in range(nkv):
                # q layout is [S, nkv, tq*g, hd] (wrapper pre-transposes):
                # only leading-dim integer indexing, which Mosaic supports
                q = q_ref[0, n].astype(jnp.float32) * scale  # [rows, hd]
                k = kv_ref[0, :, 0, n].astype(jnp.float32)   # [bs, hd]
                v = kv_ref[0, :, 1, n].astype(jnp.float32)
                _fold_page(q, k, v, visible, m_ref, l_ref, acc_ref,
                           slice(n * rows, (n + 1) * rows), rows)

    @pl.when(j == nj - 1)
    def _finalize():
        for n in range(nkv):
            rsl = slice(n * rows, (n + 1) * rows)
            l = l_ref[rsl, :1]
            l = jax.lax.select(l == 0.0, jnp.ones_like(l), l)
            out_ref[0, n] = (acc_ref[rsl, :] / l).astype(out_ref.dtype)


def paged_prefill_attention(q: jax.Array, kv_layer: jax.Array,
                            block_table: jax.Array, seg_pos0: jax.Array,
                            context_lens: jax.Array,
                            scale: float = None,
                            pages_per_compute_block: int = 1) -> jax.Array:
    """Chunked-prefill attention over paged KV (SplitFuse chunk step).

    Each segment is one sequence's contiguous chunk of ``Tq`` new tokens
    (queries at absolute positions pos0..pos0+Tq-1), already scattered
    into the paged cache. Queries attend their sequence's full paged
    history causally.

    q            [S, Tq, num_heads, head_dim] (padded rows have garbage;
                 their outputs are well-defined zeros only if the whole
                 segment is dead — callers slice real rows out)
    kv_layer     [num_blocks, block_size, 2, kv_heads, head_dim]
    block_table  [S, max_pages]
    seg_pos0     [S] absolute position of each segment's first query
    context_lens [S] keys visible to the segment's LAST query (pos0 +
                 n_real_tokens); 0 marks a dead segment

    ``pages_per_compute_block`` (kernels config / autotuner axis) folds
    that many KV pages per grid step — fewer grid steps, more DMA in
    flight per step. Outputs are bit-identical for every legal value
    (pages fold in the same sequential order).

    Returns [S, Tq, num_heads, head_dim] in q.dtype.
    """
    S, tq, nh, hd = q.shape
    nb, bs, _, nkv, _ = kv_layer.shape
    Bm = block_table.shape[1]
    if nh % nkv:
        raise ValueError(f"num_heads {nh} not a multiple of kv_heads {nkv}")
    g = nh // nkv
    if (tq * g) % 8:
        raise ValueError(f"Tq*group ({tq}*{g}) must be a multiple of 8")
    if scale is None:
        scale = 1.0 / (hd ** 0.5)

    # [S, Tq, nh, hd] -> [S, nkv, Tq*g, hd]: per-kv-head rows, query-
    # major / group-minor (matches the kernel's qpos = row // g)
    qg = (q.reshape(S, tq, nkv, g, hd)
          .transpose(0, 2, 1, 3, 4)
          .reshape(S, nkv, tq * g, hd))

    P = max(1, min(int(pages_per_compute_block), Bm))

    def page(s, j, pos0, ctx, bt, i=0):
        # clamp beyond-context iterations to the last live page: Mosaic
        # skips the DMA when consecutive grid steps request the same block
        last = jax.lax.max(ctx[s] - 1, 0) // bs
        j_eff = jax.lax.min(j * P + i, last)
        return jax.lax.min(jax.lax.max(bt[s, j_eff], 0), nb - 1)

    def kv_spec(i):
        return pl.BlockSpec(
            (1, bs, 2, nkv, hd),
            lambda s, j, pos0, ctx, bt: (page(s, j, pos0, ctx, bt, i),
                                         0, 0, 0, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(S, -(-Bm // P)),
        in_specs=[
            pl.BlockSpec((1, nkv, tq * g, hd),
                         lambda s, j, pos0, ctx, bt: (s, 0, 0, 0)),
        ] + [kv_spec(i) for i in range(P)],
        out_specs=pl.BlockSpec((1, nkv, tq * g, hd),
                               lambda s, j, pos0, ctx, bt: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nkv * tq * g, 128), jnp.float32),
            pltpu.VMEM((nkv * tq * g, 128), jnp.float32),
            pltpu.VMEM((nkv * tq * g, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_prefill_kernel, bs=bs, nkv=nkv, g=g, tq=tq,
                          scale=float(scale), pages=P),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, nkv, tq * g, hd), q.dtype),
        interpret=_interpret(),
    )(seg_pos0.astype(jnp.int32), context_lens.astype(jnp.int32),
      block_table.astype(jnp.int32), qg, *([kv_layer] * P))
    return (out.reshape(S, nkv, tq, g, hd)
            .transpose(0, 2, 1, 3, 4)
            .reshape(S, tq, nh, hd))


def paged_decode_attention(q: jax.Array, kv_layer: jax.Array,
                           block_table: jax.Array, context_lens: jax.Array,
                           scale: float = None,
                           pages_per_compute_block: int = 1) -> jax.Array:
    """Decode attention over a paged KV pool.

    q            [S, num_heads, head_dim] — one query token per sequence
    kv_layer     [num_blocks, block_size, 2, kv_heads, head_dim]
    block_table  [S, max_pages] int32 page ids (entries past the context
                 may be stale/scratch; they are read but masked)
    context_lens [S] int32 — keys visible per sequence (including the
                 token written this step); 0 marks a dead slot (output 0)

    ``pages_per_compute_block`` folds that many KV pages per grid step
    (kernels config / autotuner axis); bit-identical for every legal
    value — the pages fold in the same sequential order.

    Returns [S, num_heads, head_dim] in q.dtype.
    """
    S, nh, hd = q.shape
    nb, bs, _, nkv, _ = kv_layer.shape
    Bm = block_table.shape[1]
    if nh % nkv:
        raise ValueError(f"num_heads {nh} not a multiple of kv_heads {nkv}")
    g = nh // nkv
    gp = max(8, -(-g // 8) * 8)  # pad head group to the fp32 sublane tile
    if scale is None:
        scale = 1.0 / (hd ** 0.5)

    qg = q.reshape(S, nkv, g, hd)
    if gp != g:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, gp - g), (0, 0)))

    P = max(1, min(int(pages_per_compute_block), Bm))

    def page(s, j, bt, ctx, i=0):
        # clamp beyond-context iterations to the last live page: Mosaic
        # skips the DMA when consecutive grid steps request the same block
        last = jax.lax.max(ctx[s] - 1, 0) // bs
        j_eff = jax.lax.min(j * P + i, last)
        return jax.lax.min(jax.lax.max(bt[s, j_eff], 0), nb - 1)

    def kv_spec(i):
        return pl.BlockSpec(
            (1, bs, 2, nkv, hd),
            lambda s, j, bt, ctx: (page(s, j, bt, ctx, i), 0, 0, 0, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(S, -(-Bm // P)),
        in_specs=[
            pl.BlockSpec((1, nkv, gp, hd), lambda s, j, bt, ctx: (s, 0, 0, 0)),
        ] + [kv_spec(i) for i in range(P)],
        out_specs=pl.BlockSpec((1, nkv, gp, hd),
                               lambda s, j, bt, ctx: (s, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nkv * gp, 128), jnp.float32),  # running max
            pltpu.VMEM((nkv * gp, 128), jnp.float32),  # running denom
            pltpu.VMEM((nkv * gp, hd), jnp.float32),   # weighted-value acc
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, bs=bs, nkv=nkv, gp=gp,
                          scale=float(scale), pages=P),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((S, nkv, gp, hd), q.dtype),
        interpret=_interpret(),
    )(block_table.astype(jnp.int32), context_lens.astype(jnp.int32),
      qg, *([kv_layer] * P))
    return out[:, :, :g, :].reshape(S, nh, hd)
