"""Flash attention (Pallas TPU kernel, fwd + bwd) — GQA-native + segment ids.

The training-attention kernel of the framework — the role the reference's
fused softmax/attention CUDA kernels play (csrc/transformer/
softmax_kernels.cu, general_kernels.cu) and the memory-efficient
Evoformer/blocked-flash kernels (csrc/deepspeed4science/evoformer_attn,
inference/v2/kernels/ragged_ops/blocked_flash).

Algorithm: standard streaming-softmax flash attention. O(S) memory:
softmax statistics (m, l) are carried across key blocks; the backward
recomputes P blockwise from the saved logsumexp instead of storing the
[S, S] score matrix.

GQA is native: K/V stay at ``num_kv_heads`` in HBM and every q head's
block spec index-maps to its kv head (q-head h → kv-head h // group).
No pre-repeat — for Llama-3-8B (32q/8kv) that is 4x less KV bandwidth
and HBM than repeating. dK/dV accumulate across the q-head group inside
the kernel (grid folds group × q-blocks into one accumulation loop).

Segment ids (packed sequences) mask cross-segment attention blockwise,
so packed batches keep the O(S) kernel instead of falling back to the
O(S^2) XLA path. Non-causal is supported (padding is masked via a
synthesized segment tensor when needed).

Layout: [B, H, S, D] inside the kernels (the public wrapper transposes
from the model's [B, S, H, D]). fp32 accumulation on the MXU
(preferred_element_type), bf16 streaming.

Blocks default to 128x128 (MXU-shaped); 512 measured best on v5e at
seq >= 1024 (see ops/attention.py dispatch).

Causal grids are *triangle-packed*: the kernels iterate a static work
list of live (q-block, k-block) pairs via scalar prefetch instead of a
dense nq x nk grid with a skip gate. A skipped grid step still costs
its K/V block DMA and grid overhead — at long context that is ~2x
wasted HBM bandwidth, which is exactly what bounds the kernel at D=128.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from deepspeed_tpu.utils import jaxcompat

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30
# lse/delta carry one scalar per query row, broadcast across lanes for
# tiling; 8 lanes (the fp32 sublane tile) instead of 128 cuts their
# HBM traffic 16x — they otherwise write/read 2x the attention output
STAT_LANES = 8


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _num_items(nq: int, nk: int, causal: bool) -> int:
    """Work items in the (triangle-)packed grid. Causal requires
    block_q == block_k, giving the exact lower triangle nq*(nq+1)/2.

    Guard: the packed decomposition runs in int32 with an fp32 sqrt
    seed + double ±1 correction (_decompose_q/_decompose_kv) — exact
    while the item count fits int32. nq = 2^15 (S = 32M at block 1024)
    is still ~5e8 items; anything larger must raise, not corrupt."""
    t_total = nq * (nq + 1) // 2 if causal else nq * nk
    if t_total >= 2 ** 31:
        raise ValueError(
            f"flash grid item count {t_total} overflows the int32 packed "
            f"decomposition (nq={nq}, nk={nk}); use a larger block size")
    return t_total


def _decompose_q(t, nq: int, nk: int, causal: bool):
    """Work item t → (iq, ik), q-block-major (all k-blocks of one
    q-block consecutive — the o/lse accumulation run). Causal packs the
    lower triangle: t = iq(iq+1)/2 + ik. Closed form (fp32 sqrt + ±1
    correction — exact for t < 2^23, i.e. any S the scalar core can
    count): no SMEM work lists, so sequence length is unbounded."""
    if not causal:
        return t // nk, t % nk
    tf = t.astype(jnp.float32)
    iq = jnp.floor((jnp.sqrt(8.0 * tf + 1.0) - 1.0) * 0.5).astype(jnp.int32)
    # two ±1 corrections each way (matching _decompose_kv): one fp32 ulp
    # at large t can put the closed form two integers off; a silently
    # wrong (iq, ik) would corrupt attention with no error
    iq = jnp.where(iq * (iq + 1) // 2 > t, iq - 1, iq)
    iq = jnp.where(iq * (iq + 1) // 2 > t, iq - 1, iq)
    iq = jnp.where((iq + 1) * (iq + 2) // 2 <= t, iq + 1, iq)
    iq = jnp.where((iq + 1) * (iq + 2) // 2 <= t, iq + 1, iq)
    ik = t - iq * (iq + 1) // 2
    return iq, ik


def _decompose_kv(t, nq: int, nk: int, causal: bool):
    """k-block-major twin (the dk/dv accumulation run). Causal: for
    k-block ik the q-blocks ik..nq-1 are live; cum(ik) = ik*nq -
    ik(ik-1)/2 items precede it."""
    if not causal:
        return t % nq, t // nq
    a = 2 * nq + 1
    tf = t.astype(jnp.float32)
    disc = jnp.maximum(a * a - 8.0 * tf, 0.0)
    ik = jnp.floor((a - jnp.sqrt(disc)) * 0.5).astype(jnp.int32)
    ik = jnp.clip(ik, 0, nq - 1)

    def cum(i):
        return i * nq - i * (i - 1) // 2

    ik = jnp.where(cum(ik) > t, ik - 1, ik)
    ik = jnp.where(cum(ik) > t, ik - 1, ik)
    ik = jnp.where(cum(ik + 1) <= t, ik + 1, ik)
    ik = jnp.where(cum(ik + 1) <= t, ik + 1, ik)
    iq = ik + (t - cum(ik))
    return iq, ik


def _kv_row(b, hq: int, hkv: int):
    """GQA index map: flattened q row b = batch*hq + h → kv row for
    kv head h // (hq // hkv). The load-bearing GQA invariant — forward
    and backward must share it."""
    return (b // hq) * hkv + (b % hq) // (hq // hkv)


def _mask(s, *, iq, ik, causal: bool, seg_q, seg_k,
          block_q: int, block_k: int):
    """Apply causal and/or segment masks to a [BQ, BK] score block."""
    if causal:
        qpos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        kpos = ik * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        s = jnp.where(kpos <= qpos, s, NEG_INF)
    if seg_q is not None:
        same = seg_q[:, None] == seg_k[None, :]  # [BQ, BK]
        s = jnp.where(same, s, NEG_INF)
    return s


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(*refs, scale: float, causal: bool,
                has_segments: bool, block_q: int, block_k: int,
                nq: int, nk: int):
    if has_segments:
        q_ref, k_ref, v_ref, sq_ref, sk_ref, o_ref, lse_ref, \
            acc_sc, m_sc, l_sc = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_sc, m_sc, l_sc = refs
        sq_ref = sk_ref = None
    t = pl.program_id(1)
    # triangle-packed grid: every step is live; q-major ordering means a
    # q-block's run starts at its first k-block and ends at the diagonal
    iq, ik = _decompose_q(t, nq, nk, causal)
    first = ik == 0
    last = (ik == iq) if causal else (ik == nk - 1)

    @pl.when(first)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    q = q_ref[0]  # [BQ, D]
    k = k_ref[0]  # [BK, D]
    v = v_ref[0]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # [BQ, BK]
    s = _mask(s, iq=iq, ik=ik, causal=causal,
              seg_q=sq_ref[0] if has_segments else None,
              seg_k=sk_ref[0] if has_segments else None,
              block_q=block_q, block_k=block_k)

    m_prev = m_sc[:, :1]  # [BQ, 1]
    m_cur = jnp.max(s, axis=1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)  # [BQ, 1]
    p = jnp.exp(s - m_new)  # [BQ, BK]
    l_new = l_sc[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)  # [BQ, D]
    acc_sc[:] = acc_sc[:] * alpha + pv
    m_sc[:] = jnp.broadcast_to(m_new, m_sc.shape)
    l_sc[:] = jnp.broadcast_to(l_new, l_sc.shape)

    @pl.when(last)
    def _finalize():
        l = l_sc[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_sc[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = (m_sc[:] + jnp.log(l_safe)).astype(jnp.float32)


def _flash_fwd(q, k, v, seg_q, seg_k, scale: float, causal: bool,
               hq: int, hkv: int,
               block_q: int, block_k: int) -> Tuple[jax.Array, jax.Array]:
    """q: [B*Hq, S, D]; k,v: [B*Hkv, S, D]; seg_*: [B, S] or None.

    Returns (o [B*Hq, S, D], lse [B*Hq, S, STAT_LANES]).
    """
    BHq, S, D = q.shape
    nq, nk = S // block_q, S // block_k
    has_segments = seg_q is not None

    def kv_row(b):
        return _kv_row(b, hq, hkv)

    def d_q(t):
        return _decompose_q(t, nq, nk, causal)

    in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, t: (b, d_q(t)[0], 0)),
        pl.BlockSpec((1, block_k, D),
                     lambda b, t: (kv_row(b), d_q(t)[1], 0)),
        pl.BlockSpec((1, block_k, D),
                     lambda b, t: (kv_row(b), d_q(t)[1], 0)),
    ]
    args = [q, k, v]
    if has_segments:
        in_specs += [
            pl.BlockSpec((1, block_q), lambda b, t: (b // hq, d_q(t)[0])),
            pl.BlockSpec((1, block_k), lambda b, t: (b // hq, d_q(t)[1])),
        ]
        args += [seg_q, seg_k]
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, has_segments=has_segments,
        block_q=block_q, block_k=block_k, nq=nq, nk=nk)
    o, lse = pl.pallas_call(
        kernel,
        grid=(BHq, _num_items(nq, nk, causal)),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, t: (b, d_q(t)[0], 0)),
            pl.BlockSpec((1, block_q, STAT_LANES),
                         lambda b, t: (b, d_q(t)[0], 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, STAT_LANES), jnp.float32),
            pltpu.VMEM((block_q, STAT_LANES), jnp.float32),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BHq, S, D), q.dtype),
            jax.ShapeDtypeStruct((BHq, S, STAT_LANES), jnp.float32),
        ],
        compiler_params=jaxcompat.pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(*args)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dkdv_kernel(*refs, scale: float, causal: bool,
                     has_segments: bool, block_q: int, block_k: int,
                     nq: int, nk: int):
    if has_segments:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, sq_ref, sk_ref, \
            dk_ref, dv_ref, dk_sc, dv_sc = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, \
            dk_ref, dv_ref, dk_sc, dv_sc = refs
        sq_ref = sk_ref = None
    # grid: (B*Hkv, T, group) — T iterates the k-block-major packed
    # triangle, the inner dim the q-head group, so dk/dv accumulate over
    # (GQA group x live q-blocks) in scratch per k-block run.
    t, mem = pl.program_id(1), pl.program_id(2)
    g = pl.num_programs(2)
    iq, ik = _decompose_kv(t, nq, nk, causal)
    run_start = ik if causal else 0
    first = jnp.logical_and(mem == 0, iq == run_start)
    last = jnp.logical_and(mem == g - 1, iq == nq - 1)

    @pl.when(first)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0][:, :1]  # [BQ, 1]
    delta = delta_ref[0][:, :1]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale  # [BQ, BK]
    s = _mask(s, iq=iq, ik=ik, causal=causal,
              seg_q=sq_ref[0] if has_segments else None,
              seg_k=sk_ref[0] if has_segments else None,
              block_q=block_q, block_k=block_k)
    p = jnp.exp(s - lse)  # [BQ, BK]
    # dv += p^T @ do
    dv_sc[:] += jax.lax.dot_general(
        p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    # dp = do @ v^T ; ds = p * (dp - delta)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dk_sc[:] += jax.lax.dot_general(
        ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale

    @pl.when(last)
    def _finalize():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(*refs, scale: float, causal: bool,
                   has_segments: bool, block_q: int, block_k: int,
                   nq: int, nk: int):
    if has_segments:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, sq_ref, sk_ref, \
            dq_ref, dq_sc = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, \
            dq_ref, dq_sc = refs
        sq_ref = sk_ref = None
    t = pl.program_id(1)
    iq, ik = _decompose_q(t, nq, nk, causal)
    first = ik == 0
    last = (ik == iq) if causal else (ik == nk - 1)

    @pl.when(first)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0][:, :1]
    delta = delta_ref[0][:, :1]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    s = _mask(s, iq=iq, ik=ik, causal=causal,
              seg_q=sq_ref[0] if has_segments else None,
              seg_k=sk_ref[0] if has_segments else None,
              block_q=block_q, block_k=block_k)
    p = jnp.exp(s - lse)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)
    ds = p * (dp - delta)
    dq_sc[:] += jax.lax.dot_general(
        ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32) * scale

    @pl.when(last)
    def _finalize():
        dq_ref[0] = dq_sc[:].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, seg_q, seg_k, o, lse, do, scale, causal,
               hq, hkv, block_q, block_k):
    BHq, S, D = q.shape
    BHkv = k.shape[0]
    g = hq // hkv
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)  # [B*Hq, S]
    delta = jnp.broadcast_to(delta[..., None], (BHq, S, STAT_LANES))

    nq, nk = S // block_q, S // block_k
    has_segments = seg_q is not None

    def d_kv(t):
        return _decompose_kv(t, nq, nk, causal)

    # --- dk/dv: one pass per kv head, accumulating over its q-head group
    def q_row(b, m):
        return (b // hkv) * hq + (b % hkv) * g + m

    dkdv_in_specs = [
        pl.BlockSpec((1, block_q, D),
                     lambda b, t, m: (q_row(b, m), d_kv(t)[0], 0)),
        pl.BlockSpec((1, block_k, D),
                     lambda b, t, m: (b, d_kv(t)[1], 0)),  # k
        pl.BlockSpec((1, block_k, D),
                     lambda b, t, m: (b, d_kv(t)[1], 0)),  # v
        pl.BlockSpec((1, block_q, D),
                     lambda b, t, m: (q_row(b, m), d_kv(t)[0], 0)),
        pl.BlockSpec((1, block_q, STAT_LANES),
                     lambda b, t, m: (q_row(b, m), d_kv(t)[0], 0)),
        pl.BlockSpec((1, block_q, STAT_LANES),
                     lambda b, t, m: (q_row(b, m), d_kv(t)[0], 0)),
    ]
    dkdv_args = [q, k, v, do, lse, delta]
    if has_segments:
        dkdv_in_specs += [
            pl.BlockSpec((1, block_q),
                         lambda b, t, m: (b // hkv, d_kv(t)[0])),
            pl.BlockSpec((1, block_k),
                         lambda b, t, m: (b // hkv, d_kv(t)[1])),
        ]
        dkdv_args += [seg_q, seg_k]
    dkdv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, scale=scale, causal=causal,
                          has_segments=has_segments,
                          block_q=block_q, block_k=block_k, nq=nq, nk=nk),
        grid=(BHkv, _num_items(nq, nk, causal), g),
        in_specs=dkdv_in_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, D),
                         lambda b, t, m: (b, d_kv(t)[1], 0)),
            pl.BlockSpec((1, block_k, D),
                         lambda b, t, m: (b, d_kv(t)[1], 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BHkv, S, D), k.dtype),
            jax.ShapeDtypeStruct((BHkv, S, D), v.dtype),
        ],
        compiler_params=jaxcompat.pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(*dkdv_args)
    dk, dv = dkdv

    # --- dq: one pass per q head, kv blocks via the GQA index map
    def kv_row(b):
        return _kv_row(b, hq, hkv)

    def d_q(t):
        return _decompose_q(t, nq, nk, causal)

    dq_in_specs = [
        pl.BlockSpec((1, block_q, D), lambda b, t: (b, d_q(t)[0], 0)),
        pl.BlockSpec((1, block_k, D),
                     lambda b, t: (kv_row(b), d_q(t)[1], 0)),
        pl.BlockSpec((1, block_k, D),
                     lambda b, t: (kv_row(b), d_q(t)[1], 0)),
        pl.BlockSpec((1, block_q, D), lambda b, t: (b, d_q(t)[0], 0)),
        pl.BlockSpec((1, block_q, STAT_LANES),
                     lambda b, t: (b, d_q(t)[0], 0)),
        pl.BlockSpec((1, block_q, STAT_LANES),
                     lambda b, t: (b, d_q(t)[0], 0)),
    ]
    dq_args = [q, k, v, do, lse, delta]
    if has_segments:
        dq_in_specs += [
            pl.BlockSpec((1, block_q), lambda b, t: (b // hq, d_q(t)[0])),
            pl.BlockSpec((1, block_k), lambda b, t: (b // hq, d_q(t)[1])),
        ]
        dq_args += [seg_q, seg_k]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          has_segments=has_segments,
                          block_q=block_q, block_k=block_k, nq=nq, nk=nk),
        grid=(BHq, _num_items(nq, nk, causal)),
        in_specs=dq_in_specs,
        out_specs=pl.BlockSpec((1, block_q, D),
                               lambda b, t: (b, d_q(t)[0], 0)),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        out_shape=jax.ShapeDtypeStruct((BHq, S, D), q.dtype),
        compiler_params=jaxcompat.pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=_interpret(),
    )(*dq_args)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API with custom VJP
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _flash(q, k, v, seg_q, seg_k, causal: bool, hq: int, hkv: int,
           block_q: int, block_k: int):
    scale = 1.0 / (q.shape[-1] ** 0.5)
    o, _ = _flash_fwd(q, k, v, seg_q, seg_k, scale, causal, hq, hkv,
                      block_q, block_k)
    return o


def _flash_vjp_fwd(q, k, v, seg_q, seg_k, causal, hq, hkv,
                   block_q, block_k):
    scale = 1.0 / (q.shape[-1] ** 0.5)
    o, lse = _flash_fwd(q, k, v, seg_q, seg_k, scale, causal, hq, hkv,
                        block_q, block_k)
    return o, (q, k, v, seg_q, seg_k, o, lse)


def _flash_vjp_bwd(causal, hq, hkv, block_q, block_k, res, do):
    q, k, v, seg_q, seg_k, o, lse = res
    scale = 1.0 / (q.shape[-1] ** 0.5)
    dq, dk, dv = _flash_bwd(q, k, v, seg_q, seg_k, o, lse, do, scale,
                            causal, hq, hkv, block_q, block_k)
    return dq, dk, dv, None, None


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal: bool = True,
                    segment_ids: Optional[jax.Array] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K) -> jax.Array:
    """Public entry. q: [B, S, Nq, D]; k, v: [B, S, Nkv, D] (GQA-native —
    Nq must be a multiple of Nkv; no pre-repeat needed or wanted).

    ``segment_ids``: optional [B, S] int array; attention is masked to
    same-segment pairs (packed sequences). Causal and non-causal both
    run in the kernel.

    Pads S up to a block multiple. Padding is always masked: under a
    causal mask padded queries only attend the real prefix and are
    dropped on exit; otherwise padded keys are excluded by segment ids
    (synthesized when the caller passed none).
    """
    B, S, Nq, D = q.shape
    Nkv = k.shape[2]
    if Nq % Nkv != 0:
        raise ValueError(f"q heads ({Nq}) not a multiple of kv heads ({Nkv})")
    bq = min(block_q, _round_pow2(S))
    bk = min(block_k, _round_pow2(S))
    if causal and bq != bk:
        # the packed triangle grid's closed-form (iq, ik) decomposition
        # assumes square blocks
        bq = bk = min(bq, bk)
    Sp = -(-S // max(bq, bk)) * max(bq, bk)

    if segment_ids is None and not causal and Sp != S:
        # non-causal padding must be masked out: synthesize one segment
        segment_ids = jnp.zeros((B, S), jnp.int32)

    def prep(x):
        n = x.shape[2]
        x = jnp.swapaxes(x, 1, 2).reshape(B * n, S, D)
        if Sp != S:
            x = jnp.pad(x, ((0, 0), (0, Sp - S), (0, 0)))
        return x

    seg_q = seg_k = None
    if segment_ids is not None:
        seg = segment_ids.astype(jnp.int32)
        # distinct pad values so padded q rows match nothing at all
        seg_q = jnp.pad(seg, ((0, 0), (0, Sp - S)), constant_values=-2)
        seg_k = jnp.pad(seg, ((0, 0), (0, Sp - S)), constant_values=-1)

    o = _flash(prep(q), prep(k), prep(v), seg_q, seg_k,
               causal, Nq, Nkv, bq, bk)
    o = o[:, :S].reshape(B, Nq, S, D)
    return jnp.swapaxes(o, 1, 2)


def _round_pow2(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p
