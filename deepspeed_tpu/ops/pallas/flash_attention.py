"""Flash attention (Pallas TPU kernel, fwd + bwd).

The training-attention kernel of the framework — the role the reference's
fused softmax/attention CUDA kernels play (csrc/transformer/
softmax_kernels.cu, general_kernels.cu) and the memory-efficient
Evoformer/blocked-flash kernels (csrc/deepspeed4science/evoformer_attn,
inference/v2/kernels/ragged_ops/blocked_flash).

Algorithm: standard streaming-softmax flash attention. O(S) memory:
softmax statistics (m, l) are carried across key blocks; the backward
recomputes P blockwise from the saved logsumexp instead of storing the
[S, S] score matrix.

Layout: [B, H, S, D] inside the kernels (the public wrapper transposes
from the model's [B, S, H, D]). fp32 accumulation on the MXU
(preferred_element_type), bf16 streaming.

Blocks default to 128x128 (MXU-shaped). Sequence lengths must divide by
the block size for the causal path we pad+mask in the wrapper; the
dispatcher (ops/attention.py) falls back to the XLA implementation for
anything the kernel doesn't support (non-causal, segment ids).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_sc, m_sc, l_sc, *, scale: float, causal: bool,
                block_q: int, block_k: int):
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    # causal: skip key blocks strictly above the diagonal
    run = True
    if causal:
        run = ik * block_k <= iq * block_q + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0]  # [BQ, D]
        k = k_ref[0]  # [BK, D]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [BQ, BK]
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)

        m_prev = m_sc[:, :1]  # [BQ, 1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)  # [BQ, 1]
        p = jnp.exp(s - m_new)  # [BQ, BK]
        l_new = l_sc[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)  # [BQ, D]
        acc_sc[:] = acc_sc[:] * alpha + pv
        m_sc[:] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new, l_sc.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_sc[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_sc[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0] = (m_sc[:] + jnp.log(l_safe)).astype(jnp.float32)


def _flash_fwd(q, k, v, scale: float, causal: bool,
               block_q: int, block_k: int) -> Tuple[jax.Array, jax.Array]:
    """q,k,v: [BH, S, D] → (o [BH, S, D], lse [BH, S, 128])."""
    BH, S, D = q.shape
    nq, nk = S // block_q, S // block_k
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k)
    o, lse = pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------


def _bwd_dkdv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_sc, dv_sc, *, scale: float,
                     causal: bool, block_q: int, block_k: int):
    ik, iq = pl.program_id(1), pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    run = True
    if causal:
        run = iq * block_q + block_q - 1 >= ik * block_k

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]  # [BQ, 1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [BQ, BK]
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        p = jnp.exp(s - lse)  # [BQ, BK]
        # dv += p^T @ do
        dv_sc[:] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp = do @ v^T ; ds = p * (dp - delta)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_sc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(iq == nq - 1)
    def _finalize():
        dk_ref[0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_sc, *, scale: float, causal: bool,
                   block_q: int, block_k: int):
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    run = True
    if causal:
        run = ik * block_k <= iq * block_q + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_sc[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0] = dq_sc[:].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, scale, causal, block_q, block_k):
    BH, S, D = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)  # [BH, S]
    delta = jnp.broadcast_to(delta[..., None], (BH, S, 128))

    nq, nk = S // block_q, S // block_k
    dkdv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(BH, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),  # q
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),  # k
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),  # v
            pl.BlockSpec((1, block_q, D), lambda b, j, i: (b, i, 0)),  # do
            pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0)),  # lse
            pl.BlockSpec((1, block_q, 128), lambda b, j, i: (b, i, 0)),  # delta
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    dk, dv = dkdv

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 128), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=_interpret(),
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API with custom VJP
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal: bool, block_q: int, block_k: int):
    scale = 1.0 / (q.shape[-1] ** 0.5)
    o, _ = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return o


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k):
    scale = 1.0 / (q.shape[-1] ** 0.5)
    o, lse = _flash_fwd(q, k, v, scale, causal, block_q, block_k)
    return o, (q, k, v, o, lse)


def _flash_vjp_bwd(causal, block_q, block_k, res, do):
    q, k, v, o, lse = res
    scale = 1.0 / (q.shape[-1] ** 0.5)
    dq, dk, dv = _flash_bwd(q, k, v, o, lse, do, scale, causal,
                            block_q, block_k)
    return dq, dk, dv


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal: bool = True,
                    segment_ids: Optional[jax.Array] = None,
                    block_q: int = DEFAULT_BLOCK_Q,
                    block_k: int = DEFAULT_BLOCK_K) -> jax.Array:
    """Public entry. q,k,v: [B, S, N, D] (kv heads pre-repeated).

    Pads S up to a block multiple (safe under the causal mask: padded
    queries are dropped on exit and can only attend within the real
    prefix). Non-causal or segmented attention falls back to the XLA
    implementation via the dispatcher.
    """
    if segment_ids is not None or not causal:
        raise NotImplementedError(
            "flash kernel: causal self-attention only; dispatcher falls back")
    B, S, N, D = q.shape
    bq = min(block_q, _round_pow2(S))
    bk = min(block_k, _round_pow2(S))
    Sp = -(-S // max(bq, bk)) * max(bq, bk)

    def prep(x):
        x = jnp.swapaxes(x, 1, 2).reshape(B * N, S, D)
        if Sp != S:
            x = jnp.pad(x, ((0, 0), (0, Sp - S), (0, 0)))
        return x

    o = _flash(prep(q), prep(k), prep(v), causal, bq, bk)
    o = o[:, :S].reshape(B, N, S, D)
    return jnp.swapaxes(o, 1, 2)


def _round_pow2(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p
