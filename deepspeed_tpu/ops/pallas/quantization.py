"""Blockwise quantization kernels (INT8/INT4) + quantized collectives.

TPU-native equivalent of the reference's quantizer CUDA library
(csrc/quantization/{quantize.cu,quant_reduce.cu,swizzled_quantize.cu,
dequantize.cu} — 2,925 LoC) that powers ZeRO++:

  qwZ  — INT8 blockwise-quantized weight all-gather
         (docs/_tutorials/zeropp.md; partition_parameters.py:1446
         quantized all_gather_coalesced)
  qgZ  — quantized gradient reduce via all-to-all
         (runtime/comm/coalesced_collectives.py:31 all_to_all_quant_reduce)

Scheme: symmetric per-block scale (absmax / qmax), block along the last
dim. INT4 packs two nibbles per int8 byte. The Pallas kernel does
quantize + pack in VMEM (one HBM round-trip); a jnp path provides the
CPU/interpret fallback and the reference for tests.

The collectives (quantized_all_gather / quantized_psum_scatter) run
inside shard_map: quantize shard-locally → move int8 over ICI → dequant,
cutting wire bytes ~2x (bf16→int8) or ~4x (int4), the ZeRO++ headline.
(EQuARX, arXiv:2506.17615, is the published XLA analog of this design.)
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from deepspeed_tpu.utils import jaxcompat

DEFAULT_BLOCK = 256


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# jnp reference path (also the grad/fallback path)
# ---------------------------------------------------------------------------


def _quantize_ref(x, bits: int, block: int):
    orig_shape = x.shape
    n = x.shape[-1]
    assert n % block == 0, f"last dim {n} must divide block {block}"
    xb = x.reshape(*x.shape[:-1], n // block, block).astype(jnp.float32)
    qmax = (1 << (bits - 1)) - 1  # 127 / 7
    absmax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.where(absmax == 0, 1.0, absmax / qmax)
    q = jnp.clip(jnp.round(xb / scale), -qmax - 1, qmax).astype(jnp.int8)
    return q.reshape(orig_shape), scale[..., 0]


def _dequantize_ref(q, scale, bits: int, block: int, dtype):
    n = q.shape[-1]
    qb = q.reshape(*q.shape[:-1], n // block, block).astype(jnp.float32)
    out = qb * scale[..., None]
    return out.reshape(q.shape).astype(dtype)


# ---------------------------------------------------------------------------
# pallas kernels
# ---------------------------------------------------------------------------


def _quant_kernel(x_ref, q_ref, s_ref, *, bits: int, block: int):
    x = x_ref[:].astype(jnp.float32)  # [rows, block]
    qmax = float((1 << (bits - 1)) - 1)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.where(absmax == 0.0, 1.0, absmax / qmax)
    q = jnp.clip(jnp.round(x / scale), -qmax - 1, qmax)
    q_ref[:] = q.astype(jnp.int8)
    s_ref[:] = jnp.broadcast_to(scale, s_ref.shape)


def _dequant_kernel(q_ref, s_ref, out_ref, *, block: int):
    q = q_ref[:].astype(jnp.float32)
    out_ref[:] = (q * s_ref[:, :1]).astype(out_ref.dtype)


def quantize_blockwise(x: jax.Array, bits: int = 8,
                       block: int = DEFAULT_BLOCK
                       ) -> Tuple[jax.Array, jax.Array]:
    """x [..., N] → (int8 values [..., N], fp32 scales [..., N/block]).

    INT4 values occupy int8 storage in [-8, 7]; pack with pack_int4 for
    wire transport.
    """
    assert bits in (4, 8)
    orig_shape = x.shape
    n = x.shape[-1]
    if n % block != 0 or x.size % block != 0:
        return _quantize_ref(x, bits, min(block, n))
    rows = x.size // block
    x2 = x.reshape(rows, block)
    if _interpret() or rows % 8 != 0 or block % 128 != 0:
        q, s = _quantize_ref(x2, bits, block)
        return (q.reshape(orig_shape),
                s.reshape(*orig_shape[:-1], n // block))
    q, s = pl.pallas_call(
        functools.partial(_quant_kernel, bits=bits, block=block),
        grid=(max(1, rows // 256),),
        in_specs=[pl.BlockSpec((min(rows, 256), block), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((min(rows, 256), block), lambda i: (i, 0)),
            pl.BlockSpec((min(rows, 256), 128), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, block), jnp.int8),
            jax.ShapeDtypeStruct((rows, 128), jnp.float32),
        ],
    )(x2)
    return (q.reshape(orig_shape),
            s[:, 0].reshape(*orig_shape[:-1], n // block))


def dequantize_blockwise(q: jax.Array, scale: jax.Array, bits: int = 8,
                         block: int = DEFAULT_BLOCK,
                         dtype=jnp.bfloat16) -> jax.Array:
    n = q.shape[-1]
    blk = block if n % block == 0 else min(block, n)
    return _dequantize_ref(q, scale, bits, blk, dtype)


def pack_int4(q: jax.Array) -> jax.Array:
    """[..., N] int8 nibbles → [..., N/2] packed bytes."""
    lo = q[..., 0::2].astype(jnp.uint8) & 0x0F
    hi = (q[..., 1::2].astype(jnp.uint8) & 0x0F) << 4
    return (lo | hi).astype(jnp.uint8)


def unpack_int4(p: jax.Array) -> jax.Array:
    lo = (p & 0x0F).astype(jnp.int8)
    hi = ((p >> 4) & 0x0F).astype(jnp.int8)
    lo = jnp.where(lo > 7, lo - 16, lo)
    hi = jnp.where(hi > 7, hi - 16, hi)
    out = jnp.stack([lo, hi], axis=-1)
    return out.reshape(*p.shape[:-1], p.shape[-1] * 2)


# ---------------------------------------------------------------------------
# KV-cache helpers (per-head_dim-vector scale granularity)
# ---------------------------------------------------------------------------


def kv_quantize(x: jax.Array, bits=8) -> Tuple[jax.Array, jax.Array]:
    """Quantize a KV tensor [..., head_dim] with one fp32 scale per head
    vector: (int8 payload [..., head_dim], fp32 scales [...]).

    Block = head_dim so every (layer, block, row, k/v, head) vector carries
    its own scale — the granularity the paged cache stores alongside the
    int8 payload. Reuses the blockwise dispatch (Pallas on TPU when the
    tiling constraints hold, jnp reference on CPU CI).

    ``bits="fp8"`` stores e4m3 values instead of an integer grid — the
    quality midpoint between int8 and int4, via the fp_quantizer cast
    path (per-vector scale maps the absmax to the format's max normal).
    """
    hd = x.shape[-1]
    if bits == "fp8":
        from deepspeed_tpu.ops.fp_quantizer import fp_quantize

        q, s = fp_quantize(x, fmt="e4m3", group_size=hd)
        return q, s[..., 0]
    q, s = quantize_blockwise(x, bits=bits, block=hd)
    return q, s[..., 0]


def kv_dequantize(q: jax.Array, scale: jax.Array, bits=8,
                  dtype=jnp.bfloat16) -> jax.Array:
    """Inverse of kv_quantize: (int8/fp8 [..., head_dim], fp32 [...]) →
    dtype — value-times-scale either way (fp8 payloads upcast exactly)."""
    return (q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)
            ).astype(dtype)


def kv_pack(q: jax.Array, bits) -> jax.Array:
    """Storage codec for the quantized KV pool: int8/fp8 values pass
    through; int4 packs two per byte (uint8 payload, last dim head_dim//2
    — the same nibble codec the disagg handoff wire uses)."""
    return pack_int4(q) if bits == 4 else q


def kv_unpack(p: jax.Array, bits) -> jax.Array:
    """Inverse of kv_pack: uint8 nibble payload → int8 values in [-8, 7];
    int8/fp8 payloads pass through."""
    return unpack_int4(p) if bits == 4 else p


# ---------------------------------------------------------------------------
# quantized collectives (shard_map bodies)
# ---------------------------------------------------------------------------


def quantized_all_gather(x: jax.Array, axis: str, bits: int = 8,
                         block: int = DEFAULT_BLOCK) -> jax.Array:
    """qwZ: all-gather with int8/int4 wire format (reference quantized
    weight all-gather, partition_parameters.py:1446). Call inside a
    shard_map body; gathers along dim 0."""
    dtype = x.dtype
    q, s = quantize_blockwise(x, bits=bits, block=block)
    if bits == 4:
        q = pack_int4(q)
    qg = lax.all_gather(q, axis, axis=0, tiled=True)
    sg = lax.all_gather(s, axis, axis=0, tiled=True)
    if bits == 4:
        qg = unpack_int4(qg)
    return dequantize_blockwise(qg, sg, bits=bits, block=block, dtype=dtype)


def quantized_psum_scatter(x: jax.Array, axis: str, bits: int = 8,
                           block: int = DEFAULT_BLOCK) -> jax.Array:
    """qgZ: gradient reduce with quantized wire format via all-to-all +
    local reduce (reference all_to_all_quant_reduce,
    runtime/comm/coalesced_collectives.py:31). Inside shard_map; scatters
    dim 0. Returns the mean-reduced shard in x.dtype."""
    n = jaxcompat.axis_size(axis)
    shard = x.shape[0] // n
    q, s = quantize_blockwise(x, bits=bits, block=block)
    if bits == 4:
        q = pack_int4(q)
    # all-to-all: each rank receives its output-shard's slice from everyone
    qt = lax.all_to_all(q.reshape(n, shard, *q.shape[1:]), axis,
                        split_axis=0, concat_axis=0, tiled=False)
    st = lax.all_to_all(s.reshape(n, shard, *s.shape[1:]), axis,
                        split_axis=0, concat_axis=0, tiled=False)
    if bits == 4:
        qt = unpack_int4(qt)
    vals = _dequantize_ref(
        qt, st, bits, block if x.shape[-1] % block == 0 else min(block, x.shape[-1]),
        jnp.float32)
    return (vals.sum(axis=0) / n).astype(x.dtype)


def quantized_all_reduce(x: jax.Array, axis: str, bits: int = 8,
                         block: int = DEFAULT_BLOCK) -> jax.Array:
    """EQuARX-style quantized all-reduce (arXiv:2506.17615): quantize
    shard-local → int8 reduce-scatter with fp32 accumulation → int8
    all-gather of the reduced shards → dequant. Composes
    quantized_psum_scatter + quantized_all_gather so both wire phases move
    int8/int4 instead of bf16/fp32. Inside shard_map; reduces over `axis`
    and returns the full mean-reduced tensor on every rank.

    Pads dim 0 to a multiple of the axis size so arbitrary leading shapes
    reduce-scatter cleanly; padding is stripped after the gather.
    """
    n = jaxcompat.axis_size(axis)
    d0 = x.shape[0]
    pad = (-d0) % n
    xp = x if pad == 0 else jnp.concatenate(
        [x, jnp.zeros((pad, *x.shape[1:]), x.dtype)], axis=0)
    shard = quantized_psum_scatter(xp, axis, bits=bits, block=block)
    full = quantized_all_gather(shard, axis, bits=bits, block=block)
    return full[:d0] if pad else full
