"""Block-sparse attention (fixed / bigbird / longformer / variable).

Reference: ``deepspeed/ops/sparse_attention/`` (Triton blocksparse matmul
+ softmax, ``sparsity_config.py`` layout builders) with the sparsity modes
configured at ``runtime/config.py:250-410`` — 10x longer sequences than
dense (docs/_pages/training.md:147).

TPU design: sparsity lives at *block* granularity (MXU-shaped 128x128
tiles), never element granularity. A ``SparsityConfig`` builds a boolean
``[num_q_blocks, num_k_blocks]`` layout; the kernel is the streaming-
softmax flash loop with key blocks gated by the layout (``pl.when``
skips the matmuls of masked-out blocks, so FLOPs scale with layout
density). The XLA fallback expands the layout to an element mask and is
used off-TPU and for verification.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK = 128
NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# layout builders (reference sparsity_config.py)
# ---------------------------------------------------------------------------

class SparsityConfig:
    """Base layout builder (reference SparsityConfig: num_heads, block)."""

    def __init__(self, block: int = DEFAULT_BLOCK):
        self.block = int(block)

    def num_blocks(self, seq_len: int) -> int:
        if seq_len % self.block:
            raise ValueError(f"seq_len {seq_len} not a multiple of "
                             f"block {self.block}")
        return seq_len // self.block

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks attended (sanity/testing)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self.num_blocks(seq_len)
        return np.ones((n, n), bool)


class FixedSparsityConfig(SparsityConfig):
    """Reference 'fixed' mode: each query block attends its local window
    of ``num_local_blocks`` and the last block of every window is global
    (attended by everyone)."""

    def __init__(self, block: int = DEFAULT_BLOCK, num_local_blocks: int = 4,
                 num_global_blocks: int = 1):
        super().__init__(block)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self.num_blocks(seq_len)
        layout = np.zeros((n, n), bool)
        for q in range(n):
            w0 = (q // self.num_local_blocks) * self.num_local_blocks
            layout[q, w0:w0 + self.num_local_blocks] = True
        # last num_global_blocks of each window are global columns
        for w0 in range(0, n, self.num_local_blocks):
            hi = min(w0 + self.num_local_blocks, n)
            lo = max(hi - self.num_global_blocks, 0)
            layout[:, lo:hi] = True
        return layout


class LongformerSparsityConfig(SparsityConfig):
    """Sliding window + global attention on the first blocks."""

    def __init__(self, block: int = DEFAULT_BLOCK,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1):
        super().__init__(block)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self.num_blocks(seq_len)
        layout = np.zeros((n, n), bool)
        half = self.num_sliding_window_blocks // 2
        for q in range(n):
            lo, hi = max(0, q - half), min(n, q + half + 1)
            layout[q, lo:hi] = True
        g = min(self.num_global_blocks, n)
        layout[:, :g] = True  # everyone reads the globals
        layout[:g, :] = True  # globals read everyone
        return layout


class BigBirdSparsityConfig(SparsityConfig):
    """Random + sliding-window + global blocks (deterministic seed)."""

    def __init__(self, block: int = DEFAULT_BLOCK,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1, seed: int = 0):
        super().__init__(block)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self.num_blocks(seq_len)
        layout = LongformerSparsityConfig(
            self.block, self.num_sliding_window_blocks,
            self.num_global_blocks).make_layout(seq_len)
        rng = np.random.default_rng(self.seed)
        for q in range(n):
            picks = rng.choice(n, size=min(self.num_random_blocks, n),
                               replace=False)
            layout[q, picks] = True
        return layout


class VariableSparsityConfig(SparsityConfig):
    """Reference 'variable' mode: explicit local windows + global
    block indices."""

    def __init__(self, block: int = DEFAULT_BLOCK,
                 local_window_blocks: Sequence[int] = (4,),
                 global_block_indices: Sequence[int] = (0,)):
        super().__init__(block)
        self.local_window_blocks = list(local_window_blocks)
        self.global_block_indices = list(global_block_indices)

    def make_layout(self, seq_len: int) -> np.ndarray:
        n = self.num_blocks(seq_len)
        layout = np.zeros((n, n), bool)
        q = 0
        windows = list(self.local_window_blocks)
        while q < n:
            w = windows[0] if len(windows) == 1 else windows.pop(0)
            hi = min(q + w, n)
            layout[q:hi, q:hi] = True
            q = hi
        for g in self.global_block_indices:
            if g < n:
                layout[:, g] = True
                layout[g, :] = True
        return layout


MODES = {"dense": DenseSparsityConfig, "fixed": FixedSparsityConfig,
         "longformer": LongformerSparsityConfig,
         "bigbird": BigBirdSparsityConfig, "variable": VariableSparsityConfig}


def make_sparsity_config(mode: str, **kwargs) -> SparsityConfig:
    """Config-block entry (reference runtime/config.py:250-410 modes)."""
    if mode not in MODES:
        raise ValueError(f"unknown sparse attention mode '{mode}' "
                         f"(choose from {sorted(MODES)})")
    return MODES[mode](**kwargs)


def from_config(cfg) -> SparsityConfig:
    """Build a layout from the engine's ``sparse_attention`` config block
    (config.SparseAttentionConfig; 'bslongformer' is the reference's name
    for the longformer mode)."""
    mode = cfg.mode
    if mode == "dense":
        return DenseSparsityConfig(cfg.block)
    if mode == "fixed":
        return FixedSparsityConfig(cfg.block, cfg.num_local_blocks,
                                   cfg.num_global_blocks)
    if mode == "bslongformer":
        return LongformerSparsityConfig(cfg.block,
                                        cfg.num_sliding_window_blocks,
                                        cfg.num_global_blocks)
    if mode == "bigbird":
        return BigBirdSparsityConfig(cfg.block, cfg.num_random_blocks,
                                     cfg.num_sliding_window_blocks,
                                     cfg.num_global_blocks)
    if mode == "variable":
        return VariableSparsityConfig(cfg.block,
                                      cfg.local_window_blocks,
                                      cfg.global_block_indices)
    raise ValueError(f"unknown sparse attention mode '{mode}'")


# ---------------------------------------------------------------------------
# attention
# ---------------------------------------------------------------------------

def _expand_mask(layout: np.ndarray, block: int, seq_q: int,
                 seq_k: int) -> np.ndarray:
    m = np.repeat(np.repeat(layout, block, axis=0), block, axis=1)
    return m[:seq_q, :seq_k]


def blocksparse_attention(q, k, v, sparsity: SparsityConfig,
                          causal: bool = True,
                          scale: Optional[float] = None) -> jax.Array:
    """Block-sparse attention. q,k,v: [B, S, N, D] (model layout).

    The layout is static (built on host from the sparsity config), so the
    compiled program's FLOPs scale with layout density; XLA's masked
    path is used off-TPU. Causal composes with any layout.
    """
    B, S, N, D = q.shape
    # layout from the block-padded length; the expanded mask trims back to
    # S (ragged tails just use a partially-filled last block)
    padded = int(np.ceil(S / sparsity.block)) * sparsity.block
    layout = sparsity.make_layout(padded)
    scale = scale if scale is not None else D ** -0.5

    mask = jnp.asarray(_expand_mask(layout, sparsity.block, S, S))
    if causal:
        mask = mask & jnp.tril(jnp.ones((S, S), bool))

    qT = jnp.swapaxes(q, 1, 2)  # [B, N, S, D]
    kT = jnp.swapaxes(k, 1, 2)
    vT = jnp.swapaxes(v, 1, 2)
    scores = jnp.einsum("bnsd,bntd->bnst", qT, kT,
                        preferred_element_type=jnp.float32) * scale
    scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bnst,bntd->bnsd", probs, vT,
                     preferred_element_type=jnp.float32)
    return jnp.swapaxes(out.astype(q.dtype), 1, 2)


def _sparse_fwd_kernel(layout_ref, q_ref, k_ref, v_ref, o_ref,
                       acc_sc, m_sc, l_sc, *, scale: float, causal: bool,
                       block_q: int, block_k: int):
    """Streaming-softmax flash loop with key blocks gated by the layout:
    a masked-out (q-block, k-block) pair skips both matmuls entirely, so
    FLOPs scale with layout density."""
    iq, ik = pl.program_id(1), pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    run = layout_ref[iq, ik] != 0
    if causal:
        run = run & (ik * block_k <= iq * block_q + block_q - 1)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        if causal:
            qpos = iq * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            kpos = ik * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(kpos <= qpos, s, NEG_INF)
        m_prev = m_sc[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_sc[:, :1] * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_sc[:] = acc_sc[:] * alpha + pv
        m_sc[:] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new, l_sc.shape)

    @pl.when(ik == nk - 1)
    def _finalize():
        l = l_sc[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_sc[:] / l_safe).astype(o_ref.dtype)


def blocksparse_attention_pallas(q, k, v, sparsity: SparsityConfig,
                                 causal: bool = True,
                                 scale: Optional[float] = None) -> jax.Array:
    """Pallas block-sparse forward (inference / no-grad fast path; the
    differentiable XLA form is :func:`blocksparse_attention`). q,k,v:
    [B, S, N, D]; sparsity.block must equal the kernel block (128)."""
    B, S, N, D = q.shape
    block = sparsity.block
    layout = jnp.asarray(sparsity.make_layout(S).astype(np.int32))
    scale = scale if scale is not None else D ** -0.5
    nq = nk = S // block

    def to_bh(x):  # [B, S, N, D] → [B*N, S, D]
        return jnp.swapaxes(x, 1, 2).reshape(B * N, S, D)

    kernel = functools.partial(_sparse_fwd_kernel, scale=scale,
                               causal=causal, block_q=block, block_k=block)
    o = pl.pallas_call(
        kernel,
        grid=(B * N, nq, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # layout [nq, nk]
            pl.BlockSpec((1, block, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block, D), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * N, S, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block, D), jnp.float32),
            pltpu.VMEM((block, 128), jnp.float32),
            pltpu.VMEM((block, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(layout, to_bh(q), to_bh(k), to_bh(v))
    return jnp.swapaxes(o.reshape(B, N, S, D), 1, 2)


def sparse_self_attention(q, k, v, mode: str = "fixed", causal: bool = True,
                          block: int = DEFAULT_BLOCK, **mode_kwargs):
    """One-call form: build the layout from (mode, kwargs) and run
    (reference SparseSelfAttention module)."""
    cfg = make_sparsity_config(mode, block=block, **mode_kwargs)
    return blocksparse_attention(q, k, v, cfg, causal=causal)


def layout_density(layout: np.ndarray, causal: bool = True) -> float:
    """Fraction of the dense score matrix actually computed — the
    compute/memory saving factor."""
    n = layout.shape[0]
    if causal:
        tri = np.tril(np.ones((n, n), bool))
        return float((layout & tri).sum() / tri.sum())
    return float(layout.mean())
