"""Grouped matrix multiply (Pallas TPU kernel) — dropless-MoE execution.

The role the reference's grouped-GEMM expert engine plays
(deepspeed/moe/ep_experts.py:136 ``GroupedExperts`` — experts executed as
grouped GEMMs over per-expert token counts, no capacity padding), built
megablox-style for the MXU:

  gmm(lhs [M, K], rhs [E, K, N], group_sizes [E]) -> out [M, N]

where the rows of ``lhs`` are sorted by group (group e owns the
contiguous row range [sum(sizes[:e]), sum(sizes[:e+1]))) and row m is
multiplied by ``rhs[group(m)]``. FLOPs are exactly M*K*N — independent
of how imbalanced the groups are — versus the capacity-padded einsum
dispatch whose cost is fixed at E*capacity slots and which *drops*
tokens when a group overflows.

Mechanics: group boundaries rarely align with the 128-row MXU tile, so
the grid iterates over *work items* — (m-tile, group) pairs that
intersect — with the per-item tile id, group id, and row range
scalar-prefetched. A tile crossed by a boundary is visited once per
group; rows outside the item's group are masked from the product and
the partial products accumulate in a VMEM scratch across the
consecutive visits. The number of work items is static:
M/block_m + E - 1 in the worst case (every interior group boundary adds
one extra visit); unused slots repeat the last real item with an empty
row range so they contribute nothing.

The backward is two more grouped products: dlhs = gmm(dout, rhs^T) and
drhs[e] = lhs_e^T @ dout_e (``tgmm`` below, same metadata, accumulator
keyed by group instead of by tile).

Requires sum(group_sizes) == M (callers pad rows and assign the padding
to a real group with zero combine weight — see parallel/moe.py).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from deepspeed_tpu.utils import jaxcompat


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# work-item metadata
# ---------------------------------------------------------------------------

def make_group_metadata(group_sizes: jax.Array, m: int, block_m: int
                        ) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Static-shape work list for a grouped matmul.

    Returns (tile_ids, group_ids, row_start, row_end), each [T] int32 with
    T = m//block_m + E - 1. Work items are ordered by row, so all visits
    to one m-tile are consecutive (accumulation stays VMEM-resident) and
    all visits to one group are consecutive (for the tgmm accumulator).
    Padding items repeat the last real (tile, group) with an empty row
    range.

    Contract guard (sum(group_sizes) must equal m): sizes are clamped so
    cumulative ends never exceed ``m`` (over-sum can't index tiles out of
    range), and when sum < m the padding items are re-aimed at the
    uncovered trailing m-tiles with empty row ranges — those output
    blocks come back zero-filled instead of as uninitialized memory.
    """
    num_groups = group_sizes.shape[0]
    m_tiles = m // block_m
    t_total = m_tiles + num_groups - 1

    sizes = group_sizes.astype(jnp.int32)
    ends = jnp.minimum(jnp.cumsum(sizes), m)    # clamp: over-sum stays in range
    starts = jnp.concatenate([jnp.zeros((1,), jnp.int32), ends[:-1]])
    sizes = ends - starts
    first_tile = starts // block_m
    last_tile = jnp.where(sizes > 0, (ends - 1) // block_m, first_tile)
    items = jnp.where(sizes > 0, last_tile - first_tile + 1, 0)  # [E]
    item_cum = jnp.cumsum(items)
    item_base = item_cum - items
    total = item_cum[-1]

    w = jnp.arange(t_total, dtype=jnp.int32)
    gid = jnp.searchsorted(item_cum, w, side="right").astype(jnp.int32)
    gid = jnp.clip(gid, 0, num_groups - 1)
    tile = first_tile[gid] + (w - item_base[gid])

    valid = w < total
    last = jnp.maximum(total - 1, 0)
    # padding items: aim at any m-tiles left uncovered by an under-sum
    # (one each, empty row range → zero-filled output); once tiles are
    # exhausted, repeat the last real item (a benign re-visit)
    first_uncovered = (ends[-1] + block_m - 1) // block_m
    pad_tile = first_uncovered + (w - total)
    use_pad_tile = jnp.logical_and(~valid, pad_tile < m_tiles)
    tile = jnp.where(valid, tile,
                     jnp.where(use_pad_tile, pad_tile, tile[last]))
    tile = jnp.clip(tile, 0, max(m_tiles - 1, 0)).astype(jnp.int32)
    group = jnp.where(valid, gid, gid[last]).astype(jnp.int32)
    row_start = jnp.where(valid, starts[gid], 0).astype(jnp.int32)
    row_end = jnp.where(valid, ends[gid], 0).astype(jnp.int32)
    return tile, group, row_start, row_end


def _num_work_items(m: int, num_groups: int, block_m: int) -> int:
    return m // block_m + num_groups - 1


# ---------------------------------------------------------------------------
# gmm: out[m] = lhs[m] @ rhs[group(m)]
# ---------------------------------------------------------------------------

def _pick_block(dim: int, want: int) -> int:
    """Largest power-of-two tile <= want that divides dim (>=128 when
    possible — HBM traffic scales inversely with the tile, see module
    docstring)."""
    b = min(want, dim)
    while dim % b:
        b //= 2
    return max(b, 1)


def _gmm_kernel(tile_ids, group_ids, row_start, row_end,
                lhs_ref, rhs_ref, out_ref, acc_ref, *, block_m: int,
                transpose_rhs: bool):
    t = pl.program_id(1)
    k = pl.program_id(2)
    tile = tile_ids[t]
    prev_tile = tile_ids[jnp.maximum(t - 1, 0)]
    first = jnp.logical_and(
        k == 0, jnp.logical_or(t == 0, tile != prev_tile))

    @pl.when(first)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    rows = tile * block_m + jax.lax.broadcasted_iota(
        jnp.int32, (block_m, 1), 0)
    mask = jnp.logical_and(rows >= row_start[t], rows < row_end[t])
    if transpose_rhs:  # rhs block [bn, bk], contract both k dims
        prod = jax.lax.dot_general(
            lhs_ref[...], rhs_ref[0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:
        prod = jnp.dot(lhs_ref[...], rhs_ref[0],
                       preferred_element_type=jnp.float32)
    acc_ref[...] += jnp.where(mask, prod, 0.0)
    out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def _gmm_call(lhs: jax.Array, rhs: jax.Array, group_sizes: jax.Array,
              block_m: int, block_n: int, block_k: int,
              transpose_rhs: bool = False) -> jax.Array:
    """out[m] = lhs[m] @ rhs[g(m)] (or @ rhs[g(m)].T when transpose_rhs,
    rhs then being [E, N, K] — saves materializing the swap in the
    backward)."""
    m, kdim = lhs.shape
    if transpose_rhs:
        num_groups, n, _ = rhs.shape
    else:
        num_groups, _, n = rhs.shape
    block_m = _pick_block(m, block_m)
    block_n = _pick_block(n, block_n)
    block_k = _pick_block(kdim, block_k)
    meta = make_group_metadata(group_sizes, m, block_m)
    t_total = _num_work_items(m, num_groups, block_m)
    grid = (n // block_n, t_total, kdim // block_k)

    if transpose_rhs:
        rhs_spec = pl.BlockSpec((1, block_n, block_k),
                                lambda n, t, k, tiles, gids, rs, re:
                                (gids[t], n, k))
    else:
        rhs_spec = pl.BlockSpec((1, block_k, block_n),
                                lambda n, t, k, tiles, gids, rs, re:
                                (gids[t], k, n))
    out = pl.pallas_call(
        functools.partial(_gmm_kernel, block_m=block_m,
                          transpose_rhs=transpose_rhs),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=[
                pl.BlockSpec((block_m, block_k),
                             lambda n, t, k, tiles, gids, rs, re:
                             (tiles[t], k)),
                rhs_spec,
            ],
            out_specs=pl.BlockSpec((block_m, block_n),
                                   lambda n, t, k, tiles, gids, rs, re:
                                   (tiles[t], n)),
            scratch_shapes=[pltpu.VMEM((block_m, block_n), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((m, n), lhs.dtype),
        compiler_params=jaxcompat.pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=_interpret(),
    )(*meta, lhs, rhs)
    return out


# ---------------------------------------------------------------------------
# tgmm: out[e] = sum over rows of group e of lhs[r]^T @ dout[r]
# ---------------------------------------------------------------------------

def _tgmm_kernel(tile_ids, group_ids, row_start, row_end,
                 lhs_ref, dout_ref, out_ref, acc_ref, *, block_m: int):
    t = pl.program_id(2)
    tile = tile_ids[t]
    group = group_ids[t]
    prev_group = group_ids[jnp.maximum(t - 1, 0)]
    first = jnp.logical_or(t == 0, group != prev_group)

    @pl.when(first)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    rows = tile * block_m + jax.lax.broadcasted_iota(
        jnp.int32, (block_m, 1), 0)
    mask = jnp.logical_and(rows >= row_start[t], rows < row_end[t])
    lhs = jnp.where(mask, lhs_ref[...], 0)
    acc_ref[...] += jax.lax.dot_general(
        lhs, dout_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    out_ref[0] = acc_ref[...].astype(out_ref.dtype)


def _tgmm_call(lhs: jax.Array, dout: jax.Array, group_sizes: jax.Array,
               block_m: int, block_n: int, block_k: int) -> jax.Array:
    """[M,K], [M,N], [E] -> [E,K,N] per-group lhs^T @ dout."""
    m, kdim = lhs.shape
    _, n = dout.shape
    num_groups = group_sizes.shape[0]
    block_m = _pick_block(m, block_m)
    block_n = _pick_block(n, block_n)
    block_k = _pick_block(kdim, block_k)
    meta = make_group_metadata(group_sizes, m, block_m)
    t_total = _num_work_items(m, num_groups, block_m)
    grid = (kdim // block_k, n // block_n, t_total)

    out = pl.pallas_call(
        functools.partial(_tgmm_kernel, block_m=block_m),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=4,
            grid=grid,
            in_specs=[
                pl.BlockSpec((block_m, block_k),
                             lambda k, n, t, tiles, gids, rs, re:
                             (tiles[t], k)),
                pl.BlockSpec((block_m, block_n),
                             lambda k, n, t, tiles, gids, rs, re:
                             (tiles[t], n)),
            ],
            out_specs=pl.BlockSpec((1, block_k, block_n),
                                   lambda k, n, t, tiles, gids, rs, re:
                                   (gids[t], k, n)),
            scratch_shapes=[pltpu.VMEM((block_k, block_n), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((num_groups, kdim, n), lhs.dtype),
        compiler_params=jaxcompat.pallas_tpu_compiler_params(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(*meta, lhs, dout)
    # groups with zero rows are never visited — their blocks are
    # undefined. Mask with the same clamped sizes the metadata uses, so
    # a group zeroed by the over-sum guard is zero-filled too.
    ends = jnp.minimum(jnp.cumsum(group_sizes.astype(jnp.int32)), m)
    starts = jnp.concatenate([jnp.zeros((1,), jnp.int32), ends[:-1]])
    return jnp.where((ends > starts)[:, None, None], out, 0)


# ---------------------------------------------------------------------------
# public entry (differentiable)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def gmm(lhs: jax.Array, rhs: jax.Array, group_sizes: jax.Array,
        block_m: int = 512, block_n: int = 1024, block_k: int = 512
        ) -> jax.Array:
    """Grouped matmul: row m of ``lhs`` times ``rhs[group(m)]``.

    lhs [M, K] sorted by group, rhs [E, K, N], group_sizes [E] int32 with
    sum == M. Returns [M, N] in lhs.dtype (fp32 MXU accumulation).
    Block sizes are upper bounds — clamped to divisors of each dim.
    Large blocks keep the kernel compute-bound: rhs[g] is re-read once
    per m-tile of its group and lhs once per n-tile, so HBM traffic
    scales with 1/block. Measured on v5e at Mixtral-8x7B geometry
    (M=32k, K=4096, N=14336): (512, 1024, 512) → 98 TF/s, ~50% of peak;
    the full no-drop MoE layer runs 2.7x faster than the capacity-einsum
    dispatch.
    """
    return _gmm_call(lhs, rhs, group_sizes, block_m, block_n, block_k)


def _gmm_fwd(lhs, rhs, group_sizes, block_m, block_n, block_k):
    out = _gmm_call(lhs, rhs, group_sizes, block_m, block_n, block_k)
    return out, (lhs, rhs, group_sizes)


def _gmm_bwd(block_m, block_n, block_k, res, dout):
    lhs, rhs, group_sizes = res
    # dlhs[m] = dout[m] @ rhs[g(m)]^T — gmm with rhs contracted on its
    # last dim (no materialized transpose)
    dlhs = _gmm_call(dout, rhs, group_sizes, block_m, block_k, block_n,
                     transpose_rhs=True)
    drhs = _tgmm_call(lhs, dout, group_sizes, block_m, block_n, block_k)
    dgs = np.zeros(group_sizes.shape, dtype=jax.dtypes.float0)
    return dlhs.astype(lhs.dtype), drhs.astype(rhs.dtype), dgs


gmm.defvjp(_gmm_fwd, _gmm_bwd)
