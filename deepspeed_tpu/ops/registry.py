"""Named op registry with compatibility probing.

Reference: op_builder/ (builder.py:116 ``OpBuilder`` ABC with
``is_compatible()``/``load()``; 26 named builders,
``get_accelerator().create_op_builder(name)``). CUDA needs a JIT C++
build step; Pallas/XLA ops are jitted by XLA itself, so the registry's
job reduces to (a) a stable name → op table for tooling (`dstpu-report`
prints the compat column like ds_report), and (b) graceful-degradation
probes so callers can pick fallbacks (e.g. flash attention → XLA
attention when no TPU is present).

Since round 14 the registry also owns cost-driven dispatch
(:func:`dispatch_op`): the compat probe stays the outer guard, then
the measured per-(kernel, shape-bucket) win/loss table
(ops/kernel_table.py, written by ``make bench-kernels``) decides — a
kernel runs on a bucket only if its measured win ratio is >= 1.0
there; unmeasured buckets defer to the caller's legacy heuristic.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class OpSpec:
    name: str
    description: str
    load: Callable[[], Callable]          # returns the op's callable
    compat_probe: Optional[Callable[[], Tuple[bool, str]]] = None

    def is_compatible(self) -> Tuple[bool, str]:
        if self.compat_probe is None:
            return True, ""
        try:
            return self.compat_probe()
        except Exception as e:  # a probe must never crash tooling
            return False, f"probe error: {e}"


_REGISTRY: Dict[str, OpSpec] = {}


def register_op(name: str, description: str,
                compat_probe: Optional[Callable] = None):
    """Decorator-style registration of a loader function."""

    def deco(load_fn):
        _REGISTRY[name] = OpSpec(name, description, load_fn, compat_probe)
        return load_fn

    return deco


def get_op(name: str) -> Callable:
    _ensure_builtin()
    if name not in _REGISTRY:
        raise KeyError(f"unknown op {name!r}; known: {sorted(_REGISTRY)}")
    return _REGISTRY[name].load()


def all_ops() -> Dict[str, OpSpec]:
    _ensure_builtin()
    return dict(_REGISTRY)


@dataclasses.dataclass(frozen=True)
class DispatchDecision:
    """Outcome of a cost-driven dispatch: which registered op runs and
    why. ``blocks`` carries the measured winning geometry (table entry)
    so the caller can run the kernel exactly as benched."""

    op_name: str
    source: str  # "pallas" | "xla"
    reason: str
    ratio: Optional[float] = None
    blocks: Optional[Dict[str, int]] = None


def dispatch_op(name: str, bucket: str, fallback: str,
                default_use: bool = False,
                table_path: Optional[str] = None) -> DispatchDecision:
    """Pick ``name`` or ``fallback`` for a shape bucket.

    Guard order: (1) compat probe — an incompatible kernel never runs,
    whatever the table says; (2) win/loss table — measured entries are
    authoritative (win ratio >= 1.0 runs the kernel, < 1.0 routes the
    bucket to the fallback); (3) ``default_use`` — the caller's legacy
    heuristic for unmeasured buckets.
    """
    _ensure_builtin()
    if name not in _REGISTRY:
        raise KeyError(f"unknown op {name!r}; known: {sorted(_REGISTRY)}")
    ok, note = _REGISTRY[name].is_compatible()
    if not ok:
        return DispatchDecision(fallback, "xla",
                                f"compat probe failed: {note}")
    from deepspeed_tpu.ops import kernel_table

    d = kernel_table.decide(name, bucket, path=table_path)
    if d.measured:
        if d.win:
            return DispatchDecision(name, "pallas", d.reason,
                                    d.ratio, d.blocks)
        return DispatchDecision(fallback, "xla", d.reason,
                                d.ratio, d.blocks)
    if default_use:
        return DispatchDecision(name, "pallas",
                                f"{d.reason}; heuristic prefers kernel")
    return DispatchDecision(fallback, "xla",
                            f"{d.reason}; heuristic prefers fallback")


def _tpu_probe() -> Tuple[bool, str]:
    import jax

    backend = jax.default_backend()
    if backend == "tpu":
        return True, ""
    interp = True  # pallas interpreter mode works on cpu
    return (interp, f"backend={backend}: runs in Pallas interpreter mode "
                    "(slow; numerics-equivalent)")


_BUILTIN_LOADED = False


def _ensure_builtin() -> None:
    global _BUILTIN_LOADED
    if _BUILTIN_LOADED:
        return
    _BUILTIN_LOADED = True

    @register_op("flash_attention",
                 "Pallas blockwise flash attention, fwd+bwd custom VJP "
                 "(ref: csrc/transformer fused attention)",
                 _tpu_probe)
    def _load_flash():
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

        return flash_attention

    @register_op("quantize_blockwise",
                 "Pallas blockwise INT8/INT4 quantization "
                 "(ref: csrc/quantization/quantize.cu)",
                 _tpu_probe)
    def _load_quant():
        from deepspeed_tpu.ops.pallas.quantization import quantize_blockwise

        return quantize_blockwise

    @register_op("dequantize_blockwise",
                 "Pallas blockwise dequantization "
                 "(ref: csrc/quantization/dequantize.cu)",
                 _tpu_probe)
    def _load_dequant():
        from deepspeed_tpu.ops.pallas.quantization import dequantize_blockwise

        return dequantize_blockwise

    @register_op("xla_attention",
                 "XLA-fused multi-head attention fallback")
    def _load_xla_attn():
        from deepspeed_tpu.ops.attention import xla_attention

        return xla_attention

    @register_op("ragged_forward",
                 "paged-KV ragged inference step "
                 "(ref: inference/v2/kernels/ragged_ops)")
    def _load_ragged():
        from deepspeed_tpu.inference.model_runner import ragged_forward

        return ragged_forward
