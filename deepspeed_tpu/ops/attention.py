"""Attention ops: XLA reference impl + Pallas flash kernel dispatch.

Covers the role of the reference's fused attention kernels
(csrc/transformer/*softmax*.cu, inference flash kernels
inference/v2/kernels/ragged_ops/blocked_flash). The ``impl='auto'`` path
picks the Pallas flash kernel on TPU (ops/pallas/flash_attention.py) and
falls back to the XLA einsum implementation elsewhere — the op-builder
``is_compatible`` pattern (op_builder/builder.py:116) reduced to a runtime
platform probe.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@functools.lru_cache(None)
def _flash_available() -> bool:
    if jax.default_backend() != "tpu":
        return False
    try:
        from deepspeed_tpu.ops.pallas import flash_attention  # noqa: F401

        return True
    except Exception:
        return False


def repeat_kv_heads(q, k, v):
    """Repeat KV heads up to q's head count, for attention impls that
    need equal counts (XLA einsum, blocksparse, head-split SP paths).

    Contiguous repeat (q head h ← kv head h // group) — must match the
    flash kernel's ``_kv_row`` index map (ops/pallas/flash_attention.py).
    """
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def xla_attention(q, k, v, causal: bool = True,
                  segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Reference attention. q: [B, S, Nq, D]; k,v: [B, S, Nkv, D] with
    Nq a multiple of Nkv (GQA repeats kv heads here).

    Softmax in fp32 regardless of input dtype (numerics parity with the
    reference's attn_softmax kernels, csrc/transformer/softmax_kernels.cu).
    """
    k, v = repeat_kv_heads(q, k, v)
    dt = q.dtype
    d = q.shape[-1]
    scores = jnp.einsum("bqnd,bknd->bnqk", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(d, jnp.float32))
    Sq, Sk = scores.shape[-2], scores.shape[-1]
    if causal:
        qpos = jnp.arange(Sq)[:, None] + (Sk - Sq)
        kpos = jnp.arange(Sk)[None, :]
        scores = jnp.where(kpos <= qpos, scores, NEG_INF)
    if segment_ids is not None:
        same = segment_ids[:, :, None] == segment_ids[:, None, :]
        scores = jnp.where(same[:, None, :, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(dt)
    return jnp.einsum("bnqk,bknd->bqnd", probs, v)


# Below this sequence length XLA's fused attention beats the Pallas kernel
# on-chip; above it flash wins AND avoids the [S,S] fp32 score transient.
# Measured on v5e (B=32,N=12,D=64, fwd+bwd, block 512): seq 1024 → flash
# 1.5x over XLA; block 128 (old default) was 0.6x — block size dominates.
FLASH_MIN_SEQ = 1024


# engine-configured block-sparse layout (config.sparse_attention →
# set_sparse_config at engine init); used when impl == "blocksparse"
_SPARSE_CONFIG = None


def set_sparse_config(sparsity) -> None:
    """Install the layout for impl='blocksparse' (engine wires the
    ds_config sparse_attention block here)."""
    global _SPARSE_CONFIG
    _SPARSE_CONFIG = sparsity


def multi_head_attention(q, k, v, causal: bool = True, impl: str = "auto",
                         segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Dispatching entry point used by the model zoo."""
    seq = q.shape[1]
    if impl == "blocksparse":
        if _SPARSE_CONFIG is None:
            raise ValueError(
                "attn_impl='blocksparse' needs a sparse_attention config "
                "block (or ops.attention.set_sparse_config)")
        if segment_ids is not None:
            raise NotImplementedError(
                "blocksparse attention does not take segment_ids")
        from deepspeed_tpu.ops.pallas.blocksparse_attention import \
            blocksparse_attention

        k, v = repeat_kv_heads(q, k, v)  # blocksparse kernel is MHA-only
        return blocksparse_attention(q, k, v, _SPARSE_CONFIG, causal=causal)
    want_flash = (
        impl == "flash"
        or (impl == "auto" and _flash_available() and seq >= FLASH_MIN_SEQ)
    )
    if (impl == "auto" and seq >= FLASH_MIN_SEQ and not want_flash
            and jax.default_backend() == "tpu"):
        # the flash kernel should have dispatched here but can't load —
        # the O(S^2)-memory XLA path is a real perf downgrade on TPU
        from deepspeed_tpu.utils import telemetry

        telemetry.count("attention.flash_to_xla_fallback",
                        "pallas flash kernel unavailable on tpu backend")
    if want_flash:
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

        # v5e measurements (docs/roofline.md): 512 best at short seq;
        # 1024 wins from ~8K up (fewer grid steps amortize the packed
        # triangle's per-step overhead — 128K fwd 124 vs 52 TF/s)
        block = 1024 if seq >= 8192 else min(512, seq)
        return flash_attention(q, k, v, causal=causal,
                               segment_ids=segment_ids,
                               block_q=block, block_k=block)
    return xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)
