"""Attention ops: XLA reference impl + Pallas flash kernel dispatch.

Covers the role of the reference's fused attention kernels
(csrc/transformer/*softmax*.cu, inference flash kernels
inference/v2/kernels/ragged_ops/blocked_flash). The ``impl='auto'`` path
picks the Pallas flash kernel on TPU (ops/pallas/flash_attention.py) and
falls back to the XLA einsum implementation elsewhere — the op-builder
``is_compatible`` pattern (op_builder/builder.py:116) reduced to a runtime
platform probe.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


@functools.lru_cache(None)
def _flash_importable() -> bool:
    try:
        from deepspeed_tpu.ops.pallas import flash_attention  # noqa: F401

        return True
    except Exception:
        return False


@functools.lru_cache(None)
def _flash_available() -> bool:
    """Legacy heuristic availability: TPU backend + importable kernel.
    (The win/loss table can still route to the kernel off-TPU — e.g. a
    CPU-measured table entry in tests — interpreter mode is
    numerics-equivalent, just slow.)"""
    return jax.default_backend() == "tpu" and _flash_importable()


def repeat_kv_heads(q, k, v):
    """Repeat KV heads up to q's head count, for attention impls that
    need equal counts (XLA einsum, blocksparse, head-split SP paths).

    Contiguous repeat (q head h ← kv head h // group) — must match the
    flash kernel's ``_kv_row`` index map (ops/pallas/flash_attention.py).
    """
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def xla_attention(q, k, v, causal: bool = True,
                  segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Reference attention. q: [B, S, Nq, D]; k,v: [B, S, Nkv, D] with
    Nq a multiple of Nkv (GQA repeats kv heads here).

    Softmax in fp32 regardless of input dtype (numerics parity with the
    reference's attn_softmax kernels, csrc/transformer/softmax_kernels.cu).
    """
    k, v = repeat_kv_heads(q, k, v)
    dt = q.dtype
    d = q.shape[-1]
    scores = jnp.einsum("bqnd,bknd->bnqk", q, k).astype(jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(d, jnp.float32))
    Sq, Sk = scores.shape[-2], scores.shape[-1]
    if causal:
        qpos = jnp.arange(Sq)[:, None] + (Sk - Sq)
        kpos = jnp.arange(Sk)[None, :]
        scores = jnp.where(kpos <= qpos, scores, NEG_INF)
    if segment_ids is not None:
        same = segment_ids[:, :, None] == segment_ids[:, None, :]
        scores = jnp.where(same[:, None, :, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(dt)
    return jnp.einsum("bnqk,bknd->bqnd", probs, v)


# Legacy crossover heuristic — covers buckets the win/loss table hasn't
# measured yet. Below this sequence length XLA's fused attention beats
# the Pallas kernel on-chip; above it flash wins AND avoids the [S,S]
# fp32 score transient. Measured on v5e (B=32,N=12,D=64, fwd+bwd, block
# 512): seq 1024 → flash 1.5x over XLA; block 128 (old default) was
# 0.6x — block size dominates. Measured buckets override this entirely
# (ops/kernel_table.py; `make bench-kernels` re-measures).
FLASH_MIN_SEQ = 1024


# engine-configured block-sparse layout (config.sparse_attention →
# set_sparse_config at engine init); used when impl == "blocksparse"
_SPARSE_CONFIG = None

# engine-configured kernel geometry + dispatch policy (config.kernels →
# set_kernel_config at engine init); None = defaults (table dispatch,
# seq-derived blocks)
_KERNEL_CONFIG = None

# trace-time dispatch outcomes: pallas/xla picks plus the
# wanted-flash-but-unavailable fallbacks (the perf cliff the bare
# telemetry counter used to hide; published as a hub ratio like
# serve.paged_fallback_ratio)
_DISPATCH_STATS = {"pallas": 0, "xla": 0, "flash_fallbacks": 0}


def set_sparse_config(sparsity) -> None:
    """Install the layout for impl='blocksparse' (engine wires the
    ds_config sparse_attention block here)."""
    global _SPARSE_CONFIG
    _SPARSE_CONFIG = sparsity


def set_kernel_config(kernels) -> None:
    """Install the ds_config ``kernels`` block (engine init): block
    geometry overrides and the table-vs-heuristic dispatch switch."""
    global _KERNEL_CONFIG
    _KERNEL_CONFIG = kernels


def dispatch_stats() -> dict:
    """Copy of the trace-time dispatch counters (tests + bench)."""
    return dict(_DISPATCH_STATS)


def flash_fallback_ratio() -> float:
    """Fraction of flash-worthy dispatches that lost the kernel —
    the train-path analog of ``serve.paged_fallback_ratio``."""
    fb = _DISPATCH_STATS["flash_fallbacks"]
    return fb / max(1, _DISPATCH_STATS["pallas"] + fb)


def _reset_dispatch_stats() -> None:
    for key in _DISPATCH_STATS:
        _DISPATCH_STATS[key] = 0


def kernel_gmm_tiles() -> dict:
    """Grouped-matmul tile overrides from the installed ``kernels``
    config block (kernels.gmm_block_{m,n,k}); empty dict when no engine
    has installed a config → ``gmm`` keeps its own defaults."""
    kcfg = _KERNEL_CONFIG
    if kcfg is None:
        return {}
    return {"block_m": int(getattr(kcfg, "gmm_block_m", 512)),
            "block_n": int(getattr(kcfg, "gmm_block_n", 1024)),
            "block_k": int(getattr(kcfg, "gmm_block_k", 512))}


def _auto_block(seq: int) -> int:
    # v5e measurements (docs/roofline.md): 512 best at short seq;
    # 1024 wins from ~8K up (fewer grid steps amortize the packed
    # triangle's per-step overhead — 128K fwd 124 vs 52 TF/s)
    return 1024 if seq >= 8192 else min(512, seq)


def _pick_blocks(seq: int, measured: Optional[dict]) -> tuple:
    """Flash block geometry: measured winning blocks (table) > config
    knobs (kernels.flash_block_q/_k, 0 = auto) > seq-derived default."""
    bq = bk = _auto_block(seq)
    kcfg = _KERNEL_CONFIG
    if kcfg is not None:
        bq = getattr(kcfg, "flash_block_q", 0) or bq
        bk = getattr(kcfg, "flash_block_k", 0) or bk
    if measured:
        bq = int(measured.get("block_q", bq))
        bk = int(measured.get("block_k", bk))
    return bq, bk


def _export_dispatch(region: str, source: str, reason: str,
                     bucket: str) -> None:
    """Publish the chosen source per region to the observability hub.
    Runs at trace time (once per compiled program, not per step); never
    instantiates a hub of its own."""
    try:
        from deepspeed_tpu.observability.hub import peek_hub

        hub = peek_hub()
    except Exception:
        hub = None
    if hub is None:
        return
    hub.gauge(f"kernel.{region}.pallas", 1.0 if source == "pallas" else 0.0)
    hub.gauge("kernel.flash_fallback_ratio", flash_fallback_ratio())
    hub.record_event("kernel_dispatch", region=region, source=source,
                     reason=reason, bucket=bucket)


def multi_head_attention(q, k, v, causal: bool = True, impl: str = "auto",
                         segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """Dispatching entry point used by the model zoo.

    ``impl='auto'`` is cost-driven: the registry consults the measured
    per-(kernel, shape-bucket) win/loss table (compat probing as the
    outer guard); unmeasured buckets fall back to the FLASH_MIN_SEQ
    heuristic. Explicit ``impl='flash'``/``'xla'`` bypass the table.
    """
    seq = q.shape[1]
    if impl == "blocksparse":
        if _SPARSE_CONFIG is None:
            raise ValueError(
                "attn_impl='blocksparse' needs a sparse_attention config "
                "block (or ops.attention.set_sparse_config)")
        if segment_ids is not None:
            raise NotImplementedError(
                "blocksparse attention does not take segment_ids")
        from deepspeed_tpu.ops.pallas.blocksparse_attention import \
            blocksparse_attention

        k, v = repeat_kv_heads(q, k, v)  # blocksparse kernel is MHA-only
        return blocksparse_attention(q, k, v, _SPARSE_CONFIG, causal=causal)
    if impl == "flash":
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

        bq, bk = _pick_blocks(seq, None)
        return flash_attention(q, k, v, causal=causal,
                               segment_ids=segment_ids,
                               block_q=bq, block_k=bk)
    if impl != "auto":
        return xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)

    from deepspeed_tpu.ops import kernel_table, registry

    kcfg = _KERNEL_CONFIG
    bucket = kernel_table.attention_bucket(seq, q.shape[-1], causal)
    heuristic = _flash_available() and seq >= FLASH_MIN_SEQ
    if kcfg is not None and getattr(kcfg, "dispatch", "auto") == "heuristic":
        decision = registry.DispatchDecision(
            op_name=("flash_attention" if heuristic else "xla_attention"),
            source=("pallas" if heuristic else "xla"),
            reason="kernels.dispatch=heuristic")
    else:
        decision = registry.dispatch_op(
            "flash_attention", bucket, "xla_attention",
            default_use=heuristic,
            table_path=getattr(kcfg, "table_path", None))
    if decision.source == "pallas" and not _flash_importable():
        # the flash kernel should have dispatched here but can't load —
        # the O(S^2)-memory XLA path is a real perf downgrade on TPU
        from deepspeed_tpu.utils import telemetry

        telemetry.count("attention.flash_to_xla_fallback",
                        "pallas flash kernel unavailable "
                        f"(backend={jax.default_backend()})")
        _DISPATCH_STATS["flash_fallbacks"] += 1
        decision = registry.DispatchDecision(
            op_name="xla_attention", source="xla",
            reason=f"flash unavailable; was: {decision.reason}")
    _DISPATCH_STATS[decision.source] += 1
    _export_dispatch("attention", decision.source, decision.reason, bucket)
    if decision.source == "pallas":
        from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

        bq, bk = _pick_blocks(seq, decision.blocks)
        return flash_attention(q, k, v, causal=causal,
                               segment_ids=segment_ids,
                               block_q=bq, block_k=bk)
    return xla_attention(q, k, v, causal=causal, segment_ids=segment_ids)
