"""Evoformer attention (DS4Science / AlphaFold-family workloads).

Reference: ``csrc/deepspeed4science/evoformer_attn/`` (~14.9k LoC of
CUTLASS kernels) — memory-efficient fused attention for the Evoformer
block's two patterns, exposed as ``DS4Sci_EvoformerAttention``:

  * MSA row/column attention: per-(sequence-)row attention over the MSA
    tensor with an additive pair bias;
  * triangle attention (starting/ending node): attention over the pair
    representation biased by the third edge, with a sigmoid gate.

Both are softmax attention with (1) an additive bias term broadcast over
a leading batch group and (2) an output gate — exactly the structure XLA
fuses well and the flash kernel streams. The TPU design therefore
composes the existing pieces instead of porting CUTLASS: einsum QK^T
with fp32 accumulation, bias add, streaming softmax via chunked scan
when the pair dimension is long (the CUTLASS kernels' memory win), and
a fused sigmoid-gated output projection.

API parity: ``evoformer_attention(q, k, v, biases, gate=None)`` accepts
the reference's layout [*, H(eads) dims last]: q/k/v [B, N, S, h, d]
(B batch, N MSA rows or node axis, S keys, h heads, d head dim) and a
list of biases broadcastable to the score shape [B, N, h, S, S].
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

NEG_INF = -1e30
CHUNK_THRESHOLD = 1024  # queries per chunk when S is long


def _attention_core(q, k, v, biases: Sequence[jax.Array]) -> jax.Array:
    """Dense scores path: q,k,v [..., S, h, d]; biases broadcast to
    [..., h, Sq, Sk]. fp32 softmax (reference kernels accumulate fp32)."""
    d = q.shape[-1]
    scores = jnp.einsum("...qhd,...khd->...hqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(jnp.asarray(d, jnp.float32))
    for b in biases:
        scores = scores + b.astype(jnp.float32)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("...hqk,...khd->...qhd", probs, v)


def _chunked_attention(q, k, v, biases: Sequence[jax.Array],
                       chunk: int) -> jax.Array:
    """Query-chunked scan: peak score memory drops from O(Sq*Sk) to
    O(chunk*Sk) — the CUTLASS kernels' memory-efficiency, expressed as
    compiler-friendly control flow (lax.scan over query chunks)."""
    Sq = q.shape[-3]
    n_chunks = Sq // chunk

    def body(_, qc_and_bias):
        qc, bc = qc_and_bias
        return None, _attention_core(qc, k, v, bc)

    # [..., Sq, h, d] → [n, ..., chunk, h, d] with the chunk axis leading
    def split_q(x):
        lead = x.shape[:-3]
        return jnp.moveaxis(
            x.reshape(*lead, n_chunks, chunk, *x.shape[-2:]), -4, 0)

    def split_bias(b):
        lead = b.shape[:-2]
        return jnp.moveaxis(
            b.reshape(*lead, n_chunks, chunk, b.shape[-1]), -3, 0)

    qs = split_q(q)
    bs = [split_bias(jnp.broadcast_to(
        b, (*q.shape[:-3], q.shape[-2], Sq, k.shape[-3]))) for b in biases]
    _, out = jax.lax.scan(body, None, (qs, list(bs)))
    # [n, ..., chunk, h, d] → [..., Sq, h, d]
    out = jnp.moveaxis(out, 0, -4)
    return out.reshape(*q.shape[:-3], Sq, *q.shape[-2:])


def evoformer_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                        biases: Optional[List[jax.Array]] = None,
                        gate: Optional[jax.Array] = None,
                        chunk_size: int = 0) -> jax.Array:
    """DS4Sci_EvoformerAttention parity entry.

    q, k, v: [..., S, h, d] (any number of leading batch/row axes).
    biases:  list of arrays broadcastable to [..., h, Sq, Sk]
             (MSA mask bias, pair bias, triangle-edge bias, ...).
    gate:    optional sigmoid gate, same shape as the output (the
             reference's gating fused into the epilogue).
    chunk_size: 0 = auto (chunk when Sq > CHUNK_THRESHOLD).
    """
    biases = list(biases or [])
    Sq = q.shape[-3]
    chunk = chunk_size or (CHUNK_THRESHOLD if Sq > CHUNK_THRESHOLD else 0)
    if chunk and Sq % chunk == 0 and chunk < Sq:
        out = _chunked_attention(q, k, v, biases, chunk)
    else:
        out = _attention_core(q, k, v, biases)
    if gate is not None:
        out = jax.nn.sigmoid(gate.astype(jnp.float32)).astype(out.dtype) * out
    return out


# -- Evoformer block patterns (reference test coverage shapes) --------------

def msa_row_attention(msa: jax.Array, q_w, k_w, v_w, pair_bias: jax.Array,
                      gate_w=None, num_heads: int = 8):
    """MSA row-wise gated self-attention with pair bias
    (DS4Sci_EvoformerAttention's primary call pattern).

    msa: [B, R, S, C] (rows R, sequence S, channels C);
    pair_bias: [B, h, S, S] added to every row's scores.
    q_w/k_w/v_w/gate_w: [C, h, d] projections.
    """
    def proj(w):
        return jnp.einsum("brsc,chd->brshd", msa, w.astype(msa.dtype))

    q, k, v = proj(q_w), proj(k_w), proj(v_w)
    gate = proj(gate_w) if gate_w is not None else None
    bias = pair_bias[:, None]  # broadcast over rows: [B, 1, h, S, S]
    return evoformer_attention(q, k, v, [bias], gate=gate)


def triangle_attention(pair: jax.Array, q_w, k_w, v_w,
                       edge_bias_w, gate_w=None):
    """Triangle attention (starting node): attention along the second
    pair axis, biased by a learned projection of the third edge.

    pair: [B, I, J, C]; edge_bias_w: [C, h] → bias [B, h, J, J]
    broadcast over I.
    """
    def proj(w):
        return jnp.einsum("bijc,chd->bijhd", pair, w.astype(pair.dtype))

    q, k, v = proj(q_w), proj(k_w), proj(v_w)
    gate = proj(gate_w) if gate_w is not None else None
    # bias from the (j, k) edges: [B, J, K, h] → [B, h, J, K]
    bias = jnp.einsum("bjkc,ch->bhjk", pair, edge_bias_w.astype(pair.dtype))
    return evoformer_attention(q, k, v, [bias[:, None]], gate=gate)
