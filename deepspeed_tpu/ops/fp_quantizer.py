"""Floating-point (FP8) quantization.

Reference: ``csrc/fp_quantizer/{fp_quantize.cpp,fp_quantize_impl.cu}``
(852 LoC) — group-wise FP6/FP8/FP12 quantization with scale-per-group
and *selective dequantization* (dequantize only the rows a kernel needs,
``selective_dequantize`` in the pybind surface).

TPU-native: fp8 is a hardware dtype here (``float8_e4m3fn`` /
``float8_e5m2`` feed the MXU directly on v5p+), so quantization is a
cast with a per-group scale rather than custom bit-packing kernels; XLA
fuses the scale multiply into neighbors. FP6/FP12 have no TPU storage
dtype — requests for 6/12 bits round to fp8 with a warning (the
reference's own fallback ladder quantizes to the nearest supported
format).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from deepspeed_tpu.utils.logging import logger

FORMATS = {
    "e4m3": jnp.float8_e4m3fn,  # max normal 448
    "e5m2": jnp.float8_e5m2,  # max normal 57344
}
_FMT_MAX = {"e4m3": 448.0, "e5m2": 57344.0}
DEFAULT_GROUP = 128


def _resolve_format(q_bits: int = 8, fmt: Optional[str] = None) -> str:
    if fmt is not None:
        if fmt not in FORMATS:
            raise ValueError(f"unknown fp format '{fmt}' "
                             f"(choose from {sorted(FORMATS)})")
        return fmt
    if q_bits != 8:
        logger.warning(f"fp_quantizer: {q_bits}-bit formats have no TPU "
                       "storage dtype; rounding to fp8 e4m3")
    return "e4m3"


def fp_quantize(x: jax.Array, q_bits: int = 8, fmt: Optional[str] = None,
                group_size: int = DEFAULT_GROUP
                ) -> Tuple[jax.Array, jax.Array]:
    """x [..., N] → (fp8 values [..., N], fp32 scales [..., N/group]).

    Scales are chosen so each group's absmax maps to the format's max
    normal (full dynamic range per group — the reference's group-wise
    scaling).
    """
    fmt = _resolve_format(q_bits, fmt)
    n = x.shape[-1]
    if n % group_size:
        logger.warning(
            f"fp_quantize: last dim {n} not divisible by group_size "
            f"{group_size}; using one scale per row (coarser precision)")
    g = group_size if n % group_size == 0 else n
    xf = x.astype(jnp.float32)
    grouped = xf.reshape(*x.shape[:-1], n // g, g)
    amax = jnp.max(jnp.abs(grouped), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / _FMT_MAX[fmt], 1.0)
    q = (grouped / scale).astype(FORMATS[fmt])
    return (q.reshape(x.shape), scale[..., 0].reshape(*x.shape[:-1], n // g))


def fp_dequantize(q: jax.Array, scale: jax.Array,
                  group_size: int = DEFAULT_GROUP,
                  dtype=jnp.bfloat16) -> jax.Array:
    n = q.shape[-1]
    g = group_size if n % group_size == 0 else n
    grouped = q.astype(jnp.float32).reshape(*q.shape[:-1], n // g, g)
    out = grouped * scale[..., None]
    return out.reshape(q.shape).astype(dtype)


def selective_dequantize(q: jax.Array, scale: jax.Array,
                         rows: jax.Array, group_size: int = DEFAULT_GROUP,
                         dtype=jnp.bfloat16) -> jax.Array:
    """Dequantize only ``rows`` (leading-dim indices) — the reference's
    selective_dequantize: optimizer/attention kernels touch a slice of a
    quantized tensor without materializing the whole thing."""
    return fp_dequantize(q[rows], scale[rows], group_size, dtype)


def fp8_matmul(a: jax.Array, b: jax.Array,
               fmt: str = "e4m3", out_dtype=jnp.bfloat16) -> jax.Array:
    """Per-tensor-scaled fp8×fp8 matmul (the fp8 GEMM path the reference
    gets from its 6-bit cuda_linear kernels; on TPU the fp8 operands hit
    the MXU natively and XLA fuses the rescale)."""
    amax_a = jnp.max(jnp.abs(a)).astype(jnp.float32)
    amax_b = jnp.max(jnp.abs(b)).astype(jnp.float32)
    sa = jnp.where(amax_a > 0, amax_a / _FMT_MAX[fmt], 1.0)
    sb = jnp.where(amax_b > 0, amax_b / _FMT_MAX[fmt], 1.0)
    qa = (a.astype(jnp.float32) / sa).astype(FORMATS[fmt])
    qb = (b.astype(jnp.float32) / sb).astype(FORMATS[fmt])
    acc = jnp.matmul(qa, qb, preferred_element_type=jnp.float32)
    return (acc * (sa * sb)).astype(out_dtype)


def fp8_matmul_ste(x: jax.Array, w: jax.Array, fmt: str = "e4m3",
                   out_dtype=None) -> jax.Array:
    """fp8 forward matmul with STRAIGHT-THROUGH gradients: the forward
    quantizes both operands to fp8 (per-tensor scales, fp32 MXU
    accumulation — on v5p+ fp8 runs the MXU at 2x the bf16 rate), while
    the backward differentiates as if the matmul were exact, in the
    operands' own precision:

        dx = g @ w.T        dw = x.T @ g   (batch dims summed)

    This is the training-time fp8 recipe (Transformer-Engine-style
    delayed/just-in-time scaling without the history window): quantizing
    the gradient path too would need per-tensor e5m2 grad scaling for
    stability, and the backward matmuls are not the real-shape
    bottleneck — the forward MLP GEMMs are.

    ``x`` is [..., K] (any leading batch dims), ``w`` is [K, N].
    Returns [..., N] in ``out_dtype`` (default: x.dtype).
    """
    if out_dtype is None:
        out_dtype = x.dtype

    @jax.custom_vjp
    def _mm(x, w):
        lead = x.shape[:-1]
        x2 = x.reshape(-1, x.shape[-1])
        return fp8_matmul(x2, w, fmt=fmt,
                          out_dtype=out_dtype).reshape(*lead, w.shape[-1])

    def _fwd(x, w):
        return _mm(x, w), (x, w)

    def _bwd(res, g):
        x, w = res
        gx = g.astype(x.dtype)
        dx = jnp.matmul(gx, w.astype(x.dtype).T,
                        preferred_element_type=jnp.float32).astype(x.dtype)
        g2 = g.reshape(-1, g.shape[-1])
        x2 = x.reshape(-1, x.shape[-1])
        dw = jnp.matmul(x2.astype(jnp.float32).T, g2.astype(jnp.float32),
                        preferred_element_type=jnp.float32).astype(w.dtype)
        return dx, dw

    _mm.defvjp(_fwd, _bwd)
    return _mm(x, w)


class FPQuantizer:
    """Object API parity with the reference's ``FP_Quantize`` wrapper
    (deepspeed/ops/fp_quantizer/quantize.py): quantize / dequantize /
    selective_dequantize with stored group size + format."""

    def __init__(self, q_bits: int = 8, fmt: Optional[str] = None,
                 group_size: int = DEFAULT_GROUP):
        self.fmt = _resolve_format(q_bits, fmt)
        self.q_bits = 8
        self.group_size = group_size

    def quantize(self, x):
        return fp_quantize(x, fmt=self.fmt, group_size=self.group_size)

    def dequantize(self, q, scale, dtype=jnp.bfloat16):
        return fp_dequantize(q, scale, self.group_size, dtype)

    def selective_dequantize(self, q, scale, rows, dtype=jnp.bfloat16):
        return selective_dequantize(q, scale, rows, self.group_size, dtype)
