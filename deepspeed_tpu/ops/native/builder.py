"""JIT build + ctypes load of the native host library.

Reference: op_builder/builder.py:526,545 — JIT compile of csrc sources
into a per-version cache, with ``is_compatible()`` probing and graceful
fallback. pybind11 is unavailable in this image, so the library exposes a
plain C ABI consumed via ctypes; sources live in csrc/ at the repo root.

Cache key = SHA1 of all sources + compiler id, so editing a .cpp
invalidates the cached .so (same contract as TORCH_EXTENSIONS_DIR
rebuilds).
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess
import threading
from typing import Optional

from deepspeed_tpu.utils.logging import logger

_SOURCES = ("aio/dstpu_aio.cpp", "adam/dstpu_cpu_adam.cpp")
_LIB_BASENAME = "libdstpu_native.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _csrc_dir() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.normpath(os.path.join(here, "..", "..", "..", "csrc"))


def _cache_dir() -> str:
    root = os.environ.get("DSTPU_CACHE_DIR",
                          os.path.join(os.path.expanduser("~"), ".cache",
                                       "deepspeed_tpu"))
    return os.path.join(root, "native")


def _source_hash(paths) -> str:
    h = hashlib.sha1()
    for p in paths:
        with open(p, "rb") as f:
            h.update(f.read())
    cxx = shutil.which(os.environ.get("CXX", "g++")) or "none"
    h.update(cxx.encode())
    return h.hexdigest()[:16]


def build_native_lib(verbose: bool = False) -> Optional[ctypes.CDLL]:
    """Compile (cached) and load the native library; None if unavailable."""
    global _lib, _build_error
    with _lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            return None
        cxx = shutil.which(os.environ.get("CXX", "g++"))
        if cxx is None:
            _build_error = "no C++ compiler found"
            return None
        srcs = [os.path.join(_csrc_dir(), s) for s in _SOURCES]
        missing = [s for s in srcs if not os.path.exists(s)]
        if missing:
            _build_error = f"missing sources: {missing}"
            return None
        tag = _source_hash(srcs)
        out_dir = os.path.join(_cache_dir(), tag)
        so_path = os.path.join(out_dir, _LIB_BASENAME)
        if not os.path.exists(so_path):
            os.makedirs(out_dir, exist_ok=True)
            # per-process tmp name: concurrent builds (multi-process launch
            # sharing $HOME) must not write through the same inode
            tmp = f"{so_path}.tmp.{os.getpid()}"
            cmd = [cxx, "-O3", "-shared", "-fPIC", "-std=c++17", "-fopenmp",
                   "-march=native", *srcs, "-o", tmp, "-lpthread"]
            try:
                subprocess.run(cmd, check=True, capture_output=not verbose)
            except subprocess.CalledProcessError:
                # -march=native can fail on exotic hosts; retry portable.
                cmd = [c for c in cmd if c != "-march=native"]
                try:
                    subprocess.run(cmd, check=True, capture_output=not verbose)
                except subprocess.CalledProcessError as e:
                    _build_error = f"native build failed: {e}"
                    logger.warning(_build_error)
                    return None
            os.replace(tmp, so_path)
        try:
            _lib = ctypes.CDLL(so_path)
        except OSError as e:
            _build_error = f"dlopen failed: {e}"
            return None
        _declare(_lib)
        return _lib


def native_available() -> bool:
    return build_native_lib() is not None


def native_status() -> str:
    """For dstpu-report: 'built' or the failure reason."""
    if build_native_lib() is not None:
        return "built"
    return _build_error or "not built"


def _declare(lib: ctypes.CDLL) -> None:
    c = ctypes
    i64, vp, cp = c.c_int64, c.c_void_p, c.c_char_p
    f32p = c.POINTER(c.c_float)
    u16p = c.POINTER(c.c_uint16)
    flt, i32 = c.c_float, c.c_int

    lib.dstpu_aio_create.restype = vp
    lib.dstpu_aio_create.argtypes = [i32, i32, i32]
    lib.dstpu_aio_create2.restype = vp
    lib.dstpu_aio_create2.argtypes = [i32, i32, i32, i32]
    lib.dstpu_aio_backend.restype = i32
    lib.dstpu_aio_backend.argtypes = [vp]
    lib.dstpu_aio_destroy.argtypes = [vp]
    for name in ("dstpu_aio_pread", "dstpu_aio_sync_pread"):
        fn = getattr(lib, name)
        fn.restype = i32
        fn.argtypes = [vp, vp, i64, cp, i64]
    for name in ("dstpu_aio_pwrite", "dstpu_aio_sync_pwrite"):
        fn = getattr(lib, name)
        fn.restype = i32
        fn.argtypes = [vp, vp, i64, cp, i64]
    lib.dstpu_aio_wait.restype = i32
    lib.dstpu_aio_wait.argtypes = [vp]
    lib.dstpu_aio_bytes_read.restype = i64
    lib.dstpu_aio_bytes_read.argtypes = [vp]
    lib.dstpu_aio_bytes_written.restype = i64
    lib.dstpu_aio_bytes_written.argtypes = [vp]
    lib.dstpu_alloc_pinned.restype = vp
    lib.dstpu_alloc_pinned.argtypes = [i64]
    lib.dstpu_free_pinned.argtypes = [vp, i64]

    lib.dstpu_adam_step.argtypes = [f32p, f32p, f32p, f32p, i64, flt, flt,
                                    flt, flt, flt, i32, i32, i32, u16p]
    lib.dstpu_adam_step_bf16grad.argtypes = [f32p, u16p, f32p, f32p, i64,
                                             flt, flt, flt, flt, flt, i32,
                                             i32, i32, u16p]
    lib.dstpu_lion_step.argtypes = [f32p, f32p, f32p, i64, flt, flt, flt,
                                    flt, u16p]
    lib.dstpu_adagrad_step.argtypes = [f32p, f32p, f32p, i64, flt, flt, flt,
                                       u16p]
    lib.dstpu_f32_to_bf16.argtypes = [f32p, u16p, i64]
    lib.dstpu_bf16_to_f32.argtypes = [u16p, f32p, i64]
    lib.dstpu_sq_norm.restype = ctypes.c_double
    lib.dstpu_sq_norm.argtypes = [f32p, i64]
