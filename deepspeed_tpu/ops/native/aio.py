"""Python-facing async I/O handle over the native library.

Reference: the ``aio_handle`` Python object built by AsyncIOBuilder
(csrc/aio/py_lib/deepspeed_py_aio_handle.cpp — async_pread/async_pwrite/
wait, get_block_size/get_queue_depth...). numpy arrays stand in for
pinned torch tensors; ``PinnedBuffer`` wraps a page-aligned, mlocked
allocation so O_DIRECT can engage and addresses stay stable across async
submits.

Falls back to a pure-Python threadpool implementation when the native
build is unavailable (no compiler) so the swap stack stays functional.
"""

from __future__ import annotations

import concurrent.futures as _fut
import ctypes
import os
from typing import List, Optional

import numpy as np

from deepspeed_tpu.ops.native.builder import build_native_lib

DEFAULT_BLOCK_SIZE = 1 << 20
DEFAULT_QUEUE_DEPTH = 32
DEFAULT_THREADS = 8

_TUNED_CONFIG_ENV = "DSTPU_NVME_CONFIG"
_TUNED_CONFIG_DEFAULT = os.path.expanduser("~/.dstpu_nvme_config.json")


_tuned_cache = None


def tuned_aio_defaults() -> dict:
    """AIO knobs saved by ``dstpu-nvme-tune`` (reference ds_nvme_tune
    writes the optimal aio config for the swap stack). Returns the
    built-in defaults when no tuned file exists or it is malformed.
    Parsed once per process (per config path)."""
    global _tuned_cache
    path = os.environ.get(_TUNED_CONFIG_ENV, _TUNED_CONFIG_DEFAULT)
    if _tuned_cache is not None and _tuned_cache[0] == path:
        return _tuned_cache[1]
    try:
        import json

        with open(path) as f:
            aio = json.load(f)["aio"]
        out = {"block_size": int(aio["block_size"]),
               "queue_depth": int(aio["queue_depth"]),
               "num_threads": int(aio.get("thread_count", DEFAULT_THREADS)),
               "backend": str(aio.get("backend", "auto"))}
    except (OSError, KeyError, ValueError, TypeError, IndexError):
        out = {"block_size": DEFAULT_BLOCK_SIZE,
               "queue_depth": DEFAULT_QUEUE_DEPTH,
               "num_threads": DEFAULT_THREADS,
               "backend": "auto"}
    _tuned_cache = (path, out)
    return out


def _as_bytes_view(arr: np.ndarray) -> np.ndarray:
    assert arr.flags["C_CONTIGUOUS"], "aio buffers must be contiguous"
    return arr


class PinnedBuffer:
    """Page-aligned host buffer exposed as a numpy array.

    Reference: deepspeed_pin_tensor.cpp (new_cpu_locked_tensor).
    """

    def __init__(self, nbytes: int, dtype=np.float32):
        self._lib = build_native_lib()
        self.nbytes = int(nbytes)
        if self._lib is not None:
            self._ptr = self._lib.dstpu_alloc_pinned(self.nbytes)
            if not self._ptr:
                raise MemoryError(f"pinned alloc of {nbytes} bytes failed")
            buf = (ctypes.c_char * self.nbytes).from_address(self._ptr)
            self.array = np.frombuffer(buf, dtype=dtype)
        else:
            self._ptr = None
            self.array = np.zeros(self.nbytes // np.dtype(dtype).itemsize,
                                  dtype=dtype)

    def free(self):
        if self._ptr is not None and self._lib is not None:
            self._lib.dstpu_free_pinned(self._ptr, self.nbytes)
            self._ptr = None
            self.array = None

    def __del__(self):  # pragma: no cover - gc timing
        try:
            self.free()
        except Exception:
            pass


class AsyncIOHandle:
    """Async file reader/writer of numpy arrays.

    API parity with the reference aio_handle: async_pread/async_pwrite
    queue work, wait() blocks for all in-flight requests and returns the
    number of failed requests (0 == success).
    """

    BACKENDS = {"auto": 0, "threads": 1, "uring": 2}

    def __init__(self, block_size: Optional[int] = None,
                 queue_depth: Optional[int] = None,
                 num_threads: Optional[int] = None,
                 backend: Optional[str] = None):
        if None in (block_size, queue_depth, num_threads):
            tuned = tuned_aio_defaults()
            block_size = block_size or tuned["block_size"]
            queue_depth = queue_depth or tuned["queue_depth"]
            num_threads = num_threads or tuned["num_threads"]
        backend = (backend or os.environ.get("DSTPU_AIO_BACKEND")
                   or tuned_aio_defaults()["backend"])
        if backend not in self.BACKENDS:
            raise ValueError(f"backend must be one of {set(self.BACKENDS)}, "
                             f"got {backend!r}")
        self.block_size = block_size
        self.queue_depth = queue_depth
        self.num_threads = num_threads
        self._lib = build_native_lib()
        if self._lib is not None:
            # io_uring (DeepNVMe-class queue depth) when available;
            # create2 falls back to the thread pool inside the library
            self._h = self._lib.dstpu_aio_create2(
                block_size, queue_depth, num_threads,
                self.BACKENDS[backend])
            if not self._h:
                raise IOError(
                    f"aio backend {backend!r} unavailable on this host "
                    "(io_uring_setup refused — seccomp'd container or "
                    "old kernel); use backend='auto' for the fallback")
            self._pool = None
        else:
            if backend == "uring":
                raise IOError(
                    "aio backend 'uring' needs the native library, which "
                    "failed to build on this host; use backend='auto'")
            self._h = None
            self._pool = _fut.ThreadPoolExecutor(max_workers=num_threads)
        self._futures: List[_fut.Future] = []
        # buffers of in-flight requests: the worker threads read/write the
        # raw pointers, so the arrays must outlive the request (a GC'd
        # source array would be use-after-free in the native pool)
        self._refs: List[np.ndarray] = []

    # -- async API ---------------------------------------------------------
    def async_pread(self, arr: np.ndarray, path: str, offset: int = 0) -> int:
        arr = _as_bytes_view(arr)
        self._refs.append(arr)
        if self._h is not None:
            rid = self._lib.dstpu_aio_pread(
                self._h, arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes,
                path.encode(), offset)
            if rid < 0:
                raise IOError(f"aio pread submit failed for {path}")
            return rid
        self._futures.append(self._pool.submit(self._py_read, arr, path, offset))
        return len(self._futures)

    def async_pwrite(self, arr: np.ndarray, path: str, offset: int = 0) -> int:
        arr = _as_bytes_view(arr)
        self._refs.append(arr)
        if self._h is not None:
            rid = self._lib.dstpu_aio_pwrite(
                self._h, arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes,
                path.encode(), offset)
            if rid < 0:
                raise IOError(f"aio pwrite submit failed for {path}")
            return rid
        self._futures.append(self._pool.submit(self._py_write, arr, path, offset))
        return len(self._futures)

    def wait(self) -> int:
        if self._h is not None:
            errors = self._lib.dstpu_aio_wait(self._h)
            self._refs.clear()
            return errors
        errors = 0
        for f in self._futures:
            try:
                f.result()
            except Exception:
                errors += 1
        self._futures.clear()
        self._refs.clear()
        return errors

    # -- sync convenience --------------------------------------------------
    def pread(self, arr: np.ndarray, path: str, offset: int = 0) -> None:
        self.async_pread(arr, path, offset)
        errs = self.wait()
        if errs:
            raise IOError(f"aio read of {path} failed ({errs} errors)")

    def pwrite(self, arr: np.ndarray, path: str, offset: int = 0) -> None:
        self.async_pwrite(arr, path, offset)
        errs = self.wait()
        if errs:
            raise IOError(f"aio write of {path} failed ({errs} errors)")

    @property
    def backend(self) -> str:
        """Resolved backend: "uring" | "threads" | "python"."""
        if self._h is None:
            return "python"
        return "uring" if self._lib.dstpu_aio_backend(self._h) == 2 \
            else "threads"

    # -- stats -------------------------------------------------------------
    def bytes_read(self) -> int:
        return self._lib.dstpu_aio_bytes_read(self._h) if self._h else -1

    def bytes_written(self) -> int:
        return self._lib.dstpu_aio_bytes_written(self._h) if self._h else -1

    # -- python fallback ---------------------------------------------------
    @staticmethod
    def _py_read(arr: np.ndarray, path: str, offset: int):
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(arr.nbytes)
        if len(data) != arr.nbytes:
            raise IOError(f"short read from {path}: got {len(data)} of "
                          f"{arr.nbytes} bytes")
        arr.view(np.uint8).reshape(-1)[:] = np.frombuffer(data, np.uint8)

    @staticmethod
    def _py_write(arr: np.ndarray, path: str, offset: int):
        mode = "r+b" if os.path.exists(path) else "wb"
        with open(path, mode) as f:
            f.seek(offset)
            f.write(arr.tobytes())

    def close(self):
        if self._h is not None:
            self._lib.dstpu_aio_destroy(self._h)
            self._h = None
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    def __del__(self):  # pragma: no cover
        try:
            self.close()
        except Exception:
            pass
