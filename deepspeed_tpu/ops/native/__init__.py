"""Native (C++) host-side components: AIO, pinned buffers, host optimizers.

Reference: csrc/ tree built by op_builder JIT infrastructure
(op_builder/builder.py:526 ``load()``). Here the native pieces are
host-side only (the device compute path is XLA/Pallas), built on demand
with g++ and loaded over ctypes.
"""

from deepspeed_tpu.ops.native.builder import build_native_lib, native_available
