"""Host-side optimizer steps over numpy arrays (offloaded ZeRO states).

Reference: deepspeed/ops/adam/cpu_adam.py ``DeepSpeedCPUAdam`` wrapping
csrc/adam/cpu_adam.cpp; also cpu_lion/cpu_adagrad. Numpy arrays play the
role of CPU torch tensors; the native OpenMP kernels do the math, with a
pure-numpy fallback when no compiler exists.

Each optimizer owns fp32 master params + moments for ONE flat shard (the
caller — runtime/offload.py — handles flattening, sharding and device
transfer). ``step`` optionally emits a bf16 shadow copy for upload.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np

from deepspeed_tpu.ops.native.builder import build_native_lib


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _u16p(a: Optional[np.ndarray]):
    if a is None:
        return None
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint16))


class CPUAdam:
    """Adam/AdamW on a flat fp32 shard (reference: DeepSpeedCPUAdam)."""

    def __init__(self, n: int, lr=1e-3, betas=(0.9, 0.999), eps=1e-8,
                 weight_decay=0.0, adamw_mode=True, bias_correction=True):
        self.n = int(n)
        self.lr, self.betas, self.eps = lr, betas, eps
        self.weight_decay = weight_decay
        self.adamw_mode = adamw_mode
        self.bias_correction = bias_correction
        self.step_count = 0
        self.exp_avg = np.zeros(self.n, np.float32)
        self.exp_avg_sq = np.zeros(self.n, np.float32)
        self._lib = build_native_lib()

    def step(self, param_fp32: np.ndarray, grad: np.ndarray,
             param_bf16_out: Optional[np.ndarray] = None,
             lr: Optional[float] = None) -> None:
        assert param_fp32.dtype == np.float32 and param_fp32.size == self.n
        self.ensure_state()
        self.step_count += 1
        lr = self.lr if lr is None else float(lr)
        b1, b2 = self.betas
        if self._lib is not None:
            if grad.dtype == np.float32:
                self._lib.dstpu_adam_step(
                    _f32p(param_fp32), _f32p(grad), _f32p(self.exp_avg),
                    _f32p(self.exp_avg_sq), self.n, lr, b1, b2, self.eps,
                    self.weight_decay, self.step_count,
                    int(self.adamw_mode), int(self.bias_correction),
                    _u16p(param_bf16_out))
                return
            if grad.dtype == np.uint16:  # bf16 bit pattern
                self._lib.dstpu_adam_step_bf16grad(
                    _f32p(param_fp32), _u16p(grad), _f32p(self.exp_avg),
                    _f32p(self.exp_avg_sq), self.n, lr, b1, b2, self.eps,
                    self.weight_decay, self.step_count,
                    int(self.adamw_mode), int(self.bias_correction),
                    _u16p(param_bf16_out))
                return
        self._numpy_step(param_fp32, grad, lr, param_bf16_out)

    def _numpy_step(self, p, grad, lr, out_bf16):
        if grad.dtype == np.uint16:
            grad = bf16_to_f32(grad)
        g = grad.astype(np.float32, copy=False)
        if not self.adamw_mode and self.weight_decay > 0:
            g = g + self.weight_decay * p
        self.exp_avg *= self.betas[0]
        self.exp_avg += (1 - self.betas[0]) * g
        self.exp_avg_sq *= self.betas[1]
        self.exp_avg_sq += (1 - self.betas[1]) * g * g
        bc1 = 1 - self.betas[0] ** self.step_count if self.bias_correction else 1.0
        bc2 = 1 - self.betas[1] ** self.step_count if self.bias_correction else 1.0
        denom = np.sqrt(self.exp_avg_sq) / np.sqrt(bc2) + self.eps
        # decoupled wd uses plain lr (torch AdamW / optax), not lr/bc1
        if self.adamw_mode and self.weight_decay > 0:
            p -= lr * self.weight_decay * p
        p -= (lr / bc1) * (self.exp_avg / denom)
        if out_bf16 is not None:
            out_bf16[:] = f32_to_bf16(p)

    def state_dict(self):
        self.ensure_state()
        return {"exp_avg": self.exp_avg, "exp_avg_sq": self.exp_avg_sq,
                "step": self.step_count}

    def load_state_dict(self, sd):
        self.ensure_state()
        self.exp_avg[:] = sd["exp_avg"]
        self.exp_avg_sq[:] = sd["exp_avg_sq"]
        self.step_count = int(sd["step"])

    def ensure_state(self):
        """(Re)allocate moment buffers after detach_state."""
        if self.exp_avg is None:
            self.exp_avg = np.zeros(self.n, np.float32)
        if self.exp_avg_sq is None:
            self.exp_avg_sq = np.zeros(self.n, np.float32)

    def detach_state(self):
        """Drop moment buffers from host RAM (NVMe tier: the swap store
        holds the truth between steps)."""
        self.exp_avg = None
        self.exp_avg_sq = None


class CPULion:
    """Lion on a flat fp32 shard (reference: deepspeed/ops/lion)."""

    def __init__(self, n: int, lr=1e-4, betas=(0.9, 0.99), weight_decay=0.0):
        self.n = int(n)
        self.lr, self.betas, self.weight_decay = lr, betas, weight_decay
        self.exp_avg = np.zeros(self.n, np.float32)
        self._lib = build_native_lib()

    def step(self, param_fp32, grad, param_bf16_out=None, lr=None):
        self.ensure_state()
        lr = self.lr if lr is None else float(lr)
        b1, b2 = self.betas
        if self._lib is not None and grad.dtype == np.float32:
            self._lib.dstpu_lion_step(
                _f32p(param_fp32), _f32p(grad), _f32p(self.exp_avg), self.n,
                lr, b1, b2, self.weight_decay, _u16p(param_bf16_out))
            return
        if grad.dtype == np.uint16:
            grad = bf16_to_f32(grad)
        c = b1 * self.exp_avg + (1 - b1) * grad
        param_fp32 *= (1 - lr * self.weight_decay)
        param_fp32 -= lr * np.sign(c)
        self.exp_avg *= b2
        self.exp_avg += (1 - b2) * grad
        if param_bf16_out is not None:
            param_bf16_out[:] = f32_to_bf16(param_fp32)

    def state_dict(self):
        self.ensure_state()
        return {"exp_avg": self.exp_avg}

    def load_state_dict(self, sd):
        self.ensure_state()
        self.exp_avg[:] = sd["exp_avg"]

    def ensure_state(self):
        if self.exp_avg is None:
            self.exp_avg = np.zeros(self.n, np.float32)

    def detach_state(self):
        self.exp_avg = None


class CPUAdagrad:
    """Adagrad on a flat fp32 shard (reference: csrc/adagrad)."""

    def __init__(self, n: int, lr=1e-2, eps=1e-10, weight_decay=0.0):
        self.n = int(n)
        self.lr, self.eps, self.weight_decay = lr, eps, weight_decay
        self.exp_avg_sq = np.zeros(self.n, np.float32)
        self._lib = build_native_lib()

    def step(self, param_fp32, grad, param_bf16_out=None, lr=None):
        self.ensure_state()
        lr = self.lr if lr is None else float(lr)
        if self._lib is not None and grad.dtype == np.float32:
            self._lib.dstpu_adagrad_step(
                _f32p(param_fp32), _f32p(grad), _f32p(self.exp_avg_sq),
                self.n, lr, self.eps, self.weight_decay, _u16p(param_bf16_out))
            return
        if grad.dtype == np.uint16:
            grad = bf16_to_f32(grad)
        g = grad + self.weight_decay * param_fp32 if self.weight_decay > 0 else grad
        self.exp_avg_sq += g * g
        param_fp32 -= lr * g / (np.sqrt(self.exp_avg_sq) + self.eps)
        if param_bf16_out is not None:
            param_bf16_out[:] = f32_to_bf16(param_fp32)

    def state_dict(self):
        self.ensure_state()
        return {"exp_avg_sq": self.exp_avg_sq}

    def load_state_dict(self, sd):
        self.ensure_state()
        self.exp_avg_sq[:] = sd["exp_avg_sq"]

    def ensure_state(self):
        if self.exp_avg_sq is None:
            self.exp_avg_sq = np.zeros(self.n, np.float32)

    def detach_state(self):
        self.exp_avg_sq = None


def f32_to_bf16(x: np.ndarray) -> np.ndarray:
    """Round-to-nearest-even fp32 -> bf16 bit patterns (uint16)."""
    lib = build_native_lib()
    out = np.empty(x.size, np.uint16)
    if lib is not None:
        lib.dstpu_f32_to_bf16(_f32p(np.ascontiguousarray(x, np.float32)),
                              _u16p(out), x.size)
        return out.reshape(x.shape)
    bits = np.ascontiguousarray(x, np.float32).view(np.uint32)
    lsb = (bits >> 16) & 1
    rounded = bits + 0x7FFF + lsb
    return (rounded >> 16).astype(np.uint16).reshape(x.shape)


def bf16_to_f32(x: np.ndarray) -> np.ndarray:
    """bf16 bit patterns (uint16) -> fp32."""
    return (x.astype(np.uint32) << 16).view(np.float32).reshape(x.shape)


CPU_OPTIMIZERS = {"adam": CPUAdam, "adamw": CPUAdam, "lion": CPULion,
                  "adagrad": CPUAdagrad}
