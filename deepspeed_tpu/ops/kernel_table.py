"""Per-(kernel, shape-bucket) win/loss table driving Pallas-vs-XLA dispatch.

The reference ships ~60k LoC of hand-written kernels and trusts them
unconditionally; this repo makes every Pallas kernel justify its place
with a measurement. ``tools/kernel_bench.py`` times each kernel against
its XLA fallback per shape bucket (fwd+bwd where the kernel is
differentiable) and persists the result here, next to the autotuned
real-shape record (``docs/autotuned/kernel_table.json``).
``ops/registry.py`` consults the table at dispatch time — compat
probing stays the outer guard; a kernel runs on a bucket only when its
measured win ratio (xla_ms / kernel_ms) is >= 1.0 there.

Schema (kernel_table/v1)::

    {"_meta": {"schema": "kernel_table/v1", "backend": "tpu", ...},
     "entries": {
       "flash_attention": {
         "s8192_d64_causal": {"kernel_ms": 1.9, "xla_ms": 4.1,
                              "ratio": 2.16, "backend": "tpu",
                              "blocks": {"block_q": 1024,
                                         "block_k": 1024}}}}}

Entries are backend-scoped: a v5e measurement never drives a CPU run
(there the interpreter-mode kernel always loses, and the legacy
heuristic already answers "xla"). ``DSTPU_KERNEL_TABLE`` overrides the
table path — tests use it to flip a bucket to losing and assert the
registry routes that bucket to XLA bit-identically.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from pathlib import Path
from typing import Any, Dict, Optional

SCHEMA = "kernel_table/v1"

# docs/autotuned/kernel_table.json at the repo root, resolved relative
# to this file so in-tree checkouts find it without an env var
DEFAULT_TABLE = str(
    Path(__file__).resolve().parents[2] / "docs" / "autotuned"
    / "kernel_table.json")

_LOCK = threading.Lock()
_CACHE: Dict[str, Optional[Dict[str, Any]]] = {}


def table_path() -> str:
    return os.environ.get("DSTPU_KERNEL_TABLE", DEFAULT_TABLE)


def invalidate_cache() -> None:
    """Drop the parsed-table cache (tests swap tables via env var)."""
    with _LOCK:
        _CACHE.clear()


def load_table(path: Optional[str] = None) -> Optional[Dict[str, Any]]:
    """Parsed table dict, or None when absent/unreadable (never raises:
    a missing table must degrade to the heuristic, not crash a step)."""
    p = path or table_path()
    with _LOCK:
        if p in _CACHE:
            return _CACHE[p]
    try:
        with open(p) as f:
            data = json.load(f)
        if not isinstance(data, dict) or "entries" not in data:
            data = None
    except Exception:
        data = None
    with _LOCK:
        _CACHE[p] = data
    return data


# ---------------------------------------------------------------------------
# shape bucketing
# ---------------------------------------------------------------------------


def bucket_pow2(n: int, lo: int = 128) -> int:
    """Round ``n`` up to a power of two (floor ``lo``) — the same
    compile-cache bucketing engine_v2 uses for prefill chunk lengths, so
    one measured bucket covers every shape that compiles to it."""
    b = lo
    while b < n:
        b *= 2
    return b


def attention_bucket(seq: int, head_dim: int, causal: bool) -> str:
    """Bucket key for the attention kernels. Batch and head count are
    folded out: the flash-vs-XLA crossover is dominated by S and D (the
    grid is over B*N either way)."""
    return (f"s{bucket_pow2(seq)}_d{head_dim}"
            f"_{'causal' if causal else 'full'}")


def gmm_bucket(m: int, k: int, n: int, groups: int) -> str:
    """Bucket key for the grouped matmul: token rows bucket to powers of
    two; k/n/group-count are architecture constants."""
    return f"m{bucket_pow2(m)}_k{k}_n{n}_g{groups}"


# ---------------------------------------------------------------------------
# decisions
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TableDecision:
    measured: bool
    win: bool
    ratio: Optional[float]
    blocks: Optional[Dict[str, int]]
    reason: str


def _backend() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:
        return "unknown"


def decide(kernel: str, bucket: str,
           path: Optional[str] = None) -> TableDecision:
    """Look up (kernel, bucket) for the current backend.

    Measured → win iff ratio >= 1.0 (ratio = xla_ms / kernel_ms; the
    kernel must at least tie to earn the slot). Unmeasured → the caller
    falls back to its legacy heuristic.
    """
    data = load_table(path)
    if data is None:
        return TableDecision(False, False, None, None, "no kernel table")
    entry = (data.get("entries") or {}).get(kernel, {}).get(bucket)
    if not isinstance(entry, dict):
        return TableDecision(False, False, None, None,
                             f"bucket {bucket} unmeasured")
    be = entry.get("backend")
    if be is not None and be != _backend():
        return TableDecision(
            False, False, None, None,
            f"bucket {bucket} measured on {be}, running on {_backend()}")
    try:
        ratio = float(entry["ratio"])
    except Exception:
        return TableDecision(False, False, None, None,
                             f"bucket {bucket} entry malformed")
    blocks = entry.get("blocks")
    if not isinstance(blocks, dict):
        blocks = None
    win = ratio >= 1.0
    verdict = "win" if win else "loss"
    return TableDecision(True, win, ratio, blocks,
                         f"measured {verdict} ratio {ratio:.2f} on {bucket}")


def record(kernel: str, bucket: str, kernel_ms: float, xla_ms: float,
           blocks: Optional[Dict[str, int]] = None,
           backend: Optional[str] = None,
           path: Optional[str] = None) -> Dict[str, Any]:
    """Persist one measurement (read-modify-write; kernel_bench calls
    this per bucket). Returns the entry written."""
    p = path or table_path()
    try:
        with open(p) as f:
            data = json.load(f)
        if not isinstance(data, dict):
            data = {}
    except Exception:
        data = {}
    data.setdefault("_meta", {})["schema"] = SCHEMA
    entry = {
        "kernel_ms": round(float(kernel_ms), 4),
        "xla_ms": round(float(xla_ms), 4),
        "ratio": round(float(xla_ms) / max(float(kernel_ms), 1e-9), 4),
        "backend": backend or _backend(),
    }
    if blocks:
        entry["blocks"] = {k: int(v) for k, v in blocks.items()}
    data.setdefault("entries", {}).setdefault(kernel, {})[bucket] = entry
    Path(p).parent.mkdir(parents=True, exist_ok=True)
    with open(p, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
    invalidate_cache()
    return entry
