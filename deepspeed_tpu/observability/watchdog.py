"""Stall watchdog: silent hangs become actionable reports.

Two failure shapes, two mechanisms:

* a step that *finishes* but takes N× the rolling-mean step time is
  flagged post-hoc by :meth:`StallWatchdog.observe` (counter + warning
  with the ratio);
* a step that *never finishes* — a wedged collective, a deadlocked host
  callback, an NFS checkpoint hang — is caught by a daemon thread: the
  engine ``arm()``s a deadline before dispatching the compiled step and
  ``disarm()``s after it completes; if the deadline passes while armed,
  the thread dumps every Python thread's stack plus device memory stats
  to the log and the hub, exactly once per armed window.

The thread sleeps on an Event and is started lazily on first arm, so a
disabled watchdog costs nothing and an enabled one costs one mostly-
blocked daemon thread.

Env overrides (beat the config block): ``DSTPU_WATCHDOG=0`` disables,
``DSTPU_WATCHDOG_FACTOR`` and ``DSTPU_WATCHDOG_MIN_S`` tune the
threshold ``max(factor * rolling_mean_step, min_seconds)``.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from collections import deque
from typing import Callable, Optional

from deepspeed_tpu.utils.logging import logger


class StallWatchdog:
    def __init__(self, factor: float = 8.0, min_seconds: float = 30.0,
                 history: int = 64, warmup_steps: int = 5,
                 enabled: bool = True,
                 report_fn: Optional[Callable[[str], None]] = None):
        self.enabled = enabled
        self.factor = float(factor)
        self.min_seconds = float(min_seconds)
        self.warmup_steps = int(warmup_steps)
        self._durations: deque = deque(maxlen=history)
        self._report_fn = report_fn
        self.stalls = 0       # hang reports fired by the thread
        self.slow_steps = 0   # finished steps over threshold
        self._lock = threading.Lock()
        self._deadline: Optional[float] = None
        self._armed_step: Optional[int] = None
        self._fired = False
        self._thread: Optional[threading.Thread] = None
        self._wake = threading.Event()
        self._stop = False

    @classmethod
    def from_config(cls, cfg, report_fn=None) -> "StallWatchdog":
        enabled = getattr(cfg, "enabled", True)
        factor = getattr(cfg, "factor", 8.0)
        min_s = getattr(cfg, "min_seconds", 30.0)
        if os.environ.get("DSTPU_WATCHDOG", "") == "0":
            enabled = False
        factor = float(os.environ.get("DSTPU_WATCHDOG_FACTOR", factor))
        min_s = float(os.environ.get("DSTPU_WATCHDOG_MIN_S", min_s))
        return cls(factor=factor, min_seconds=min_s, enabled=enabled,
                   report_fn=report_fn)

    # -- rolling statistics -------------------------------------------
    def rolling_mean(self) -> Optional[float]:
        with self._lock:
            if len(self._durations) < self.warmup_steps:
                return None
            return sum(self._durations) / len(self._durations)

    def threshold(self) -> Optional[float]:
        mean = self.rolling_mean()
        if mean is None:
            return None
        return max(self.factor * mean, self.min_seconds)

    def observe(self, duration_s: float, step: Optional[int] = None) -> bool:
        """Record a finished step; returns True if it was flagged slow."""
        if not self.enabled:
            return False
        thr = self.threshold()
        slow = thr is not None and duration_s > thr
        if slow:
            self.slow_steps += 1
            mean = self.rolling_mean() or duration_s
            logger.warning(
                f"stall watchdog: step{'' if step is None else ' ' + str(step)}"
                f" took {duration_s:.2f}s = {duration_s / max(mean, 1e-9):.1f}x"
                f" the rolling mean ({mean:.2f}s over "
                f"{len(self._durations)} steps)")
        with self._lock:
            # a flagged step does not poison the baseline: the mean keeps
            # reflecting normal steps so one hiccup can't mask the next
            if not slow:
                self._durations.append(float(duration_s))
        return slow

    # -- hang detection (armed window + daemon thread) ----------------
    def arm(self, step: Optional[int] = None, window: int = 1) -> None:
        """Arm a deadline for ``step``. ``window`` scales the deadline to
        cover a dispatch-ahead in-flight window: with K unresolved steps
        queued behind ``step`` the pipelined engine arms the OLDEST one
        with window=K, so the deadline budgets K steps of device work
        instead of flagging a healthy full pipeline as a stall."""
        if not self.enabled:
            return
        thr = self.threshold()
        if thr is None:
            return  # not enough history yet
        with self._lock:
            self._deadline = time.monotonic() + thr * max(1, int(window))
            self._armed_step = step
            self._fired = False
        self._ensure_thread()
        self._wake.set()

    def disarm(self) -> None:
        with self._lock:
            self._deadline = None
            self._armed_step = None

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(
            target=self._run, name="dstpu-stall-watchdog", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop = True
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)

    def _run(self) -> None:
        poll = max(0.01, min(1.0, self.min_seconds / 10.0))
        while not self._stop:
            with self._lock:
                deadline, fired = self._deadline, self._fired
                step = self._armed_step
            if deadline is None:
                self._wake.wait()   # nothing armed: sleep until arm()
                self._wake.clear()
                continue
            now = time.monotonic()
            if not fired and now >= deadline:
                with self._lock:
                    self._fired = True
                self.stalls += 1
                self._report(step, now - deadline)
            else:
                time.sleep(min(poll, max(deadline - now, 0.0) + poll))

    # -- reporting -----------------------------------------------------
    def _report(self, step: Optional[int], overdue_s: float) -> None:
        try:
            report = self.build_report(step, overdue_s)
            logger.error(report)
            if self._report_fn is not None:
                self._report_fn(report)
        except Exception as e:  # the watchdog must never kill the run
            logger.warning(f"stall watchdog report failed: {e}")
        try:
            # the post-mortem artifact for a worker that never recovers:
            # the ring's last events survive on disk even if the process
            # is OOM-killed seconds after this fires
            from deepspeed_tpu.observability.flight_recorder import \
                dump_flight_recorder

            dump_flight_recorder("watchdog", step=step,
                                 overdue_s=round(overdue_s, 3))
        except Exception:
            pass

    def build_report(self, step: Optional[int] = None,
                     overdue_s: float = 0.0) -> str:
        thr = self.threshold()
        lines = [
            "=" * 70,
            f"STALL WATCHDOG: step{'' if step is None else ' ' + str(step)} "
            f"has run {overdue_s:.1f}s past its "
            f"{0.0 if thr is None else thr:.1f}s deadline "
            f"(rolling mean {self.rolling_mean() or 0.0:.2f}s, "
            f"factor {self.factor}x)",
        ]
        try:
            from deepspeed_tpu.utils.memory import device_memory_stats

            mem = device_memory_stats()
            lines.append(f"device memory: {mem if mem else 'unavailable'}")
        except Exception as e:
            lines.append(f"device memory: error ({e})")
        lines.append("python stacks:")
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        for ident, frame in frames.items():
            name = names.get(ident, "?")
            if name == "dstpu-stall-watchdog":
                continue
            lines.append(f"--- thread {name} ({ident}) ---")
            lines.append("".join(traceback.format_stack(frame)).rstrip())
        # the last seconds before the hang: flight-recorder tail (step
        # phases, traced collectives, checkpoint/offload transitions)
        # plus the last completed StepTrace rows — together they say what
        # the worker was *doing*, where the stacks say where it is stuck
        try:
            from deepspeed_tpu.observability.flight_recorder import \
                get_flight_recorder

            tail = get_flight_recorder().tail_lines(last=32)
            if tail:
                lines.append("flight recorder tail (newest last):")
                lines.append(tail)
        except Exception as e:
            lines.append(f"flight recorder: error ({e})")
        try:
            from deepspeed_tpu.observability.hub import peek_hub

            hub = peek_hub()
            rows = list(hub.step_history)[-8:] if hub is not None else []
            if rows:
                lines.append("last step traces:")
                for t in rows:
                    lines.append(
                        f"  step {t.step}: wall {t.wall_ms:.1f} ms"
                        + (f", loss {t.loss:.4f}" if t.loss is not None
                           else "")
                        + (f", host_gap {t.host_gap_ms:.1f} ms"
                           if t.host_gap_ms is not None else "")
                        + (f", compiles {t.compile_events}"
                           if t.compile_events else ""))
        except Exception as e:
            lines.append(f"step traces: error ({e})")
        lines.append("=" * 70)
        return "\n".join(lines)
