"""Multi-window SLO burn-rate alerting for the serving fleet.

A p99.9 gate (``chaos.ttft_p999_ratio`` in bench_diff) tells you the SLO
was blown *after* the run. This module is the layer that pages first:
the SRE-workbook multi-window, multi-burn-rate alert. With an SLO
target of 99.9%, the **error budget** is 0.1% of requests; the **burn
rate** is how many times faster than budget-neutral the fleet is
currently spending it::

    burn = miss_rate / (1 - slo_target)

A burn rate of 1.0 exhausts the budget exactly at the SLO window's end;
14.4 exhausts a 30-day budget in 2 days. The alert fires only when BOTH
a fast window (default 60 s, burn >= 14.4 — catches a cliff in minutes)
and a slow window (default 600 s, burn >= 6.0 — suppresses blips that
self-heal) are over their thresholds. That pairing is the standard
defense against both flavors of false page: a single fast window alerts
on one unlucky batch, a single slow window alerts an hour late.

Hysteresis: once firing, the alert clears only after ``clear_checks``
consecutive evaluations below *both* thresholds — a fleet oscillating
around the threshold pages once, not every 5 seconds.

The alerter owns its own deadline (``deadline_ms``) rather than reusing
``RequestTracer.slo_deadline_ms`` because the router-side per-replica
tracers (supervisor.RemoteEngineView) have no deadline configured —
they mirror worker traces. ``observe_trace`` computes the miss verdict
locally from TTFT (or e2e with ``objective="e2e"``).

Alert transitions emit three ways so no consumer needs a new pipe:
typed ``slo_alert`` hub events (JSONL sink), ``slo.alerts_fired`` /
``slo.burn_rate_fast`` / ``slo.burn_rate_slow`` metrics, and a flight-
recorder record (a post-crash dump shows the page that preceded the
wedge). Host-side, jax-free, lock-protected (router submit threads +
health-check thread).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, Optional

from deepspeed_tpu.observability.clocksync import wall_time as _wall


class BurnRateAlerter:
    """Dual-window burn-rate evaluator over a stream of SLO verdicts.

    Parameters
    ----------
    deadline_ms:
        The SLO deadline applied to each observed trace (TTFT by
        default). Required — an alerter without a deadline has no error
        to rate.
    slo_target:
        Fraction of requests that must meet the deadline (0.999 ->
        0.1% error budget).
    fast_window_s / fast_burn, slow_window_s / slow_burn:
        The two (window, threshold) pairs; the alert fires when both
        windows' burn rates are at/above their thresholds.
    clear_checks:
        Consecutive clean evaluations required to clear a firing alert.
    min_events:
        Minimum observations inside the fast window before the alert
        may fire (a 1-request window with 1 miss is not a page).
    objective:
        ``"ttft"`` (default) or ``"e2e"`` — which latency the deadline
        applies to.
    """

    def __init__(self, deadline_ms: float, slo_target: float = 0.999,
                 fast_window_s: float = 60.0, fast_burn: float = 14.4,
                 slow_window_s: float = 600.0, slow_burn: float = 6.0,
                 clear_checks: int = 3, min_events: int = 10,
                 objective: str = "ttft", hub=None, flight=None):
        if not (0.0 < slo_target < 1.0):
            raise ValueError(f"slo_target must be in (0,1), got {slo_target}")
        if objective not in ("ttft", "e2e"):
            raise ValueError(f"objective must be ttft|e2e, got {objective!r}")
        self.deadline_ms = float(deadline_ms)
        self.slo_target = float(slo_target)
        self.fast_window_s = float(fast_window_s)
        self.fast_burn = float(fast_burn)
        self.slow_window_s = float(slow_window_s)
        self.slow_burn = float(slow_burn)
        self.clear_checks = max(1, int(clear_checks))
        self.min_events = max(1, int(min_events))
        self.objective = objective
        self._hub = hub
        self._flight = flight
        self._lock = threading.Lock()
        # (ts, ok) pairs, newest last, trimmed to the slow window
        self._events: deque = deque()
        self.firing = False
        self._clean_streak = 0
        self.stats = {"observed": 0, "misses": 0, "alerts_fired": 0,
                      "alerts_cleared": 0}
        self._last_eval: Dict[str, Any] = {}

    # -- ingest ----------------------------------------------------------

    def observe(self, ok: bool, now: Optional[float] = None) -> None:
        """One request outcome (True = met the SLO)."""
        ts = _wall() if now is None else float(now)
        with self._lock:
            self._events.append((ts, bool(ok)))
            self.stats["observed"] += 1
            if not ok:
                self.stats["misses"] += 1
            self._trim(ts)

    def observe_trace(self, t, now: Optional[float] = None) -> None:
        """Feed one finished RequestTrace, judging it against THIS
        alerter's deadline (the trace's tracer may have none)."""
        lat_s = t.e2e_s if self.objective == "e2e" else t.ttft_s
        if lat_s is None:
            # finished without the measured latency (flushed before the
            # first token, given a deadline): budget-relevant miss
            ok = False
        else:
            ok = lat_s * 1e3 <= self.deadline_ms
        self.observe(ok, now=now)

    def _trim(self, now: float) -> None:
        horizon = now - self.slow_window_s
        ev = self._events
        while ev and ev[0][0] < horizon:
            ev.popleft()

    # -- evaluation ------------------------------------------------------

    def _window_burn(self, now: float, window_s: float):
        """(burn_rate, events) for the trailing window. Caller holds
        the lock."""
        lo = now - window_s
        n = miss = 0
        for ts, ok in self._events:
            if ts >= lo:
                n += 1
                if not ok:
                    miss += 1
        if n == 0:
            return 0.0, 0
        return (miss / n) / (1.0 - self.slo_target), n

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Recompute both windows and run the fire/clear state machine.
        Returns the evaluation snapshot (also kept for
        :meth:`snapshot`). Call on the health-check cadence."""
        ts = _wall() if now is None else float(now)
        with self._lock:
            self._trim(ts)
            fast, fast_n = self._window_burn(ts, self.fast_window_s)
            slow, slow_n = self._window_burn(ts, self.slow_window_s)
            over = (fast >= self.fast_burn and slow >= self.slow_burn
                    and fast_n >= self.min_events)
            fired = cleared = False
            if over:
                self._clean_streak = 0
                if not self.firing:
                    self.firing = True
                    fired = True
                    self.stats["alerts_fired"] += 1
            elif self.firing:
                self._clean_streak += 1
                if self._clean_streak >= self.clear_checks:
                    self.firing = False
                    cleared = True
                    self.stats["alerts_cleared"] += 1
            ev = {"ts": ts, "firing": self.firing,
                  "burn_fast": round(fast, 4), "burn_slow": round(slow, 4),
                  "events_fast": fast_n, "events_slow": slow_n,
                  "fired": fired, "cleared": cleared}
            self._last_eval = ev
        if self._hub is not None:
            self._hub.gauge("slo.burn_rate_fast", fast)
            self._hub.gauge("slo.burn_rate_slow", slow)
            self._hub.gauge("slo.alert_firing", 1.0 if self.firing else 0.0)
            if fired:
                self._hub.counter_add("slo.alerts_fired")
                self._hub.record_event(
                    "slo_alert", state="firing", objective=self.objective,
                    deadline_ms=self.deadline_ms,
                    burn_fast=round(fast, 4), burn_slow=round(slow, 4),
                    events_fast=fast_n)
            elif cleared:
                self._hub.record_event(
                    "slo_alert", state="cleared", objective=self.objective,
                    deadline_ms=self.deadline_ms,
                    burn_fast=round(fast, 4), burn_slow=round(slow, 4))
        if self._flight is not None and (fired or cleared):
            self._flight.record(
                "slo_alert", state="firing" if fired else "cleared",
                objective=self.objective, deadline_ms=self.deadline_ms,
                burn_fast=round(fast, 4), burn_slow=round(slow, 4),
                events_fast=fast_n, events_slow=slow_n)
        return ev

    # -- readout ---------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "firing": self.firing,
                "objective": self.objective,
                "deadline_ms": self.deadline_ms,
                "slo_target": self.slo_target,
                "windows": {
                    "fast": {"window_s": self.fast_window_s,
                             "burn_threshold": self.fast_burn},
                    "slow": {"window_s": self.slow_window_s,
                             "burn_threshold": self.slow_burn},
                },
                "last_eval": dict(self._last_eval),
                "stats": dict(self.stats),
            }

    @classmethod
    def from_config(cls, cfg, hub=None, flight=None
                    ) -> Optional["BurnRateAlerter"]:
        """Build from a BurnRateConfig / dict; None when disabled or
        no deadline is configured (the off-switch)."""
        if cfg is None:
            return None
        get = (cfg.get if isinstance(cfg, dict)
               else lambda k, d=None: getattr(cfg, k, d))
        if not get("enabled", False):
            return None
        deadline = get("deadline_ms", None)
        if deadline is None:
            return None
        return cls(deadline_ms=float(deadline),
                   slo_target=float(get("slo_target", 0.999)),
                   fast_window_s=float(get("fast_window_seconds", 60.0)),
                   fast_burn=float(get("fast_burn", 14.4)),
                   slow_window_s=float(get("slow_window_seconds", 600.0)),
                   slow_burn=float(get("slow_burn", 6.0)),
                   clear_checks=int(get("clear_checks", 3)),
                   min_events=int(get("min_events", 10)),
                   objective=str(get("objective", "ttft")),
                   hub=hub, flight=flight)
