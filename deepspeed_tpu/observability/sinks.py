"""Metrics sinks: JSON-lines stream + Prometheus text exposition.

Two export shapes the existing monitor backends (CSV/TensorBoard/W&B,
deepspeed_tpu/monitor) don't cover:

* ``JSONLSink`` — one JSON object per line, appended and flushed per
  record, so a crashed or stalled run leaves a machine-readable trail up
  to its last completed step (the post-mortem artifact the stall
  watchdog points at).
* ``PrometheusTextSink`` — node-exporter *textfile collector* format:
  the full current snapshot is rewritten atomically (tmp + rename) so a
  scraper never reads a torn file. There is no HTTP server on TPU pod
  workers; the textfile handoff is the standard pattern there.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time
from typing import Any, Dict, Optional

from deepspeed_tpu.utils.logging import logger

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def prometheus_name(name: str, prefix: str = "dstpu") -> str:
    """'serve.ttft_seconds' -> 'dstpu_serve_ttft_seconds'."""
    clean = _NAME_RE.sub("_", name.replace(".", "_").replace("/", "_"))
    if clean and clean[0].isdigit():
        clean = "_" + clean  # exposition names must not start with a digit
    return f"{prefix}_{clean}" if prefix else clean


def escape_label_value(value: str) -> str:
    """Prometheus text-exposition label escaping: backslash, double
    quote, and newline (in that order — escaping the escapes first).
    Label values are arbitrary strings here (telemetry *reason* text),
    and a raw newline or quote would corrupt the whole exposition file
    for every scraper."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def labeled_name(name: str, labels: Optional[Dict[str, str]]) -> str:
    """Compose a registry key that carries Prometheus labels:
    ``labeled_name('serve.queue_depth', {'replica': 'r0'})`` ->
    ``serve.queue_depth{replica="r0"}``. Values are escaped here, at
    composition time, so the renderer can paste the label part through
    verbatim. Per-replica serving metrics use this so fleet aggregation
    does not collapse N replicas into one series."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


def split_labeled_name(name: str):
    """Inverse of the composition above: ``(base, label_part)`` where
    ``label_part`` is ``'{...}'`` or ``''``. The base goes through
    ``prometheus_name`` (which strips braces); the label part does not."""
    i = name.find("{")
    if i < 0:
        return name, ""
    return name[:i], name[i:]


class JSONLSink:
    """Append-and-flush JSON-lines writer (one record per call)."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._fh = open(path, "a", buffering=1)
        self._failed = False

    def write(self, record: Dict[str, Any]) -> None:
        if self._failed:
            return
        try:
            line = json.dumps(record, default=_json_default)
            with self._lock:
                self._fh.write(line + "\n")
                self._fh.flush()
        except Exception as e:  # a full disk must not kill training
            self._failed = True
            logger.warning(f"JSONL metrics sink disabled after error: {e}")

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except Exception:
                pass


def _json_default(obj):
    # numpy / jax scalars → python numbers; anything else → str
    for attr in ("item",):
        if hasattr(obj, attr):
            try:
                return obj.item()
            except Exception:
                pass
    return str(obj)


def render_prometheus(gauges: Dict[str, float], counters: Dict[str, float],
                      histograms: Dict[str, Any],
                      labeled_counters: Optional[
                          Dict[str, Dict[str, float]]] = None) -> str:
    """Render a metrics snapshot in Prometheus text exposition format.

    ``histograms`` maps name -> Histogram (duck-typed: needs
    ``prometheus_lines``). ``labeled_counters`` maps a metric name to
    ``{label_value: count}`` rendered with a ``name`` label (used for
    the capability-fallback telemetry counters).
    """
    lines = [f"# dstpu metrics snapshot ts={time.time():.3f}"]
    # registry keys may carry labels (``name{k="v"}``, composed by
    # labeled_name): the base goes through prometheus_name, the label
    # part is pasted through (values were escaped at composition time),
    # and the TYPE line is emitted once per base
    typed = set()
    for name in sorted(gauges):
        base, label = split_labeled_name(name)
        m = prometheus_name(base)
        if m not in typed:
            typed.add(m)
            lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m}{label} {gauges[name]:.6g}")
    typed = set()
    for name in sorted(counters):
        base, label = split_labeled_name(name)
        m = prometheus_name(base)
        if not m.endswith("_total"):
            m += "_total"
        if m not in typed:
            typed.add(m)
            lines.append(f"# TYPE {m} counter")
        lines.append(f"{m}{label} {counters[name]:.6g}")
    for name, per_label in sorted((labeled_counters or {}).items()):
        m = prometheus_name(name)
        if not m.endswith("_total"):
            m += "_total"
        lines.append(f"# TYPE {m} counter")
        for label, v in sorted(per_label.items()):
            lines.append(f'{m}{{name="{escape_label_value(label)}"}} '
                         f'{v:.6g}')
    typed = set()
    for name, hist in sorted(histograms.items()):
        base, label = split_labeled_name(name)
        rendered = hist.prometheus_lines(prometheus_name(base))
        if label:
            rendered = [_inject_labels(ln, label[1:-1]) for ln in rendered]
        for ln in rendered:  # one TYPE line per base across label sets
            if ln.startswith("# TYPE"):
                if ln in typed:
                    continue
                typed.add(ln)
            lines.append(ln)
    return "\n".join(lines) + "\n"


def _inject_labels(line: str, inner: str) -> str:
    """Merge ``inner`` (``k="v",...``) into one exposition line: before
    existing labels (``m_bucket{le="x"} v``) or as a fresh label set
    (``m_sum v``). Comment lines pass through."""
    if line.startswith("#"):
        return line
    if "{" in line:
        return line.replace("{", "{" + inner + ",", 1)
    name, _, rest = line.partition(" ")
    return f"{name}{{{inner}}} {rest}"


class PrometheusTextSink:
    """Atomic whole-file snapshot writer (textfile-collector handoff)."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._failed = False

    def write_text(self, text: str) -> None:
        if self._failed:
            return
        try:
            with self._lock:
                d = os.path.dirname(os.path.abspath(self.path))
                fd, tmp = tempfile.mkstemp(dir=d, suffix=".prom.tmp")
                try:
                    with os.fdopen(fd, "w") as f:
                        f.write(text)
                    os.replace(tmp, self.path)
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
        except Exception as e:
            self._failed = True
            logger.warning(f"Prometheus metrics sink disabled after "
                           f"error: {e}")
