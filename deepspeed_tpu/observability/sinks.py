"""Metrics sinks: JSON-lines stream + Prometheus text exposition.

Two export shapes the existing monitor backends (CSV/TensorBoard/W&B,
deepspeed_tpu/monitor) don't cover:

* ``JSONLSink`` — one JSON object per line, appended and flushed per
  record, so a crashed or stalled run leaves a machine-readable trail up
  to its last completed step (the post-mortem artifact the stall
  watchdog points at).
* ``PrometheusTextSink`` — node-exporter *textfile collector* format:
  the full current snapshot is rewritten atomically (tmp + rename) so a
  scraper never reads a torn file. There is no HTTP server on TPU pod
  workers; the textfile handoff is the standard pattern there.
"""

from __future__ import annotations

import json
import os
import re
import tempfile
import threading
import time
from typing import Any, Dict, Optional

from deepspeed_tpu.utils.logging import logger

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def prometheus_name(name: str, prefix: str = "dstpu") -> str:
    """'serve.ttft_seconds' -> 'dstpu_serve_ttft_seconds'."""
    clean = _NAME_RE.sub("_", name.replace(".", "_").replace("/", "_"))
    if clean and clean[0].isdigit():
        clean = "_" + clean  # exposition names must not start with a digit
    return f"{prefix}_{clean}" if prefix else clean


def escape_label_value(value: str) -> str:
    """Prometheus text-exposition label escaping: backslash, double
    quote, and newline (in that order — escaping the escapes first).
    Label values are arbitrary strings here (telemetry *reason* text),
    and a raw newline or quote would corrupt the whole exposition file
    for every scraper."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


class JSONLSink:
    """Append-and-flush JSON-lines writer (one record per call)."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._fh = open(path, "a", buffering=1)
        self._failed = False

    def write(self, record: Dict[str, Any]) -> None:
        if self._failed:
            return
        try:
            line = json.dumps(record, default=_json_default)
            with self._lock:
                self._fh.write(line + "\n")
                self._fh.flush()
        except Exception as e:  # a full disk must not kill training
            self._failed = True
            logger.warning(f"JSONL metrics sink disabled after error: {e}")

    def close(self) -> None:
        with self._lock:
            try:
                self._fh.close()
            except Exception:
                pass


def _json_default(obj):
    # numpy / jax scalars → python numbers; anything else → str
    for attr in ("item",):
        if hasattr(obj, attr):
            try:
                return obj.item()
            except Exception:
                pass
    return str(obj)


def render_prometheus(gauges: Dict[str, float], counters: Dict[str, float],
                      histograms: Dict[str, Any],
                      labeled_counters: Optional[
                          Dict[str, Dict[str, float]]] = None) -> str:
    """Render a metrics snapshot in Prometheus text exposition format.

    ``histograms`` maps name -> Histogram (duck-typed: needs
    ``prometheus_lines``). ``labeled_counters`` maps a metric name to
    ``{label_value: count}`` rendered with a ``name`` label (used for
    the capability-fallback telemetry counters).
    """
    lines = [f"# dstpu metrics snapshot ts={time.time():.3f}"]
    for name in sorted(gauges):
        m = prometheus_name(name)
        lines.append(f"# TYPE {m} gauge")
        lines.append(f"{m} {gauges[name]:.6g}")
    for name in sorted(counters):
        m = prometheus_name(name)
        if not m.endswith("_total"):
            m += "_total"
        lines.append(f"# TYPE {m} counter")
        lines.append(f"{m} {counters[name]:.6g}")
    for name, per_label in sorted((labeled_counters or {}).items()):
        m = prometheus_name(name)
        if not m.endswith("_total"):
            m += "_total"
        lines.append(f"# TYPE {m} counter")
        for label, v in sorted(per_label.items()):
            lines.append(f'{m}{{name="{escape_label_value(label)}"}} '
                         f'{v:.6g}')
    for name, hist in sorted(histograms.items()):
        lines.extend(hist.prometheus_lines(prometheus_name(name)))
    return "\n".join(lines) + "\n"


class PrometheusTextSink:
    """Atomic whole-file snapshot writer (textfile-collector handoff)."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        self._lock = threading.Lock()
        self._failed = False

    def write_text(self, text: str) -> None:
        if self._failed:
            return
        try:
            with self._lock:
                d = os.path.dirname(os.path.abspath(self.path))
                fd, tmp = tempfile.mkstemp(dir=d, suffix=".prom.tmp")
                try:
                    with os.fdopen(fd, "w") as f:
                        f.write(text)
                    os.replace(tmp, self.path)
                finally:
                    if os.path.exists(tmp):
                        os.unlink(tmp)
        except Exception as e:
            self._failed = True
            logger.warning(f"Prometheus metrics sink disabled after "
                           f"error: {e}")
