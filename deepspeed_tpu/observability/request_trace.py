"""Per-request serving traces with SLO-miss attribution.

The serving histograms (hub: ``serve.ttft_seconds`` etc.) can say p99
TTFT is 900 ms, not *why*: queue wait, prefill compute, a preemption
round trip, or a cold prefix. This module gives every serving request a
trace id and a typed span timeline — ENQUEUE, ADMIT, PREFILL (per
chunk), DECODE_EMIT, SPEC_DRAFT/SPEC_ACCEPT, PREFIX_HIT,
PREEMPT/REQUEUE, FINISH — recorded by the engine's emit points
(inference/engine_v2.py, inference/scheduler.py) into a bounded ring
with TAIL-BASED sampling: the keep/drop decision happens at FINISH,
when the request's fate is known, so every SLO violator is kept and
only a configurable random slice of the healthy bulk pays the ring
slot. Active requests cost one list append per span either way — that
is what makes the in-flight state dumpable on a crash (the tracer
registers a flight-recorder dump context).

On top sits the SLO attribution report (the serving analogue of
``observability/attribution.py``): each traced request's TTFT and e2e
wall time decompose into **queue_wait / prefill / decode / preempted /
spec_overhead** phases via a state-machine walk over the span
timeline, so the phases sum to the measured wall time by construction.
:func:`slo_attribution` aggregates the traces into a "why did p99
miss" table (dominant phase per missed request, per-phase percentiles)
rendered by :func:`slo_attribution_markdown`, embedded in the
``make serve-slo`` JSON, and served by ``tools/serve_top.py``. Finished
traces also feed per-phase hub histograms
(``serve.phase_<name>_seconds``) so the decomposition exports through
the existing Prometheus/JSONL sinks.

Phase semantics (docs/serving.md "Request tracing"):

- ``queue_wait`` — first ENQUEUE to first ADMIT (admission-queue wait).
- ``prefill``   — ADMIT to first emitted token while no token has been
  emitted yet (includes scheduling wait for prefill chunks — exactly
  the non-queue part of TTFT).
- ``decode``    — time between token emissions after the first token.
- ``preempted`` — PREEMPT to re-ADMIT requeue wait, plus (for requests
  preempted after their first token) the re-prefill recompute until the
  next emission: the full cost of the round trip.
- ``spec_overhead`` — the share of speculative verify rounds spent on
  rejected drafts, carved out of ``decode`` (decode + spec_overhead
  together cover the emission gaps).

Clock domains: every timestamp here comes from :func:`clocksync.
wall_time` — identical to ``time.time()`` unless a skew is injected.
A trace produced in another process (a fleet worker) lives in that
process's clock domain until :meth:`RequestTrace.rebase` shifts it by
the per-channel estimated offset; spans whose duration is smaller than
the offset estimate's uncertainty bound are flagged
``clock_uncertain=true`` rather than silently presented as ordered.

All host-side and jax-free.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import threading
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, Optional

from deepspeed_tpu.observability.clocksync import wall_time as _wall

# Typed span kinds (the on-wire vocabulary; chrome_trace.py renders one
# lane per request from these).
SPAN_KINDS = (
    "ENQUEUE", "ADMIT", "PREFILL", "DECODE_EMIT", "SPEC_DRAFT",
    "SPEC_ACCEPT", "PREFIX_HIT", "PREEMPT", "REQUEUE", "KV_STARVED",
    "ROUTE", "HANDOFF", "FAILOVER", "FINISH",
)

PHASES = ("queue_wait", "prefill", "decode", "preempted", "spec_overhead")


@dataclasses.dataclass
class Span:
    """One typed event on a request's timeline. ``ts`` is the span
    start (wall clock, same base as the flight recorder); ``dur_ms`` is
    0 for instant markers."""

    kind: str
    ts: float
    dur_ms: float = 0.0
    fields: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d = {"kind": self.kind, "ts": self.ts}
        if self.dur_ms:
            d["dur_ms"] = round(self.dur_ms, 4)
        if self.fields:
            d.update(self.fields)
        return d


@dataclasses.dataclass
class RequestTrace:
    """The full lifecycle of one serving request."""

    trace_id: str
    uid: int
    prompt_tokens: int = 0
    spans: List[Span] = dataclasses.field(default_factory=list)
    enqueue_ts: float = 0.0
    first_token_ts: Optional[float] = None
    finish_ts: Optional[float] = None
    status: str = "active"  # active | finished | truncated | flushed
    generated_tokens: int = 0
    prefix_hit_tokens: int = 0
    preemptions: int = 0
    spec_drafted: int = 0
    spec_accepted: int = 0
    spec_overhead_ms: float = 0.0
    # set by rebase(): which process's clock produced the original
    # timestamps, the offset that was subtracted, and its uncertainty.
    # None means the trace never crossed a clock domain — to_dict emits
    # no clock keys then, keeping pre-clocksync output bit-exact.
    clock_domain: Optional[str] = None
    clock_offset_s: float = 0.0
    clock_uncertainty_s: float = 0.0

    def add(self, kind: str, ts: float, dur_ms: float = 0.0,
            **fields) -> None:
        self.spans.append(Span(kind, ts, dur_ms, fields))

    def rebase(self, offset_s: float, uncertainty_s: float = 0.0,
               domain: Optional[str] = None) -> "RequestTrace":
        """Shift every timestamp out of the producing process's clock
        domain into the caller's: ``local_ts = peer_ts - offset_s``
        (``offset_s`` = peer minus local, the
        clocksync.ClockSyncEstimator convention). Spans shorter than
        the offset's uncertainty bound get ``clock_uncertain=true`` —
        their *internal* ordering against same-domain neighbors is
        exact, but their placement against the other domain is not, and
        pretending otherwise is how misordered timelines ship. Returns
        self (ingest-path chaining)."""
        off = float(offset_s)
        unc = float(uncertainty_s)
        self.enqueue_ts -= off
        if self.first_token_ts is not None:
            self.first_token_ts -= off
        if self.finish_ts is not None:
            self.finish_ts -= off
        for s in self.spans:
            s.ts -= off
            if s.dur_ms and unc * 1e3 > s.dur_ms:
                s.fields["clock_uncertain"] = True
        self.clock_domain = domain
        self.clock_offset_s += off
        self.clock_uncertainty_s = max(self.clock_uncertainty_s, unc)
        return self

    # -- measurements --------------------------------------------------

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_ts is None:
            return None
        return self.first_token_ts - self.enqueue_ts

    @property
    def e2e_s(self) -> Optional[float]:
        if self.finish_ts is None:
            return None
        return self.finish_ts - self.enqueue_ts

    def phases(self, until: Optional[float] = None) -> Dict[str, float]:
        """Decompose wall time from first ENQUEUE up to ``until``
        (default: FINISH, falling back to the last span) into the five
        PHASES. The walk attributes every inter-event gap to exactly one
        phase, so ``sum(phases.values())`` equals the decomposed wall
        time by construction (spec_overhead is carved out of decode,
        never added on top)."""
        out = {p: 0.0 for p in PHASES}
        spans = sorted(self.spans, key=lambda s: s.ts)
        if not spans:
            return out
        end = until
        if end is None:
            end = (self.finish_ts if self.finish_ts is not None
                   else spans[-1].ts)
        cur = "queue_wait"
        last_ts = spans[0].ts
        emitted = False
        spec_overhead_ms = 0.0
        for sp in spans:
            ts = min(sp.ts, end)
            if ts > last_ts:
                out[cur] += ts - last_ts
                last_ts = ts
            if sp.ts > end:
                break
            if sp.kind == "ADMIT":
                cur = "prefill" if not emitted else "preempted"
            elif sp.kind == "DECODE_EMIT":
                emitted = True
                cur = "decode"
                spec_overhead_ms += float(
                    sp.fields.get("spec_overhead_ms", 0.0))
            elif sp.kind == "PREEMPT":
                cur = "preempted"
        if end > last_ts:
            out[cur] += end - last_ts
        # rejected-draft verify work is a decode sub-cost: carve it out
        # so the five phases still sum to the same wall time
        carve = min(out["decode"], spec_overhead_ms / 1e3)
        out["decode"] -= carve
        out["spec_overhead"] = carve
        return out

    def ttft_phases(self) -> Dict[str, float]:
        """The TTFT decomposition: phases up to the first emitted token
        (all zero when no token was ever emitted)."""
        if self.first_token_ts is None:
            return {p: 0.0 for p in PHASES}
        return self.phases(until=self.first_token_ts)

    def to_dict(self) -> Dict[str, Any]:
        d = {
            "trace_id": self.trace_id,
            "uid": self.uid,
            "status": self.status,
            "prompt_tokens": self.prompt_tokens,
            "generated_tokens": self.generated_tokens,
            "prefix_hit_tokens": self.prefix_hit_tokens,
            "preemptions": self.preemptions,
            "spec_drafted": self.spec_drafted,
            "spec_accepted": self.spec_accepted,
            "enqueue_ts": self.enqueue_ts,
            "first_token_ts": self.first_token_ts,
            "finish_ts": self.finish_ts,
            "ttft_s": self.ttft_s,
            "e2e_s": self.e2e_s,
            "phases": {k: round(v, 6) for k, v in self.phases().items()},
            "ttft_phases": {k: round(v, 6)
                            for k, v in self.ttft_phases().items()},
            "spans": [s.to_dict() for s in self.spans],
        }
        if self.clock_domain is not None:
            d["clock_domain"] = self.clock_domain
            d["clock_offset_s"] = round(self.clock_offset_s, 9)
            d["clock_uncertainty_s"] = round(self.clock_uncertainty_s, 9)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RequestTrace":
        t = cls(trace_id=d["trace_id"], uid=int(d["uid"]),
                prompt_tokens=int(d.get("prompt_tokens", 0)),
                enqueue_ts=float(d.get("enqueue_ts", 0.0)),
                first_token_ts=d.get("first_token_ts"),
                finish_ts=d.get("finish_ts"),
                status=d.get("status", "finished"),
                generated_tokens=int(d.get("generated_tokens", 0)),
                prefix_hit_tokens=int(d.get("prefix_hit_tokens", 0)),
                preemptions=int(d.get("preemptions", 0)),
                spec_drafted=int(d.get("spec_drafted", 0)),
                spec_accepted=int(d.get("spec_accepted", 0)))
        if d.get("clock_domain") is not None:
            t.clock_domain = str(d["clock_domain"])
            t.clock_offset_s = float(d.get("clock_offset_s", 0.0))
            t.clock_uncertainty_s = float(
                d.get("clock_uncertainty_s", 0.0))
        for s in d.get("spans", []):
            fields = {k: v for k, v in s.items()
                      if k not in ("kind", "ts", "dur_ms")}
            t.spans.append(Span(s["kind"], float(s["ts"]),
                                float(s.get("dur_ms", 0.0)), fields))
        return t


class RequestTracer:
    """Emit-point sink + tail-sampled ring of finished request traces.

    Thread-safety matches the serving engine (single-threaded step
    loop); the ring swap under ``finished()`` takes a lock only because
    tooling may read it from another thread. Every ``on_*`` method is a
    cheap no-op when ``enabled`` is False.
    """

    def __init__(self, enabled: bool = True, sample_rate: float = 0.05,
                 ring_size: int = 4096,
                 slo_deadline_ms: Optional[float] = None,
                 seed: int = 0, hub=None, flight=None):
        self.enabled = bool(enabled)
        self.sample_rate = float(sample_rate)
        self.ring_size = int(ring_size)
        self.slo_deadline_ms = slo_deadline_ms
        self._rng = random.Random(seed)
        self._active: Dict[int, RequestTrace] = {}
        self._ring: deque = deque(maxlen=max(1, self.ring_size))
        self._lock = threading.Lock()
        self._n_started = 0
        self.stats = {"started": 0, "finished": 0, "kept": 0,
                      "dropped": 0, "slo_misses": 0}
        self._hub = hub
        self._flight = flight
        # optional BurnRateAlerter (observability/burn_rate.py): fed one
        # observation per finished trace; owns its own deadline so it
        # works even when this tracer has no slo_deadline_ms.
        self.alerter = None
        if flight is not None:
            self.attach_flight(flight)

    # -- construction helpers ------------------------------------------

    @classmethod
    def from_config(cls, cfg: Any = None, hub=None,
                    flight=None) -> "RequestTracer":
        """Build from an ``observability.request_trace`` config block
        (RequestTraceConfig, dict, or None for defaults), with env
        overrides: ``DSTPU_REQUEST_TRACE=0`` disables,
        ``DSTPU_REQ_TRACE_SAMPLE`` / ``DSTPU_REQ_TRACE_RING`` /
        ``DSTPU_REQ_TRACE_SLO_MS`` override the knobs."""
        get = (cfg.get if isinstance(cfg, dict)
               else lambda k, d=None: getattr(cfg, k, d))
        enabled = bool(get("enabled", True)) if cfg is not None else True
        sample = float(get("sample_rate", 0.05)) if cfg is not None else 0.05
        ring = int(get("ring_size", 4096)) if cfg is not None else 4096
        slo = get("slo_deadline_ms", None) if cfg is not None else None
        env = os.environ.get
        if env("DSTPU_REQUEST_TRACE") is not None:
            enabled = env("DSTPU_REQUEST_TRACE") not in ("0", "false", "")
        if env("DSTPU_REQ_TRACE_SAMPLE"):
            sample = float(env("DSTPU_REQ_TRACE_SAMPLE"))
        if env("DSTPU_REQ_TRACE_RING"):
            ring = int(env("DSTPU_REQ_TRACE_RING"))
        if env("DSTPU_REQ_TRACE_SLO_MS"):
            slo = float(env("DSTPU_REQ_TRACE_SLO_MS"))
        return cls(enabled=enabled, sample_rate=sample, ring_size=ring,
                   slo_deadline_ms=slo, hub=hub, flight=flight)

    def attach_flight(self, flight) -> None:
        """Register the in-flight request state as crash-dump context:
        a flight-recorder dump (exception/SIGTERM/watchdog) includes the
        live request timelines, so a wedged serve step shows *which*
        requests were in flight and what phase each was in."""
        self._flight = flight
        add = getattr(flight, "add_dump_context", None)
        if add is not None:
            add("requests_in_flight", self._inflight_summary)

    def _inflight_summary(self) -> List[Dict[str, Any]]:
        out = []
        for t in list(self._active.values()):
            out.append({"trace_id": t.trace_id, "uid": t.uid,
                        "status": t.status,
                        "prompt_tokens": t.prompt_tokens,
                        "generated_tokens": t.generated_tokens,
                        "preemptions": t.preemptions,
                        "age_s": round(_wall() - t.enqueue_ts, 4),
                        "last_span": (t.spans[-1].to_dict()
                                      if t.spans else None),
                        "phases": {k: round(v, 4)
                                   for k, v in t.phases(
                                       until=_wall()).items()}})
        return out

    # -- emit points ----------------------------------------------------

    def active(self, uid: int) -> Optional[RequestTrace]:
        return self._active.get(uid)

    def on_enqueue(self, uid: int, prompt_tokens: int,
                   queue_depth: int = 0) -> Optional[RequestTrace]:
        if not self.enabled:
            return None
        old = self._active.pop(uid, None)
        if old is not None:
            # uid reuse while a trace is still open (caller recycled the
            # uid without finishing): close the old one out
            self._finish_trace(old, "superseded", _wall())
        self._n_started += 1
        self.stats["started"] += 1
        now = _wall()
        t = RequestTrace(trace_id=f"req-{uid}-{self._n_started}", uid=uid,
                         prompt_tokens=int(prompt_tokens), enqueue_ts=now)
        t.add("ENQUEUE", now, prompt_tokens=int(prompt_tokens),
              queue_depth=int(queue_depth))
        self._active[uid] = t
        return t

    def on_admit(self, uid: int, wait_s: float = 0.0,
                 requeued: bool = False) -> None:
        t = self._active.get(uid) if self.enabled else None
        if t is None:
            return
        now = _wall()
        t.add("ADMIT", now, wait_s=round(wait_s, 6), requeued=bool(requeued))
        if requeued and self._hub is not None:
            # queue re-entry latency of a preemption round trip,
            # measurable end-to-end (PREEMPT span -> this ADMIT)
            self._hub.histogram("serve.requeue_wait_seconds").observe(
                wait_s)

    def on_prefix_hit(self, uid: int, tokens: int) -> None:
        t = self._active.get(uid) if self.enabled else None
        if t is None:
            return
        t.prefix_hit_tokens += int(tokens)
        t.add("PREFIX_HIT", _wall(), tokens=int(tokens))

    def on_prefill(self, uid: int, start: float, dur_ms: float,
                   tokens: int, start_pos: int) -> None:
        t = self._active.get(uid) if self.enabled else None
        if t is None:
            return
        t.add("PREFILL", start, dur_ms=dur_ms, tokens=int(tokens),
              start_pos=int(start_pos))

    def on_emit(self, uid: int, n_tokens: int,
                spec_overhead_ms: float = 0.0) -> None:
        t = self._active.get(uid) if self.enabled else None
        if t is None:
            return
        now = _wall()
        first = t.first_token_ts is None
        if first:
            t.first_token_ts = now
        t.generated_tokens += int(n_tokens)
        fields: Dict[str, Any] = {"n": int(n_tokens)}
        if first:
            fields["first"] = True
        if spec_overhead_ms > 0.0:
            fields["spec_overhead_ms"] = round(spec_overhead_ms, 4)
            t.spec_overhead_ms += spec_overhead_ms
        t.add("DECODE_EMIT", now, **fields)

    def on_spec(self, uid: int, drafted: int, accepted: int) -> None:
        t = self._active.get(uid) if self.enabled else None
        if t is None:
            return
        now = _wall()
        t.spec_drafted += int(drafted)
        t.spec_accepted += int(accepted)
        t.add("SPEC_DRAFT", now, n=int(drafted))
        t.add("SPEC_ACCEPT", now, n=int(accepted))

    def on_preempt(self, uid: int, reason: str,
                   generated: int = 0) -> None:
        t = self._active.get(uid) if self.enabled else None
        if t is None:
            return
        now = _wall()
        t.preemptions += 1
        t.add("PREEMPT", now, reason=reason, generated=int(generated))
        t.add("REQUEUE", now, reason=reason)

    def note(self, uid: int, kind: str, **fields) -> None:
        """Zero-duration marker on the request lane (e.g. the
        scheduler's KV_STARVED skips)."""
        t = self._active.get(uid) if self.enabled else None
        if t is None:
            return
        t.add(kind, _wall(), **fields)

    def on_finish(self, uid: int, status: str = "finished") -> None:
        t = self._active.pop(uid, None) if self.enabled else None
        if t is None:
            return
        self._finish_trace(t, status, _wall())

    # -- finish / sampling ----------------------------------------------

    def _finish_trace(self, t: RequestTrace, status: str,
                      now: float) -> None:
        t.finish_ts = now
        t.status = status
        t.add("FINISH", now, status=status)
        self.stats["finished"] += 1
        miss = self.is_slo_miss(t)
        if miss:
            self.stats["slo_misses"] += 1
        if self._hub is not None:
            for phase, secs in t.phases().items():
                self._hub.histogram(
                    f"serve.phase_{phase}_seconds").observe(secs)
            if t.e2e_s is not None:
                self._hub.histogram("serve.e2e_seconds").observe(t.e2e_s)
            if miss:
                self._hub.counter_add("serve.slo_misses")
        if self._flight is not None:
            self._flight.record(
                "request_finish", trace_id=t.trace_id, uid=t.uid,
                status=status, slo_miss=miss,
                ttft_ms=(round(t.ttft_s * 1e3, 3)
                         if t.ttft_s is not None else None),
                e2e_ms=(round(t.e2e_s * 1e3, 3)
                        if t.e2e_s is not None else None),
                tokens=t.generated_tokens, preemptions=t.preemptions)
        if self.alerter is not None:
            self.alerter.observe_trace(t, now=now)
        # tail-based sampling: the drop decision happens HERE, with the
        # outcome known — every violator is kept, the healthy bulk is
        # down-sampled, and a dropped trace costs nothing further
        if miss or self._rng.random() < self.sample_rate:
            with self._lock:
                self._ring.append(t)
            self.stats["kept"] += 1
        else:
            self.stats["dropped"] += 1

    def is_slo_miss(self, t: RequestTrace) -> bool:
        """A request misses the SLO when its TTFT exceeds the deadline
        (or it never produced a first token at all, given a deadline)."""
        if self.slo_deadline_ms is None:
            return False
        if t.ttft_s is None:
            return t.status != "active"
        return t.ttft_s * 1e3 > float(self.slo_deadline_ms)

    # -- access ---------------------------------------------------------

    def finished(self, last: int = 0) -> List[RequestTrace]:
        with self._lock:
            out = list(self._ring)
        return out[-last:] if last > 0 else out

    def in_flight(self) -> int:
        return len(self._active)

    def reset(self) -> None:
        """Drop ring + counters (bench warmup boundary). Active traces
        survive — requests in flight keep their timelines."""
        with self._lock:
            self._ring.clear()
        for k in self.stats:
            self.stats[k] = 0

    def snapshot(self) -> Dict[str, Any]:
        return dict(self.stats, enabled=self.enabled,
                    sample_rate=self.sample_rate,
                    ring_size=self.ring_size,
                    slo_deadline_ms=self.slo_deadline_ms,
                    ring_len=len(self._ring),
                    in_flight=len(self._active))

    def dump_jsonl(self, path: str) -> str:
        """Write every kept trace as one JSON line (the schema
        ``tools/serve_top.py report`` consumes; docs/serving.md)."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            for t in self.finished():
                d = t.to_dict()
                # stamp the tracer's deadline + verdict on every line so
                # an offline reader (tools/serve_top.py) can reproduce
                # the miss set without being told the SLO
                d["slo_deadline_ms"] = self.slo_deadline_ms
                d["slo_miss"] = self.is_slo_miss(t)
                f.write(json.dumps(d, default=str) + "\n")
        os.replace(tmp, path)
        return path


def load_traces_jsonl(path: str) -> List[RequestTrace]:
    out: List[RequestTrace] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(RequestTrace.from_dict(json.loads(line)))
            except (json.JSONDecodeError, KeyError):
                continue
    return out


# -- SLO attribution ---------------------------------------------------------


def _percentiles(vals: List[float]) -> Dict[str, float]:
    if not vals:
        return {"p50": 0.0, "p99": 0.0, "mean": 0.0}
    s = sorted(vals)

    def pct(p: float) -> float:
        if len(s) == 1:
            return s[0]
        k = (len(s) - 1) * p / 100.0
        lo = int(k)
        hi = min(lo + 1, len(s) - 1)
        return s[lo] + (s[hi] - s[lo]) * (k - lo)

    return {"p50": round(pct(50), 6), "p99": round(pct(99), 6),
            "mean": round(sum(s) / len(s), 6)}


def slo_attribution(traces: Iterable[RequestTrace],
                    deadline_s: Optional[float] = None) -> Dict[str, Any]:
    """Aggregate finished traces into the "why did p99 miss" report.

    For every trace: TTFT + e2e phase decompositions. For every
    missed-deadline trace: the dominant TTFT phase (the answer to "what
    ate the deadline"). The report is JSON-serializable (embedded in
    the ``make serve-slo`` output) and renders as a table via
    :func:`slo_attribution_markdown`."""
    traces = [t for t in traces if t.finish_ts is not None]
    rows: List[Dict[str, Any]] = []
    phase_vals: Dict[str, List[float]] = {p: [] for p in PHASES}
    miss_phase_vals: Dict[str, List[float]] = {p: [] for p in PHASES}
    dominant: Dict[str, int] = {}
    misses = 0
    for t in traces:
        ph = t.phases()
        tph = t.ttft_phases()
        miss = (deadline_s is not None and t.ttft_s is not None
                and t.ttft_s > deadline_s)
        if deadline_s is not None and t.ttft_s is None:
            miss = True  # never reached first token: worst miss
        row = {"trace_id": t.trace_id, "uid": t.uid, "status": t.status,
               "ttft_s": (round(t.ttft_s, 6)
                          if t.ttft_s is not None else None),
               "e2e_s": round(t.e2e_s, 6),
               "slo_miss": miss,
               "preemptions": t.preemptions,
               "prefix_hit_tokens": t.prefix_hit_tokens,
               "generated_tokens": t.generated_tokens,
               "phases": {k: round(v, 6) for k, v in ph.items()},
               "ttft_phases": {k: round(v, 6) for k, v in tph.items()}}
        if miss:
            misses += 1
            # dominant phase of the TTFT window: what to fix first
            dom = max(tph, key=lambda k: tph[k]) if any(
                tph.values()) else "queue_wait"
            row["dominant_phase"] = dom
            dominant[dom] = dominant.get(dom, 0) + 1
            for p in PHASES:
                miss_phase_vals[p].append(tph[p])
        for p in PHASES:
            phase_vals[p].append(ph[p])
        rows.append(row)
    return {
        "schema": "slo_attribution/v1",
        "deadline_s": deadline_s,
        "requests": len(traces),
        "slo_misses": misses,
        "phases": PHASES,
        "phase_seconds": {p: _percentiles(v)
                          for p, v in phase_vals.items()},
        "miss_ttft_phase_seconds": {p: _percentiles(v)
                                    for p, v in miss_phase_vals.items()},
        "miss_dominant_phase": dict(sorted(dominant.items(),
                                           key=lambda kv: -kv[1])),
        "ttft": _percentiles([t.ttft_s for t in traces
                              if t.ttft_s is not None]),
        "e2e": _percentiles([t.e2e_s for t in traces]),
        "requests_detail": rows,
    }


def slo_attribution_markdown(report: Dict[str, Any]) -> str:
    """Render the report as the "why did p99 miss" table."""
    lines = []
    dl = report.get("deadline_s")
    lines.append(f"## SLO attribution — {report['requests']} requests, "
                 f"{report['slo_misses']} misses"
                 + (f" (TTFT deadline {dl * 1e3:.0f} ms)"
                    if dl is not None else ""))
    lines.append("")
    lines.append("| phase | all p50 (ms) | all p99 (ms) | "
                 "miss-TTFT p50 (ms) | miss-TTFT p99 (ms) |")
    lines.append("|---|---|---|---|---|")
    for p in report["phases"]:
        a = report["phase_seconds"][p]
        m = report["miss_ttft_phase_seconds"][p]
        lines.append(f"| {p} | {a['p50'] * 1e3:.2f} | {a['p99'] * 1e3:.2f}"
                     f" | {m['p50'] * 1e3:.2f} | {m['p99'] * 1e3:.2f} |")
    dom = report.get("miss_dominant_phase") or {}
    if dom:
        lines.append("")
        lines.append("Dominant phase of missed requests: "
                     + ", ".join(f"{k} ({v})" for k, v in dom.items()))
    return "\n".join(lines)


def check_phase_closure(traces: Iterable[RequestTrace],
                        rel_tol: float = 0.05,
                        abs_tol_s: float = 0.002) -> Dict[str, Any]:
    """Regression check for the trace math (``SLO_TRACE=1`` arm of
    ``make serve-slo``): for every finished trace, the phase
    decomposition must sum to the measured e2e wall time — and the TTFT
    decomposition to the measured TTFT — within
    ``max(rel_tol * measured, abs_tol_s)``. Raises AssertionError with
    the worst offender on failure; returns a summary dict on success."""
    checked = 0
    worst = 0.0
    for t in traces:
        if t.finish_ts is None:
            continue
        e2e = t.e2e_s
        gap = abs(sum(t.phases().values()) - e2e)
        tol = max(rel_tol * e2e, abs_tol_s)
        assert gap <= tol, (
            f"{t.trace_id}: phases sum off by {gap * 1e3:.3f} ms "
            f"(e2e {e2e * 1e3:.3f} ms, tol {tol * 1e3:.3f} ms)")
        worst = max(worst, gap)
        if t.ttft_s is not None:
            tgap = abs(sum(t.ttft_phases().values()) - t.ttft_s)
            ttol = max(rel_tol * t.ttft_s, abs_tol_s)
            assert tgap <= ttol, (
                f"{t.trace_id}: TTFT phases sum off by "
                f"{tgap * 1e3:.3f} ms (ttft {t.ttft_s * 1e3:.3f} ms)")
            worst = max(worst, tgap)
        checked += 1
    return {"checked": checked, "worst_gap_ms": round(worst * 1e3, 4),
            "rel_tol": rel_tol}
