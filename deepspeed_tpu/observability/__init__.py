"""Unified observability hub.

One process-wide registry (``get_hub()``) collecting per-step training
traces (MFU/roofline included), serving latency histograms, stall
watchdog reports and capability-fallback counters, exported through the
existing monitor backends plus JSON-lines and Prometheus text sinks.
See docs/observability.md.
"""

from deepspeed_tpu.observability.histogram import Histogram
from deepspeed_tpu.observability.hub import (MetricsHub, compile_stats,
                                             get_hub, reset_hub)
from deepspeed_tpu.observability.profile_trace import (TraceCapture,
                                                       parse_trace_steps)
from deepspeed_tpu.observability.roofline import (HBM_GBPS, PEAK_TFLOPS,
                                                  detect_hbm_gbps,
                                                  detect_peak_tflops, mfu,
                                                  roofline_summary)
from deepspeed_tpu.observability.sinks import (JSONLSink, PrometheusTextSink,
                                               prometheus_name,
                                               render_prometheus)
from deepspeed_tpu.observability.step_trace import StepTrace
from deepspeed_tpu.observability.watchdog import StallWatchdog

__all__ = [
    "Histogram",
    "MetricsHub",
    "get_hub",
    "reset_hub",
    "compile_stats",
    "TraceCapture",
    "parse_trace_steps",
    "PEAK_TFLOPS",
    "HBM_GBPS",
    "detect_peak_tflops",
    "detect_hbm_gbps",
    "mfu",
    "roofline_summary",
    "JSONLSink",
    "PrometheusTextSink",
    "prometheus_name",
    "render_prometheus",
    "StepTrace",
    "StallWatchdog",
]
