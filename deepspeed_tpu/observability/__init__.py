"""Unified observability hub.

One process-wide registry (``get_hub()``) collecting per-step training
traces (MFU/roofline included), serving latency histograms, stall
watchdog reports and capability-fallback counters, exported through the
existing monitor backends plus JSON-lines and Prometheus text sinks.
See docs/observability.md.
"""

from deepspeed_tpu.observability.attribution import (REGIONS, RegionCost,
                                                     attribute_step,
                                                     attribution_markdown)
from deepspeed_tpu.observability.burn_rate import BurnRateAlerter
from deepspeed_tpu.observability.chrome_trace import (
    chrome_trace_events, export_chrome_trace, export_fleet_merged_trace,
    export_rank_from_run_dir, export_request_traces, request_trace_events)
from deepspeed_tpu.observability.clocksync import (ClockSyncEstimator,
                                                   wall_time)
from deepspeed_tpu.observability.fleet import (FleetAggregator, FleetPublisher,
                                               format_report, resolve_run_dir)
from deepspeed_tpu.observability.fleet_metrics import (FleetMetricsPlane,
                                                       compact_snapshot,
                                                       merge_snapshots)
from deepspeed_tpu.observability.flight_recorder import (
    FlightRecorder, dump_flight_recorder, get_flight_recorder,
    install_crash_handlers, reset_flight_recorder)
from deepspeed_tpu.observability.histogram import Histogram
from deepspeed_tpu.observability.journal import (FleetJournal,
                                                 config_fingerprint,
                                                 get_journal, load_journal,
                                                 render_incident_log,
                                                 reset_journal, set_journal,
                                                 verify_streams)
from deepspeed_tpu.observability.hub import (MetricsHub, compile_stats,
                                             get_hub, peek_hub, reset_hub)
from deepspeed_tpu.observability.profile_trace import (TraceCapture,
                                                       parse_trace_steps)
from deepspeed_tpu.observability.request_trace import (
    PHASES, SPAN_KINDS, RequestTrace, RequestTracer, check_phase_closure,
    load_traces_jsonl, slo_attribution, slo_attribution_markdown)
from deepspeed_tpu.observability.roofline import (HBM_GBPS, PEAK_TFLOPS,
                                                  detect_hbm_gbps,
                                                  detect_peak_tflops, mfu,
                                                  roofline_summary)
from deepspeed_tpu.observability.sinks import (JSONLSink, PrometheusTextSink,
                                               escape_label_value,
                                               prometheus_name,
                                               render_prometheus)
from deepspeed_tpu.observability.step_trace import StepTrace
from deepspeed_tpu.observability.watchdog import StallWatchdog

__all__ = [
    "REGIONS",
    "RegionCost",
    "attribute_step",
    "attribution_markdown",
    "Histogram",
    "MetricsHub",
    "get_hub",
    "peek_hub",
    "reset_hub",
    "compile_stats",
    "TraceCapture",
    "parse_trace_steps",
    "PEAK_TFLOPS",
    "HBM_GBPS",
    "detect_peak_tflops",
    "detect_hbm_gbps",
    "mfu",
    "roofline_summary",
    "JSONLSink",
    "PrometheusTextSink",
    "prometheus_name",
    "escape_label_value",
    "render_prometheus",
    "StepTrace",
    "StallWatchdog",
    "FlightRecorder",
    "get_flight_recorder",
    "reset_flight_recorder",
    "dump_flight_recorder",
    "install_crash_handlers",
    "FleetPublisher",
    "FleetAggregator",
    "format_report",
    "resolve_run_dir",
    "chrome_trace_events",
    "export_chrome_trace",
    "export_rank_from_run_dir",
    "export_request_traces",
    "request_trace_events",
    "PHASES",
    "SPAN_KINDS",
    "RequestTrace",
    "RequestTracer",
    "check_phase_closure",
    "load_traces_jsonl",
    "slo_attribution",
    "slo_attribution_markdown",
    "BurnRateAlerter",
    "ClockSyncEstimator",
    "wall_time",
    "FleetJournal",
    "get_journal",
    "set_journal",
    "reset_journal",
    "load_journal",
    "verify_streams",
    "render_incident_log",
    "config_fingerprint",
    "FleetMetricsPlane",
    "compact_snapshot",
    "merge_snapshots",
    "export_fleet_merged_trace",
]
