"""Per-step training trace record.

One ``StepTrace`` is emitted by the engine per optimizer boundary
(train_batch): wall time, loss/grad-norm/lr, token throughput, MFU,
cumulative traced communication volume, compile/retrace events, and
device memory. This is the row every sink exports and every regression
hunt greps for — the per-step analog of the one-shot numbers bench.py
prints.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict, Optional


@dataclasses.dataclass
class StepTrace:
    step: int
    wall_ms: float
    # throughput (global tokens across all chips; per-chip rates divide
    # by n_chips so they line up with bench.py's tokens/s/chip headline)
    tokens: Optional[int] = None
    tokens_per_sec: Optional[float] = None
    tokens_per_sec_per_chip: Optional[float] = None
    n_chips: int = 1
    # training signals
    loss: Optional[float] = None
    grad_norm: Optional[float] = None
    lr: Optional[float] = None
    loss_scale: Optional[float] = None
    overflow: bool = False
    skipped_steps: int = 0
    # model-FLOPs utilization (same formula as bench.py:
    # tok/s/chip * flops_per_token / peak). ``mfu_source`` records where
    # flops_per_token came from: "model" (analytic, bench-identical) or
    # "xla" (compiled-program cost analysis).
    mfu: Optional[float] = None
    mfu_source: Optional[str] = None
    flops_per_token: Optional[float] = None
    peak_tflops: Optional[float] = None
    # host-side time this step spent on the device critical path before
    # dispatch (input pull/stack/transfer + jit call overhead). Under the
    # pipelined loop (performance.pipeline_depth) this is the overhead
    # the dispatch-ahead window hides; bench.py aggregates it per window
    # as ``host_gap_ms``
    host_gap_ms: Optional[float] = None
    # dispatched-but-unresolved steps in flight when this step resolved
    # (0 = blocking loop)
    inflight: int = 0
    # compile/retrace activity observed since the previous step (a
    # nonzero value mid-run is the classic silent-regression smell)
    compile_events: int = 0
    compile_secs: float = 0.0
    # cumulative traced collective volume by op (utils/comms_logging),
    # and the delta vs the previous step's snapshot
    comm_bytes_total: Optional[Dict[str, float]] = None
    comm_bytes_delta: Optional[Dict[str, float]] = None
    # device memory (Device.memory_stats; None on backends without PJRT
    # memory stats, e.g. the CPU simulator)
    device_mem: Optional[Dict[str, float]] = None
    timestamp: float = dataclasses.field(default_factory=time.time)
    extras: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["kind"] = "step_trace"
        # drop Nones so JSONL rows stay compact
        return {k: v for k, v in d.items() if v is not None}
