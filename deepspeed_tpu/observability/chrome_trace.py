"""Chrome-trace (Perfetto-loadable) export of a rank's step history.

Renders the StepTrace history and/or flight-recorder event tail of one
rank as a ``trace.json`` in the Chrome trace-event format — open it at
``ui.perfetto.dev`` or ``chrome://tracing``. This is the lightweight
structural view (step wall, host gap, dispatch window, traced
collectives as markers) that needs no ``jax.profiler`` capture and can
be produced *after the fact* from a fleet run dir or a flight dump —
including for a worker that is already dead.

Track layout (one Chrome "process" per rank):

    tid 0  step      one span per train step (wall time)
    tid 1  host      the host-gap slice at the start of each step
    tid 2  dispatch  step_entry → step_dispatch window (flight events)
    tid 3  comm      traced collectives — dispatch→completion "X" spans
                     when the event carries ``dur_ms`` (comm.py
                     _traced_op), instant markers otherwise; overlapping
                     dispatches render as overlapping slices (the
                     overlap lanes the ISSUE-6 engine is tuned against)
    tid 4  events    everything else (compile, checkpoint, offload, ...)
                     — also "X" spans when the event has ``dur_ms``
                     (flight_recorder.span)
    tid 100+  req …  per-request serving lanes (request_trace.py): one
                     track per traced request, so one request's whole
                     life — queue wait, prefill chunks, decode
                     emissions, preemption round trips — renders as one
                     Perfetto row (:func:`request_trace_events`)
"""

from __future__ import annotations

import copy
import json
import os
from typing import Any, Dict, Iterable, List, Optional

_TID_NAMES = {0: "step", 1: "host", 2: "dispatch", 3: "comm", 4: "events"}


def _us(t_seconds: float, t0: float) -> float:
    return (t_seconds - t0) * 1e6


def chrome_trace_events(step_rows: Iterable[Dict[str, Any]] = (),
                        flight_events: Iterable[Dict[str, Any]] = (),
                        rank: int = 0,
                        t0: Optional[float] = None
                        ) -> List[Dict[str, Any]]:
    """Build the ``traceEvents`` list.

    ``step_rows``: StepTrace dicts (``to_dict()``), hub history rows, or
    fleet shard rows — needs ``step``, ``wall_ms``, ``timestamp`` (step
    *end*, wall clock). ``flight_events``: flight-recorder event dicts
    (``ts`` + ``kind`` + fields). ``t0`` overrides the time base so
    other lane builders (request_trace_events) can share it."""
    step_rows = [r for r in step_rows
                 if r.get("wall_ms") is not None
                 and r.get("timestamp") is not None]
    flight_events = [e for e in flight_events if e.get("ts") is not None]
    starts = [r["timestamp"] - r["wall_ms"] / 1e3 for r in step_rows]
    if t0 is None:
        t0 = min(starts + [e["ts"] for e in flight_events], default=0.0)

    evs: List[Dict[str, Any]] = [
        {"name": "thread_name", "ph": "M", "pid": rank, "tid": tid,
         "args": {"name": name}} for tid, name in _TID_NAMES.items()
    ] + [{"name": "process_name", "ph": "M", "pid": rank,
          "args": {"name": f"rank {rank}"}}]

    for row, start in zip(step_rows, starts):
        args = {k: row[k] for k in ("loss", "tokens_per_sec", "mfu",
                                    "compile_events", "inflight")
                if row.get(k) is not None}
        evs.append({"name": f"step {row['step']}", "ph": "X", "cat": "step",
                    "ts": _us(start, t0), "dur": row["wall_ms"] * 1e3,
                    "pid": rank, "tid": 0, "args": args})
        gap = row.get("host_gap_ms")
        if gap:
            evs.append({"name": "host_gap", "ph": "X", "cat": "host",
                        "ts": _us(start, t0), "dur": gap * 1e3,
                        "pid": rank, "tid": 1,
                        "args": {"step": row["step"]}})

    # flight events: pair step_entry → step_dispatch into dispatch-window
    # spans; everything else becomes an instant marker
    entry_ts: Dict[int, float] = {}
    for e in flight_events:
        kind, ts = e["kind"], e["ts"]
        fields = {k: v for k, v in e.items() if k not in ("kind", "ts")}
        if kind == "step_entry":
            entry_ts[fields.get("step", -1)] = ts
            continue
        if kind == "step_dispatch":
            step = fields.get("step", -1)
            t_in = entry_ts.pop(step, None)
            if t_in is not None:
                evs.append({"name": f"dispatch {step}", "ph": "X",
                            "cat": "dispatch", "ts": _us(t_in, t0),
                            "dur": max(ts - t_in, 0.0) * 1e6,
                            "pid": rank, "tid": 2, "args": fields})
            continue
        tid = 3 if kind == "collective" else 4
        name = fields.get("op", kind) if kind == "collective" else kind
        dur_ms = fields.get("dur_ms")
        if dur_ms is not None:
            # dispatch→completion span (comm._traced_op /
            # flight_recorder.span): a real slice on the lane, so
            # concurrent dispatches visibly overlap
            evs.append({"name": str(name), "ph": "X", "cat": kind,
                        "ts": _us(ts, t0),
                        "dur": max(float(dur_ms), 0.0) * 1e3,
                        "pid": rank, "tid": tid, "args": fields})
        else:
            evs.append({"name": str(name), "ph": "i", "cat": kind,
                        "s": "t", "ts": _us(ts, t0), "pid": rank,
                        "tid": tid, "args": fields})
    return evs


REQUEST_TID_BASE = 100

# phase-boundary span kinds that render as slices covering the time
# UNTIL the next boundary (the lane then reads as a phase timeline);
# everything else on the lane is an instant marker or an explicit
# dur_ms slice (PREFILL chunks)
_PHASE_SLICE_KINDS = {"ENQUEUE": "queue_wait", "ADMIT": "running",
                      "PREEMPT": "preempted"}


def request_trace_events(traces, rank: int = 0,
                         t0: Optional[float] = None
                         ) -> List[Dict[str, Any]]:
    """Per-request Perfetto lanes from finished ``RequestTrace``s
    (observability/request_trace.py): one named track per request under
    the rank's process. Phase boundaries (ENQUEUE/ADMIT/PREEMPT) become
    slices spanning to the next boundary, PREFILL chunks render with
    their measured ``dur_ms``, and DECODE_EMIT / SPEC / PREFIX_HIT /
    FINISH land as instant markers — so one request's life reads as one
    row. Compose with :func:`chrome_trace_events` output by passing the
    same ``t0`` base."""
    traces = [t for t in traces if t.spans]
    if not traces:
        return []
    if t0 is None:
        t0 = min(t.spans[0].ts for t in traces)
    evs: List[Dict[str, Any]] = []
    for i, t in enumerate(traces):
        tid = REQUEST_TID_BASE + i
        evs.append({"name": "thread_name", "ph": "M", "pid": rank,
                    "tid": tid, "args": {"name": f"req {t.trace_id}"}})
        spans = sorted(t.spans, key=lambda s: s.ts)
        end_ts = t.finish_ts if t.finish_ts is not None else spans[-1].ts
        boundaries = [s for s in spans if s.kind in _PHASE_SLICE_KINDS]
        for j, s in enumerate(boundaries):
            nxt = (boundaries[j + 1].ts if j + 1 < len(boundaries)
                   else end_ts)
            label = _PHASE_SLICE_KINDS[s.kind]
            if s.kind == "ADMIT" and s.fields.get("requeued"):
                label = "re-running"
            evs.append({"name": label, "ph": "X", "cat": "request",
                        "ts": _us(s.ts, t0),
                        "dur": max(nxt - s.ts, 0.0) * 1e6,
                        "pid": rank, "tid": tid,
                        "args": dict(s.fields, kind=s.kind)})
        for s in spans:
            if s.kind in _PHASE_SLICE_KINDS:
                continue
            if s.dur_ms:
                evs.append({"name": s.kind, "ph": "X", "cat": "request",
                            "ts": _us(s.ts, t0),
                            "dur": max(s.dur_ms, 0.0) * 1e3,
                            "pid": rank, "tid": tid,
                            "args": dict(s.fields)})
            else:
                evs.append({"name": s.kind, "ph": "i", "cat": "request",
                            "s": "t", "ts": _us(s.ts, t0), "pid": rank,
                            "tid": tid, "args": dict(s.fields)})
    return evs


def export_request_traces(path: str, traces,
                          flight_events: Optional[
                              Iterable[Dict[str, Any]]] = None,
                          rank: int = 0) -> str:
    """Write a Perfetto trace of per-request lanes (plus, optionally,
    the rank's flight events on the shared lanes — both use wall-clock
    timestamps, so they align)."""
    flight_events = list(flight_events or ())
    ts_floor = [e["ts"] for e in flight_events if e.get("ts") is not None]
    ts_floor += [t.spans[0].ts for t in traces if t.spans]
    t0 = min(ts_floor, default=0.0)
    evs = chrome_trace_events((), flight_events, rank=rank, t0=t0) if \
        flight_events else []
    evs += request_trace_events(traces, rank=rank, t0=t0)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": evs, "displayTimeUnit": "ms"}, f)
    os.replace(tmp, path)
    return path


def export_fleet_request_traces(path: str, traces_by_replica) -> str:
    """Write one Perfetto file with a lane group (pid) per serving
    replica: ``traces_by_replica`` maps replica id (int) -> finished
    request traces. All replicas share one wall-clock ``t0``, so a
    request that fails over (or hands off prefill→decode) shows its two
    halves aligned across the replica lanes."""
    all_spans = [t.spans[0].ts
                 for traces in traces_by_replica.values()
                 for t in traces if t.spans]
    t0 = min(all_spans, default=0.0)
    evs: List[Dict[str, Any]] = []
    for rid in sorted(traces_by_replica):
        evs.append({"name": "process_name", "ph": "M", "pid": rid,
                    "args": {"name": f"replica r{rid}"}})
        evs += request_trace_events(traces_by_replica[rid], rank=rid, t0=t0)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": evs, "displayTimeUnit": "ms"}, f)
    os.replace(tmp, path)
    return path


def export_fleet_merged_trace(path: str, lanes) -> str:
    """The fleet's ONE timeline: every OS process (router + workers) as
    one Chrome process, every timestamp shifted into the first lane's
    clock domain by that lane's estimated offset.

    ``lanes`` is a list of dicts, one per OS process::

        {"pid": 0, "name": "router", "traces": [...],   # RequestTraces
         "flight_events": [...],                        # optional
         "offset_s": 0.0,          # peer-minus-reference clock offset
         "uncertainty_s": 0.0}     # reported alongside, not applied

    ``offset_s`` follows the clocksync convention (lane clock minus
    reference clock): each lane's timestamps have it *subtracted*, so a
    worker 250 ms ahead renders exactly where the router observed its
    effects. The uncertainty is stamped on the lane's process metadata
    (``clock_uncertainty_ms``) — Perfetto shows it in the process
    tooltip; span-level flags are request_trace.rebase's job. Traces
    already rebased upstream (supervisor ingest) belong in a lane with
    ``offset_s=0``: double-shifting is the one way to make this export
    lie."""
    shifted = []
    for lane in lanes:
        off = float(lane.get("offset_s", 0.0))
        traces = [t for t in lane.get("traces") or () if t.spans]
        fl = [dict(e, ts=e["ts"] - off)
              for e in lane.get("flight_events") or ()
              if e.get("ts") is not None]
        shifted.append((lane, off, traces, fl))
    floor = [t.spans[0].ts - off
             for _, off, traces, _ in shifted for t in traces]
    floor += [e["ts"] for _, _, _, fl in shifted for e in fl]
    t0 = min(floor, default=0.0)
    evs: List[Dict[str, Any]] = []
    for i, (lane, off, traces, fl) in enumerate(shifted):
        pid = int(lane.get("pid", i))
        unc_ms = round(float(lane.get("uncertainty_s", 0.0)) * 1e3, 4)
        evs.append({"name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": str(lane.get("name", f"proc {pid}")),
                             "clock_offset_ms": round(off * 1e3, 4),
                             "clock_uncertainty_ms": unc_ms}})
        if fl:
            evs += chrome_trace_events((), fl, rank=pid, t0=t0)
        if traces:
            if off or lane.get("uncertainty_s"):
                # shift copies, not the caller's live trace objects
                unc = float(lane.get("uncertainty_s", 0.0))
                traces = [copy.deepcopy(t).rebase(
                    off, unc, domain=str(lane.get("name", f"proc {pid}")))
                    for t in traces]
            evs += request_trace_events(traces, rank=pid, t0=t0)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": evs, "displayTimeUnit": "ms"}, f)
    os.replace(tmp, path)
    return path


def export_chrome_trace(path: str,
                        step_rows: Optional[Iterable[Dict[str, Any]]] = None,
                        flight_events: Optional[
                            Iterable[Dict[str, Any]]] = None,
                        rank: Optional[int] = None) -> str:
    """Write ``{"traceEvents": [...]}`` to ``path``. With no explicit
    inputs, pulls the live process's hub history and flight recorder."""
    if step_rows is None and flight_events is None:
        from deepspeed_tpu.observability.flight_recorder import \
            get_flight_recorder
        from deepspeed_tpu.observability.hub import peek_hub

        hub = peek_hub()
        step_rows = [t.to_dict() for t in hub.step_history] if hub else []
        rec = get_flight_recorder()
        flight_events = [{"ts": ts, "kind": kind, **fields}
                         for ts, kind, fields in rec.events()]
        rank = rec.rank if rank is None else rank
    evs = chrome_trace_events(step_rows or (), flight_events or (),
                              rank=rank or 0)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": evs, "displayTimeUnit": "ms"}, f)
    os.replace(tmp, path)
    return path


def export_rank_from_run_dir(run_dir: str, rank: int, path: str) -> str:
    """Offline export: read one rank's fleet shard + any flight dumps
    from a run dir (works for dead workers — that is the point)."""
    from deepspeed_tpu.observability.fleet import (FLIGHT_DIR, STEPS_DIR,
                                                   _rank_name)

    rows: List[Dict[str, Any]] = []
    shard = os.path.join(run_dir, STEPS_DIR, _rank_name(rank) + ".jsonl")
    if os.path.exists(shard):
        with open(shard) as f:
            for line in f:
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    events: List[Dict[str, Any]] = []
    flight_d = os.path.join(run_dir, FLIGHT_DIR)
    if os.path.isdir(flight_d):
        for name in sorted(os.listdir(flight_d)):
            if name.startswith(f"flight_rank{rank}_") and \
                    name.endswith(".json"):
                try:
                    with open(os.path.join(flight_d, name)) as f:
                        events.extend(json.load(f).get("events", []))
                except Exception:
                    continue
    return export_chrome_trace(path, step_rows=rows, flight_events=events,
                               rank=rank)
