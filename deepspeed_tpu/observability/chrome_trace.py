"""Chrome-trace (Perfetto-loadable) export of a rank's step history.

Renders the StepTrace history and/or flight-recorder event tail of one
rank as a ``trace.json`` in the Chrome trace-event format — open it at
``ui.perfetto.dev`` or ``chrome://tracing``. This is the lightweight
structural view (step wall, host gap, dispatch window, traced
collectives as markers) that needs no ``jax.profiler`` capture and can
be produced *after the fact* from a fleet run dir or a flight dump —
including for a worker that is already dead.

Track layout (one Chrome "process" per rank):

    tid 0  step      one span per train step (wall time)
    tid 1  host      the host-gap slice at the start of each step
    tid 2  dispatch  step_entry → step_dispatch window (flight events)
    tid 3  comm      traced collectives — dispatch→completion "X" spans
                     when the event carries ``dur_ms`` (comm.py
                     _traced_op), instant markers otherwise; overlapping
                     dispatches render as overlapping slices (the
                     overlap lanes the ISSUE-6 engine is tuned against)
    tid 4  events    everything else (compile, checkpoint, offload, ...)
                     — also "X" spans when the event has ``dur_ms``
                     (flight_recorder.span)
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Iterable, List, Optional

_TID_NAMES = {0: "step", 1: "host", 2: "dispatch", 3: "comm", 4: "events"}


def _us(t_seconds: float, t0: float) -> float:
    return (t_seconds - t0) * 1e6


def chrome_trace_events(step_rows: Iterable[Dict[str, Any]] = (),
                        flight_events: Iterable[Dict[str, Any]] = (),
                        rank: int = 0) -> List[Dict[str, Any]]:
    """Build the ``traceEvents`` list.

    ``step_rows``: StepTrace dicts (``to_dict()``), hub history rows, or
    fleet shard rows — needs ``step``, ``wall_ms``, ``timestamp`` (step
    *end*, wall clock). ``flight_events``: flight-recorder event dicts
    (``ts`` + ``kind`` + fields)."""
    step_rows = [r for r in step_rows
                 if r.get("wall_ms") is not None
                 and r.get("timestamp") is not None]
    flight_events = [e for e in flight_events if e.get("ts") is not None]
    starts = [r["timestamp"] - r["wall_ms"] / 1e3 for r in step_rows]
    t0 = min(starts + [e["ts"] for e in flight_events], default=0.0)

    evs: List[Dict[str, Any]] = [
        {"name": "thread_name", "ph": "M", "pid": rank, "tid": tid,
         "args": {"name": name}} for tid, name in _TID_NAMES.items()
    ] + [{"name": "process_name", "ph": "M", "pid": rank,
          "args": {"name": f"rank {rank}"}}]

    for row, start in zip(step_rows, starts):
        args = {k: row[k] for k in ("loss", "tokens_per_sec", "mfu",
                                    "compile_events", "inflight")
                if row.get(k) is not None}
        evs.append({"name": f"step {row['step']}", "ph": "X", "cat": "step",
                    "ts": _us(start, t0), "dur": row["wall_ms"] * 1e3,
                    "pid": rank, "tid": 0, "args": args})
        gap = row.get("host_gap_ms")
        if gap:
            evs.append({"name": "host_gap", "ph": "X", "cat": "host",
                        "ts": _us(start, t0), "dur": gap * 1e3,
                        "pid": rank, "tid": 1,
                        "args": {"step": row["step"]}})

    # flight events: pair step_entry → step_dispatch into dispatch-window
    # spans; everything else becomes an instant marker
    entry_ts: Dict[int, float] = {}
    for e in flight_events:
        kind, ts = e["kind"], e["ts"]
        fields = {k: v for k, v in e.items() if k not in ("kind", "ts")}
        if kind == "step_entry":
            entry_ts[fields.get("step", -1)] = ts
            continue
        if kind == "step_dispatch":
            step = fields.get("step", -1)
            t_in = entry_ts.pop(step, None)
            if t_in is not None:
                evs.append({"name": f"dispatch {step}", "ph": "X",
                            "cat": "dispatch", "ts": _us(t_in, t0),
                            "dur": max(ts - t_in, 0.0) * 1e6,
                            "pid": rank, "tid": 2, "args": fields})
            continue
        tid = 3 if kind == "collective" else 4
        name = fields.get("op", kind) if kind == "collective" else kind
        dur_ms = fields.get("dur_ms")
        if dur_ms is not None:
            # dispatch→completion span (comm._traced_op /
            # flight_recorder.span): a real slice on the lane, so
            # concurrent dispatches visibly overlap
            evs.append({"name": str(name), "ph": "X", "cat": kind,
                        "ts": _us(ts, t0),
                        "dur": max(float(dur_ms), 0.0) * 1e3,
                        "pid": rank, "tid": tid, "args": fields})
        else:
            evs.append({"name": str(name), "ph": "i", "cat": kind,
                        "s": "t", "ts": _us(ts, t0), "pid": rank,
                        "tid": tid, "args": fields})
    return evs


def export_chrome_trace(path: str,
                        step_rows: Optional[Iterable[Dict[str, Any]]] = None,
                        flight_events: Optional[
                            Iterable[Dict[str, Any]]] = None,
                        rank: Optional[int] = None) -> str:
    """Write ``{"traceEvents": [...]}`` to ``path``. With no explicit
    inputs, pulls the live process's hub history and flight recorder."""
    if step_rows is None and flight_events is None:
        from deepspeed_tpu.observability.flight_recorder import \
            get_flight_recorder
        from deepspeed_tpu.observability.hub import peek_hub

        hub = peek_hub()
        step_rows = [t.to_dict() for t in hub.step_history] if hub else []
        rec = get_flight_recorder()
        flight_events = [{"ts": ts, "kind": kind, **fields}
                         for ts, kind, fields in rec.events()]
        rank = rec.rank if rank is None else rank
    evs = chrome_trace_events(step_rows or (), flight_events or (),
                              rank=rank or 0)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump({"traceEvents": evs, "displayTimeUnit": "ms"}, f)
    os.replace(tmp, path)
    return path


def export_rank_from_run_dir(run_dir: str, rank: int, path: str) -> str:
    """Offline export: read one rank's fleet shard + any flight dumps
    from a run dir (works for dead workers — that is the point)."""
    from deepspeed_tpu.observability.fleet import (FLIGHT_DIR, STEPS_DIR,
                                                   _rank_name)

    rows: List[Dict[str, Any]] = []
    shard = os.path.join(run_dir, STEPS_DIR, _rank_name(rank) + ".jsonl")
    if os.path.exists(shard):
        with open(shard) as f:
            for line in f:
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
    events: List[Dict[str, Any]] = []
    flight_d = os.path.join(run_dir, FLIGHT_DIR)
    if os.path.isdir(flight_d):
        for name in sorted(os.listdir(flight_d)):
            if name.startswith(f"flight_rank{rank}_") and \
                    name.endswith(".json"):
                try:
                    with open(os.path.join(flight_d, name)) as f:
                        events.extend(json.load(f).get("events", []))
                except Exception:
                    continue
    return export_chrome_trace(path, step_rows=rows, flight_events=events,
                               rank=rank)
