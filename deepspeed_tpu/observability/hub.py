"""Process-wide metrics hub.

Every signal the runtime already produces — step timing, loss/grad-norm,
traced collective volume (utils/comms_logging), capability-fallback
counters (utils/telemetry), serving latencies (inference/engine_v2) —
flows through one registry with three export paths:

* ``record_step`` keeps a bounded in-memory history of ``StepTrace``
  rows and mirrors the headline numbers into gauges;
* a JSON-lines sink streams every row to disk as it happens;
* a Prometheus text snapshot is rewritten (atomically) on a cadence for
  textfile-collector scraping.

The hub is a singleton (``get_hub``): training engine, serving engine
and user code in one process share the registry, so one Prometheus page
shows the whole picture. Sinks attach via :meth:`configure` (config
block or ``DSTPU_METRICS_JSONL`` / ``DSTPU_METRICS_PROM`` env vars).

Compile/retrace visibility: jax.monitoring event listeners (registered
once, best-effort — older jax may lack the API) count XLA compilations
and their wall time; ``StepTrace.compile_events`` > 0 on a mid-run step
is the classic silent-retrace regression signature.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Any, Dict, Optional

from deepspeed_tpu.observability.histogram import Histogram
from deepspeed_tpu.observability.sinks import (JSONLSink, PrometheusTextSink,
                                               labeled_name,
                                               render_prometheus)
from deepspeed_tpu.observability.step_trace import StepTrace
from deepspeed_tpu.utils.logging import logger

# process-global compile accounting: jax.monitoring listeners cannot be
# unregistered, so they feed module state rather than a hub instance
# (reset_hub() would otherwise leak dead hubs into the listener)
_COMPILE_LOCK = threading.Lock()
_COMPILE_EVENTS = 0
_COMPILE_SECS = 0.0
_LISTENERS_REGISTERED = False


def _on_compile_duration(event: str, duration: float, **kw) -> None:
    global _COMPILE_EVENTS, _COMPILE_SECS
    if "compil" not in event:
        return
    with _COMPILE_LOCK:
        _COMPILE_EVENTS += 1
        _COMPILE_SECS += float(duration)


def _register_compile_listeners() -> None:
    global _LISTENERS_REGISTERED
    if _LISTENERS_REGISTERED:
        return
    _LISTENERS_REGISTERED = True
    try:
        from jax import monitoring

        monitoring.register_event_duration_secs_listener(
            _on_compile_duration)
    except Exception as e:  # jax.monitoring API varies across versions
        logger.debug(f"compile-event listener unavailable: {e}")


def compile_stats() -> Dict[str, float]:
    with _COMPILE_LOCK:
        return {"events": _COMPILE_EVENTS, "secs": _COMPILE_SECS}


class MetricsHub:
    def __init__(self, step_history: int = 512):
        self._lock = threading.Lock()
        self.gauges: Dict[str, float] = {}
        self.counters: Dict[str, float] = {}
        self.histograms: Dict[str, Histogram] = {}
        self.step_history: deque = deque(maxlen=step_history)
        self._jsonl: Optional[JSONLSink] = None
        self._prom: Optional[PrometheusTextSink] = None
        self._prom_every = 10  # steps between Prometheus snapshot rewrites
        self._fleet = None  # FleetPublisher when a run dir is configured
        self._last_comm_totals: Dict[str, float] = {}
        self._last_fallbacks: Dict[str, float] = {}
        self._last_compile = compile_stats()
        _register_compile_listeners()

    # -- configuration -------------------------------------------------
    def configure(self, obs_config=None, rank=None) -> None:
        """Attach sinks from the config block and/or env vars. Safe to
        call more than once (a second engine in the process reuses the
        already-attached sinks). With a run dir configured
        (``observability.run_dir`` / ``DSTPU_RUN_DIR``) a
        ``FleetPublisher`` additionally shards every step row into it
        (docs/observability.md "Fleet view"); no run dir → no publisher,
        no shard I/O."""
        jsonl = os.environ.get("DSTPU_METRICS_JSONL") or getattr(
            obs_config, "jsonl_path", None)
        prom = os.environ.get("DSTPU_METRICS_PROM") or getattr(
            obs_config, "prometheus_path", None)
        hist = int(getattr(obs_config, "step_history", 0) or 0)
        every = int(getattr(obs_config, "prometheus_every_steps", 0) or 0)
        with self._lock:
            if jsonl and (self._jsonl is None or self._jsonl.path != jsonl):
                self._jsonl = JSONLSink(jsonl)
            if prom and (self._prom is None or self._prom.path != prom):
                self._prom = PrometheusTextSink(prom)
            if every > 0:
                self._prom_every = every
            if hist > 0 and hist != self.step_history.maxlen:
                self.step_history = deque(self.step_history, maxlen=hist)
        try:
            from deepspeed_tpu.observability.fleet import (FleetPublisher,
                                                           resolve_run_dir)

            run_dir = resolve_run_dir(obs_config)
            if run_dir and (self._fleet is None
                            or self._fleet.run_dir != run_dir):
                self._fleet = FleetPublisher(
                    run_dir, rank=rank,
                    publish_every_steps=getattr(
                        obs_config, "publish_every_steps", 1))
        except Exception as e:  # the fleet layer must never block startup
            logger.warning(f"fleet publisher unavailable: {e}")

    # -- primitive metrics ---------------------------------------------
    # ``labels`` composes a distinct series per label set
    # (``serve.queue_depth{replica="r0"}``) — fleet serving metrics use
    # it so aggregation never collapses N replicas into one series; the
    # Prometheus renderer understands the composed keys (sinks.py)
    def gauge(self, name: str, value: float,
              labels: Optional[Dict[str, str]] = None) -> None:
        if labels:
            name = labeled_name(name, labels)
        with self._lock:
            self.gauges[name] = float(value)

    def counter_add(self, name: str, n: float = 1.0,
                    labels: Optional[Dict[str, str]] = None) -> None:
        if labels:
            name = labeled_name(name, labels)
        with self._lock:
            self.counters[name] = self.counters.get(name, 0.0) + n

    def histogram(self, name: str,
                  labels: Optional[Dict[str, str]] = None,
                  **kw) -> Histogram:
        if labels:
            name = labeled_name(name, labels)
        with self._lock:
            h = self.histograms.get(name)
            if h is None:
                h = self.histograms[name] = Histogram(name, **kw)
            return h

    # -- step traces -----------------------------------------------------
    def comm_deltas(self) -> (dict, dict):
        """(cumulative, delta-since-last-call) traced collective bytes
        by op — empty when the comms logger is disabled."""
        try:
            from deepspeed_tpu.utils.comms_logging import get_comms_logger

            totals = get_comms_logger().totals()
        except Exception:
            totals = {}
        delta = {k: v - self._last_comm_totals.get(k, 0.0)
                 for k, v in totals.items()
                 if v != self._last_comm_totals.get(k, 0.0)}
        self._last_comm_totals = dict(totals)
        return totals, delta

    def compile_delta(self) -> Dict[str, float]:
        now = compile_stats()
        delta = {"events": now["events"] - self._last_compile["events"],
                 "secs": now["secs"] - self._last_compile["secs"]}
        self._last_compile = now
        return delta

    def fallback_delta(self) -> Dict[str, float]:
        """Capability-fallback counters (utils/telemetry) that moved
        since the last call — empty in the steady state, so exporting
        the delta costs nothing per step."""
        try:
            from deepspeed_tpu.utils import telemetry

            now = telemetry.snapshot()
        except Exception:
            return {}
        delta = {k: v - self._last_fallbacks.get(k, 0)
                 for k, v in now.items()
                 if v != self._last_fallbacks.get(k, 0)}
        self._last_fallbacks = {k: float(v) for k, v in now.items()}
        return delta

    def record_step(self, trace: StepTrace) -> None:
        with self._lock:
            self.step_history.append(trace)
            self.gauges["train.step"] = trace.step
            self.gauges["train.step_seconds"] = trace.wall_ms / 1000.0
            for name, val in (("train.loss", trace.loss),
                              ("train.grad_norm", trace.grad_norm),
                              ("train.lr", trace.lr),
                              ("train.tokens_per_sec", trace.tokens_per_sec),
                              ("train.tokens_per_sec_per_chip",
                               trace.tokens_per_sec_per_chip),
                              ("train.mfu", trace.mfu),
                              ("train.host_gap_ms", trace.host_gap_ms)):
                if val is not None:
                    self.gauges[name] = float(val)
            self.counters["train.steps"] = \
                self.counters.get("train.steps", 0.0) + 1.0
            if trace.tokens:
                self.counters["train.tokens"] = \
                    self.counters.get("train.tokens", 0.0) + trace.tokens
            if trace.overflow:
                self.counters["train.overflow_steps"] = \
                    self.counters.get("train.overflow_steps", 0.0) + 1.0
            if trace.compile_events:
                self.counters["jit.compile_events"] = \
                    self.counters.get("jit.compile_events", 0.0) \
                    + trace.compile_events
        self.histogram("train.step_seconds").observe(trace.wall_ms / 1000.0)
        # capability downgrades land on the same dashboard as throughput:
        # moved telemetry counters mirror into hub counters (-> Prometheus
        # as dstpu_fallback_*_total) and emit one JSONL event per change
        fb = self.fallback_delta()
        for name, d in fb.items():
            self.counter_add(f"fallback.{name}", d)
        if fb:
            self.record_event("capability_fallback", step=trace.step,
                              delta=fb)
        if self._jsonl is not None:
            self._jsonl.write(trace.to_dict())
        if self._fleet is not None:
            self._fleet.publish_step(trace)
        if self._prom is not None and \
                trace.step % max(1, self._prom_every) == 0:
            self.write_prometheus()

    def record_event(self, kind: str, **fields) -> None:
        """Free-form JSONL row (watchdog reports, trace markers, ...)."""
        if self._jsonl is not None:
            self._jsonl.write(dict(fields, kind=kind))

    # -- export ----------------------------------------------------------
    def mean_mfu(self, last_n: int = 0) -> Optional[float]:
        """Mean MFU over the most recent ``last_n`` traced steps (all
        history when 0); None when no step carried an MFU."""
        with self._lock:
            rows = list(self.step_history)
        if last_n > 0:
            rows = rows[-last_n:]
        vals = [t.mfu for t in rows if t.mfu is not None]
        if not vals:
            return None
        return sum(vals) / len(vals)

    def window_mfu(self, last_n: int = 0) -> Optional[float]:
        """MFU of the most recent ``last_n`` traced steps computed the
        way bench.py computes its window: total tokens over total wall
        time (token-weighted — a mean of per-step rates would overweight
        fast steps). None when the window carries no MFU inputs."""
        with self._lock:
            rows = list(self.step_history)
        if last_n > 0:
            rows = rows[-last_n:]
        rows = [t for t in rows
                if t.mfu is not None and t.wall_ms > 0 and t.tokens]
        if not rows:
            return None
        total_tokens = sum(t.tokens for t in rows)
        total_s = sum(t.wall_ms for t in rows) / 1000.0
        last = rows[-1]
        from deepspeed_tpu.observability.roofline import mfu as _mfu

        return _mfu(total_tokens / total_s / max(1, last.n_chips),
                    last.flops_per_token, last.peak_tflops)

    def window_host_gap_ms(self, last_n: int = 0) -> Optional[float]:
        """Mean host-side gap per step over the most recent ``last_n``
        traced steps (all history when 0) — the per-window aggregate
        bench.py reports next to tokens/s/chip so host-overhead
        regressions are visible in every BENCH artifact. None when no
        step in the window carried the measurement."""
        with self._lock:
            rows = list(self.step_history)
        if last_n > 0:
            rows = rows[-last_n:]
        vals = [t.host_gap_ms for t in rows if t.host_gap_ms is not None]
        if not vals:
            return None
        return sum(vals) / len(vals)

    def snapshot(self) -> Dict[str, Any]:
        from deepspeed_tpu.utils import telemetry

        with self._lock:
            out: Dict[str, Any] = {
                "gauges": dict(self.gauges),
                "counters": dict(self.counters),
                "histograms": {n: h.snapshot()
                               for n, h in self.histograms.items()},
                "fallbacks": telemetry.snapshot(),
            }
            last = self.step_history[-1] if self.step_history else None
        if last is not None:
            out["last_step"] = last.to_dict()
        return out

    def to_prometheus(self) -> str:
        from deepspeed_tpu.utils import telemetry

        with self._lock:
            gauges = dict(self.gauges)
            counters = dict(self.counters)
            hists = dict(self.histograms)
        return render_prometheus(
            gauges, counters, hists,
            labeled_counters={"capability_fallback":
                              {k: float(v)
                               for k, v in telemetry.snapshot().items()}})

    def write_prometheus(self) -> None:
        if self._prom is not None:
            self._prom.write_text(self.to_prometheus())

    def close(self) -> None:
        if self._jsonl is not None:
            self._jsonl.close()
        if self._fleet is not None:
            self._fleet.close()
        self.write_prometheus()


_HUB: Optional[MetricsHub] = None
_HUB_LOCK = threading.Lock()


def get_hub() -> MetricsHub:
    global _HUB
    with _HUB_LOCK:
        if _HUB is None:
            _HUB = MetricsHub()
        return _HUB


def peek_hub() -> Optional[MetricsHub]:
    """The singleton if one exists, without creating it — for report
    paths (watchdog, crash dumps) that must not allocate mid-failure."""
    return _HUB


def reset_hub() -> None:
    """Drop the singleton (tests). Sinks on the old hub are closed."""
    global _HUB
    with _HUB_LOCK:
        if _HUB is not None:
            try:
                if _HUB._jsonl is not None:
                    _HUB._jsonl.close()
                if _HUB._fleet is not None:
                    _HUB._fleet.close()
            except Exception:
                pass
        _HUB = None
