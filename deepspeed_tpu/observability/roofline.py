"""Peak-rate tables, MFU, and roofline classification.

Single home for the chip peak numbers (bench.py imports from here so the
engine-reported MFU and the benchmark headline are computed from the
same table and the same formula — the 2%-agreement contract in
tests/test_observability.py). Roofline math follows docs/roofline.md:
arithmetic intensity from XLA's compiled-program cost analysis
(flops / bytes accessed) against the chip's ridge point.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

# per-chip dense bf16 peak TFLOPS by TPU generation
PEAK_TFLOPS = {
    "v4": 275.0,
    "v5 lite": 197.0,  # v5e
    "v5e": 197.0,
    "v5p": 459.0,
    "v6 lite": 918.0,  # v6e (Trillium)
    "v6e": 918.0,
}

# per-chip HBM bandwidth, GB/s (public TPU system specs)
HBM_GBPS = {
    "v4": 1228.0,
    "v5 lite": 819.0,
    "v5e": 819.0,
    "v5p": 2765.0,
    "v6 lite": 1640.0,
    "v6e": 1640.0,
}

_CPU_SIM_PEAK = 197.0  # arbitrary reference chip for cpu-sim MFU numbers


def detect_peak_tflops(device) -> float:
    """bf16 peak for ``device``; BENCH_PEAK_TFLOPS env overrides."""
    if "BENCH_PEAK_TFLOPS" in os.environ:
        return float(os.environ["BENCH_PEAK_TFLOPS"])
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_TFLOPS.items():
        if key in kind:
            return val
    return _CPU_SIM_PEAK


def detect_hbm_gbps(device) -> float:
    if "BENCH_HBM_GBPS" in os.environ:
        return float(os.environ["BENCH_HBM_GBPS"])
    kind = getattr(device, "device_kind", "").lower()
    for key, val in HBM_GBPS.items():
        if key in kind:
            return val
    return 819.0


def mfu(tokens_per_sec_per_chip: float, flops_per_token: float,
        peak_tflops: float) -> float:
    """Model-FLOPs utilization — bench.py's exact formula."""
    if peak_tflops <= 0:
        return 0.0
    return tokens_per_sec_per_chip * flops_per_token / (peak_tflops * 1e12)


def roofline_summary(cost: Dict[str, float], peak_tflops: float,
                     hbm_gbps: float,
                     step_seconds: Optional[float] = None
                     ) -> Dict[str, Any]:
    """Classify a compiled program against the chip roofline.

    ``cost`` is XLA cost analysis output ({"flops", "bytes_accessed",
    ...}, see utils/hlo_bytes.program_costs). Returns arithmetic
    intensity, the chip ridge point, which side of it the program sits
    on, the attainable TFLOPS ceiling, and — when ``step_seconds`` is
    given — the achieved TFLOPS and fraction of attainable.
    """
    flops = float(cost.get("flops", 0.0))
    bytes_accessed = float(cost.get("bytes_accessed", 0.0))
    intensity = flops / bytes_accessed if bytes_accessed > 0 else float("inf")
    ridge = peak_tflops * 1e12 / (hbm_gbps * 1e9)  # FLOPs per HBM byte
    bound = "compute" if intensity >= ridge else "memory"
    attainable = (peak_tflops if bound == "compute"
                  else hbm_gbps * intensity / 1e3)  # GB/s * F/B -> TFLOPS
    out = {
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "arithmetic_intensity": round(intensity, 3),
        "ridge_intensity": round(ridge, 3),
        "bound": bound,
        "peak_tflops": peak_tflops,
        "hbm_gbps": hbm_gbps,
        "attainable_tflops": round(attainable, 3),
    }
    if step_seconds and step_seconds > 0:
        achieved = flops / step_seconds / 1e12
        out["achieved_tflops"] = round(achieved, 4)
        out["hw_flops_utilization"] = round(achieved / peak_tflops, 4)
        if attainable > 0:
            out["fraction_of_attainable"] = round(achieved / attainable, 4)
    return out
