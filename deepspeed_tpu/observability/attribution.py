"""Per-region roofline attribution for a training step.

Splits the step's cost into the five buckets that matter at the real
shape (8L · 131k vocab on one chip, docs/roofline.md): **attn**,
**mlp**, **vocab_head**, **optimizer**, **param_fetch**.

The three compute buckets are measured, not modeled: each region is a
small jitted closure over the model's own block functions
(``models.transformer._layer`` / ``_layer_mlp`` / the fused
final-norm+unembed+CE tail), lowered + compiled on abstract
``ShapeDtypeStruct`` inputs and read back through XLA's cost analysis —
so the numbers track whatever the compiler actually emits (remat, fp8,
tiling) and the pass runs anywhere jax compiles, including CPU CI.
The attn bucket is the full-block cost minus the MLP-half cost
(the block is fused end-to-end; XLA cannot attribute a residual add to
one side, and the subtraction is exact for the matmul-dominated terms).

The two non-compute buckets are analytic transfer models:

- ``optimizer``: fused-Adam HBM (or host-RAM, under offload) traffic —
  reads master+m+v (12 B/param) + the grad, writes master+m+v + the
  bf16 model cast.
- ``param_fetch``: ZeRO-Infinity layer streaming — per-layer param
  bytes × layers × (fwd + bwd), against the host link bandwidth
  (``DSTPU_FETCH_GBPS``, default the measured ~3.3 GB/s tunnel H2D).
  This traffic *overlaps* compute via the prefetch ring
  (``performance.param_prefetch_depth``); its row reports the bandwidth
  floor it needs to stay hidden, not an additive cost.

The long-context bench tier adds two more transfer regions —
**sp_comm** (sequence-parallel collectives on ICI) and
**host_kv_stream** (FPDT host-KV D2H/H2D) — modeled analytically by
:func:`attribute_longctx_step` (a compiled step at 256k tokens is
O(S²)-infeasible on the CPU sim). All three transfer regions share the
``DMA_REGIONS`` exposed/hidden machinery.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp

from deepspeed_tpu.observability.roofline import roofline_summary

REGIONS = ("attn", "mlp", "vocab_head", "optimizer", "param_fetch")

# the BENCH_LONGCTX tier's analytic regions (attribute_longctx_step)
LONGCTX_REGIONS = ("attn", "sp_comm", "host_kv_stream")

# Transfer (DMA) regions: their roofline time is bytes/bandwidth on the
# link they ride, not flops/bytes against HBM. sp_comm rides ICI; the
# host streams ride the host link; grad_reduce (the qgZ region,
# attribute_quant_step) rides ICI/DCN per its level structure.
DMA_REGIONS = frozenset({"param_fetch", "sp_comm", "host_kv_stream",
                         "grad_reduce"})

# measured sustained H2D on the tunnel-attached v5e (docs/roofline.md);
# a pod's per-layer bf16 all-gather over ICI is ≥20x this
_DEFAULT_FETCH_GBPS = 3.3

# one v5e ICI link direction (sustained, docs/roofline.md); override
# with DSTPU_ICI_GBPS for other topologies
_DEFAULT_ICI_GBPS = 45.0

# inter-slice data-center network per chip (the link hpZ keeps gathers
# off); override with DSTPU_DCN_GBPS
_DEFAULT_DCN_GBPS = 6.25


def _dma_gbps(region: str, fetch_gbps: Optional[float] = None,
              ici_gbps: Optional[float] = None) -> float:
    """Bandwidth a DMA region's bytes divide by: sp collectives ride
    ICI, param/KV streams ride the host link."""
    if region == "sp_comm":
        return (ici_gbps if ici_gbps is not None
                else float(os.environ.get("DSTPU_ICI_GBPS",
                                          _DEFAULT_ICI_GBPS)))
    return (fetch_gbps if fetch_gbps is not None
            else float(os.environ.get("DSTPU_FETCH_GBPS",
                                      _DEFAULT_FETCH_GBPS)))


@dataclasses.dataclass
class RegionCost:
    region: str
    flops: float            # total for the step (already × num_layers)
    bytes_accessed: float
    note: str = ""
    overlapped: bool = False  # traffic hidden behind compute when true
    # DMA regions only: pin the link this region's bytes divide by
    # (attribute_quant_step sets these — e.g. grad_reduce's effective
    # bandwidth over its ICI+DCN level mix). None falls back to the
    # region-name default in _dma_gbps.
    gbps: Optional[float] = None
    link: Optional[str] = None

    @property
    def intensity(self) -> float:
        if self.bytes_accessed <= 0:
            return float("inf")
        return self.flops / self.bytes_accessed

    def to_dict(self) -> Dict[str, Any]:
        return {**dataclasses.asdict(self),
                "arithmetic_intensity": (
                    None if self.bytes_accessed <= 0
                    else round(self.intensity, 3))}


def _grad_cost(fn, *abstract_args,
               argnums: Optional[tuple] = None) -> Dict[str, float]:
    """Compile grad-of-sum of ``fn`` on abstract inputs; return XLA cost
    analysis (fwd+bwd flops / bytes — the shape a train step pays).
    ``argnums`` defaults to every non-integer argument."""
    from deepspeed_tpu.profiling.flops_profiler import profile_compiled

    def total(*a):
        out = fn(*a)
        if isinstance(out, tuple):
            out = out[0]
        return jnp.sum(out.astype(jnp.float32))

    if argnums is None:
        argnums = tuple(
            i for i, a in enumerate(abstract_args)
            if not all(jnp.issubdtype(jnp.dtype(s.dtype), jnp.integer)
                       for s in jax.tree.leaves(a)))
    g = jax.jit(jax.grad(total, argnums=argnums))
    return profile_compiled(g, *abstract_args)


def _abstract_params(cfg):
    """ShapeDtypeStruct tree of the full model params (no compute)."""
    from deepspeed_tpu.models.transformer import init_params

    return jax.eval_shape(lambda k: init_params(cfg, k),
                          jax.random.PRNGKey(0))


def _per_layer_shapes(stacked_layers):
    """Strip the leading stacked-layer dim: [L, ...] -> [...]."""
    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape[1:], s.dtype),
        stacked_layers)


def _tree_bytes(tree) -> int:
    return int(sum(
        int(jnp.prod(jnp.asarray(s.shape))) * jnp.dtype(s.dtype).itemsize
        for s in jax.tree.leaves(tree)))


def _head_fn(cfg):
    """Fused final-norm + unembed + CE tail (mirrors loss_fn's tiled and
    plain branches; the qwz fetch hooks are identity when unconfigured)."""
    from deepspeed_tpu.models.transformer import _norm
    from deepspeed_tpu.runtime.sharding import effective_dtype

    dt = effective_dtype(cfg.dtype)

    def head(hidden, head_params, labels):
        unembed = head_params["unembed"].astype(dt)
        if cfg.tiled_logits > 1:
            from deepspeed_tpu.parallel.tiled_compute import \
                tiled_logits_loss

            def fnorm_tile(h):
                return _norm(h, head_params["final_norm"], cfg.norm,
                             cfg.norm_eps)

            nll_sum, total = tiled_logits_loss(
                hidden, unembed, labels, None, cfg.tiled_logits,
                transpose_unembed=cfg.tie_embeddings,
                tile_transform=fnorm_tile)
            return nll_sum / jnp.maximum(total, 1.0)
        normed = _norm(hidden, head_params["final_norm"], cfg.norm,
                       cfg.norm_eps)
        eq = ("bsh,vh->bsv" if cfg.tie_embeddings else "bsh,hv->bsv")
        logits = jnp.einsum(eq, normed.astype(dt), unembed).astype(
            jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(
            logits, labels[..., None], axis=-1)[..., 0]
        return jnp.mean(logz - gold)

    return head


def attribute_step(cfg, micro_batch: int, seq: int, *,
                   fetch_gbps: Optional[float] = None,
                   optimizer: str = "adamw",
                   optimizer_on_host: Optional[bool] = None,
                   grad_bytes_per_param: int = 2) -> List[RegionCost]:
    """Measure/model the five region costs for one fwd+bwd+update step.

    ``cfg`` is a TransformerConfig; compute regions are compiled at
    [micro_batch, seq, hidden] activations and scaled by ``num_layers``.
    """
    from deepspeed_tpu.models.transformer import _layer, _layer_mlp
    from deepspeed_tpu.runtime.sharding import effective_dtype

    dt = effective_dtype(cfg.dtype)
    H, L = cfg.hidden_size, cfg.num_layers
    x = jax.ShapeDtypeStruct((micro_batch, seq, H), dt)
    pos = jax.ShapeDtypeStruct((micro_batch, seq), jnp.int32)
    labels = jax.ShapeDtypeStruct((micro_batch, seq), jnp.int32)

    params = _abstract_params(cfg)
    lp = _per_layer_shapes(params["layers"])

    layer_cost = _grad_cost(
        lambda lp_, x_, pos_: _layer(cfg, x_, lp_, pos_), lp, x, pos)
    mlp_cost = _grad_cost(
        lambda lp_, x_, attn_: _layer_mlp(cfg, x_, attn_, lp_),
        lp, x, x)

    unembed = (params["embed"]["tokens"] if cfg.tie_embeddings
               else params["unembed"]["kernel"])
    head_params = {"final_norm": params["final_norm"], "unembed": unembed}
    head_cost = _grad_cost(
        lambda h_, hp_, lab_: _head_fn(cfg)(h_, hp_, lab_),
        x, head_params, labels)

    regions = [
        RegionCost(
            "attn",
            max(0.0, (layer_cost["flops"] - mlp_cost["flops"])) * L,
            max(0.0, (layer_cost["bytes_accessed"]
                      - mlp_cost["bytes_accessed"])) * L,
            note="block minus MLP-half, x num_layers"),
        RegionCost(
            "mlp", mlp_cost["flops"] * L,
            mlp_cost["bytes_accessed"] * L,
            note=("fp8 GEMMs" if cfg.fp8_mlp else "bf16 GEMMs")
                 + ", x num_layers"),
        RegionCost(
            "vocab_head", head_cost["flops"],
            head_cost["bytes_accessed"],
            note=(f"tiled_logits={cfg.tiled_logits}"
                  if cfg.tiled_logits > 1 else "untiled logits")),
    ]

    # -- optimizer: analytic fused-Adam traffic -------------------------
    n_params = cfg.num_params()
    model_bytes = jnp.dtype(dt).itemsize
    if optimizer.lower() in ("adam", "adamw"):
        opt_reads = 12 + grad_bytes_per_param    # master+m+v + grad
        opt_writes = 12 + model_bytes            # master+m+v + cast
    else:                                        # sgd-class
        opt_reads = 4 + grad_bytes_per_param
        opt_writes = 4 + model_bytes
    on_host = (optimizer_on_host if optimizer_on_host is not None
               else bool(cfg.prefetch_stream))
    regions.append(RegionCost(
        "optimizer", float(n_params) * 4,        # ~4 flop/param update
        float(n_params) * (opt_reads + opt_writes),
        note=("host-RAM traffic (offload_optimizer)" if on_host
              else "HBM traffic, overlapped with backward"),
        overlapped=not on_host))

    # -- param_fetch: ZeRO-Infinity layer streaming ---------------------
    layer_bytes = _tree_bytes(lp)
    fetch = (fetch_gbps if fetch_gbps is not None
             else float(os.environ.get("DSTPU_FETCH_GBPS",
                                       _DEFAULT_FETCH_GBPS)))
    depth = cfg.prefetch_depth if cfg.prefetch_depth else 1
    regions.append(RegionCost(
        "param_fetch", 0.0,
        float(layer_bytes) * L * 2,              # fwd + bwd passes
        note=(f"host->device @ ~{fetch:g} GB/s, prefetch ring depth "
              f"{depth}" if cfg.prefetch_stream
              else "params resident (no streaming)"),
        overlapped=True))
    return regions


# ---------------------------------------------------------------------------
# Analytic long-context attribution (BENCH_LONGCTX tier)
# ---------------------------------------------------------------------------
# At ≥256k tokens the O(S²) attention cannot be compiled on the CPU sim
# (attribute_step's measured closures would run for hours), so this tier
# models the three long-context regions analytically, per chip, from the
# same closed forms the planner (parallel/auto_sp.py) reasons with. The
# formulas are stated inline; docs/roofline.md round 8 records a table.


def attribute_longctx_step(*, seq_len: int, hidden_size: int,
                           num_heads: int,
                           num_kv_heads: Optional[int] = None,
                           head_dim: Optional[int] = None,
                           num_layers: int = 1, batch_size: int = 1,
                           sp: int = 1, strategy: Optional[str] = None,
                           attn_chunks: int = 0,
                           fpdt_host_kv: bool = False,
                           dtype_bytes: int = 2) -> List[RegionCost]:
    """Per-chip analytic costs for the long-context regions of one
    fwd+bwd step: **attn** (compute), **sp_comm** (ICI collectives for
    the chosen sp strategy), **host_kv_stream** (FPDT host-KV D2H/H2D
    when spilling). Regions with zero cost at this plan are still
    emitted (zero rows) so the bench table shape is stable.

    - attn flops: causal QKᵀ+PV is 4·B·S²·H halved by causality, ×3 for
      fwd+bwd, ÷sp (each rank owns S/sp query rows): 6·B·S²·H/sp.
    - sp_comm bytes (×2 fwd+bwd, per layer):
      ulysses — 4 all-to-alls (q, out at num_heads width; k, v at
      kv_heads width), each moving (sp-1)/sp of its tensor;
      ring — KV blocks traverse sp-1 hops: 2·B·S·kv·D·(sp-1)/sp;
      fpdt-composed (attn_chunks>1 under sp) — KV all-gather fwd +
      reduce-scatter bwd, same (sp-1)/sp fraction of the full KV.
    - host_kv_stream bytes: full KV stacks D2H once, then H2D refetch
      averaged over the causal chunk schedule ((chunks+1)/2 of the
      stacks per pass), ×2 for the backward re-stream.
    """
    kv = num_kv_heads or num_heads
    D = head_dim or hidden_size // num_heads
    H = hidden_size
    B, S, L = batch_size, seq_len, num_layers
    p = max(int(sp), 1)
    db = dtype_bytes

    attn_flops = 6.0 * B * float(S) * S * H / p * L
    # score-free streaming traffic: q + out + per-chunk KV rereads
    kv_bytes = 2.0 * B * S * kv * D * db          # full K+V stacks
    chunks = max(int(attn_chunks), 1)
    attn_bytes = (2.0 * B * (S / p) * num_heads * D * db
                  + chunks * kv_bytes / p) * L

    if p > 1:
        frac = (p - 1) / p
        if strategy == "ulysses" and chunks <= 1:
            per_layer = (2.0 * B * S * num_heads * D
                         + 2.0 * B * S * kv * D) * db * frac
            note = "ulysses: 4 all-to-alls/layer (q,out + k,v @ GQA width)"
        elif strategy == "ring" and chunks <= 1:
            per_layer = kv_bytes * frac
            note = f"ring: {p - 1} ppermute KV hops/layer"
        else:
            per_layer = kv_bytes * frac
            note = ("fpdt+sp: KV all-gather fwd / reduce-scatter bwd "
                    "per layer")
        sp_bytes = per_layer * 2 * L              # fwd + bwd
    else:
        sp_bytes, note = 0.0, "sp=1: no sequence-parallel collectives"
    regions = [
        RegionCost("attn", attn_flops, attn_bytes,
                   note=f"causal, per chip (S/sp={S // p} query rows), "
                        "x num_layers"),
        RegionCost("sp_comm", 0.0, sp_bytes, note=note, overlapped=True),
    ]

    if fpdt_host_kv:
        hk_bytes = kv_bytes * (1.0 + (chunks + 1) / 2.0) * 2 * L
        hk_note = (f"D2H once + causal-avg H2D over {chunks} chunks, "
                   "x2 bwd, x num_layers")
    else:
        hk_bytes, hk_note = 0.0, "KV resident on device (no spill)"
    regions.append(RegionCost("host_kv_stream", 0.0, hk_bytes,
                              note=hk_note, overlapped=True))
    return regions


# ---------------------------------------------------------------------------
# Quantized-comm attribution (ZeRO++ trio: qwZ / qgZ / hpZ)
# ---------------------------------------------------------------------------
# The before/after table ROADMAP item 1 asks for: what do the quantized
# wire formats do to the two collective regions on a pod projection?
# Wire bytes come from the same closed form observability/quant_stats.py
# measures (int payload + one fp32 scale per block); links come from the
# mesh factorization hpZ controls. Analytic on purpose — it runs on CPU
# CI and extrapolates to chip counts the rig doesn't have, exactly like
# attribute_longctx_step.

def _wire_ratio(bits: int, block: int, full_bytes: float) -> float:
    """(int payload + fp32 scale per block) / full-precision bytes."""
    return (bits / 8.0 + 4.0 / block) / full_bytes


def attribute_quant_step(cfg, *, qwz: bool = False, qgz: bool = False,
                         qar: bool = False, hpz: int = 1,
                         n_chips: int = 16, slice_size: int = 8,
                         ici_gbps: Optional[float] = None,
                         dcn_gbps: Optional[float] = None
                         ) -> List[RegionCost]:
    """Per-chip analytic costs of the two quantized-collective regions
    for one fwd+bwd step of ``cfg`` on ``n_chips`` arranged in slices of
    ``slice_size`` (intra-slice ICI, inter-slice DCN):

    - **param_fetch** — the stage-3 per-layer param all-gather: each
      chip receives (g-1)/g of every layer's params, fwd + bwd, where
      g is the gather group (hpZ partition k when set, else all
      chips). qwZ turns the bf16 wire into int8 payload + one fp32
      scale per QWZ_BLOCK ((1+4/128)/2 ≈ 0.52×); hpZ keeps the group
      intra-slice so the bytes ride ICI instead of DCN.
    - **grad_reduce** — the qgZ reduction: level 1 moves every
      gradient element once over the fsdp group ((g1-1)/g1 of the fp32
      wire); when hpZ splits the mesh a second level reduces partial
      sums over the dp axis across slices. qgZ quantizes level 1 to
      int8 and the inter-slice level to int4, each + fp32 scales per
      QGZ_BLOCK. ``qar`` replaces the reduce entirely with the
      EQuARX-style quantized all-reduce: an int8 reduce-scatter plus an
      int8 all-gather over the full dp axis, each hop moving (N-1)/N of
      the gradient wire + fp32 scales per QUANT_BLOCK (qar and qgZ are
      mutually exclusive, mirroring ZeroConfig.validate).

    Each region's ``gbps``/``link`` pin the byte-weighted effective
    bandwidth of its level mix, so the roofline ms reflects the link
    flip, not just the byte shrink."""
    from deepspeed_tpu.runtime.qgz import QGZ_BLOCK
    from deepspeed_tpu.runtime.sharding import QWZ_BLOCK

    ici = (ici_gbps if ici_gbps is not None
           else float(os.environ.get("DSTPU_ICI_GBPS", _DEFAULT_ICI_GBPS)))
    dcn = (dcn_gbps if dcn_gbps is not None
           else float(os.environ.get("DSTPU_DCN_GBPS", _DEFAULT_DCN_GBPS)))
    N = max(int(n_chips), 1)
    S = max(min(int(slice_size), N), 1)
    k = max(int(hpz), 1)
    L = cfg.num_layers

    params = _abstract_params(cfg)
    lp = _per_layer_shapes(params["layers"])
    layer_elems = sum(int(jnp.prod(jnp.asarray(s.shape)))
                      for s in jax.tree.leaves(lp))
    n_params = cfg.num_params()

    # -- param_fetch: per-layer all-gather, fwd + bwd -------------------
    g = k if k > 1 else N
    frac = (g - 1) / g if g > 1 else 0.0
    fetch_full = 2.0 * layer_elems * frac * L * 2     # bf16 wire
    w_ratio = _wire_ratio(8, QWZ_BLOCK, 2.0) if qwz else 1.0
    fetch_bytes = fetch_full * w_ratio
    fetch_link = "ici" if (k > 1 and k <= S) or N <= S else "dcn"
    fetch_gbps_eff = ici if fetch_link == "ici" else dcn
    fetch_note = (
        ("int8+scales all-gather" if qwz else "bf16 all-gather")
        + f" over g={g} ({fetch_link.upper()})"
        + (f", hpZ k={k} keeps it intra-slice" if k > 1 else ""))

    if qar and qgz:
        raise ValueError("qar and qgz are mutually exclusive (both own "
                         "the gradient wire)")

    # -- grad_reduce: qgZ level structure -------------------------------
    g1 = k if k > 1 else N
    dp = N // g1 if k > 1 else 1
    l1_link = "ici" if g1 <= S else "dcn"
    l1_frac = (g1 - 1) / g1 if g1 > 1 else 0.0
    l1_ratio = _wire_ratio(8, QGZ_BLOCK, 4.0) if qgz else 1.0
    l1_bytes = 4.0 * n_params * l1_frac * l1_ratio
    l2_frac = (dp - 1) / dp if dp > 1 else 0.0
    l2_ratio = _wire_ratio(4, QGZ_BLOCK, 4.0) if qgz else 1.0
    l2_bytes = 4.0 * n_params * l2_frac * l2_ratio
    l1_ms = l1_bytes / ((ici if l1_link == "ici" else dcn) * 1e9) * 1e3
    l2_ms = l2_bytes / (dcn * 1e9) * 1e3
    red_bytes = l1_bytes + l2_bytes
    red_ms = l1_ms + l2_ms
    red_gbps = (red_bytes / (red_ms * 1e6)) if red_ms > 0 else ici
    red_link = (l1_link if dp <= 1
                else f"{l1_link}+dcn")
    red_note = (
        (f"int8 level1 over fsdp={g1} ({l1_link.upper()})" if qgz
         else f"fp32 reduce over fsdp={g1} ({l1_link.upper()})")
        + ((f" + {'int4' if qgz else 'fp32'} level2 over dp={dp} (DCN)")
           if dp > 1 else ""))

    if qar:
        # qar overrides the level structure: one flat int8 all-reduce
        # (reduce-scatter + all-gather) over the full dp axis; fp32
        # scales per QUANT_BLOCK on both hops
        from deepspeed_tpu.runtime.zeropp import QUANT_BLOCK
        ar_frac = (N - 1) / N if N > 1 else 0.0
        ar_ratio = _wire_ratio(8, QUANT_BLOCK, 4.0)
        red_link = "ici" if N <= S else "dcn"
        ar_gbps = ici if red_link == "ici" else dcn
        red_bytes = 2.0 * 4.0 * n_params * ar_frac * ar_ratio
        red_ms = red_bytes / (ar_gbps * 1e9) * 1e3
        red_gbps = ar_gbps
        red_note = (f"qar: int8 reduce-scatter + int8 all-gather over "
                    f"dp={N} ({red_link.upper()})")

    return [
        RegionCost("param_fetch", 0.0, fetch_bytes, note=fetch_note,
                   overlapped=True, gbps=fetch_gbps_eff,
                   link=fetch_link),
        RegionCost("grad_reduce", 0.0, red_bytes, note=red_note,
                   overlapped=False, gbps=red_gbps, link=red_link),
    ]


# ---------------------------------------------------------------------------
# Exposed-vs-hidden split (ISSUE 6 overlap engine)
# ---------------------------------------------------------------------------
# The overlap engine (runtime/param_stream.py pin_stage) stages each
# layer's transfers against that layer's compute: with overlap_depth=k,
# the transfer of one stage can hide behind up to k stages of compute
# before the consumer needs it. The split below is the analytic form of
# that schedule — per-stage transfer time clipped by the k-stage compute
# window — calibrated by the measured probe (tools/
# latency_hiding_probe.py): at k=0 XLA's default schedule hid none of
# the host-link traffic on v5e-1, so k=0 reports fully exposed.


def overlap_split_ms(transfer_ms: float, stage_ms: float,
                     overlap_depth: int, stages: int) -> Dict[str, float]:
    """Split a transfer's roofline time into hidden vs exposed ms under
    the staged overlap schedule.

    ``transfer_ms`` total transfer time for the step; ``stage_ms`` the
    compute time of ONE scheduling stage (a layer's fwd or bwd);
    ``stages`` how many stages the transfer is spread across (2 x layers
    for a per-layer stream); ``overlap_depth`` k = how many stages of
    compute each stage's transfer may hide behind. k=0 -> fully exposed
    (the measured no-overlap default schedule)."""
    total = max(float(transfer_ms), 0.0)
    n = max(int(stages), 1)
    k = max(int(overlap_depth), 0)
    per_stage = total / n
    hidden_per = min(per_stage, k * max(float(stage_ms), 0.0))
    hidden = hidden_per * n
    exposed = total - hidden
    return {"total_ms": total, "hidden_ms": hidden, "exposed_ms": exposed,
            "hidden_frac": 0.0 if total <= 0 else hidden / total}


def split_exposed_hidden(regions: List[RegionCost], *,
                         peak_tflops: float, hbm_gbps: float,
                         fetch_gbps: Optional[float] = None,
                         overlap_depth: int = 0,
                         num_layers: int = 1) -> List[Dict[str, Any]]:
    """Per-region exposed/hidden attribution: compute regions are fully
    exposed (they ARE the step); transfer regions (``DMA_REGIONS`` —
    param_fetch, sp_comm, host_kv_stream) split by
    :func:`overlap_split_ms` against the per-layer compute window."""
    ms: Dict[str, float] = {}
    for r in regions:
        if r.region in DMA_REGIONS:
            bw = r.gbps or _dma_gbps(r.region, fetch_gbps)
            ms[r.region] = r.bytes_accessed / (bw * 1e9) * 1e3
        else:
            compute_ms = r.flops / (peak_tflops * 1e12) * 1e3
            mem_ms = r.bytes_accessed / (hbm_gbps * 1e9) * 1e3
            ms[r.region] = max(compute_ms, mem_ms)
    stages = 2 * max(int(num_layers), 1)  # fwd + bwd stage per layer
    stage_ms = (ms.get("attn", 0.0) + ms.get("mlp", 0.0)) / stages
    out = []
    for r in regions:
        if r.region in DMA_REGIONS:
            split = overlap_split_ms(ms[r.region], stage_ms,
                                     overlap_depth, stages)
            out.append({"region": r.region, "kind": "dma",
                        "bytes": r.bytes_accessed, **split})
        else:
            total = ms[r.region]
            out.append({"region": r.region, "kind": "compute",
                        "bytes": r.bytes_accessed, "total_ms": total,
                        "hidden_ms": 0.0, "exposed_ms": total,
                        "hidden_frac": 0.0})
    return out


def attribution_markdown(regions: List[RegionCost], peak_tflops: float,
                         hbm_gbps: float,
                         fetch_gbps: Optional[float] = None,
                         title: str = "Per-region roofline attribution",
                         overlap_depth: Optional[int] = None,
                         num_layers: int = 1) -> str:
    """Render the region table docs/roofline.md embeds. Passing
    ``overlap_depth`` adds exposed/hidden ms columns from
    :func:`split_exposed_hidden` (same rows, wider table)."""
    fetch = fetch_gbps
    with_split = overlap_depth is not None
    split_by: Dict[str, Dict[str, Any]] = {}
    if with_split:
        split_by = {s["region"]: s for s in split_exposed_hidden(
            regions, peak_tflops=peak_tflops, hbm_gbps=hbm_gbps,
            fetch_gbps=fetch, overlap_depth=int(overlap_depth),
            num_layers=num_layers)}
    extra_hdr = " exposed ms | hidden ms |" if with_split else ""
    extra_sep = "---|---|" if with_split else ""
    lines = [f"### {title}", "",
             "| region | GFLOPs | GB moved | F/B | bound | "
             f"roofline ms |{extra_hdr} notes |",
             f"|---|---|---|---|---|---|{extra_sep}---|"]
    for r in regions:
        if r.region in DMA_REGIONS:
            bw = r.gbps or _dma_gbps(r.region, fetch)
            ms = r.bytes_accessed / (bw * 1e9) * 1e3
            bound = r.link or ("ici" if r.region == "sp_comm"
                               else "host-link")
        else:
            summ = roofline_summary(
                {"flops": r.flops, "bytes_accessed": r.bytes_accessed},
                peak_tflops, hbm_gbps)
            bound = summ["bound"]
            compute_ms = r.flops / (peak_tflops * 1e12) * 1e3
            mem_ms = r.bytes_accessed / (hbm_gbps * 1e9) * 1e3
            ms = max(compute_ms, mem_ms)
        inten = ("—" if r.bytes_accessed <= 0 or r.flops <= 0
                 else f"{r.flops / r.bytes_accessed:.1f}")
        note = r.note + (" (overlapped)" if r.overlapped else "")
        extra = ""
        if with_split:
            s = split_by[r.region]
            extra = (f" {s['exposed_ms']:,.2f} | "
                     f"{s['hidden_ms']:,.2f} |")
        lines.append(
            f"| {r.region} | {r.flops / 1e9:,.1f} | "
            f"{r.bytes_accessed / 1e9:,.2f} | {inten} | {bound} | "
            f"{ms:,.2f} |{extra} {note} |")
    lines.append("")
    lines.append(
        "Roofline ms = max(flops/peak, bytes/HBM-bw) per region in "
        "isolation; overlapped rows stream behind compute and bound "
        "throughput only if their bandwidth floor is missed."
        + ((" Exposed/hidden split: overlap_depth="
            f"{int(overlap_depth)} staged schedule "
            "(observability/attribution.py overlap_split_ms).")
           if with_split else ""))
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# CLI: python -m deepspeed_tpu.observability.attribution --layers 8 \
#          --vocab 131072 --out docs/roofline.md  (appends the table)
# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="dstpu-attribution",
        description="compile per-region closures at a given shape and "
                    "print the roofline attribution table")
    ap.add_argument("--model", default="llama3-8b")
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=131072)
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--micro", type=int, default=4)
    ap.add_argument("--tiled-logits", type=int, default=None)
    ap.add_argument("--overlap-depth", type=int, default=None,
                    help="add exposed/hidden ms columns for the overlap "
                         "engine at this stage depth (0 = unstaged)")
    ap.add_argument("--peak-tflops", type=float, default=None)
    ap.add_argument("--hbm-gbps", type=float, default=None)
    ap.add_argument("--json", action="store_true",
                    help="emit the raw region dicts instead of markdown")
    args = ap.parse_args(argv)

    import dataclasses as _dc

    from deepspeed_tpu.models.zoo import get_model
    from deepspeed_tpu.observability.roofline import (detect_hbm_gbps,
                                                      detect_peak_tflops)

    model = get_model(args.model, max_seq_len=args.seq)
    updates = {"num_layers": args.layers, "vocab_size": args.vocab}
    if args.tiled_logits is not None:
        updates["tiled_logits"] = args.tiled_logits
    cfg = _dc.replace(model.config, **updates)

    dev = jax.devices()[0]
    peak = args.peak_tflops or detect_peak_tflops(dev)
    hbm = args.hbm_gbps or detect_hbm_gbps(dev)
    regions = attribute_step(cfg, args.micro, args.seq)
    if args.json:
        payload = [r.to_dict() for r in regions]
        if args.overlap_depth is not None:
            payload = {"regions": payload,
                       "overlap_depth": args.overlap_depth,
                       "split": split_exposed_hidden(
                           regions, peak_tflops=peak, hbm_gbps=hbm,
                           overlap_depth=args.overlap_depth,
                           num_layers=cfg.num_layers)}
        print(json.dumps(payload, indent=2))
    else:
        shape = (f"{args.model} {args.layers}L vocab {args.vocab:,} "
                 f"seq {args.seq} micro {args.micro}")
        print(attribution_markdown(
            regions, peak, hbm,
            title=f"Per-region roofline attribution — {shape}",
            overlap_depth=args.overlap_depth,
            num_layers=cfg.num_layers))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
