"""Shared-nothing fleet metrics: hub snapshots over the transport.

``observability/fleet.py`` aggregates cross-rank metrics through the
run directory — fine for SPMD training ranks that already share a
filesystem, wrong for a serving fleet whose workers may live on other
hosts. This module is the complement: each worker condenses its
process-local MetricsHub into a **compact snapshot** (filtered gauges +
counters, histograms reduced to their summary stats) and piggybacks it
on the heartbeat/emit replies it is already sending
(serving/proc_worker.py). The supervisor folds the per-replica
snapshots into one ``fleet_metrics`` view — no shared run dir, no extra
connections, no new protocol message.

Compactness matters because the snapshot rides the heartbeat hot path:
``compact_snapshot`` keeps only metric names under the given prefixes
(default: the ``serve.*`` and ``slo.*`` families) and ships histogram
*summaries* (count/sum/mean/p50/p95/p99), not bucket arrays. Merging
histogram summaries across workers is lossy by nature — counts and sums
add exactly; percentiles cannot be averaged, so the merged view reports
the per-worker range (max p99 is the fleet p99 lower bound a dashboard
actually wants).

Host-side, jax-free. The plane is lock-protected: rx threads ingest per
replica while the supervisor thread renders the merged view.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterable, Optional

DEFAULT_PREFIXES = ("serve.", "slo.", "router.", "fleet.")


def compact_snapshot(hub, prefixes: Iterable[str] = DEFAULT_PREFIXES
                     ) -> Dict[str, Any]:
    """Condense a MetricsHub into a wire-friendly dict: gauges and
    counters filtered by name prefix, histograms as summary stats.
    Returns ``{}`` when the hub is None/empty — callers can skip the
    key entirely and keep pre-metrics-plane payloads bit-exact."""
    if hub is None:
        return {}
    snap = hub.snapshot()
    pfx = tuple(prefixes)

    def keep(name: str) -> bool:
        return name.startswith(pfx)

    out: Dict[str, Any] = {}
    gauges = {k: v for k, v in (snap.get("gauges") or {}).items()
              if keep(k)}
    counters = {k: v for k, v in (snap.get("counters") or {}).items()
                if keep(k)}
    hists = {k: v for k, v in (snap.get("histograms") or {}).items()
             if keep(k) and v.get("count")}
    if gauges:
        out["gauges"] = gauges
    if counters:
        out["counters"] = counters
    if hists:
        out["histograms"] = hists
    return out


def merge_snapshots(per_replica: Dict[str, Dict[str, Any]]
                    ) -> Dict[str, Any]:
    """Fold per-replica compact snapshots into one fleet view.

    Counters sum. Gauges ship per-replica (a fleet "queue depth" gauge
    summed across workers is meaningful; a summed "utilization" is
    not — the caller knows which is which, we don't guess) plus a
    ``sum`` convenience. Histogram summaries merge exactly where math
    allows (count, sum, min, max -> true fleet values; mean recomputed
    from the merged sum/count) and report the per-worker spread where
    it doesn't (p50/p95/p99 -> max across workers: the conservative
    fleet tail)."""
    counters: Dict[str, float] = {}
    gauges: Dict[str, Dict[str, Any]] = {}
    hists: Dict[str, Dict[str, Any]] = {}
    for rid, snap in sorted(per_replica.items()):
        for name, v in (snap.get("counters") or {}).items():
            counters[name] = counters.get(name, 0) + v
        for name, v in (snap.get("gauges") or {}).items():
            g = gauges.setdefault(name, {"by_replica": {}, "sum": 0.0})
            g["by_replica"][rid] = v
            try:
                g["sum"] += float(v)
            except (TypeError, ValueError):
                pass
        for name, h in (snap.get("histograms") or {}).items():
            m = hists.get(name)
            if m is None:
                hists[name] = m = {"count": 0, "sum": 0.0,
                                   "min": None, "max": None,
                                   "p50": 0.0, "p95": 0.0, "p99": 0.0,
                                   "replicas": 0}
            m["count"] += int(h.get("count", 0))
            m["sum"] += float(h.get("sum", 0.0))
            for k, fold in (("min", min), ("max", max)):
                hv = h.get(k)
                if hv is not None:
                    m[k] = hv if m[k] is None else fold(m[k], hv)
            for p in ("p50", "p95", "p99"):
                m[p] = max(m[p], float(h.get(p, 0.0)))
            m["replicas"] += 1
    for m in hists.values():
        m["mean"] = m["sum"] / m["count"] if m["count"] else 0.0
    return {"counters": counters, "gauges": gauges, "histograms": hists}


class FleetMetricsPlane:
    """The supervisor/router-side aggregator: ingests one compact
    snapshot per replica (from the rx thread handling that replica's
    heartbeat) and renders the merged fleet view on demand.

    ``stale_after_s`` guards the merge against dead workers: a replica
    whose last snapshot is older than the bound is reported in
    ``stale`` and excluded from the merged numbers — a crashed worker's
    frozen queue-depth gauge must not prop up the fleet view."""

    def __init__(self, stale_after_s: float = 5.0):
        self.stale_after_s = float(stale_after_s)
        self._lock = threading.Lock()
        self._by_replica: Dict[str, Dict[str, Any]] = {}
        self._mono: Dict[str, float] = {}
        self.ingested = 0

    def ingest(self, replica_id: str, snapshot: Optional[Dict[str, Any]]
               ) -> None:
        """Store a replica's latest snapshot (empty/None snapshots are
        ignored — heartbeats from a worker with no hub activity yet)."""
        if not snapshot:
            return
        with self._lock:
            self._by_replica[str(replica_id)] = snapshot
            self._mono[str(replica_id)] = time.monotonic()
            self.ingested += 1

    def forget(self, replica_id: str) -> None:
        with self._lock:
            self._by_replica.pop(str(replica_id), None)
            self._mono.pop(str(replica_id), None)

    def replica_snapshot(self, replica_id: str
                         ) -> Optional[Dict[str, Any]]:
        with self._lock:
            snap = self._by_replica.get(str(replica_id))
            return dict(snap) if snap is not None else None

    def merged(self, now_mono: Optional[float] = None) -> Dict[str, Any]:
        """The fleet view: merged metrics over fresh replicas plus the
        staleness report."""
        now = time.monotonic() if now_mono is None else float(now_mono)
        with self._lock:
            fresh = {}
            stale = {}
            for rid, snap in self._by_replica.items():
                age = now - self._mono.get(rid, 0.0)
                if age <= self.stale_after_s:
                    fresh[rid] = snap
                else:
                    stale[rid] = round(age, 3)
            merged = merge_snapshots(fresh)
            merged["replicas"] = sorted(fresh)
            if stale:
                merged["stale"] = stale
            merged["ingested"] = self.ingested
            return merged
