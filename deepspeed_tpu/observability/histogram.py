"""Fixed-bucket latency histograms with percentile readout.

Serving latency (TTFT, per-decode-token) needs percentiles, not means —
a t-digest would be exact but is more state than the job needs: a
geometric bucket ladder bounds the relative error of any percentile by
the bucket growth factor, costs O(1) per observe, and renders directly
as a Prometheus histogram (cumulative ``le`` buckets). Reference analog:
the FastGen benchmark suite reports P50/P90/P95 token latencies
(DeepSpeed-MII benchmarks); here the histogram is a first-class runtime
object exported via the observability hub.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional


class Histogram:
    """Geometric fixed-bucket histogram.

    Bucket upper bounds are ``lo * growth**i`` for i in [0, n); values
    below ``lo`` land in bucket 0, values >= the last bound in an
    overflow bucket. With the default growth of 1.15, any percentile is
    reproduced within ~7% relative error (half a bucket), which is
    plenty for latency SLO work.
    """

    def __init__(self, name: str, unit: str = "seconds",
                 lo: float = 1e-5, hi: float = 1e3,
                 growth: float = 1.15):
        assert growth > 1.0 and hi > lo > 0.0
        self.name = name
        self.unit = unit
        self._lo = lo
        self._growth = growth
        n = int(math.ceil(math.log(hi / lo) / math.log(growth)))
        self._bounds = [lo * growth ** i for i in range(n)]  # upper bounds
        self._counts = [0] * (n + 1)  # +1 overflow bucket
        self._lock = threading.Lock()
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None

    def _bucket_index(self, value: float) -> int:
        if value < self._lo:
            return 0
        # log-index is O(1) vs bisect's O(log n); clamp for float fuzz
        i = int(math.log(value / self._lo) / math.log(self._growth)) + 1
        if i < len(self._bounds) and value > self._bounds[i]:
            i += 1
        elif i > 0 and value <= self._bounds[i - 1]:
            i -= 1
        return min(i, len(self._counts) - 1)

    def observe(self, value: float) -> None:
        value = float(value)
        if not math.isfinite(value) or value < 0:
            return
        with self._lock:
            self._counts[self._bucket_index(value)] += 1
            self.count += 1
            self.sum += value
            self.min = value if self.min is None else min(self.min, value)
            self.max = value if self.max is None else max(self.max, value)

    def percentile(self, p: float) -> float:
        """Value at percentile ``p`` in [0, 100], linearly interpolated
        inside the containing bucket. 0.0 when empty."""
        with self._lock:
            if self.count == 0:
                return 0.0
            rank = p / 100.0 * self.count
            seen = 0
            for i, c in enumerate(self._counts):
                if c == 0:
                    continue
                if seen + c >= rank:
                    lo = self._lo * self._growth ** (i - 1) if i > 0 else 0.0
                    hi = (self._bounds[i] if i < len(self._bounds)
                          else (self.max if self.max is not None else lo))
                    lo = max(lo, self.min or 0.0) if seen == 0 else lo
                    hi = min(hi, self.max) if self.max is not None else hi
                    frac = (rank - seen) / c
                    return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
                seen += c
            return self.max or 0.0

    def mean(self) -> float:
        with self._lock:
            return self.sum / self.count if self.count else 0.0

    def snapshot(self) -> Dict[str, float]:
        """Count/sum/min/max/mean plus p50/p95/p99."""
        out = {
            "count": self.count,
            "sum": round(self.sum, 6),
            "mean": round(self.mean(), 6),
            "min": round(self.min, 6) if self.min is not None else 0.0,
            "max": round(self.max, 6) if self.max is not None else 0.0,
        }
        for p in (50, 95, 99):
            out[f"p{p}"] = round(self.percentile(p), 6)
        return out

    def reset(self) -> None:
        with self._lock:
            self._counts = [0] * len(self._counts)
            self.count = 0
            self.sum = 0.0
            self.min = None
            self.max = None

    # -- Prometheus text rendering ------------------------------------
    def prometheus_lines(self, metric_name: str) -> List[str]:
        """Cumulative-bucket exposition lines (TYPE histogram)."""
        lines = [f"# TYPE {metric_name} histogram"]
        with self._lock:
            cum = 0
            # collapse empty leading/trailing ladder: emit only buckets
            # up to the last non-empty one (plus +Inf) to keep the page
            # readable; cumulative semantics stay exact
            last = max((i for i, c in enumerate(self._counts) if c), default=-1)
            for i in range(last + 1):
                cum += self._counts[i]
                le = (self._bounds[i] if i < len(self._bounds) else "+Inf")
                le_s = f"{le:.6g}" if isinstance(le, float) else le
                lines.append(
                    f'{metric_name}_bucket{{le="{le_s}"}} {cum}')
            lines.append(f'{metric_name}_bucket{{le="+Inf"}} {self.count}')
            lines.append(f"{metric_name}_sum {self.sum:.6g}")
            lines.append(f"{metric_name}_count {self.count}")
        return lines
