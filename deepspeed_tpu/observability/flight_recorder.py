"""Crash flight recorder: the last seconds of a worker, always on.

A bounded, lock-cheap ring buffer of structured runtime events — step
entry/dispatch/drain from the training loop, every traced collective
from ``comm/comm.py``, compile activity, checkpoint/offload transitions,
serving steps — that costs one deque append per event while the run is
healthy and becomes the post-mortem when it is not. The ring dumps to
disk on:

* an uncaught exception (``sys.excepthook`` chain),
* SIGTERM (the preemption/OOM-killer path on pod workers), and
* a stall-watchdog fire (``observability/watchdog.py`` calls
  :func:`dump_flight_recorder` from its report path),

answering "what happened in the last 2s before the hang" for a worker
whose JSONL metrics stream stops mid-step. Appends rely on the GIL-atomic
``deque.append`` (maxlen evicts the oldest) so the hot path takes no
lock; only ``dump``/``events`` snapshot under one.

The recorder is process-global (:func:`get_flight_recorder`) and jax-free
so host-side tooling (``tools/fleet_top.py``, the launcher) can use it
without paying the jax import.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from deepspeed_tpu.utils.logging import logger

DEFAULT_CAPACITY = 4096

# (monotonic-ordered wall-clock ts, kind, fields)
_Event = Tuple[float, str, Dict[str, Any]]


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 rank: Optional[int] = None,
                 run_dir: Optional[str] = None):
        self._ring: deque = deque(maxlen=max(0, int(capacity)) or 1)
        self.enabled = int(capacity) > 0
        self.rank = rank if rank is not None else _env_rank()
        self.run_dir = run_dir
        self._dump_lock = threading.Lock()
        self.dumps: Dict[str, str] = {}  # reason -> last written path
        # name -> zero-arg provider whose return value is embedded in
        # every dump (e.g. the request tracer's in-flight timelines);
        # providers run inside dump()'s try so a failing one cannot
        # break the post-mortem
        self._dump_context: Dict[str, Any] = {}

    # -- hot path ------------------------------------------------------
    def record(self, kind: str, **fields) -> None:
        """One ring append; no lock, no I/O. Safe from any thread."""
        if not self.enabled:
            return
        self._ring.append((time.time(), kind, fields))

    def span(self, kind: str, **fields):
        """Context manager recording one event with a ``dur_ms`` field —
        the dispatch→completion span of the wrapped block (comm.py wraps
        each traced collective's dispatch; chrome_trace.py renders
        dur_ms events as Perfetto "X" slices on the overlap lanes). The
        event timestamp is the span START so lanes line up with the step
        timeline; one append at exit, same GIL-atomic hot path."""
        return _Span(self, kind, fields)

    def add_dump_context(self, name: str, provider) -> None:
        """Register a zero-arg callable whose result is embedded under
        ``name`` in every dump — live state (in-flight serving requests,
        scheduler occupancy, ...) that a ring of past events cannot
        carry. Last registration per name wins."""
        self._dump_context[name] = provider

    # -- configuration -------------------------------------------------
    def configure(self, capacity: Optional[int] = None,
                  rank: Optional[int] = None,
                  run_dir: Optional[str] = None) -> None:
        """Resize/re-point the recorder (engine init). Resizing keeps the
        newest events; capacity 0 disables recording entirely."""
        if capacity is not None and int(capacity) != self._ring.maxlen:
            self.enabled = int(capacity) > 0
            self._ring = deque(self._ring, maxlen=max(0, int(capacity)) or 1)
        if rank is not None:
            self.rank = int(rank)
        if run_dir:
            self.run_dir = run_dir

    # -- snapshots -----------------------------------------------------
    def events(self, last: int = 0) -> List[_Event]:
        with self._dump_lock:
            evs = list(self._ring)
        return evs[-last:] if last > 0 else evs

    def tail_lines(self, last: int = 32) -> str:
        """Human-formatted newest-last tail for stall/crash reports."""
        out = []
        for ts, kind, fields in self.events(last=last):
            kv = " ".join(f"{k}={v}" for k, v in fields.items())
            out.append(f"  {ts:.3f} {kind:<18} {kv}")
        return "\n".join(out)

    # -- dump ----------------------------------------------------------
    def _dump_dir(self) -> str:
        env = os.environ.get("DSTPU_FLIGHT_DIR")
        if env:
            return env
        if self.run_dir:
            return os.path.join(self.run_dir, "flight")
        if os.path.isdir(".git"):
            # bare default inside a repo checkout would litter the working
            # tree (and tempt a `git add .`) — park dumps under tmp instead
            import tempfile

            uid = os.getuid() if hasattr(os, "getuid") else 0
            return os.path.join(tempfile.gettempdir(),
                                f"dstpu_flight-{uid}")
        return "dstpu_flight"

    def dump(self, reason: str = "manual",
             path: Optional[str] = None, **extra) -> Optional[str]:
        """Write the ring (plus context) as one JSON file; returns the
        path, or None on failure — a dump must never raise into the
        crashing frame it is documenting."""
        try:
            with self._dump_lock:
                evs = list(self._ring)
            if path is None:
                d = self._dump_dir()
                os.makedirs(d, exist_ok=True)
                path = os.path.join(
                    d, f"flight_rank{self.rank}_{reason}.json")
            doc = {
                "kind": "flight_recorder_dump",
                "reason": reason,
                "rank": self.rank,
                "pid": os.getpid(),
                "host": socket.gethostname(),
                "ts": time.time(),
                "n_events": len(evs),
                "events": [
                    {"ts": ts, "kind": kind, **fields}
                    for ts, kind, fields in evs
                ],
            }
            doc.update(extra)
            for name, provider in list(self._dump_context.items()):
                try:
                    doc[name] = provider()
                except Exception as e:  # context must never kill a dump
                    doc[name] = f"<dump context failed: {e}>"
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, default=str)
            os.replace(tmp, path)
            self.dumps[reason] = path
            # crash-path reasons shout; manual/planned dumps stay quiet
            level = logger.error if reason in (
                "exception", "sigterm", "watchdog") else logger.info
            level(f"flight recorder: dumped {len(evs)} events to {path} "
                  f"(reason: {reason})")
            return path
        except Exception as e:
            logger.warning(f"flight recorder dump failed: {e}")
            return None


class _Span:
    __slots__ = ("_rec", "_kind", "_fields", "_t0")

    def __init__(self, rec: "FlightRecorder", kind: str,
                 fields: Dict[str, Any]):
        self._rec = rec
        self._kind = kind
        self._fields = fields

    def __enter__(self):
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        rec = self._rec
        if rec.enabled:
            t0 = self._t0
            rec._ring.append((t0, self._kind, {
                **self._fields,
                "dur_ms": (time.time() - t0) * 1e3}))
        return False


def _env_rank() -> int:
    for var in ("RANK", "PROCESS_ID"):
        v = os.environ.get(var)
        if v is not None:
            try:
                return int(v)
            except ValueError:
                pass
    return 0


_RECORDER: Optional[FlightRecorder] = None
_RECORDER_LOCK = threading.Lock()


def get_flight_recorder() -> FlightRecorder:
    global _RECORDER
    with _RECORDER_LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder()
        return _RECORDER


def reset_flight_recorder() -> None:
    """Drop the singleton (tests). Installed crash handlers keep working:
    they resolve the recorder at fire time."""
    global _RECORDER
    with _RECORDER_LOCK:
        _RECORDER = None


def dump_flight_recorder(reason: str, **extra) -> Optional[str]:
    """Module-level dump hook (watchdog, user code): dumps the current
    singleton if one exists and has events; never raises."""
    try:
        rec = get_flight_recorder()
        if not rec.events(last=1):
            return None
        return rec.dump(reason=reason, **extra)
    except Exception:
        return None


# -- crash handler installation ---------------------------------------------

_HANDLERS_INSTALLED = False
_HANDLERS_LOCK = threading.Lock()


def install_crash_handlers() -> None:
    """Dump the flight recorder on uncaught exception and SIGTERM.

    Idempotent; chains any previously-installed ``sys.excepthook`` and
    SIGTERM handler so launchers keep their exit semantics (e.g.
    launcher/launch.py's SIGTERM → ``sys.exit(143)``). SIGTERM install is
    skipped off the main thread — ``signal.signal`` raises there."""
    global _HANDLERS_INSTALLED
    with _HANDLERS_LOCK:
        if _HANDLERS_INSTALLED:
            return
        _HANDLERS_INSTALLED = True

    prev_hook = sys.excepthook

    def _hook(exc_type, exc, tb):
        dump_flight_recorder(
            "exception", exception=f"{exc_type.__name__}: {exc}")
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _hook

    if threading.current_thread() is not threading.main_thread():
        return
    try:
        prev_sig = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            dump_flight_recorder("sigterm")
            if callable(prev_sig):
                prev_sig(signum, frame)
            else:
                # restore the default disposition and re-raise so the
                # exit status stays "killed by SIGTERM"
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError) as e:  # non-main thread / exotic host
        logger.debug(f"flight recorder SIGTERM handler not installed: {e}")
