"""Fleet black box: deterministic traffic capture and decision forensics.

The observability stack can *describe* an incident — request traces,
flight-recorder dumps, one merged timeline — but until this module it
could not *reproduce* one. :class:`FleetJournal` is an append-only,
CRC-framed, ``wall_time()``-stamped journal that captures everything a
fresh fleet needs to re-run a serving session bit-identically:

* a **HEADER** record with the config fingerprint (serving / router /
  engine config hashes, autotuned-table identity, model seed — weights
  are identified by fingerprint, never serialized) plus the literal
  re-drive recipe (model spec, seed, engine/router kwargs);
* an **ADMIT** record per request: uid, prompt tokens,
  ``max_new_tokens``, and the scheduled arrival offset from run start;
* every **decision with its inputs**: ROUTE carries the per-candidate
  predicted-TTFT / health / load scores (not just the winner);
  PREEMPT / PAGE_OUT / HEDGE / FAILOVER / AUTOSCALE / SUPERVISOR acts
  carry the state that triggered them;
* **CHAOS** records for every injected fault (kind + seed + sequence
  position) so a replay can re-arm the same injector;
* an **EMIT** checksum chain per request: a rolling CRC32 over the
  emitted token ids, one link per decode step — the ground truth the
  replayer compares against, at ~13 bytes/token instead of re-recording
  the stream.

Frames reuse the length-prefixed CRC32 wire format from
``serving/transport/framing.py`` (``MAGIC | len | crc32 | payload``) —
no second ad-hoc format. Unlike the socket path, a journal that ends
mid-frame is *expected* (the process crashed while appending), so
:func:`load_journal` is a salvage reader: it returns every complete,
CRC-valid frame and stops cleanly at the first torn or corrupt one,
never raising.

The journal is process-wide and optional: ``get_journal()`` returns
``None`` unless a run installed one with ``set_journal`` — every
call site guards on that, so the disabled path costs one global read.
All stamps come from :func:`deepspeed_tpu.observability.clocksync.wall_time`
so the journal, request spans, and fleet snapshot share one clock
domain.

Everything here is host-side, jax-free, and import-cheap.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import threading
import time
import zlib
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from deepspeed_tpu.observability.clocksync import wall_time

SCHEMA = "fleet_journal/v1"

_framing_mod = None


def _framing():
    """The transport framing module, imported on first use — the
    serving package's __init__ imports the router, which imports this
    module, so a top-level import here would be a cycle (and would make
    every observability import pay the serving/jax import chain)."""
    global _framing_mod
    if _framing_mod is None:
        from deepspeed_tpu.serving.transport import framing
        _framing_mod = framing
    return _framing_mod

# Decision kinds with dedicated helpers / renderers. ``decision()``
# accepts any kind string — this list is documentation plus the
# incident-log ordering, not an allowlist.
DECISION_KINDS = ("ROUTE", "PREEMPT", "PAGE_OUT", "HEDGE", "FAILOVER",
                  "AUTOSCALE", "SUPERVISOR",
                  # zero-downtime ops (ISSUE 20): live session
                  # migration, rolling weight hot-swap stages (manifest
                  # / quiesce / reload / parity / done), and
                  # supervisor-acted scale decisions (desired vs
                  # actual) — each carries the inputs that drove it
                  "MIGRATE", "SWAP", "SCALE")


def token_chain(prev: int, token: int) -> int:
    """One link of the per-request emitted-token checksum chain:
    ``crc32(token_le64, prev)``. Chains compose per decode step, so a
    divergence names the exact step, not just the request."""
    return zlib.crc32(
        int(token).to_bytes(8, "little", signed=True),
        int(prev)) & 0xFFFFFFFF


def chain_tokens(tokens: Iterable[int], prev: int = 0) -> List[int]:
    """The full chain for a token stream (``prev`` seeds continuation)."""
    out: List[int] = []
    c = int(prev)
    for t in tokens:
        c = token_chain(c, t)
        out.append(c)
    return out


def config_fingerprint(**blocks: Any) -> Dict[str, str]:
    """Short content hashes for named config blocks plus a combined
    digest. Values are canonical-JSON'd (sorted keys, default=str so
    dtypes and paths hash stably); the combined hash covers the block
    names too, so adding a block changes the fingerprint."""
    out: Dict[str, str] = {}
    acc = hashlib.sha256()
    for name in sorted(blocks):
        blob = json.dumps(blocks[name], sort_keys=True,
                          separators=(",", ":"), default=str)
        out[name] = hashlib.sha256(blob.encode()).hexdigest()[:12]
        acc.update(name.encode())
        acc.update(blob.encode())
    out["combined"] = acc.hexdigest()[:16]
    return out


class FleetJournal:
    """Append-only CRC-framed journal writer.

    Thread-safe: the router's pump threads, the supervisor's maintain
    loop, and the chaos injector all append concurrently. Each record
    is one frame holding compact JSON with at least ``kind`` and ``ts``
    (``wall_time()``). The writer self-times every append
    (``append_s``) so the bench can gate journal overhead without a
    separate harness, and enforces ``max_mb`` by dropping records past
    the cap (after one TRUNCATED marker) rather than erroring mid-run.
    """

    def __init__(self, path: str, max_mb: float = 64.0):
        self.path = str(path)
        self.max_bytes = int(float(max_mb) * (1 << 20))
        d = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(d, exist_ok=True)
        self.t0 = wall_time()
        _framing()  # import at construction, not inside the first
        # self-timed append (the overhead gate measures appends only)
        self._lock = threading.Lock()
        self._f: Optional[io.BufferedWriter] = None
        self._chains: Dict[Any, int] = {}
        self._chain_len: Dict[Any, int] = {}
        self._ingress: Optional[str] = None
        self.n_records = 0
        self.n_dropped = 0
        self.bytes_written = 0
        self.append_s = 0.0
        self._truncated = False
        self._closed = False

    # -- ingress ownership --------------------------------------------
    def claim_ingress(self, owner: str) -> str:
        """First claimant owns ADMIT/EMIT journaling. In an in-process
        fleet both the router and its engines see the same journal; the
        router claims first so token streams are journaled exactly once
        (at the point that owns request identity). A standalone engine
        run has no router, so the engine's claim wins there."""
        with self._lock:
            if self._ingress is None:
                self._ingress = str(owner)
            return self._ingress

    def owns_ingress(self, owner: str) -> bool:
        with self._lock:
            return self._ingress is None or self._ingress == str(owner)

    # -- record writers ------------------------------------------------
    def _append(self, rec: Dict[str, Any]) -> None:
        t_in = time.perf_counter()
        rec.setdefault("ts", wall_time())
        try:
            payload = json.dumps(rec, separators=(",", ":"),
                                 default=str).encode()
        except (TypeError, ValueError):
            with self._lock:
                self.n_dropped += 1
            return
        frame = _framing().encode_frame(payload)
        with self._lock:
            if self._closed:
                self.n_dropped += 1
                return
            if self.bytes_written + len(frame) > self.max_bytes:
                if not self._truncated:
                    self._truncated = True
                    marker = _framing().encode_frame(json.dumps(
                        {"kind": "TRUNCATED", "ts": wall_time(),
                         "records": self.n_records},
                        separators=(",", ":")).encode())
                    self._write(marker)
                self.n_dropped += 1
            else:
                self._write(frame)
                self.n_records += 1
        self.append_s += time.perf_counter() - t_in

    def _write(self, frame: bytes) -> None:
        if self._f is None:
            self._f = open(self.path, "wb")
        self._f.write(frame)
        self._f.flush()
        self.bytes_written += len(frame)

    def write_header(self, fingerprint: Dict[str, str],
                     replay: Optional[Dict[str, Any]] = None,
                     **extra: Any) -> None:
        """The run header: fingerprint identifies what ran (weights by
        hash, not bytes); ``replay`` is the literal re-drive recipe
        (model spec + seed + engine/router kwargs) a replayer feeds to
        the same constructors the recorded run used."""
        rec = {"kind": "HEADER", "schema": SCHEMA, "t0": self.t0,
               "fingerprint": dict(fingerprint)}
        if replay is not None:
            rec["replay"] = replay
        rec.update(extra)
        self._append(rec)

    def admit(self, uid: Any, prompt_tokens: Sequence[int],
              max_new_tokens: int,
              arrival_offset_s: Optional[float] = None,
              **extra: Any) -> None:
        if arrival_offset_s is None:
            arrival_offset_s = wall_time() - self.t0
        rec = {"kind": "ADMIT", "uid": uid,
               "prompt_tokens": [int(t) for t in prompt_tokens],
               "max_new_tokens": int(max_new_tokens),
               "arrival_offset_s": round(float(arrival_offset_s), 6)}
        rec.update(extra)
        self._append(rec)

    def decision(self, kind: str, **fields: Any) -> None:
        """One decision with its inputs. ``fields`` must carry enough
        of the triggering state to audit the decision post-hoc (ROUTE:
        per-candidate scores; PREEMPT: free blocks + queue depth; ...).
        """
        rec: Dict[str, Any] = {"kind": str(kind)}
        rec.update(fields)
        self._append(rec)

    def chaos(self, fault: str, **fields: Any) -> None:
        rec: Dict[str, Any] = {"kind": "CHAOS", "fault": str(fault)}
        rec.update(fields)
        self._append(rec)

    def emit(self, uid: Any, tokens: Sequence[int]) -> None:
        """Extend ``uid``'s checksum chain by one record per decode
        batch. ``start`` is the chain index of the first link so the
        replayer can detect gaps as well as mismatches."""
        toks = [int(t) for t in tokens]
        if not toks:
            return
        with self._lock:
            prev = self._chains.get(uid, 0)
            start = self._chain_len.get(uid, 0)
            chain = chain_tokens(toks, prev)
            self._chains[uid] = chain[-1]
            self._chain_len[uid] = start + len(chain)
        self._append({"kind": "EMIT", "uid": uid, "start": start,
                      "chain": chain})

    def note(self, kind: str, **fields: Any) -> None:
        """Free-form annotation record (chaos spec text, arm labels...).
        Ignored by the replayer's verification pass."""
        rec: Dict[str, Any] = {"kind": str(kind)}
        rec.update(fields)
        self._append(rec)

    # -- lifecycle -----------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            n_req = len(self._chain_len)
            return {
                "path": self.path,
                "records": self.n_records,
                "dropped": self.n_dropped,
                "bytes": self.bytes_written,
                "truncated": self._truncated,
                "requests": n_req,
                "append_us_total": round(self.append_s * 1e6, 1),
                "append_us_per_request": round(
                    self.append_s * 1e6 / max(1, n_req), 2),
                "bytes_per_request": round(
                    self.bytes_written / max(1, n_req), 1),
                "ingress": self._ingress,
            }

    def close(self) -> None:
        with self._lock:
            self._closed = True
            if self._f is not None:
                try:
                    self._f.flush()
                    self._f.close()
                except OSError:
                    pass
                self._f = None

    @classmethod
    def from_config(cls, cfg: Any, name: str = "fleet.journal"
                    ) -> Optional["FleetJournal"]:
        """Build from an ``observability.journal`` config block
        (``{enabled, dir, max_mb}``); None when disabled/absent."""
        jc = getattr(getattr(cfg, "observability", cfg), "journal", None)
        if jc is None or not getattr(jc, "enabled", False):
            return None
        return cls(os.path.join(jc.dir, name), max_mb=jc.max_mb)


# -- process-wide handle (mirrors flight_recorder's singleton) ---------
_journal: Optional[FleetJournal] = None
_journal_lock = threading.Lock()


def get_journal() -> Optional[FleetJournal]:
    """The installed journal, or None (the default: journaling off and
    every hook reduced to one global read)."""
    return _journal


def set_journal(journal: Optional[FleetJournal]) -> Optional[FleetJournal]:
    global _journal
    with _journal_lock:
        prev = _journal
        _journal = journal
    return prev


def reset_journal() -> None:
    global _journal
    with _journal_lock:
        j, _journal = _journal, None
    if j is not None:
        j.close()


# -- salvage reader ----------------------------------------------------
def load_journal(path: str) -> List[Dict[str, Any]]:
    """Every complete, CRC-valid record in ``path``, in order.

    A journal's tail is torn whenever the recording process died
    mid-append, so unlike the socket ``FrameReader`` (which must treat
    desync as fatal) this walks the same wire format directly and stops
    cleanly at the first incomplete or corrupt frame — all the records
    before it are intact by construction (each frame's CRC covers its
    payload). Never raises on journal content; a missing file is just
    an empty journal."""
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return []
    fr = _framing()
    records: List[Dict[str, Any]] = []
    off, n = 0, len(data)
    while n - off >= fr.HEADER_BYTES:
        magic, length, crc = fr._HEADER.unpack_from(data, off)
        if magic != fr.MAGIC:
            break
        end = off + fr.HEADER_BYTES + length
        if length > fr.DEFAULT_MAX_FRAME_BYTES or end > n:
            break  # torn tail (or corrupt length field)
        payload = data[off + fr.HEADER_BYTES:end]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            break
        try:
            rec = json.loads(payload)
        except ValueError:
            break
        if isinstance(rec, dict):
            records.append(rec)
        off = end
    return records


def dump_journal(path: str, records: Iterable[Dict[str, Any]]) -> int:
    """Re-frame ``records`` to ``path`` (tests and tooling: corrupt a
    chain, rewrite, replay). Returns the record count."""
    n = 0
    with open(path, "wb") as f:
        for rec in records:
            payload = json.dumps(rec, separators=(",", ":"),
                                 default=str).encode()
            f.write(_framing().encode_frame(payload))
            n += 1
    return n


# -- verification ------------------------------------------------------
def journal_header(records: Sequence[Dict[str, Any]]
                   ) -> Optional[Dict[str, Any]]:
    for rec in records:
        if rec.get("kind") == "HEADER":
            return rec
    return None


def admitted_requests(records: Sequence[Dict[str, Any]]
                      ) -> List[Dict[str, Any]]:
    """ADMIT records in journal (= arrival) order."""
    return [r for r in records if r.get("kind") == "ADMIT"]


def recorded_chains(records: Sequence[Dict[str, Any]]
                    ) -> Dict[Any, List[int]]:
    """Per-uid emitted-token checksum chains, reassembled from EMIT
    records. A ``start`` gap (lost EMIT record) truncates that uid's
    chain at the gap — verification then flags the first un-verifiable
    step instead of silently skipping it."""
    chains: Dict[Any, List[int]] = {}
    for rec in records:
        if rec.get("kind") != "EMIT":
            continue
        uid = rec.get("uid")
        chain = chains.setdefault(uid, [])
        if rec.get("start") != len(chain):
            continue  # gap: keep the verified prefix only
        chain.extend(int(c) for c in rec.get("chain", ()))
    return chains


def verify_streams(records: Sequence[Dict[str, Any]],
                   streams: Dict[Any, Sequence[int]]
                   ) -> Dict[str, Any]:
    """Compare replayed token ``streams`` against the recorded checksum
    chains. Returns a verdict naming the **first diverging request and
    decode step** (first = recorded admission order, then step index).

    Divergence reasons: ``chain_mismatch`` (same step, different
    token), ``short_stream`` / ``long_stream`` (replay emitted fewer /
    more tokens than recorded), ``missing_request`` (replay produced no
    stream for an admitted uid)."""
    expected = recorded_chains(records)
    admits = admitted_requests(records)
    order = [r.get("uid") for r in admits]
    known = set(order)
    for uid in expected:
        if uid not in known:
            known.add(uid)
            order.append(uid)

    def norm(uid: Any) -> Any:
        # JSON round-trips int keys fine (values, not dict keys), but a
        # caller may pass str uids — match on equality of str() forms
        # when the exact key is absent.
        if uid in streams:
            return uid
        for k in streams:
            if str(k) == str(uid):
                return k
        return uid

    first: Optional[Dict[str, Any]] = None
    divergent = 0
    verified_tokens = 0
    for uid in order:
        exp = expected.get(uid, [])
        got_tokens = list(streams.get(norm(uid), []))
        got = chain_tokens(got_tokens)
        div: Optional[Dict[str, Any]] = None
        for step in range(min(len(exp), len(got))):
            if exp[step] != got[step]:
                div = {"uid": uid, "step": step,
                       "reason": "chain_mismatch",
                       "expected_chain": exp[step],
                       "got_chain": got[step]}
                break
            verified_tokens += 1
        if div is None and len(got) < len(exp):
            div = {"uid": uid, "step": len(got),
                   "reason": ("missing_request" if not got_tokens
                              and uid not in streams
                              and norm(uid) not in streams
                              else "short_stream"),
                   "expected_chain": exp[len(got)],
                   "got_chain": None}
        elif div is None and len(got) > len(exp):
            div = {"uid": uid, "step": len(exp),
                   "reason": "long_stream",
                   "expected_chain": None,
                   "got_chain": got[len(exp)]}
        if div is not None:
            divergent += 1
            if first is None:
                first = div
    return {
        "schema": "fleet_replay_verdict/v1",
        "bit_identical": first is None,
        "requests": len(order),
        "verified_tokens": verified_tokens,
        "divergent_requests": divergent,
        "first_divergence": first,
    }


# -- incident-log rendering (serve_top --journal) ----------------------
def request_outcomes(records: Sequence[Dict[str, Any]]
                     ) -> Dict[Any, Dict[str, Any]]:
    """Per-request outcome summary: emitted token count vs budget, and
    every decision that touched the request."""
    out: Dict[Any, Dict[str, Any]] = {}
    chains = recorded_chains(records)
    for rec in records:
        if rec.get("kind") == "ADMIT":
            uid = rec.get("uid")
            out[uid] = {"uid": uid, "prompt": len(
                rec.get("prompt_tokens", ())),
                "max_new_tokens": rec.get("max_new_tokens"),
                "arrival_offset_s": rec.get("arrival_offset_s"),
                "emitted": len(chains.get(uid, ())),
                "decisions": []}
        elif rec.get("kind") in DECISION_KINDS:
            uid = rec.get("uid")
            if uid in out:
                out[uid]["decisions"].append(rec.get("kind"))
    for uid, row in out.items():
        budget = row.get("max_new_tokens")
        row["outcome"] = ("complete" if budget and row["emitted"] >= budget
                          else "partial" if row["emitted"] else "no_tokens")
    return out


def _fmt_fields(rec: Dict[str, Any], skip: Tuple[str, ...]) -> str:
    parts = []
    for k in sorted(rec):
        if k in skip or k in ("kind", "ts"):
            continue
        v = rec[k]
        if isinstance(v, float):
            v = round(v, 4)
        parts.append(f"{k}={v}")
    return " ".join(parts)


def render_incident_log(records: Sequence[Dict[str, Any]],
                        kinds: Optional[Sequence[str]] = None
                        ) -> List[str]:
    """Human-readable decision-by-decision incident log. Each line is
    ``+offset  KIND  fields`` — inputs included, because a decision
    without its inputs is not auditable."""
    hdr = journal_header(records)
    t0 = float(hdr.get("t0", 0.0)) if hdr else None
    lines: List[str] = []
    want = set(kinds) if kinds else None
    for rec in records:
        kind = rec.get("kind", "?")
        if want is not None and kind not in want:
            continue
        ts = rec.get("ts")
        if t0 is None and isinstance(ts, (int, float)):
            t0 = float(ts)
        off = (f"+{float(ts) - t0:9.4f}s"
               if isinstance(ts, (int, float)) and t0 is not None
               else " " * 11)
        if kind == "HEADER":
            fp = rec.get("fingerprint", {})
            lines.append(f"{off}  HEADER    fingerprint="
                         f"{fp.get('combined', '?')} schema="
                         f"{rec.get('schema')}")
        elif kind == "ADMIT":
            lines.append(
                f"{off}  ADMIT     uid={rec.get('uid')} "
                f"prompt={len(rec.get('prompt_tokens', ()))}tok "
                f"max_new={rec.get('max_new_tokens')} "
                f"arrival=+{rec.get('arrival_offset_s')}s")
        elif kind == "EMIT":
            lines.append(
                f"{off}  EMIT      uid={rec.get('uid')} "
                f"steps={rec.get('start')}.."
                f"{rec.get('start', 0) + len(rec.get('chain', ()))}")
        elif kind == "CHAOS":
            lines.append(f"{off}  CHAOS     fault={rec.get('fault')} "
                         + _fmt_fields(rec, ("fault",)))
        elif kind == "MIGRATE":
            lines.append(
                f"{off}  MIGRATE   uid={rec.get('uid')} "
                f"r{rec.get('from_replica')}->r{rec.get('to_replica')} "
                f"rung={rec.get('rung')} "
                + _fmt_fields(rec, ("uid", "from_replica",
                                    "to_replica", "rung")))
        elif kind == "SWAP":
            lines.append(
                f"{off}  SWAP      tag={rec.get('tag')} "
                f"r{rec.get('replica')} stage={rec.get('stage')} "
                f"ok={rec.get('ok')} "
                + _fmt_fields(rec, ("tag", "replica", "stage", "ok")))
        elif kind == "SCALE":
            lines.append(
                f"{off}  SCALE     {rec.get('action')} "
                f"r{rec.get('replica')} desired={rec.get('desired')} "
                f"live={rec.get('live')} "
                + _fmt_fields(rec, ("action", "replica", "desired",
                                    "live")))
        else:
            lines.append(f"{off}  {kind:<9} " + _fmt_fields(rec, ()))
    return lines


__all__ = [
    "SCHEMA", "DECISION_KINDS", "FleetJournal",
    "get_journal", "set_journal", "reset_journal",
    "token_chain", "chain_tokens", "config_fingerprint",
    "load_journal", "dump_journal", "journal_header",
    "admitted_requests", "recorded_chains", "verify_streams",
    "request_outcomes", "render_incident_log",
]
