"""On-demand jax.profiler trace capture, gated by env var.

``DSTPU_TRACE_STEPS=5:8`` makes the engine capture an xplane trace of
global steps 5 through 8 (inclusive; a single number traces that one
step) into ``DSTPU_TRACE_DIR`` (default ``/tmp/dstpu_trace``) — open it
with TensorBoard's profile plugin or xprof. No code change, no restart
with different flags: the window is checked against the engine's step
counter at the train_batch boundary, so a long run can be profiled by
setting the env var before launch and letting the window pass.

The per-step phases inside the capture are named by the
``jax.profiler.StepTraceAnnotation`` wrapped around each traced step
plus the ``utils/annotate.py`` scopes already present in the model code
(attention/mlp/collective ranges show up under those names).
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

from deepspeed_tpu.utils.logging import logger


def parse_trace_steps(spec: str) -> Optional[Tuple[int, int]]:
    """'5:8' -> (5, 8); '12' -> (12, 12); '' / malformed -> None."""
    spec = (spec or "").strip()
    if not spec:
        return None
    try:
        if ":" in spec:
            a, b = spec.split(":", 1)
            lo, hi = int(a), int(b)
        else:
            lo = hi = int(spec)
        if lo < 0 or hi < lo:
            raise ValueError(spec)
        return lo, hi
    except ValueError:
        logger.warning(
            f"DSTPU_TRACE_STEPS={spec!r} not understood (want 'N' or "
            "'LO:HI'); trace capture disabled")
        return None


class TraceCapture:
    """Start/stop ``jax.profiler`` around a step window."""

    def __init__(self, window: Optional[Tuple[int, int]] = None,
                 out_dir: Optional[str] = None):
        self.window = window
        self.out_dir = out_dir or os.environ.get("DSTPU_TRACE_DIR",
                                                 "/tmp/dstpu_trace")
        self.active = False
        self.done = False
        self._step_ann = None

    @classmethod
    def from_env(cls) -> "TraceCapture":
        return cls(window=parse_trace_steps(
            os.environ.get("DSTPU_TRACE_STEPS", "")))

    @property
    def enabled(self) -> bool:
        return self.window is not None and not self.done

    def on_step_begin(self, step: int) -> None:
        """Call with the 1-based index of the step about to run."""
        if not self.enabled:
            return
        lo, hi = self.window
        if not self.active and lo <= step <= hi:
            import jax

            try:
                os.makedirs(self.out_dir, exist_ok=True)
                jax.profiler.start_trace(self.out_dir)
                self.active = True
                logger.warning(
                    f"profiler trace started at step {step} "
                    f"(window {lo}:{hi}) -> {self.out_dir}")
            except Exception as e:
                logger.warning(f"profiler trace start failed: {e}")
                self.done = True
                return
        if self.active:
            import jax

            # named step boundary inside the capture (xprof groups by it)
            self._step_ann = jax.profiler.StepTraceAnnotation(
                "train_batch", step_num=step)
            self._step_ann.__enter__()

    def on_step_end(self, step: int) -> None:
        if self._step_ann is not None:
            self._step_ann.__exit__(None, None, None)
            self._step_ann = None
        if self.active and step >= self.window[1]:
            import jax

            try:
                jax.profiler.stop_trace()
                logger.warning(
                    f"profiler trace stopped after step {step}; view with "
                    f"`tensorboard --logdir {self.out_dir}` (profile tab)")
            except Exception as e:
                logger.warning(f"profiler trace stop failed: {e}")
            self.active = False
            self.done = True   # one capture per process
