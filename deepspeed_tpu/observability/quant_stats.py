"""Quantization-error and wire-bytes telemetry for the ZeRO++ trio.

ROADMAP item 1 calls qwZ/qgZ/hpZ "LANDED but unproven": the mechanisms
exist (``runtime/sharding.py quantized_param_fetch``, ``runtime/qgz.py``
``qgz_reduce_tree``, ``zero_hpz_partition_size``) but nothing measured
the error they introduce or the bytes they save. The reference frames
ZeRO++ as exactly that trade (4x comm reduction vs bounded blockwise
error), and EQuARX-class quantized collectives are only trustworthy with
explicit error accounting — so this module is the measurement layer:

* closed-form error metrics — :func:`snr_db`, :func:`max_rel_error`
  (blockwise peak relative error, provably <= 0.5/qmax for symmetric
  round-to-nearest), :func:`scale_summary` (blockwise scale
  distribution, clamped-zero-block fraction);
* quantize/dequantize replicas of the runtime math — int8/QWZ_BLOCK for
  the qwZ fetch, int8+int4/QGZ_BLOCK two-level for qgZ, e4m3 for the
  fp8 MLP — measured on REAL tensors (params, grads), not synthetic
  noise;
* a wire-bytes model (:func:`wire_bytes`: int payload + fp32 scale per
  block) shared with the attribution extension
  (``observability/attribution.py attribute_quant_step``);
* export: ``quant.*`` hub gauges/counters -> JSONL + Prometheus through
  the existing sinks, one ``quant_stats`` JSONL event per measurement,
  and a flight-recorder dump context so every crash dump carries the
  last quantization-error snapshot;
* fail-loud acceptance gates (:data:`DEFAULT_GATES`,
  :func:`evaluate_gates`): minimum SNR dB and maximum blockwise
  relative error per region. ``BENCH_QUANT=1`` (bench.py) runs
  :func:`run_quant_bench`, which evaluates the gates on real tensors,
  verifies the all-knobs-off path is bit-exact, and exits nonzero on
  violation. ``BENCH_QUANT_INJECT=corrupt_scale`` (or
  ``DSTPU_QUANT_CHAOS``) corrupts one block scale so the gate trip is
  demonstrable, not theoretical.

See docs/quantized_comm.md "Measuring the trade" for metric names and
gate semantics.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

# the regions every quantized path reports under (quant.<region>.*)
QUANT_REGIONS = ("qwz_param_fetch", "qgz_grad_reduce", "hpz_partition",
                 "fp8_mlp", "kv_cache", "kv_wire", "qar")

# int8 blockwise RTN peak-rel-error bound is 0.5/127 ~= 0.00394; int4 is
# 0.5/7 ~= 0.0714; fp8 e4m3 has 3 mantissa bits -> rel step 2^-4 with
# round-to-nearest half that. The two-level qgZ composition stacks G
# int8 errors plus one int4 re-quantization of a partial sum, so its
# gate sits at ~2x the int4 bound. A corrupted scale (injection) lands
# at ~0.25 rel err — beyond every gate by construction.
DEFAULT_GATES: Dict[str, Dict[str, float]] = {
    "qwz_param_fetch": {"min_snr_db": 30.0, "max_rel_err": 0.005},
    "qgz_grad_reduce": {"min_snr_db": 15.0, "max_rel_err": 0.15},
    "fp8_mlp": {"min_snr_db": 18.0, "max_rel_err": 0.05},
    # hpZ changes which link the gather rides, never the values
    "hpz_partition": {"bit_exact": True},
    # int8 KV blocks: one scale per head_dim vector, so the RTN bound is
    # the plain int8 one (0.5/127)
    "kv_cache": {"min_snr_db": 30.0, "max_rel_err": 0.005},
    # the handoff wire may run int4 (0.5/7 ~= 0.0714 bound)
    "kv_wire": {"min_snr_db": 18.0, "max_rel_err": 0.08},
    # quantized all-reduce stacks two int8 hops (scatter + gather);
    # real grad tensors carry many near-zero blocks whose clamped
    # scales dominate the worst-case element, so the rel-err bound is
    # looser than the single-hop paths
    "qar": {"min_snr_db": 25.0, "max_rel_err": 0.03},
}

# -- fault injection (the gate-trip demo) -----------------------------------
# corrupt_scale: multiply the first block's scale by 64 before
# quantizing — the dequantized block lands on a 64x-coarser grid, so
# max_rel_error jumps ~0.004 -> ~0.25 and every SNR gate fails. Armed
# from env (BENCH_QUANT_INJECT / DSTPU_QUANT_CHAOS) or set_injection().
_INJECT: Optional[str] = None
INJECTION_MODES = ("corrupt_scale",)


def set_injection(mode: Optional[str]) -> None:
    global _INJECT
    if mode is not None and mode not in INJECTION_MODES:
        raise ValueError(f"unknown quant injection {mode!r} "
                         f"(choose from {INJECTION_MODES})")
    _INJECT = mode


def injection_from_env(env=None) -> Optional[str]:
    env = os.environ if env is None else env
    return (env.get("BENCH_QUANT_INJECT")
            or env.get("DSTPU_QUANT_CHAOS") or None)


# -- closed-form error metrics ----------------------------------------------


def snr_db(ref, approx) -> float:
    """Signal-to-noise ratio in dB: 10*log10(sum ref^2 / sum err^2).

    inf when the error is exactly zero (bit-exact path); -inf when the
    reference is zero but the approximation is not.
    """
    r = jnp.asarray(ref, jnp.float32).reshape(-1)
    e = jnp.asarray(approx, jnp.float32).reshape(-1) - r
    sig = float(jnp.sum(r * r))
    noise = float(jnp.sum(e * e))
    if noise == 0.0:
        return float("inf")
    if sig == 0.0:
        return float("-inf")
    return 10.0 * math.log10(sig / noise)


def max_rel_error(ref, approx, block: int = 0) -> float:
    """Blockwise peak relative error: max over blocks of
    (max |err| in block) / (max |ref| in block).

    This is the quantity symmetric round-to-nearest bounds in closed
    form: |err| <= scale/2 = max|ref|/(2*qmax) per block, so int8 RTN
    satisfies max_rel_error <= 0.5/127 exactly — the gates assert it.
    ``block`` 0 treats the whole tensor as one block. All-zero blocks
    contribute 0 (the runtime clamps their scale to 1 and emits zeros).
    """
    r = jnp.asarray(ref, jnp.float32).reshape(-1)
    e = jnp.abs(jnp.asarray(approx, jnp.float32).reshape(-1) - r)
    n = r.size
    b = int(block) if block and n % int(block) == 0 else n
    ra = jnp.max(jnp.abs(r.reshape(-1, b)), axis=1)
    ea = jnp.max(e.reshape(-1, b), axis=1)
    rel = jnp.where(ra > 0, ea / jnp.where(ra > 0, ra, 1.0), 0.0)
    return float(jnp.max(rel)) if n else 0.0


def scale_summary(scales) -> Dict[str, float]:
    """Distribution summary of the blockwise scales: min/max/mean plus
    the fraction of blocks whose scale was clamped to 1.0 (all-zero
    blocks — a high fraction means the block size is wasted on
    padding/dead weights)."""
    s = jnp.asarray(scales, jnp.float32).reshape(-1)
    if s.size == 0:
        return {"n_blocks": 0, "min": 0.0, "max": 0.0, "mean": 0.0,
                "clamped_frac": 0.0}
    return {"n_blocks": int(s.size),
            "min": float(jnp.min(s)), "max": float(jnp.max(s)),
            "mean": float(jnp.mean(s)),
            "clamped_frac": float(jnp.mean((s == 1.0).astype(
                jnp.float32)))}


# -- quantize/dequantize replicas of the runtime math -----------------------


def qdq_blockwise(x, block: int, bits: int = 8):
    """Blockwise symmetric quantize→dequantize of a flattened tensor —
    the same math ``sharding.quantized_param_fetch`` (int8, QWZ_BLOCK)
    and ``qgz._quant`` (int8/int4, QGZ_BLOCK) trace, run eagerly for
    measurement. Returns (dequantized fp32 [n], scales fp32 [n_blocks]).

    The effective block is gcd(n, block), mirroring the runtime's
    must-tile rule; block <= 1 falls back to the exact path (identity,
    no scales) exactly as the runtime does for unblockable leaves.
    Honors the armed fault injection (see :func:`set_injection`).
    """
    f = jnp.asarray(x, jnp.float32).reshape(-1)
    n = int(f.size)
    b = math.gcd(n, int(block)) if block else 0
    if b <= 1 or n == 0:
        return f, jnp.zeros((0,), jnp.float32)
    qmax = float(2 ** (int(bits) - 1) - 1)
    fb = f.reshape(n // b, b)
    s = jnp.max(jnp.abs(fb), axis=1) / qmax
    s = jnp.where(s == 0.0, 1.0, s)
    if _INJECT == "corrupt_scale":
        s = s.at[0].multiply(64.0)
    dtype = jnp.int4 if int(bits) == 4 else jnp.int8
    q = jnp.round(fb / s[:, None]).astype(dtype)
    deq = (q.astype(jnp.float32) * s[:, None]).reshape(-1)
    return deq, s


def wire_bytes(n_elems: int, bits: int, block: int,
               scale_bytes: int = 4) -> int:
    """Bytes one quantized tensor puts on the wire: the integer payload
    plus one fp32 scale per block (the runtime gathers/reshards scales
    alongside the payload). ``block`` <= 1 means the exact path — the
    caller should charge full-precision bytes instead."""
    if block <= 1:
        return n_elems * 4  # exact fp32 fallback path
    payload = math.ceil(n_elems * bits / 8)
    return payload + (n_elems // block) * scale_bytes


# -- per-region stats --------------------------------------------------------


@dataclasses.dataclass
class QuantRegionStats:
    """One quantized region's error + byte accounting."""

    region: str
    snr_db: Optional[float]          # None for bit-exact regions
    max_rel_err: float
    logical_bytes: int               # full-precision bytes the wire replaces
    wire_bytes: int                  # quantized payload + scales
    n_elements: int
    bits: int
    block: int
    scales: Dict[str, float] = dataclasses.field(default_factory=dict)
    bit_exact: bool = False
    note: str = ""

    @property
    def compression(self) -> float:
        return (self.logical_bytes / self.wire_bytes
                if self.wire_bytes else 1.0)

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["compression"] = round(self.compression, 3)
        if self.snr_db is not None and math.isfinite(self.snr_db):
            d["snr_db"] = round(self.snr_db, 2)
        d["max_rel_err"] = (round(self.max_rel_err, 6)
                            if math.isfinite(self.max_rel_err)
                            else self.max_rel_err)
        return d


def _sample_leaves(tree, cap_elements: int) -> List[Any]:
    """Flattened >=2-D leaves, largest first, until ``cap_elements``
    total — bounded measurement cost on multi-billion-param trees.
    1-D leaves (norm scales, biases) ride the runtime's exact path and
    carry no quantization error to measure."""
    leaves = [x for x in jax.tree.leaves(tree)
              if hasattr(x, "ndim") and x.ndim >= 2]
    leaves.sort(key=lambda x: -x.size)
    out, total = [], 0
    for x in leaves:
        if total >= cap_elements:
            break
        out.append(x)
        total += int(x.size)
    return out


def measure_region(region: str, tensors: Sequence[Any], *, block: int,
                   bits: int = 8, full_bytes_per_elem: int = 2,
                   cap_elements: int = 1 << 22,
                   note: str = "") -> QuantRegionStats:
    """Quantize each tensor with the region's blockwise math and fold
    the error/byte accounting into one :class:`QuantRegionStats`."""
    sig = noise = 0.0
    worst_rel = 0.0
    n_elems = 0
    all_scales: List[Any] = []
    budget = int(cap_elements)
    for t in tensors:
        f = jnp.asarray(t, jnp.float32).reshape(-1)
        if budget <= 0:
            break
        if f.size > budget:
            f = f[: (budget // max(block, 1)) * max(block, 1) or budget]
        budget -= int(f.size)
        deq, s = qdq_blockwise(f, block, bits)
        err = deq - f
        sig += float(jnp.sum(f * f))
        noise += float(jnp.sum(err * err))
        worst_rel = max(worst_rel, max_rel_error(f, deq, block))
        n_elems += int(f.size)
        if s.size:
            all_scales.append(s)
    if noise == 0.0:
        snr = float("inf")
    elif sig == 0.0:
        snr = float("-inf")
    else:
        snr = 10.0 * math.log10(sig / noise)
    scales = (scale_summary(jnp.concatenate(all_scales))
              if all_scales else scale_summary(jnp.zeros((0,))))
    return QuantRegionStats(
        region=region, snr_db=snr, max_rel_err=worst_rel,
        logical_bytes=n_elems * full_bytes_per_elem,
        wire_bytes=wire_bytes(n_elems, bits, block),
        n_elements=n_elems, bits=bits, block=block, scales=scales,
        note=note)


def measure_param_fetch(params, *, cap_elements: int = 1 << 22
                        ) -> QuantRegionStats:
    """qwZ region: int8/QWZ_BLOCK error on the model's real parameters
    (the tensors the stage-3 all-gather actually moves)."""
    from deepspeed_tpu.runtime.sharding import QWZ_BLOCK

    return measure_region(
        "qwz_param_fetch", _sample_leaves(params, cap_elements),
        block=QWZ_BLOCK, bits=8, full_bytes_per_elem=2,
        cap_elements=cap_elements,
        note="int8 blockwise param all-gather wire (vs bf16)")


def measure_grad_reduce(grad_groups: Sequence[Any], *, bits1: int = 8,
                        bits2: Optional[int] = 4,
                        cap_elements: int = 1 << 22) -> QuantRegionStats:
    """qgZ region: two-level quantized group reduction error on REAL
    per-group gradients — each group's grad quantizes at ``bits1``
    (the fsdp all-to-all wire), partial sums re-quantize at ``bits2``
    (the dp level) when more than two groups, and the result compares
    against the exact fp32 group mean. Mirrors ``qgz._reduce_leaf``'s
    level structure without needing a multi-device mesh."""
    from deepspeed_tpu.runtime.qgz import QGZ_BLOCK

    groups = list(grad_groups)
    if not groups:
        raise ValueError("measure_grad_reduce needs >= 1 gradient group")
    flats = [jax.tree.leaves(g) for g in groups]
    n_leaves = len(flats[0])
    sig = noise = 0.0
    worst_rel = 0.0
    n_elems = 0
    all_scales: List[Any] = []
    budget = int(cap_elements)
    # level split mirroring the mesh factorization: fsdp groups reduce
    # at bits1; when >2 groups the second half plays the dp level and
    # its partial sum re-quantizes at bits2 (the int4 hop)
    two_level = bits2 is not None and len(groups) > 2
    half = (len(groups) + 1) // 2 if two_level else len(groups)
    for i in range(n_leaves):
        leaves = [jnp.asarray(f[i], jnp.float32).reshape(-1)
                  for f in flats]
        size = int(leaves[0].size)
        if leaves[0].ndim != 1 or budget <= 0:
            continue
        if jnp.asarray(flats[0][i]).ndim < 2:
            continue  # 1-D leaves ride the exact path in the runtime
        budget -= size
        exact = sum(leaves) / len(leaves)
        acc = jnp.zeros_like(leaves[0])
        lvl2: List[Any] = []
        for gi, leaf in enumerate(leaves):
            deq, s = qdq_blockwise(leaf, QGZ_BLOCK, bits1)
            if s.size:
                all_scales.append(s)
            if two_level and gi >= half:
                lvl2.append(deq)
            else:
                acc = acc + deq
        if lvl2:
            partial = sum(lvl2)
            deq2, s2 = qdq_blockwise(partial, QGZ_BLOCK, bits2)
            if s2.size:
                all_scales.append(s2)
            acc = acc + deq2
        approx = acc / len(leaves)
        err = approx - exact
        sig += float(jnp.sum(exact * exact))
        noise += float(jnp.sum(err * err))
        worst_rel = max(worst_rel,
                        max_rel_error(exact, approx, QGZ_BLOCK))
        n_elems += size
    if noise == 0.0:
        snr = float("inf")
    elif sig == 0.0:
        snr = float("-inf")
    else:
        snr = 10.0 * math.log10(sig / noise)
    scales = (scale_summary(jnp.concatenate(all_scales))
              if all_scales else scale_summary(jnp.zeros((0,))))
    # wire: every group's int8 payload crosses the fsdp a2a; the dp
    # level re-ships the partial at bits2 — per-chip accounting matches
    # attribute_quant_step's closed form
    wire = len(groups) * wire_bytes(n_elems, bits1, QGZ_BLOCK)
    if two_level:
        wire += wire_bytes(n_elems, int(bits2), QGZ_BLOCK)
    return QuantRegionStats(
        region="qgz_grad_reduce", snr_db=snr, max_rel_err=worst_rel,
        logical_bytes=len(groups) * n_elems * 4,
        wire_bytes=wire, n_elements=n_elems, bits=bits1, block=QGZ_BLOCK,
        scales=scales,
        note=(f"int{bits1} group a2a"
              + (f" + int{bits2} second level" if two_level else "")
              + f" over {len(groups)} groups (vs fp32 reduce)"))


def measure_fp8_mlp(params, *, cap_elements: int = 1 << 22
                    ) -> QuantRegionStats:
    """fp8 MLP region: e4m3 per-tensor quantization error on the real
    weight matrices the opt-in fp8 GEMMs (ops/fp_quantizer
    fp8_matmul_ste) would quantize."""
    from deepspeed_tpu.ops.fp_quantizer import _FMT_MAX

    tensors = _sample_leaves(params, cap_elements)
    sig = noise = 0.0
    worst_rel = 0.0
    n_elems = 0
    for t in tensors:
        f = jnp.asarray(t, jnp.float32).reshape(-1)
        amax = jnp.max(jnp.abs(f))
        s = jnp.where(amax > 0, amax / _FMT_MAX["e4m3"], 1.0)
        if _INJECT == "corrupt_scale":
            s = s * 64.0
        deq = (f / s).astype(jnp.float8_e4m3fn).astype(jnp.float32) * s
        sig += float(jnp.sum(f * f))
        noise += float(jnp.sum((deq - f) ** 2))
        worst_rel = max(worst_rel, max_rel_error(f, deq))
        n_elems += int(f.size)
    if noise == 0.0:
        snr = float("inf")
    elif sig == 0.0:
        snr = float("-inf")
    else:
        snr = 10.0 * math.log10(sig / noise)
    return QuantRegionStats(
        region="fp8_mlp", snr_db=snr, max_rel_err=worst_rel,
        logical_bytes=n_elems * 2, wire_bytes=n_elems + 4 * len(tensors),
        n_elements=n_elems, bits=8, block=0,
        note="e4m3 per-tensor MLP GEMM operands (vs bf16)")


def hpz_partition_stats(n_params: int, partition_size: int
                        ) -> QuantRegionStats:
    """hpZ region: a byte-accounting row, not an error row — the
    secondary partition changes which link the gather rides (intra-slice
    ICI at fsdp=k vs inter-slice DCN), never the gathered values. The
    region exists so the gate table can assert bit-exactness and the
    sweep table can show the link flip."""
    k = max(int(partition_size), 1)
    b = int(n_params) * 2  # bf16 gather bytes per pass
    return QuantRegionStats(
        region="hpz_partition", snr_db=None, max_rel_err=0.0,
        logical_bytes=b, wire_bytes=b, n_elements=int(n_params),
        bits=16, block=0, bit_exact=True,
        note=(f"secondary partition k={k}: gather stays intra-slice "
              "(ICI)" if k > 1
              else "k=1: gather spans the full fsdp group"))


def measure_kv_cache(kv_tensors: Sequence[Any], head_dim: int, *,
                     bits: int = 8, cap_elements: int = 1 << 22
                     ) -> QuantRegionStats:
    """kv_cache region: int8 per-head-vector error on REAL K/V tensors
    (one fp32 scale per head_dim vector — the pool layout of
    ``BlockedKVCache`` with ``quant_bits=8``)."""
    st = measure_region(
        "kv_cache", kv_tensors, block=int(head_dim), bits=bits,
        full_bytes_per_elem=2, cap_elements=cap_elements,
        note=f"int{bits} KV blocks, scale per head_dim={head_dim} vector "
             "(vs bf16 pool)")
    return st


def measure_kv_wire(block_data, head_dim: int, *, bits: int = 4,
                    cap_elements: int = 1 << 22) -> QuantRegionStats:
    """kv_wire region: error + byte accounting of quantizing bf16 handoff
    blocks for the disagg wire at ``bits`` (int4 packs two values per
    byte, the <=0.35x-of-bf16 mode)."""
    st = measure_region(
        "kv_wire", [block_data], block=int(head_dim), bits=bits,
        full_bytes_per_elem=2, cap_elements=cap_elements,
        note=f"int{bits} handoff wire, scale per head_dim={head_dim} "
             "vector (vs bf16 block payload)")
    return st


def measure_qar(grad_groups: Sequence[Any], *, bits: int = 8,
                block: int = 256, cap_elements: int = 1 << 22
                ) -> QuantRegionStats:
    """qar region: EQuARX-style quantized all-reduce error on REAL
    per-rank gradients — each rank's contribution quantizes at ``bits``
    for the reduce-scatter hop, the fp32-accumulated mean re-quantizes
    for the all-gather hop, and the result compares against the exact
    fp32 mean (mirrors ``quantized_all_reduce``'s two wire hops without
    needing a multi-device mesh)."""
    groups = list(grad_groups)
    if not groups:
        raise ValueError("measure_qar needs >= 1 gradient group")
    flats = [jax.tree.leaves(g) for g in groups]
    sig = noise = 0.0
    worst_rel = 0.0
    n_elems = 0
    all_scales: List[Any] = []
    budget = int(cap_elements)
    for i in range(len(flats[0])):
        if budget <= 0:
            break
        if jnp.asarray(flats[0][i]).ndim < 2:
            continue  # 1-D leaves ride the exact path in the runtime
        leaves = [jnp.asarray(f[i], jnp.float32).reshape(-1)
                  for f in flats]
        budget -= int(leaves[0].size)
        exact = sum(leaves) / len(leaves)
        # hop 1: per-rank quantize, fp32 accumulate (reduce-scatter wire)
        acc = jnp.zeros_like(leaves[0])
        for leaf in leaves:
            deq, s = qdq_blockwise(leaf, block, bits)
            acc = acc + deq
            if s.size:
                all_scales.append(s)
        mean = acc / len(leaves)
        # hop 2: the reduced shard re-quantizes for the all-gather wire
        approx, s2 = qdq_blockwise(mean, block, bits)
        if s2.size:
            all_scales.append(s2)
        err = approx - exact
        sig += float(jnp.sum(exact * exact))
        noise += float(jnp.sum(err * err))
        worst_rel = max(worst_rel, max_rel_error(exact, approx, block))
        n_elems += int(leaves[0].size)
    if noise == 0.0:
        snr = float("inf")
    elif sig == 0.0:
        snr = float("-inf")
    else:
        snr = 10.0 * math.log10(sig / noise)
    scales = (scale_summary(jnp.concatenate(all_scales))
              if all_scales else scale_summary(jnp.zeros((0,))))
    # wire per chip: one int payload + scales out (scatter) and the
    # world's reduced shards back in (gather) — 2x one tensor's wire
    return QuantRegionStats(
        region="qar", snr_db=snr, max_rel_err=worst_rel,
        logical_bytes=2 * n_elems * 4,
        wire_bytes=2 * wire_bytes(n_elems, bits, block),
        n_elements=n_elems, bits=bits, block=block, scales=scales,
        note=(f"int{bits} all-reduce (scatter+gather hops) over "
              f"{len(groups)} ranks (vs fp32 all-reduce)"))


# -- warn-once ----------------------------------------------------------------

_WARNED: set = set()


def warn_once(key: str, msg: str) -> None:
    """Log ``msg`` at WARNING level once per process per ``key`` — the
    shared warn-once used by the serving quant paths (e.g. a handoff
    shipping full-precision blocks into a quantized cache)."""
    if key in _WARNED:
        return
    _WARNED.add(key)
    from deepspeed_tpu.utils.logging import logger

    logger.warning(msg)


# -- export: hub gauges/counters, JSONL event, flight-recorder context ------

_LAST_SNAPSHOT: Dict[str, Any] = {}
_DUMP_CONTEXT_REGISTERED = False


def last_snapshot() -> Dict[str, Any]:
    """The newest published stats (what the flight recorder embeds)."""
    return dict(_LAST_SNAPSHOT)


def publish(stats: Sequence[QuantRegionStats], hub=None, step=None) -> None:
    """Export region stats as ``quant.*`` hub metrics + one JSONL event
    and stamp them into the flight-recorder dump context (registered
    once; every subsequent crash dump carries the latest snapshot)."""
    global _DUMP_CONTEXT_REGISTERED
    if hub is None:
        from deepspeed_tpu.observability.hub import get_hub

        hub = get_hub()
    rows = []
    for st in stats:
        p = f"quant.{st.region}"
        if st.snr_db is not None and math.isfinite(st.snr_db):
            hub.gauge(f"{p}.snr_db", st.snr_db)
        hub.gauge(f"{p}.max_rel_err", st.max_rel_err)
        hub.gauge(f"{p}.compression", st.compression)
        hub.counter_add(f"{p}.wire_bytes", st.wire_bytes)
        hub.counter_add(f"{p}.logical_bytes", st.logical_bytes)
        if st.scales.get("n_blocks"):
            hub.gauge(f"{p}.scale_clamped_frac",
                      st.scales["clamped_frac"])
        rows.append(st.to_dict())
    hub.record_event("quant_stats", step=step, regions=rows)
    _LAST_SNAPSHOT.clear()
    _LAST_SNAPSHOT.update({"step": step, "regions": rows})
    try:
        from deepspeed_tpu.observability.flight_recorder import \
            get_flight_recorder

        rec = get_flight_recorder()
        if not _DUMP_CONTEXT_REGISTERED:
            rec.add_dump_context("quant_stats", last_snapshot)
            _DUMP_CONTEXT_REGISTERED = True
        rec.record("quant_stats", regions=len(rows))
    except Exception:
        pass


def collection_configured(obs_cfg=None, env=None) -> bool:
    """Is quant.* collection on? ``observability.quant_stats`` config
    flag or DSTPU_QUANT_STATS=1 env — the warn-once in engine init fires
    when quantization runs without this."""
    env = os.environ if env is None else env
    if str(env.get("DSTPU_QUANT_STATS", "")).strip() in ("1", "true"):
        return True
    return bool(getattr(obs_cfg, "quant_stats", False))


def install_engine_collector(engine, cap_elements: int = 1 << 21) -> None:
    """One-shot init-time collection for an engine running quantized
    paths: sampled qwZ param-fetch error on the engine's real params,
    published as ``quant.*`` metrics + dump context. Gradients are
    measured by the bench arm (they need a real step); this collector
    makes sure a training run with qwZ/qgZ on always has at least the
    param-side error + wire bytes on the dashboard."""
    params = getattr(engine, "params", None)
    if params is None:
        return
    stats = [measure_param_fetch(params, cap_elements=cap_elements)]
    zq = getattr(getattr(engine, "_config", None) or
                 getattr(engine, "config", None), "zero_optimization",
                 None)
    if zq is not None and getattr(zq, "zero_hpz_partition_size", 1) > 1:
        stats.append(hpz_partition_stats(
            stats[0].n_elements, zq.zero_hpz_partition_size))
    publish(stats, hub=getattr(engine, "hub", None))


# -- acceptance gates --------------------------------------------------------


def evaluate_gates(stats: Sequence[QuantRegionStats],
                   gates: Optional[Dict[str, Dict[str, float]]] = None
                   ) -> (bool, List[Dict[str, Any]]):
    """Check each region against its gate; returns (ok, violations).
    Regions without a gate entry pass; gated regions missing from
    ``stats`` are NOT violations (the path may be off this run)."""
    gates = DEFAULT_GATES if gates is None else gates
    violations: List[Dict[str, Any]] = []
    for st in stats:
        g = gates.get(st.region)
        if not g:
            continue
        if g.get("bit_exact") and not st.bit_exact:
            violations.append({"region": st.region, "gate": "bit_exact",
                               "limit": True, "observed": st.bit_exact})
        if "min_snr_db" in g and st.snr_db is not None \
                and st.snr_db < g["min_snr_db"]:
            violations.append({"region": st.region, "gate": "min_snr_db",
                               "limit": g["min_snr_db"],
                               "observed": round(st.snr_db, 2)})
        if "max_rel_err" in g and st.max_rel_err > g["max_rel_err"]:
            violations.append({"region": st.region, "gate": "max_rel_err",
                               "limit": g["max_rel_err"],
                               "observed": round(st.max_rel_err, 6)})
    return (not violations), violations


# -- the BENCH_QUANT=1 arm ---------------------------------------------------


def _bench_model_cfg(env):
    """Small-but-real llama geometry for the gate measurement: big
    enough that blockwise scales exercise QWZ/QGZ blocks, small enough
    for CPU CI. BENCH_* dims override."""
    from deepspeed_tpu.models.zoo import get_model

    return get_model(
        env.get("BENCH_MODEL", "llama3-8b"),
        num_layers=int(env.get("BENCH_LAYERS", "2")),
        hidden_size=int(env.get("BENCH_HIDDEN", "256")),
        num_heads=8, num_kv_heads=4, ffn_size=512,
        vocab_size=int(env.get("BENCH_VOCAB", "2048")),
        max_seq_len=int(env.get("BENCH_SEQ", "128")))


def off_switch_bitexact(steps: int = 2, env=None) -> bool:
    """All-knobs-off must be BIT-exact: an engine config that spells
    zero_quantized_weights/gradients/hpz as off must produce bitwise
    identical losses and parameters to one that never mentions them.
    Tiny model, same seed/data; tier-1 tested and asserted by the
    BENCH_QUANT arm."""
    import numpy as np

    import deepspeed_tpu as dstpu
    from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                  TransformerLM)

    env = os.environ if env is None else env
    tiny = TransformerConfig(
        vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
        max_seq_len=32, pos_emb="learned", norm="layernorm",
        activation="gelu", tie_embeddings=True, remat=False)

    def run(zero_block):
        engine, *_ = dstpu.initialize(model=TransformerLM(tiny), config={
            "train_micro_batch_size_per_chip": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": zero_block,
            "steps_per_print": 1_000_000,
        })
        rng = np.random.default_rng(0)
        B = engine.micro_batch_size * engine.dp_world_size
        batch = {"input_ids": rng.integers(
            0, tiny.vocab_size, (B, 17)).astype(np.int32)}

        def it():
            while True:
                yield batch

        losses = [float(engine.train_batch(it())) for _ in range(steps)]
        return losses, jax.tree.leaves(engine.params)

    loss_off, p_off = run({"stage": 2, "zero_quantized_weights": False,
                           "zero_quantized_gradients": False,
                           "zero_hpz_partition_size": 1})
    loss_bare, p_bare = run({"stage": 2})
    if loss_off != loss_bare:
        return False
    return all(np.array_equal(np.asarray(a), np.asarray(b))
               for a, b in zip(p_off, p_bare))


def kv_off_switch_structural(cfg=None, params=None) -> bool:
    """``quant_bits=None`` must lower TODAY's serving program verbatim:
    the unquantized ragged step's HLO carries no int8 ops at all, while
    the quantized pytree's lowering does. Structural (lowered-text)
    check, mirroring test_param_prefetch_ring's no-barrier assertion."""
    from functools import partial

    import numpy as np

    from deepspeed_tpu.inference.model_runner import ragged_forward
    from deepspeed_tpu.models.transformer import (TransformerConfig,
                                                  init_params)

    if cfg is None:
        cfg = TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
            num_kv_heads=2, ffn_size=64, max_seq_len=32)
        params = init_params(cfg, jax.random.PRNGKey(0))
    L, nb, bs = cfg.num_layers, 4, 4
    kv = jnp.zeros((L, nb, bs, 2, cfg.kv_heads, cfg.head_dim),
                   jnp.bfloat16)
    kvq = (jnp.zeros(kv.shape, jnp.int8),
           jnp.ones(kv.shape[:-1], jnp.float32))
    T = 4
    a = (jnp.zeros(T, jnp.int32), jnp.zeros(T, jnp.int32),
         jnp.arange(T, dtype=jnp.int32),
         jnp.zeros((1, 2), jnp.int32), jnp.int32(T))
    fn = jax.jit(partial(ragged_forward, cfg))
    off = fn.lower(params, kv, *a).as_text()
    on = fn.lower(params, kvq, *a).as_text()

    def has_int8(txt: str) -> bool:
        # StableHLO spells int8 tensors "xi8>"/"tensor<i8>"; HLO text
        # (older jax as_text) spells them "s8[" — accept either
        return "s8[" in txt or "i8>" in txt

    return (not has_int8(off)) and has_int8(on)


def gate_markdown(stats: Sequence[QuantRegionStats],
                  gates: Optional[Dict[str, Dict[str, float]]] = None
                  ) -> str:
    gates = DEFAULT_GATES if gates is None else gates
    lines = ["### Quantization acceptance gates", "",
             "| region | SNR dB | max rel err | wire/logical | gate | "
             "pass |", "|---|---|---|---|---|---|"]
    for st in stats:
        g = gates.get(st.region, {})
        ok, v = evaluate_gates([st], gates)
        snr = ("exact" if st.bit_exact else
               ("inf" if st.snr_db is None or not math.isfinite(st.snr_db)
                else f"{st.snr_db:.1f}"))
        gate_s = (" / ".join(f"{k}>={v_}" if k == "min_snr_db"
                             else f"{k}<={v_}" if k == "max_rel_err"
                             else k for k, v_ in g.items()) or "—")
        lines.append(
            f"| {st.region} | {snr} | {st.max_rel_err:.2e} | "
            f"{1.0 / st.compression:.3f}x | {gate_s} | "
            f"{'PASS' if ok else 'FAIL'} |")
    lines.append("")
    return "\n".join(lines)


def run_quant_bench(env=None):
    """The BENCH_QUANT=1 arm (make bench-quant): measure every quantized
    region's error on REAL tensors (params + per-group grads of a small
    llama-geometry model), publish ``quant.*`` metrics, evaluate the
    acceptance gates, and verify the bit-exact off-switch.

    Returns (markdown, json_payload, ok). ``ok`` False — a gate
    violation (e.g. an injected corrupted scale) or a non-bit-exact
    off path — makes bench.py exit nonzero. Runs on CPU CI (no device
    mesh needed: the quantizer math is measured directly; the on-mesh
    wire is the same math by construction, traced by the runtime's
    traced_span instrumentation)."""
    import numpy as np

    env = os.environ if env is None else env
    set_injection(injection_from_env(env))
    try:
        model = _bench_model_cfg(env)
        cfg = model.config
        from deepspeed_tpu.models.transformer import init_params

        params = init_params(cfg, jax.random.PRNGKey(0))

        # real per-group gradients: split one batch into G groups, one
        # grad tree each — the exact construction the engine's qgZ vmap
        # produces (one group per batch shard)
        G = int(env.get("BENCH_QUANT_GROUPS", "4"))
        rng = np.random.default_rng(0)
        seq = cfg.max_seq_len
        grad_fn = jax.jit(jax.grad(lambda p, b: model.loss(p, b)[0]))
        groups = []
        for _ in range(G):
            batch = {"input_ids": rng.integers(
                0, cfg.vocab_size, (2, seq + 1)).astype(np.int32)}
            groups.append(grad_fn(params, batch))

        hpz_k = int(env.get("BENCH_QUANT_HPZ", "4"))
        n_params = sum(int(x.size) for x in jax.tree.leaves(params))

        # REAL K/V for the serving regions: run a short prefill through
        # the dense-cache forward and measure the cache it actually wrote
        from deepspeed_tpu.inference.model_runner import (
            forward_with_cache, init_dense_cache)

        kv_len = min(64, cfg.max_seq_len)
        toks = jnp.asarray(rng.integers(
            0, cfg.vocab_size, (2, kv_len)).astype(np.int32))
        cache = init_dense_cache(cfg, 2, kv_len, dtype=jnp.bfloat16)
        _, cache = forward_with_cache(cfg, params, toks, cache, 0)

        stats = [
            measure_param_fetch(params),
            measure_grad_reduce(groups),
            measure_fp8_mlp(params),
            hpz_partition_stats(n_params, hpz_k),
            measure_kv_cache([cache], cfg.head_dim),
            measure_kv_wire(cache, cfg.head_dim,
                            bits=int(env.get("BENCH_KV_WIRE_BITS", "4"))),
            measure_qar(groups),
        ]
        publish(stats)
        ok, violations = evaluate_gates(stats)

        bit_exact = None
        kv_off = None
        if not int(env.get("BENCH_QUANT_SKIP_EXACT", "0")):
            bit_exact = off_switch_bitexact(env=env)
            if not bit_exact:
                ok = False
                violations.append({"region": "off_switch",
                                   "gate": "bit_exact", "limit": True,
                                   "observed": False})
            kv_off = kv_off_switch_structural()
            if not kv_off:
                ok = False
                violations.append({"region": "kv_off_switch",
                                   "gate": "bit_exact", "limit": True,
                                   "observed": False})

        md = gate_markdown(stats)
        payload = {
            "metric": (f"quant acceptance gates ({cfg.num_layers}L, "
                       f"h={cfg.hidden_size}, vocab={cfg.vocab_size}, "
                       f"{G} grad groups)"),
            "value": len(violations),
            "unit": "gate violations",
            "ok": ok,
            "injection": _INJECT,
            "bit_exact_off": bit_exact,
            "kv_off_struct": kv_off,
            "regions": [st.to_dict() for st in stats],
            "gates": {k: dict(v) for k, v in DEFAULT_GATES.items()},
            "violations": violations,
        }
        return md, payload, ok
    finally:
        set_injection(None)
