"""Fleet observability: cross-host aggregation over a shared run dir.

The hub (``observability/hub.py``) is per-process; on a multi-host run
every worker keeps its own StepTrace history and nobody can answer
"which host is slow". This module adds the pod-scale layer without any
extra collectives: each process *atomically publishes* per-rank shards
into a shared run directory (``DSTPU_RUN_DIR`` env or config
``observability.run_dir`` — any shared filesystem works: GCS fuse, NFS,
or plain /tmp for the CPU hostsim tests), and an aggregator merges the
shards into a fleet view:

* per-step cross-rank skew (max-min wall time, attributed to the
  slowest rank of that step),
* per-rank EWMA straggler scores (wall time relative to the per-step
  cross-rank minimum, smoothed — a persistently slow host floats to the
  top even when individual steps are noisy),
* stale-heartbeat dead-host detection (a rank whose heartbeat file
  stops aging is hung or OOM-killed; its flight-recorder dump, if any,
  sits next to its shard).

Run dir layout (all writes are tmp+rename atomic, all reads tolerate
missing/partial files):

    <run_dir>/heartbeat/rank_00000.json   rewritten every publish
    <run_dir>/steps/rank_00000.jsonl      appended one row per step
    <run_dir>/flight/flight_rank0_*.json  flight-recorder dumps

No run dir configured → no publisher, no shard I/O, zero overhead: the
single-process path never touches this module.
"""

from __future__ import annotations

import json
import os
import socket
import threading
import time
from typing import Any, Dict, List, Optional

from deepspeed_tpu.utils.logging import logger

HEARTBEAT_DIR = "heartbeat"
STEPS_DIR = "steps"
FLIGHT_DIR = "flight"
REPLICAS_DIR = "replicas"  # serving fleet load reports (serving/replica.py)

# EWMA straggler score above which a rank is named the straggler (1.0 =
# exactly the per-step minimum; 1.15 = persistently 15% slower than the
# fastest rank — beyond cross-host jitter, below a real hang)
STRAGGLER_THRESHOLD = 1.15


def resolve_run_dir(obs_config=None) -> Optional[str]:
    """Shared run dir: DSTPU_RUN_DIR env beats config
    ``observability.run_dir``; None when neither is set."""
    return os.environ.get("DSTPU_RUN_DIR") or getattr(
        obs_config, "run_dir", None)


def _rank_name(rank: int) -> str:
    return f"rank_{rank:05d}"


def _atomic_write_json(path: str, doc: Dict[str, Any]) -> None:
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


class FleetPublisher:
    """Per-process shard writer: one heartbeat file (rewritten) plus one
    append-only step-summary JSONL. Write failures disable the publisher
    (a full shared filesystem must not kill training)."""

    def __init__(self, run_dir: str, rank: Optional[int] = None,
                 publish_every_steps: int = 1):
        from deepspeed_tpu.observability.flight_recorder import _env_rank

        self.run_dir = run_dir
        self.rank = int(rank) if rank is not None else _env_rank()
        self.publish_every = max(1, int(publish_every_steps or 1))
        self._lock = threading.Lock()
        self._failed = False
        self._fh = None
        try:
            os.makedirs(os.path.join(run_dir, HEARTBEAT_DIR), exist_ok=True)
            os.makedirs(os.path.join(run_dir, STEPS_DIR), exist_ok=True)
            os.makedirs(os.path.join(run_dir, FLIGHT_DIR), exist_ok=True)
            self._hb_path = os.path.join(
                run_dir, HEARTBEAT_DIR, _rank_name(self.rank) + ".json")
            self._fh = open(
                os.path.join(run_dir, STEPS_DIR,
                             _rank_name(self.rank) + ".jsonl"),
                "a", buffering=1)
            self.heartbeat(status="starting")
        except Exception as e:
            self._failed = True
            logger.warning(f"fleet publisher disabled: {e}")

    def publish_step(self, trace) -> None:
        """One shard row per traced step (StepTrace or dict). Rows keep
        only the cross-rank-comparable scalars — the full trace stays in
        the per-process JSONL sink."""
        if self._failed:
            return
        d = trace if isinstance(trace, dict) else trace.to_dict()
        step = int(d.get("step", 0))
        if step % self.publish_every != 0:
            return
        row = {"rank": self.rank, "step": step}
        for key in ("wall_ms", "host_gap_ms", "loss", "tokens_per_sec",
                    "mfu", "compile_events", "timestamp", "inflight"):
            v = d.get(key)
            if v is not None:
                row[key] = v
        try:
            with self._lock:
                self._fh.write(json.dumps(row) + "\n")
                self._fh.flush()
            self.heartbeat(step=step)
        except Exception as e:
            self._failed = True
            logger.warning(f"fleet publisher disabled after error: {e}")

    def heartbeat(self, step: Optional[int] = None,
                  status: str = "running") -> None:
        if self._failed:
            return
        try:
            _atomic_write_json(self._hb_path, {
                "rank": self.rank,
                "host": socket.gethostname(),
                "pid": os.getpid(),
                "ts": time.time(),
                "step": step,
                "status": status,
            })
        except Exception as e:
            self._failed = True
            logger.warning(f"fleet heartbeat disabled after error: {e}")

    def close(self, status: str = "done") -> None:
        self.heartbeat(status=status)
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                except Exception:
                    pass


class FleetAggregator:
    """Merge per-rank shards into the fleet view. Read-only: runs on any
    host with the run dir mounted (``tools/fleet_top.py``), or inside a
    test asserting on the merged report."""

    def __init__(self, run_dir: str, stale_after_seconds: float = 30.0,
                 ewma_alpha: float = 0.25, tail_steps: int = 2048):
        self.run_dir = run_dir
        self.stale_after = float(stale_after_seconds)
        self.alpha = float(ewma_alpha)
        self.tail_steps = int(tail_steps)

    # -- shard reading -------------------------------------------------
    def _read_heartbeats(self) -> Dict[int, Dict[str, Any]]:
        out: Dict[int, Dict[str, Any]] = {}
        d = os.path.join(self.run_dir, HEARTBEAT_DIR)
        if not os.path.isdir(d):
            return out
        for name in sorted(os.listdir(d)):
            if not name.endswith(".json") or ".tmp." in name:
                continue
            try:
                with open(os.path.join(d, name)) as f:
                    hb = json.load(f)
                out[int(hb["rank"])] = hb
            except Exception:
                continue  # mid-rewrite or foreign file: skip
        return out

    def _read_steps(self) -> Dict[int, List[Dict[str, Any]]]:
        out: Dict[int, List[Dict[str, Any]]] = {}
        d = os.path.join(self.run_dir, STEPS_DIR)
        if not os.path.isdir(d):
            return out
        for name in sorted(os.listdir(d)):
            if not name.endswith(".jsonl"):
                continue
            rows = []
            try:
                with open(os.path.join(d, name)) as f:
                    for line in f:
                        try:
                            rows.append(json.loads(line))
                        except json.JSONDecodeError:
                            continue  # torn tail line of a live writer
            except OSError:
                continue
            if rows:
                out[int(rows[0].get("rank", -1))] = rows[-self.tail_steps:]
        return out

    # -- aggregation ---------------------------------------------------
    def report(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The merged fleet view (see module docstring for the signals).

        ``straggler`` names the rank with the highest EWMA score when it
        clears STRAGGLER_THRESHOLD (None below it — on a healthy fleet
        nobody is "the straggler"); ``skew.worst_rank`` attributes the
        largest single-step spread."""
        now = time.time() if now is None else now
        heartbeats = self._read_heartbeats()
        shards = self._read_steps()
        ranks = sorted(set(heartbeats) | set(shards))

        per_rank: Dict[int, Dict[str, Any]] = {}
        for r in ranks:
            rows = shards.get(r, [])
            walls = [row["wall_ms"] for row in rows if "wall_ms" in row]
            hb = heartbeats.get(r)
            age = (now - hb["ts"]) if hb else None
            per_rank[r] = {
                "steps": len(rows),
                "last_step": rows[-1]["step"] if rows else None,
                "mean_wall_ms": (sum(walls) / len(walls)) if walls else None,
                "host": hb.get("host") if hb else None,
                "status": hb.get("status") if hb else "unknown",
                "heartbeat_age_s": age,
                "alive": age is not None and age < self.stale_after,
                "slowest_steps": 0,
                "straggler_score": None,
            }

        # merge on step number: skew + slowest-rank attribution + EWMA
        by_step: Dict[int, Dict[int, float]] = {}
        for r, rows in shards.items():
            for row in rows:
                if "wall_ms" in row:
                    by_step.setdefault(row["step"], {})[r] = row["wall_ms"]
        merged = {s: w for s, w in by_step.items() if len(w) >= 2}
        scores: Dict[int, float] = {}
        skews: List[float] = []
        max_skew = {"ms": 0.0, "step": None, "worst_rank": None}
        for s in sorted(merged):
            walls = merged[s]
            lo = min(walls.values())
            hi_rank = max(walls, key=walls.get)
            skew = walls[hi_rank] - lo
            skews.append(skew)
            per_rank[hi_rank]["slowest_steps"] += 1
            if skew > max_skew["ms"]:
                max_skew = {"ms": skew, "step": s, "worst_rank": hi_rank}
            for r, w in walls.items():
                ratio = w / lo if lo > 0 else 1.0
                prev = scores.get(r)
                scores[r] = ratio if prev is None else \
                    self.alpha * ratio + (1 - self.alpha) * prev
        for r, sc in scores.items():
            per_rank[r]["straggler_score"] = sc

        straggler = None
        if scores:
            worst = max(scores, key=scores.get)
            if scores[worst] >= STRAGGLER_THRESHOLD:
                straggler = {"rank": worst, "score": scores[worst],
                             "host": per_rank[worst]["host"]}

        dead = [r for r in ranks
                if not per_rank[r]["alive"]
                and per_rank[r]["status"] not in ("done", "crashed")]
        return {
            "run_dir": self.run_dir,
            "ts": now,
            "n_ranks": len(ranks),
            "merged_steps": len(merged),
            "ranks": per_rank,
            "skew": {
                "mean_ms": (sum(skews) / len(skews)) if skews else None,
                "max_ms": max_skew["ms"] if skews else None,
                "max_step": max_skew["step"],
                "worst_rank": max_skew["worst_rank"],
            },
            "straggler": straggler,
            "dead_ranks": dead,
        }


class ReplicaPublisher:
    """Serving-replica load reports over the same run-dir discipline as
    the rank heartbeats: one atomically rewritten JSON per replica under
    ``<run_dir>/replicas/``. The report doc *is* the heartbeat — its
    ``ts`` doubles as liveness, so the router's stale-heartbeat failover
    and an external ``serve_top --fleet`` read the same file. Write
    failures disable the publisher (serving must not die with the
    shared filesystem)."""

    def __init__(self, run_dir: str, replica_id: int):
        self.run_dir = run_dir
        self.replica_id = int(replica_id)
        self._failed = False
        try:
            os.makedirs(os.path.join(run_dir, REPLICAS_DIR), exist_ok=True)
            self._path = os.path.join(
                run_dir, REPLICAS_DIR, f"replica_{self.replica_id:05d}.json")
        except Exception as e:
            self._failed = True
            logger.warning(f"replica publisher disabled: {e}")

    def publish(self, report: Dict[str, Any]) -> None:
        if self._failed:
            return
        try:
            _atomic_write_json(self._path, report)
        except Exception as e:
            self._failed = True
            logger.warning(f"replica publisher disabled after error: {e}")


def read_replica_reports(run_dir: str) -> Dict[int, Dict[str, Any]]:
    """Load every replica's last published load report (read side of
    ReplicaPublisher; tolerates mid-rewrite and foreign files)."""
    out: Dict[int, Dict[str, Any]] = {}
    d = os.path.join(run_dir, REPLICAS_DIR)
    if not os.path.isdir(d):
        return out
    for name in sorted(os.listdir(d)):
        if not name.endswith(".json") or ".tmp." in name:
            continue
        try:
            with open(os.path.join(d, name)) as f:
                doc = json.load(f)
            out[int(doc["replica"])] = doc
        except Exception:
            continue
    return out


def _fmt(v, spec: str, width: int) -> str:
    return format(v, spec) if v is not None else "-".rjust(width)


def format_report(report: Dict[str, Any]) -> str:
    """Human fleet view (tools/fleet_top.py, the Makefile demo)."""
    lines = [
        f"fleet: {report['n_ranks']} ranks, "
        f"{report['merged_steps']} merged steps  ({report['run_dir']})",
        f"{'rank':>5} {'host':<16} {'status':<9} {'steps':>6} "
        f"{'last':>6} {'mean ms':>9} {'slowest':>8} {'score':>7} {'hb age':>7}",
    ]
    for r in sorted(report["ranks"]):
        row = report["ranks"][r]
        lines.append(
            f"{r:>5} {str(row['host'] or '?'):<16} {row['status']:<9} "
            f"{row['steps']:>6} {_fmt(row['last_step'], '>6', 6)} "
            f"{_fmt(row['mean_wall_ms'], '>9.1f', 9)} "
            f"{row['slowest_steps']:>8} "
            f"{_fmt(row['straggler_score'], '>7.3f', 7)} "
            f"{_fmt(row['heartbeat_age_s'], '>6.1f', 7)}"
            + ("s" if row["heartbeat_age_s"] is not None else ""))
    skew = report["skew"]
    if skew["max_ms"] is not None:
        lines.append(
            f"skew: mean {skew['mean_ms']:.1f} ms, max {skew['max_ms']:.1f} "
            f"ms at step {skew['max_step']} (rank {skew['worst_rank']})")
    s = report["straggler"]
    lines.append(
        f"straggler: rank {s['rank']} (EWMA {s['score']:.2f}x the fastest"
        f"{', host ' + s['host'] if s.get('host') else ''})" if s
        else "straggler: none (all ranks within "
             f"{STRAGGLER_THRESHOLD:.2f}x of the fastest)")
    if report["dead_ranks"]:
        lines.append(f"DEAD (stale heartbeat): ranks {report['dead_ranks']} "
                     f"— check <run_dir>/flight/ for their dumps")
    return "\n".join(lines)
